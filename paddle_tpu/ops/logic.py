"""Comparison / logical / predicate ops (reference: python/paddle/tensor/logic.py).
All non-differentiable; outputs are bool tensors."""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor import Tensor
from ._apply import binary, ensure_tensor, unary

__all__ = [
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "equal_all", "logical_and", "logical_or", "logical_xor",
    "logical_not", "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "isnan", "isinf", "isfinite", "is_empty", "isin",
]


def _cmp(fn, name):
    def op(x, y, name_=None):
        return binary(fn, x, y, differentiable=False, name=name)

    op.__name__ = name
    return op


equal = _cmp(jnp.equal, "equal")
not_equal = _cmp(jnp.not_equal, "not_equal")
greater_than = _cmp(jnp.greater, "greater_than")
greater_equal = _cmp(jnp.greater_equal, "greater_equal")
less_than = _cmp(jnp.less, "less_than")
less_equal = _cmp(jnp.less_equal, "less_equal")
logical_and = _cmp(jnp.logical_and, "logical_and")
logical_or = _cmp(jnp.logical_or, "logical_or")
logical_xor = _cmp(jnp.logical_xor, "logical_xor")
bitwise_and = _cmp(jnp.bitwise_and, "bitwise_and")
bitwise_or = _cmp(jnp.bitwise_or, "bitwise_or")
bitwise_xor = _cmp(jnp.bitwise_xor, "bitwise_xor")


def equal_all(x, y, name=None):
    return binary(lambda a, b: jnp.array_equal(a, b), x, y, differentiable=False, name="equal_all")


def logical_not(x, name=None):
    return unary(jnp.logical_not, x, differentiable=False, name="logical_not")


def bitwise_not(x, name=None):
    return unary(jnp.bitwise_not, x, differentiable=False, name="bitwise_not")


def isnan(x, name=None):
    return unary(jnp.isnan, x, differentiable=False, name="isnan")


def isinf(x, name=None):
    return unary(jnp.isinf, x, differentiable=False, name="isinf")


def isfinite(x, name=None):
    return unary(jnp.isfinite, x, differentiable=False, name="isfinite")


def is_empty(x, name=None):
    return Tensor(jnp.asarray(ensure_tensor(x).size == 0))


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    t = ensure_tensor(test_x)._value
    return unary(lambda a: jnp.isin(a, t, invert=invert), x, differentiable=False, name="isin")
