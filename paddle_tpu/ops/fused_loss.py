"""Fused linear + softmax-cross-entropy over vocab chunks.

Reference parity: the fused softmax-CE family
(paddle/phi/kernels/gpu/cross_entropy_kernel.cu fuses softmax+CE;
fused_softmax_mask ops) — but the TPU pain point is upstream of the softmax:
the LM head materializes logits [B·S, V] (V≈50K ⇒ 0.8GB bf16 forward and a
multi-GB fp32 softmax/grad footprint in backward), which is what capped the
round-2 bench at B=8–16 per chip (BENCH_NOTES.md: B≥24 OOMs).

TPU-native redesign: never materialize [N, V]. The vocab dim is scanned in
chunks with an online logsumexp (the flash-attention trick applied to the
vocab softmax):

  forward:  lax.scan over W chunks [C, H] → chunk logits [N, C] live only in
            registers/VMEM-scale working set; carry (m, l, label_logit).
  backward: second scan recomputes chunk logits, forms p−onehot per chunk,
            accumulates dh += (p−onehot)·W_c and emits dW per chunk.

Peak extra memory drops from O(N·V) to O(N·C); FLOPs are identical to the
dense path (the same matmuls, chunked). Pure XLA (scan of MXU matmuls) — a
Pallas kernel adds nothing here because each chunk is already one large
matmul XLA schedules well; the win is the algorithmic memory bound.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["fused_linear_cross_entropy"]

DEFAULT_CHUNK = 8192


def _pick_chunk(v: int, chunk: int) -> int:
    """Chunk size actually used for a (possibly padded) vocab of v rows."""
    return min(chunk, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_linear_cross_entropy(hidden, weight, labels, chunk: int = DEFAULT_CHUNK,
                               ignore_index: int = -100):
    """mean CE of softmax(hidden @ weightᵀ) vs labels, without [N, V].

    hidden: [N, H] (any float dtype; math in f32), weight: [V, H],
    labels: [N] int. Returns scalar mean loss over non-ignored labels.
    """
    loss, _ = _fwd(hidden, weight, labels, chunk, ignore_index)
    return loss


def _chunks(weight, chunk):
    """Split W [V, H] into [n, C, H]; V not divisible by C gets zero-row
    padding (the scan masks the padded tail, so the O(N·C) memory bound
    holds for EVERY vocab size — silently falling back to C=V would
    re-materialize exactly the [N, V] block this module exists to avoid)."""
    v, h = weight.shape
    c = _pick_chunk(v, chunk)
    pad = (-v) % c
    if pad:
        weight = jnp.pad(weight, ((0, pad), (0, 0)))
    return weight.reshape((v + pad) // c, c, h), c, v


def _fwd(hidden, weight, labels, chunk, ignore_index):
    n, h = hidden.shape
    wch, c, v = _chunks(weight, chunk)
    hid32 = hidden.astype(jnp.float32)
    valid = labels != ignore_index
    lab = jnp.where(valid, labels, 0).astype(jnp.int32)

    def body(carry, xs):
        m, l, lab_logit = carry
        w_c, base = xs
        logits = hid32 @ w_c.astype(jnp.float32).T  # [N, C]
        col_ok = base + jnp.arange(c, dtype=jnp.int32) < v
        logits = jnp.where(col_ok[None, :], logits, -jnp.inf)
        m_cur = jnp.max(logits, axis=1)
        m_new = jnp.maximum(m, m_cur)
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=1)
        # label logit if it falls in this chunk
        idx = lab - base
        in_chunk = (idx >= 0) & (idx < c)
        picked = jnp.take_along_axis(
            logits, jnp.clip(idx, 0, c - 1)[:, None], axis=1)[:, 0]
        lab_logit = jnp.where(in_chunk, picked, lab_logit)
        return (m_new, l, lab_logit), None

    init = (jnp.full((n,), -jnp.inf, jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32))
    bases = jnp.arange(wch.shape[0], dtype=jnp.int32) * c
    (m, l, lab_logit), _ = jax.lax.scan(body, init, (wch, bases))
    lse = m + jnp.log(l)
    per_tok = jnp.where(valid, lse - lab_logit, 0.0)
    denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    loss = jnp.sum(per_tok) / denom
    return loss, (hidden, weight, lab, valid, lse, denom)


def _bwd(chunk, ignore_index, res, g):
    hidden, weight, lab, valid, lse, denom = res
    n, h = hidden.shape
    wch, c, v = _chunks(weight, chunk)
    hid32 = hidden.astype(jnp.float32)
    scale = (g / denom) * valid.astype(jnp.float32)  # [N]

    def body(dh, xs):
        w_c, base = xs
        w32 = w_c.astype(jnp.float32)
        logits = hid32 @ w32.T                        # [N, C]
        col_ok = base + jnp.arange(c, dtype=jnp.int32) < v
        p = jnp.where(col_ok[None, :],
                      jnp.exp(logits - lse[:, None]), 0.0)  # softmax chunk
        idx = lab - base
        in_chunk = (idx >= 0) & (idx < c)
        onehot = (jnp.arange(c, dtype=jnp.int32)[None, :]
                  == jnp.clip(idx, 0, c - 1)[:, None]) \
            & in_chunk[:, None]
        d = (p - onehot.astype(jnp.float32)) * scale[:, None]  # [N, C]
        dh = dh + d @ w32
        dw_c = d.T @ hid32                            # [C, H]
        return dh, dw_c.astype(weight.dtype)

    bases = jnp.arange(wch.shape[0], dtype=jnp.int32) * c
    dh, dwch = jax.lax.scan(body, jnp.zeros((n, h), jnp.float32),
                            (wch, bases))
    dw = dwch.reshape(-1, h)[:v]  # drop the zero-padded tail rows
    return (dh.astype(hidden.dtype), dw, None)


fused_linear_cross_entropy.defvjp(_fwd, _bwd)
