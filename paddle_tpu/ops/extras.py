"""Remaining top-level tensor API surface.

Reference parity: the tail of ``python/paddle/__init__.py``'s ``__all__``
(tensor/math.py, tensor/manipulation.py, tensor/attribute.py,
tensor/creation.py entries) not yet covered by the core op modules —
numerics (logit, heaviside, nan_to_num, trapezoid...), complex helpers
(real/imag/conj/angle/polar), integer math (gcd/lcm), manipulation
(multiplex, index_add, take, broadcast_tensors, renorm, vander) and the
trailing-underscore in-place variants.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd.engine import apply_op, inplace_rebind
from ._apply import binary, ensure_tensor, unary

__all__ = [
    "logit", "mv", "floor_mod", "multiplex", "real", "imag", "conj",
    "rad2deg", "deg2rad", "gcd", "lcm", "count_nonzero", "increment",
    "scatter_nd", "reverse", "add_n", "angle", "renorm", "nan_to_num",
    "heaviside", "index_add", "index_add_", "sgn", "take", "frexp", "trapezoid",
    "cumulative_trapezoid", "polar", "vander", "broadcast_tensors",
    "broadcast_shape", "is_complex", "is_integer", "is_floating_point",
    "rank", "shape", "tolist", "tanh_", "reshape_", "unsqueeze_",
    "squeeze_", "scatter_", "vsplit", "ceil_", "exp_", "floor_",
    "reciprocal_", "round_", "rsqrt_", "sqrt_", "scale_", "remainder_",
    "subtract_", "clip_", "flatten_", "lerp_", "erfinv_", "sigmoid_",
    "put_along_axis_",
]


# ------------------------------------------------------------- numerics


def logit(x, eps=None, name=None):
    def fn(v):
        if eps is not None:
            v = jnp.clip(v, eps, 1.0 - eps)
        return jnp.log(v) - jnp.log1p(-v)
    return unary(fn, x, name="logit")


def heaviside(x, y, name=None):
    return binary(lambda a, b: jnp.heaviside(a, b), x, y, name="heaviside")


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return unary(lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf,
                                          neginf=neginf), x, name="nan_to_num")


def sgn(x, name=None):
    """Sign; for complex inputs x/|x| (reference: tensor/math.py sgn)."""
    def fn(v):
        if jnp.iscomplexobj(v):
            mag = jnp.abs(v)
            return jnp.where(mag == 0, 0, v / jnp.where(mag == 0, 1, mag))
        return jnp.sign(v)
    return unary(fn, x, name="sgn")


def frexp(x, name=None):
    x = ensure_tensor(x)
    return apply_op(lambda v: jnp.frexp(v), [x], name="frexp")


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = ensure_tensor(y)
    if x is not None:
        return apply_op(lambda yy, xx: jnp.trapezoid(yy, xx, axis=axis),
                        [y, ensure_tensor(x)], name="trapezoid")
    d = 1.0 if dx is None else dx
    return apply_op(lambda yy: jnp.trapezoid(yy, dx=d, axis=axis), [y],
                    name="trapezoid")


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = ensure_tensor(y)

    def fn(yy, xx=None):
        n = yy.shape[axis]
        lo = jax.lax.slice_in_dim(yy, 0, n - 1, axis=axis)
        hi = jax.lax.slice_in_dim(yy, 1, n, axis=axis)
        if xx is not None:
            xlo = jax.lax.slice_in_dim(xx, 0, n - 1, axis=axis)
            xhi = jax.lax.slice_in_dim(xx, 1, n, axis=axis)
            widths = xhi - xlo
        else:
            widths = 1.0 if dx is None else dx
        return jnp.cumsum((lo + hi) * 0.5 * widths, axis=axis)

    if x is not None:
        return apply_op(fn, [y, ensure_tensor(x)],
                        name="cumulative_trapezoid")
    return apply_op(fn, [y], name="cumulative_trapezoid")


def rad2deg(x, name=None):
    return unary(lambda v: jnp.rad2deg(v.astype(jnp.float32)
                                       if jnp.issubdtype(v.dtype, jnp.integer)
                                       else v), x, name="rad2deg")


def deg2rad(x, name=None):
    return unary(lambda v: jnp.deg2rad(v.astype(jnp.float32)
                                       if jnp.issubdtype(v.dtype, jnp.integer)
                                       else v), x, name="deg2rad")


def gcd(x, y, name=None):
    return binary(jnp.gcd, x, y, name="gcd")


def lcm(x, y, name=None):
    return binary(jnp.lcm, x, y, name="lcm")


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return unary(lambda v: jnp.count_nonzero(v, axis=axis, keepdims=keepdim),
                 x, differentiable=False, name="count_nonzero")


def increment(x, value=1.0, name=None):
    """In-place add of a scalar (reference: tensor/math.py increment)."""
    x = ensure_tensor(x)
    out = unary(lambda v: v + jnp.asarray(value, v.dtype), x,
                name="increment")
    inplace_rebind(x, out)
    return x


def floor_mod(x, y, name=None):
    from .math import mod

    return mod(x, y, name=name)


def mv(x, vec, name=None):
    return apply_op(lambda m, v: m @ v,
                    [ensure_tensor(x), ensure_tensor(vec)], name="mv")


# -------------------------------------------------------------- complex


def real(x, name=None):
    return unary(jnp.real, x, name="real")


def imag(x, name=None):
    return unary(jnp.imag, x, name="imag")


def conj(x, name=None):
    return unary(jnp.conj, x, name="conj")


def angle(x, name=None):
    return unary(jnp.angle, x, name="angle")


def polar(abs, angle, name=None):
    return apply_op(lambda r, t: (r * jnp.cos(t) + 1j * r * jnp.sin(t)
                                  ).astype(jnp.complex64),
                    [ensure_tensor(abs), ensure_tensor(angle)], name="polar")


# --------------------------------------------------------- manipulation


def multiplex(inputs, index, name=None):
    """Row-wise select among candidate tensors (reference:
    tensor/math.py multiplex: out[i] = inputs[index[i]][i])."""
    ts = [ensure_tensor(t) for t in inputs]
    idx = ensure_tensor(index)

    def fn(ix, *cands):
        stacked = jnp.stack(cands)  # [n_candidates, batch, ...]
        rows = jnp.arange(stacked.shape[1])
        return stacked[ix.reshape(-1).astype(jnp.int32), rows]

    return apply_op(fn, [idx] + ts, name="multiplex")


def index_add(x, index, axis, value, name=None):
    return apply_op(
        lambda v, ix, val: _index_add_impl(v, ix, axis, val),
        [ensure_tensor(x), ensure_tensor(index), ensure_tensor(value)],
        name="index_add")


def index_add_(x, index, axis, value, name=None):
    """In-place index_add (reference: tensor/manipulation.py:4764)."""
    x = ensure_tensor(x)
    out = index_add(x, index, axis, value)
    inplace_rebind(x, out)
    return x


def _index_add_impl(v, ix, axis, val):
    ix = ix.astype(jnp.int32)
    moved = jnp.moveaxis(v, axis, 0)
    valm = jnp.moveaxis(val, axis, 0)
    out = moved.at[ix].add(valm)
    return jnp.moveaxis(out, 0, axis)


def take(x, index, mode="raise", name=None):
    """Flat-index gather (reference: tensor/math.py take)."""
    if mode not in ("raise", "wrap", "clip"):
        raise ValueError("mode must be 'raise', 'wrap' or 'clip'")

    def fn(v, ix):
        flat = v.reshape(-1)
        n = flat.shape[0]
        ixf = ix.astype(jnp.int64)
        if mode == "wrap":
            ixf = ((ixf % n) + n) % n
        elif mode == "clip":
            ixf = jnp.clip(ixf, 0, n - 1)
        else:  # raise-mode bounds checks can't run under trace: negative wrap
            ixf = jnp.where(ixf < 0, ixf + n, ixf)
        return flat[ixf]

    return apply_op(fn, [ensure_tensor(x), ensure_tensor(index)], name="take")


def reverse(x, axis, name=None):
    from .manipulation import flip

    return flip(x, axis, name=name)


def add_n(inputs, name=None):
    ts = [ensure_tensor(t) for t in
          (inputs if isinstance(inputs, (list, tuple)) else [inputs])]
    return apply_op(lambda *vs: sum(vs[1:], vs[0]), ts, name="add_n")


def scatter_nd(index, updates, shape, name=None):
    def fn(ix, up):
        out = jnp.zeros(tuple(int(s) for s in shape), up.dtype)
        return out.at[tuple(jnp.moveaxis(ix.astype(jnp.int32), -1, 0))].add(up)

    return apply_op(fn, [ensure_tensor(index), ensure_tensor(updates)],
                    name="scatter_nd")


def renorm(x, p, axis, max_norm, name=None):
    """Clamp each slice along ``axis`` to p-norm ≤ max_norm."""
    def fn(v):
        moved = jnp.moveaxis(v, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.linalg.norm(flat, ord=p, axis=1)
        scale = jnp.where(norms > max_norm,
                          max_norm / jnp.maximum(norms, 1e-12), 1.0)
        out = flat * scale[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)

    return unary(fn, x, name="renorm")


def vander(x, n=None, increasing=False, name=None):
    return unary(lambda v: jnp.vander(v, N=n, increasing=increasing), x,
                 name="vander")


def broadcast_tensors(inputs, name=None):
    ts = [ensure_tensor(t) for t in inputs]
    shape = jnp.broadcast_shapes(*[tuple(t.shape) for t in ts])
    return [apply_op(lambda v: jnp.broadcast_to(v, shape), [t],
                     name="broadcast_tensors") for t in ts]


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def vsplit(x, num_or_indices, name=None):
    x = ensure_tensor(x)
    if x.ndim < 2:
        raise ValueError("vsplit expects a tensor of rank >= 2")
    n = x.shape[0]
    if isinstance(num_or_indices, int):
        if n % num_or_indices != 0:
            raise ValueError(f"dim 0 ({n}) not divisible by "
                             f"{num_or_indices}")
        bounds = [n // num_or_indices * i
                  for i in range(1, num_or_indices)]
    else:
        # list form is split INDICES (numpy semantics), not section sizes
        bounds = [int(i) for i in num_or_indices]
    edges = [0] + bounds + [n]
    return [apply_op(lambda v, lo=lo, hi=hi: v[lo:hi], [x], name="vsplit")
            for lo, hi in zip(edges[:-1], edges[1:])]


# ----------------------------------------------------------- predicates


def is_complex(x) -> bool:
    return jnp.iscomplexobj(ensure_tensor(x)._value)


def is_integer(x) -> bool:
    return jnp.issubdtype(ensure_tensor(x)._value.dtype, jnp.integer)


def is_floating_point(x) -> bool:
    return jnp.issubdtype(ensure_tensor(x)._value.dtype, jnp.floating)


def rank(input) -> "object":
    from ..tensor import Tensor

    return Tensor(jnp.asarray(ensure_tensor(input).ndim, jnp.int32))


def shape(input):
    from ..tensor import Tensor

    return Tensor(jnp.asarray(ensure_tensor(input).shape, jnp.int32))


def tolist(x):
    return ensure_tensor(x).numpy().tolist()


# -------------------------------------------------------------- inplace


def _inplace(fn_name, x, *args, **kwargs):
    from . import manipulation, math

    x = ensure_tensor(x)
    fn = getattr(math, fn_name, None) or getattr(manipulation, fn_name)
    out = fn(x, *args, **kwargs)
    inplace_rebind(x, out)
    return x


def tanh_(x, name=None):
    return _inplace("tanh", x)


def reshape_(x, shape, name=None):
    return _inplace("reshape", x, shape)


def unsqueeze_(x, axis, name=None):
    return _inplace("unsqueeze", x, axis)


def squeeze_(x, axis=None, name=None):
    return _inplace("squeeze", x, axis)


def scatter_(x, index, updates, overwrite=True, name=None):
    from .manipulation import scatter

    x = ensure_tensor(x)
    out = scatter(x, index, updates, overwrite=overwrite)
    inplace_rebind(x, out)
    return x


# ----------------------------------------- remaining in-place variants
# (reference: tensor_method_func trailing-underscore entries)


def ceil_(x, name=None):
    return _inplace("ceil", x)


def exp_(x, name=None):
    return _inplace("exp", x)


def floor_(x, name=None):
    return _inplace("floor", x)


def reciprocal_(x, name=None):
    return _inplace("reciprocal", x)


def round_(x, name=None):
    return _inplace("round", x)


def rsqrt_(x, name=None):
    return _inplace("rsqrt", x)


def sqrt_(x, name=None):
    return _inplace("sqrt", x)


def scale_(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None,
           name=None):
    return _inplace("scale", x, scale, bias, bias_after_scale, act)


def remainder_(x, y, name=None):
    return _inplace("mod", x, y)


def subtract_(x, y, name=None):
    return _inplace("subtract", x, y)


def clip_(x, min=None, max=None, name=None):
    return _inplace("clip", x, min, max)


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    return _inplace("flatten", x, start_axis, stop_axis)


def lerp_(x, y, weight, name=None):
    return _inplace("lerp", x, y, weight)


def erfinv_(x, name=None):
    return _inplace("erfinv", x)


def sigmoid_(x, name=None):
    return _inplace("sigmoid", x)


def put_along_axis_(arr, indices, values, axis, reduce="assign", name=None):
    return _inplace("put_along_axis", arr, indices, values, axis, reduce)
