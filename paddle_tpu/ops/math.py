"""Elementwise math, matmul, reductions.

reference parity: paddle/phi/kernels/{cpu,gpu}/*_kernel.* exposed through
python/paddle/tensor/math.py. On TPU each op is one jnp/lax call; XLA fuses
chains of them into single kernels, so there is no fused-elementwise tier to
hand-maintain (reference: phi/kernels/funcs elementwise machinery).
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

from .. import dtypes
from ..autograd.engine import apply_op
from ..tensor import Tensor
from ._apply import binary, ensure_tensor, unary

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "remainder", "mod",
    "pow", "matmul", "scale", "exp", "expm1", "log", "log2", "log10", "log1p",
    "sqrt", "rsqrt", "square", "abs", "neg", "sign", "floor", "ceil", "round",
    "trunc", "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh",
    "tanh", "asinh", "acosh", "atanh", "sigmoid", "reciprocal", "maximum",
    "minimum", "fmax", "fmin", "clip", "sum", "mean", "max", "min", "prod",
    "all", "any", "argmax", "argmin", "cumsum", "cumprod", "logsumexp",
    "logcumsumexp", "einsum", "dot", "mm", "bmm", "t", "multiply_", "add_",
    "addmm", "inner", "outer", "kron", "diff", "nanmean", "nansum", "amax",
    "amin", "lerp", "erf", "erfinv", "stanh", "atan2", "hypot", "frac",
    "isclose", "allclose", "lgamma", "digamma", "i0", "i0e", "i1", "i1e",
]


# -------------------------------------------------------------- elementwise
def add(x, y, name=None):
    return binary(jnp.add, x, y, name="add")


def subtract(x, y, name=None):
    return binary(jnp.subtract, x, y, name="subtract")


def multiply(x, y, name=None):
    return binary(jnp.multiply, x, y, name="multiply")


def divide(x, y, name=None):
    return binary(jnp.divide, x, y, name="divide")


def floor_divide(x, y, name=None):
    return binary(jnp.floor_divide, x, y, differentiable=False, name="floor_divide")


def remainder(x, y, name=None):
    return binary(jnp.remainder, x, y, differentiable=False, name="remainder")


mod = remainder


def pow(x, y, name=None):
    return binary(jnp.power, x, y, name="pow")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """reference: phi ScaleKernel (phi/kernels/scale_kernel.h)."""
    s, b = scale, bias
    if bias_after_scale:
        out = unary(lambda a: a * s + b, x, name="scale")
    else:
        out = unary(lambda a: (a + b) * s, x, name="scale")
    if act is not None:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


def _unary_factory(fn, name, differentiable=True):
    def op(x, name_=None):
        return unary(fn, x, differentiable=differentiable, name=name)

    op.__name__ = name
    return op


exp = _unary_factory(jnp.exp, "exp")
expm1 = _unary_factory(jnp.expm1, "expm1")
log = _unary_factory(jnp.log, "log")
log2 = _unary_factory(jnp.log2, "log2")
log10 = _unary_factory(jnp.log10, "log10")
log1p = _unary_factory(jnp.log1p, "log1p")
sqrt = _unary_factory(jnp.sqrt, "sqrt")
rsqrt = _unary_factory(jax.lax.rsqrt, "rsqrt")
square = _unary_factory(jnp.square, "square")
abs = _unary_factory(jnp.abs, "abs")
neg = _unary_factory(jnp.negative, "neg")
sign = _unary_factory(jnp.sign, "sign", differentiable=False)
floor = _unary_factory(jnp.floor, "floor", differentiable=False)
ceil = _unary_factory(jnp.ceil, "ceil", differentiable=False)
round = _unary_factory(jnp.round, "round", differentiable=False)
trunc = _unary_factory(jnp.trunc, "trunc", differentiable=False)
sin = _unary_factory(jnp.sin, "sin")
cos = _unary_factory(jnp.cos, "cos")
tan = _unary_factory(jnp.tan, "tan")
asin = _unary_factory(jnp.arcsin, "asin")
acos = _unary_factory(jnp.arccos, "acos")
atan = _unary_factory(jnp.arctan, "atan")
sinh = _unary_factory(jnp.sinh, "sinh")
cosh = _unary_factory(jnp.cosh, "cosh")
tanh = _unary_factory(jnp.tanh, "tanh")
asinh = _unary_factory(jnp.arcsinh, "asinh")
acosh = _unary_factory(jnp.arccosh, "acosh")
atanh = _unary_factory(jnp.arctanh, "atanh")
sigmoid = _unary_factory(jax.nn.sigmoid, "sigmoid")
reciprocal = _unary_factory(jnp.reciprocal, "reciprocal")
erf = _unary_factory(jax.lax.erf, "erf")
erfinv = _unary_factory(jax.lax.erf_inv, "erfinv")
lgamma = _unary_factory(jax.lax.lgamma, "lgamma")
digamma = _unary_factory(jax.lax.digamma, "digamma")
i0 = _unary_factory(jax.scipy.special.i0, "i0")
i0e = _unary_factory(jax.scipy.special.i0e, "i0e")
i1 = _unary_factory(jax.scipy.special.i1, "i1")
i1e = _unary_factory(jax.scipy.special.i1e, "i1e")


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return unary(lambda a: scale_b * jnp.tanh(scale_a * a), x, name="stanh")


def frac(x, name=None):
    return unary(lambda a: a - jnp.trunc(a), x, name="frac")


def atan2(x, y, name=None):
    return binary(jnp.arctan2, x, y, name="atan2")


def hypot(x, y, name=None):
    return binary(jnp.hypot, x, y, name="hypot")


def maximum(x, y, name=None):
    return binary(jnp.maximum, x, y, name="maximum")


def minimum(x, y, name=None):
    return binary(jnp.minimum, x, y, name="minimum")


def fmax(x, y, name=None):
    return binary(jnp.fmax, x, y, name="fmax")


def fmin(x, y, name=None):
    return binary(jnp.fmin, x, y, name="fmin")


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        x, y, weight = ensure_tensor(x), ensure_tensor(y), weight
        return apply_op(lambda a, b, w: a + w * (b - a), [x, y, weight], name="lerp")
    return apply_op(lambda a, b: a + weight * (b - a), [ensure_tensor(x), ensure_tensor(y)], name="lerp")


def clip(x, min=None, max=None, name=None):
    lo = min._value if isinstance(min, Tensor) else min
    hi = max._value if isinstance(max, Tensor) else max
    return unary(lambda a: jnp.clip(a, lo, hi), x, name="clip")


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return binary(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                  x, y, differentiable=False, name="isclose")


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return binary(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                  x, y, differentiable=False, name="allclose")


# ------------------------------------------------------------------- matmul
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    """reference: phi MatmulKernel (phi/kernels/gpu/matmul_kernel.cu) /
    MatmulInferMeta (phi/infermeta/binary.cc). Lowers to a single dot_general
    — the MXU path; keep operands bf16 under AMP for full MXU rate."""

    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b)

    return binary(fn, x, y, name="matmul")


def dot(x, y, name=None):
    return binary(lambda a, b: jnp.sum(a * b, axis=-1), x, y, name="dot")


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return matmul(x, y)


def t(x, name=None):
    return unary(lambda a: a.T, x, name="t")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_op(
        lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
        [ensure_tensor(input), ensure_tensor(x), ensure_tensor(y)],
        name="addmm",
    )


def inner(x, y, name=None):
    return binary(jnp.inner, x, y, name="inner")


def outer(x, y, name=None):
    return binary(lambda a, b: jnp.outer(a, b), x, y, name="outer")


def kron(x, y, name=None):
    return binary(jnp.kron, x, y, name="kron")


def einsum(equation, *operands):
    """reference: python/paddle/tensor/einsum.py — on TPU one dot_general chain."""
    ts = [ensure_tensor(o) for o in operands]
    return apply_op(lambda *arrs: jnp.einsum(equation, *arrs), ts, name="einsum")


# --------------------------------------------------------------- reductions
def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    if isinstance(axis, Tensor):
        return tuple(int(a) for a in axis.numpy().reshape(-1))
    return int(axis)


def _reduce_factory(fn, name, differentiable=True):
    def op(x, axis=None, keepdim=False, name_=None):
        ax = _norm_axis(axis)
        return unary(lambda a: fn(a, axis=ax, keepdims=keepdim), x,
                     differentiable=differentiable, name=name)

    op.__name__ = name
    return op


sum = _reduce_factory(jnp.sum, "sum")
mean = _reduce_factory(jnp.mean, "mean")
max = _reduce_factory(jnp.max, "max")
min = _reduce_factory(jnp.min, "min")
prod = _reduce_factory(jnp.prod, "prod")
amax = _reduce_factory(jnp.max, "amax")
amin = _reduce_factory(jnp.min, "amin")
all = _reduce_factory(jnp.all, "all", differentiable=False)
any = _reduce_factory(jnp.any, "any", differentiable=False)
nansum = _reduce_factory(jnp.nansum, "nansum")
nanmean = _reduce_factory(jnp.nanmean, "nanmean")


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    ax = _norm_axis(axis)
    return unary(
        lambda a: jnp.argmax(a, axis=ax, keepdims=keepdim).astype(dtypes.convert_dtype(dtype)),
        x, differentiable=False, name="argmax",
    )


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    ax = _norm_axis(axis)
    return unary(
        lambda a: jnp.argmin(a, axis=ax, keepdims=keepdim).astype(dtypes.convert_dtype(dtype)),
        x, differentiable=False, name="argmin",
    )


def cumsum(x, axis=None, dtype=None, name=None):
    dt = dtypes.convert_dtype(dtype)

    def fn(a):
        if axis is None:
            a = a.reshape(-1)
            return jnp.cumsum(a, dtype=dt)
        return jnp.cumsum(a, axis=int(axis), dtype=dt)

    return unary(fn, x, name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    dt = dtypes.convert_dtype(dtype)
    return unary(lambda a: jnp.cumprod(a, axis=dim, dtype=dt), x, name="cumprod")


def logsumexp(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return unary(lambda a: jax.nn.logsumexp(a, axis=ax, keepdims=keepdim), x, name="logsumexp")


def logcumsumexp(x, axis=None, name=None):
    def fn(a):
        if axis is None:
            flat = a.reshape(-1)
            return jax.lax.cumlogsumexp(flat, axis=0)
        return jax.lax.cumlogsumexp(a, axis=int(axis))

    return unary(fn, x, name="logcumsumexp")


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    pre = prepend._value if isinstance(prepend, Tensor) else prepend
    app = append._value if isinstance(append, Tensor) else append
    return unary(lambda a: jnp.diff(a, n=n, axis=axis, prepend=pre, append=app), x, name="diff")


# --------------------------------------------------------------- inplace-ish
def add_(x, y, name=None):
    from ..autograd.engine import inplace_rebind

    return inplace_rebind(x, add(x, y))


def multiply_(x, y, name=None):
    from ..autograd.engine import inplace_rebind

    return inplace_rebind(x, multiply(x, y))
