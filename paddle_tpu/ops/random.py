"""Random ops.

reference parity: python/paddle/tensor/random.py + phi RNG kernels backed by
``Generator`` state (phi/core/generator.h). Here every op consumes a split of
the global JAX PRNG key (paddle_tpu.generator) — stateless threefry on device,
no host RNG round trips, and capturable as jit state.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import dtypes
from ..generator import default_generator
from ..tensor import Tensor
from ._apply import ensure_tensor

__all__ = [
    "uniform", "uniform_", "normal", "gaussian", "standard_normal", "randn",
    "rand", "randint", "randint_like", "randperm", "bernoulli", "poisson",
    "multinomial", "exponential_", "rand_like", "normal_like",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy().reshape(-1))
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s._value) if isinstance(s, Tensor) else int(s) for s in shape)


def uniform(shape, dtype="float32", min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else default_generator.next_key()
    dt = dtypes.convert_dtype(dtype)
    return Tensor(jax.random.uniform(key, _shape(shape), dt, minval=min, maxval=max))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    x._set_value(uniform(x.shape, x.dtype, min, max, seed)._value)
    return x


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype="float32", name=None):
    key = jax.random.key(seed) if seed else default_generator.next_key()
    dt = dtypes.convert_dtype(dtype)
    return Tensor(mean + std * jax.random.normal(key, _shape(shape), dt))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = ensure_tensor(mean)._value if isinstance(mean, Tensor) else mean
        s = ensure_tensor(std)._value if isinstance(std, Tensor) else std
        out_shape = jnp.broadcast_shapes(
            getattr(m, "shape", ()), getattr(s, "shape", ())
        )
        key = default_generator.next_key()
        return Tensor(m + s * jax.random.normal(key, out_shape, jnp.float32))
    return gaussian(shape, mean, std)


def standard_normal(shape, dtype="float32", name=None):
    return gaussian(shape, 0.0, 1.0, dtype=dtype)


def randn(shape, dtype="float32", name=None):
    return standard_normal(shape, dtype)


def rand(shape, dtype="float32", name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def rand_like(x, dtype=None, name=None):
    x = ensure_tensor(x)
    return rand(x.shape, dtype or x.dtype)


def normal_like(x, mean=0.0, std=1.0, name=None):
    x = ensure_tensor(x)
    return gaussian(x.shape, mean, std, dtype=x.dtype)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    key = default_generator.next_key()
    dt = dtypes.convert_dtype(dtype)
    return Tensor(jax.random.randint(key, _shape(shape), low, high, dt))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    """reference: paddle.randint_like — dtype defaults to x.dtype, which may
    be floating; sample integers then cast (jax randint is int-only)."""
    x = ensure_tensor(x)
    out_dtype = dtype or x.dtype
    ints = randint(low, high, x.shape, "int64")
    from .manipulation import cast
    return cast(ints, out_dtype)


def randperm(n, dtype="int64", name=None):
    key = default_generator.next_key()
    return Tensor(jax.random.permutation(key, n).astype(dtypes.convert_dtype(dtype)))


def bernoulli(x, name=None):
    x = ensure_tensor(x)
    key = default_generator.next_key()
    return Tensor(jax.random.bernoulli(key, x._value).astype(x.dtype))


def poisson(x, name=None):
    x = ensure_tensor(x)
    key = default_generator.next_key()
    return Tensor(jax.random.poisson(key, x._value).astype(x.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = ensure_tensor(x)
    key = default_generator.next_key()
    probs = x._value / jnp.sum(x._value, axis=-1, keepdims=True)
    logits = jnp.log(jnp.maximum(probs, 1e-30))
    if replacement:
        out = jax.random.categorical(key, logits, axis=-1, shape=(num_samples,) + x._value.shape[:-1])
        out = jnp.moveaxis(out, 0, -1)
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(key, x._value.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(jnp.int64))


def exponential_(x, lam=1.0, name=None):
    key = default_generator.next_key()
    x._set_value(jax.random.exponential(key, tuple(x.shape), x.dtype) / lam)
    return x
