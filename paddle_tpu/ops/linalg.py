"""Linear algebra ops (reference: python/paddle/tensor/linalg.py, phi
linalg kernels → on TPU these lower to XLA's native decompositions)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd.engine import apply_op
from ..tensor import Tensor
from ._apply import binary, ensure_tensor, unary

__all__ = [
    "norm", "cholesky", "inverse", "pinv", "solve", "triangular_solve",
    "cholesky_solve", "svd", "qr", "eig", "eigh", "eigvals", "eigvalsh",
    "matrix_power", "matrix_rank", "det", "slogdet", "lu", "lstsq", "cov",
    "corrcoef", "histogram", "bincount", "cross", "trace", "dist", "cdist",
]


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)

    def fn(a):
        if axis is None and p == "fro":
            return jnp.sqrt(jnp.sum(a * a))
        if p == "fro":
            return jnp.linalg.norm(a, ord=None, axis=tuple(axis) if isinstance(axis, (list, tuple)) else axis,
                                   keepdims=keepdim)
        if p == float("inf"):
            return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=axis, keepdims=keepdim)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        return jnp.sum(jnp.abs(a) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)

    return unary(fn, x, name="norm")


def cholesky(x, upper=False, name=None):
    return unary(lambda a: jnp.linalg.cholesky(jnp.swapaxes(a, -1, -2) if upper else a).swapaxes(-1, -2)
                 if upper else jnp.linalg.cholesky(a), x, name="cholesky")


def inverse(x, name=None):
    return unary(jnp.linalg.inv, x, name="inverse")


def _f64_guard(fn):
    """This jax build's f32 LAPACK svd kernel segfaults when x64 is enabled;
    route svd-family ops through f64 and cast back (CPU-only code path —
    decompositions are host ops on TPU too)."""

    def wrapped(a, *args, **kwargs):
        if a.dtype == jnp.float32:
            out = fn(a.astype(jnp.float64), *args, **kwargs)
            if isinstance(out, tuple):
                return tuple(o.astype(jnp.float32) if o.dtype == jnp.float64 else o for o in out)
            return out.astype(jnp.float32) if out.dtype == jnp.float64 else out
        return fn(a, *args, **kwargs)

    return wrapped


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return unary(_f64_guard(lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian)), x, name="pinv")


def solve(x, y, name=None):
    return binary(jnp.linalg.solve, x, y, name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular
        )

    return binary(fn, x, y, name="triangular_solve")


def cholesky_solve(x, y, upper=False, name=None):
    def fn(b, chol):
        return jax.scipy.linalg.cho_solve((chol, not upper), b)

    return binary(fn, x, y, name="cholesky_solve")


def svd(x, full_matrices=False, name=None):
    out = apply_op(_f64_guard(lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices))),
                   [ensure_tensor(x)], name="svd")
    return out


def qr(x, mode="reduced", name=None):
    return apply_op(lambda a: jnp.linalg.qr(a, mode=mode), [ensure_tensor(x)], name="qr")


def eig(x, name=None):
    import numpy as np

    arr = ensure_tensor(x).numpy()
    w, v = np.linalg.eig(arr)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigh(x, UPLO="L", name=None):
    return apply_op(lambda a: tuple(jnp.linalg.eigh(a, symmetrize_input=False)), [ensure_tensor(x)], name="eigh")


def eigvals(x, name=None):
    import numpy as np

    return Tensor(jnp.asarray(np.linalg.eigvals(ensure_tensor(x).numpy())))


def eigvalsh(x, UPLO="L", name=None):
    return unary(jnp.linalg.eigvalsh, x, name="eigvalsh")


def matrix_power(x, n, name=None):
    return unary(lambda a: jnp.linalg.matrix_power(a, n), x, name="matrix_power")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return unary(_f64_guard(lambda a: jnp.linalg.matrix_rank(a, rtol=tol).astype(jnp.int64)), x,
                 differentiable=False, name="matrix_rank")


def det(x, name=None):
    return unary(jnp.linalg.det, x, name="det")


def slogdet(x, name=None):
    def fn(a):
        sign, logabs = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logabs])

    return unary(fn, x, name="slogdet")


def lu(x, pivot=True, get_infos=False, name=None):
    def fn(a):
        lu_mat, piv = jax.scipy.linalg.lu_factor(a)
        return lu_mat, piv.astype(jnp.int32) + 1  # paddle pivots are 1-based

    out = apply_op(fn, [ensure_tensor(x)], name="lu")
    if get_infos:
        return out[0], out[1], Tensor(jnp.zeros((), jnp.int32))
    return out[0], out[1]


def lstsq(x, y, rcond=None, driver=None, name=None):
    def fn(a, b):
        f64 = a.dtype == jnp.float32
        if f64:
            a, b = a.astype(jnp.float64), b.astype(jnp.float64)
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        if f64:
            sol, res, sv = (v.astype(jnp.float32) for v in (sol, res, sv))
        return sol, res, rank.astype(jnp.int64), sv

    out = apply_op(fn, [ensure_tensor(x), ensure_tensor(y)], name="lstsq")
    return out


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    fw = ensure_tensor(fweights)._value if fweights is not None else None
    aw = ensure_tensor(aweights)._value if aweights is not None else None
    return unary(lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0, fweights=fw, aweights=aw),
                 x, name="cov")


def corrcoef(x, rowvar=True, name=None):
    return unary(lambda a: jnp.corrcoef(a, rowvar=rowvar), x, name="corrcoef")


def histogram(input, bins=100, min=0, max=0, name=None):
    x = ensure_tensor(input)
    lo, hi = (min, max) if (min != 0 or max != 0) else (float(x.numpy().min()), float(x.numpy().max()))
    return unary(lambda a: jnp.histogram(a, bins=bins, range=(lo, hi))[0].astype(jnp.int64),
                 x, differentiable=False, name="histogram")


def bincount(x, weights=None, minlength=0, name=None):
    w = ensure_tensor(weights)._value if weights is not None else None
    return unary(lambda a: jnp.bincount(a.reshape(-1), weights=w, minlength=minlength),
                 x, differentiable=False, name="bincount")


def cross(x, y, axis=9, name=None):
    ax = axis if axis != 9 else -1
    x = ensure_tensor(x)
    if axis == 9:
        for i, d in enumerate(x.shape):
            if d == 3:
                ax = i
                break
    return binary(lambda a, b: jnp.cross(a, b, axis=ax), x, y, name="cross")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return unary(lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2), x, name="trace")


def dist(x, y, p=2, name=None):
    return binary(lambda a, b: jnp.linalg.norm((a - b).reshape(-1), ord=p), x, y, name="dist")


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary", name=None):
    def fn(a, b):
        diff = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(diff * diff, axis=-1) + 1e-30)
        return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)

    return binary(fn, x, y, name="cdist")
