"""Statistics ops (reference: python/paddle/tensor/stat.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ._apply import unary

__all__ = ["std", "var", "median", "nanmedian", "quantile", "nanquantile", "kthvalue", "mode"]


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return unary(lambda a: jnp.var(a, axis=ax, ddof=1 if unbiased else 0, keepdims=keepdim),
                 x, name="var")


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return unary(lambda a: jnp.std(a, axis=ax, ddof=1 if unbiased else 0, keepdims=keepdim),
                 x, name="std")


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    ax = _norm_axis(axis)
    if mode == "avg":
        return unary(lambda a: jnp.median(a, axis=ax, keepdims=keepdim), x, name="median")
    return unary(lambda a: jnp.quantile(a, 0.5, axis=ax, keepdims=keepdim, method="lower"),
                 x, name="median")


def nanmedian(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return unary(lambda a: jnp.nanmedian(a, axis=ax, keepdims=keepdim), x, name="nanmedian")


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    ax = _norm_axis(axis)
    qa = jnp.asarray(q)
    return unary(lambda a: jnp.quantile(a, qa, axis=ax, keepdims=keepdim, method=interpolation),
                 x, name="quantile")


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    ax = _norm_axis(axis)
    qa = jnp.asarray(q)
    return unary(lambda a: jnp.nanquantile(a, qa, axis=ax, keepdims=keepdim, method=interpolation),
                 x, name="nanquantile")


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    from ..autograd.engine import apply_op
    from ._apply import ensure_tensor

    def fn(a):
        s = jnp.sort(a, axis=axis)
        i = jnp.argsort(a, axis=axis)
        vals = jnp.take(s, k - 1, axis=axis)
        idx = jnp.take(i, k - 1, axis=axis).astype(jnp.int64)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, idx

    out = apply_op(fn, [ensure_tensor(x)], name="kthvalue")
    return out[0], out[1]


def mode(x, axis=-1, keepdim=False, name=None):
    from ..autograd.engine import apply_op
    from ._apply import ensure_tensor

    def fn(a):
        sorted_a = jnp.sort(a, axis=axis)
        moved = jnp.moveaxis(sorted_a, axis, -1)
        # count occurrences of each element via pairwise comparison (fine for
        # the small trailing dims this op sees in practice)
        counts = jnp.sum(moved[..., :, None] == moved[..., None, :], axis=-1)
        best = jnp.argmax(counts, axis=-1)
        vals = jnp.take_along_axis(moved, best[..., None], axis=-1)[..., 0]
        orig_moved = jnp.moveaxis(a, axis, -1)
        idx = jnp.argmax(orig_moved == vals[..., None], axis=-1).astype(jnp.int64)
        vals_out = jnp.moveaxis(vals[..., None], -1, axis) if keepdim else vals
        idx_out = jnp.moveaxis(idx[..., None], -1, axis) if keepdim else idx
        return vals_out, idx_out

    out = apply_op(fn, [ensure_tensor(x)], name="mode")
    return out[0], out[1]
