"""Shape/layout manipulation ops.

reference parity: python/paddle/tensor/manipulation.py + phi kernels
(reshape/transpose/concat/split/gather/scatter/...). All are metadata or
gather/scatter ops that XLA handles natively; indices passed as Tensors are
captured as nondifferentiable closure residuals.
"""
from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp

from .. import dtypes
from ..autograd.engine import apply_op
from ..tensor import Tensor
from ._apply import ensure_tensor, unary

__all__ = [
    "reshape", "transpose", "concat", "split", "chunk", "stack", "unstack",
    "squeeze", "unsqueeze", "expand", "broadcast_to", "expand_as", "tile",
    "flatten", "flip", "rot90", "roll", "gather", "gather_nd", "take_along_axis",
    "put_along_axis", "index_select", "index_sample", "masked_select", "where",
    "scatter", "scatter_nd_add", "slice", "strided_slice", "cast", "pad",
    "topk", "sort", "argsort", "unique", "unique_consecutive", "searchsorted",
    "nonzero", "repeat_interleave", "unbind", "numel", "shard_index",
    "moveaxis", "swapaxes", "as_real", "as_complex", "view", "view_as",
    "crop", "tensordot", "bucketize", "masked_fill", "index_put", "diagonal",
]


def reshape(x, shape, name=None):
    x = ensure_tensor(x)
    if isinstance(shape, Tensor):
        shape = tuple(int(s) for s in shape.numpy().reshape(-1))
    else:
        shape = tuple(int(s._value) if isinstance(s, Tensor) else int(s) for s in shape)
    return unary(lambda a: jnp.reshape(a, shape), x, name="reshape")


view = reshape


def view_as(x, other, name=None):
    return reshape(x, ensure_tensor(other).shape)


def transpose(x, perm, name=None):
    perm = tuple(int(p) for p in perm)
    return unary(lambda a: jnp.transpose(a, perm), x, name="transpose")


def moveaxis(x, source, destination, name=None):
    return unary(lambda a: jnp.moveaxis(a, source, destination), x, name="moveaxis")


def swapaxes(x, axis0, axis1, name=None):
    return unary(lambda a: jnp.swapaxes(a, axis0, axis1), x, name="swapaxes")


def concat(x: Sequence, axis=0, name=None):
    ts = [ensure_tensor(t) for t in x]
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply_op(lambda *arrs: jnp.concatenate(arrs, axis=axis), ts, name="concat")


def stack(x: Sequence, axis=0, name=None):
    ts = [ensure_tensor(t) for t in x]
    return apply_op(lambda *arrs: jnp.stack(arrs, axis=axis), ts, name="stack")


def unstack(x, axis=0, num=None, name=None):
    x = ensure_tensor(x)
    n = num if num is not None else x.shape[axis]
    out = apply_op(
        lambda a: tuple(jnp.moveaxis(a, axis, 0)[i] for i in range(n)), [x], name="unstack"
    )
    return list(out) if isinstance(out, tuple) else [out]


def unbind(input, axis=0):
    return unstack(input, axis=axis)


def split(x, num_or_sections, axis=0, name=None):
    x = ensure_tensor(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"paddle.split: axis {axis} size {dim} is not divisible by num {num_or_sections}"
            )
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in num_or_sections]
        n_neg = sum(1 for s in sizes if s < 0)
        if n_neg:
            rest = dim - sum(s for s in sizes if s >= 0)
            sizes = [rest if s < 0 else s for s in sizes]
    offsets = [0]
    for s in sizes:
        offsets.append(offsets[-1] + s)

    def fn(a):
        return tuple(jax.lax.slice_in_dim(a, offsets[i], offsets[i + 1], axis=axis) for i in range(len(sizes)))

    out = apply_op(fn, [x], name="split")
    return list(out) if isinstance(out, tuple) else [out]


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def squeeze(x, axis=None, name=None):
    x = ensure_tensor(x)
    if axis is None:
        ax = None
    elif isinstance(axis, (list, tuple)):
        ax = tuple(int(a) for a in axis if x.shape[int(a)] == 1)
    else:
        ax = (int(axis),) if x.shape[int(axis)] == 1 else ()
    if ax == ():
        return unary(lambda a: a, x, name="squeeze")
    return unary(lambda a: jnp.squeeze(a, axis=ax), x, name="squeeze")


def unsqueeze(x, axis, name=None):
    if isinstance(axis, Tensor):
        axis = [int(a) for a in axis.numpy().reshape(-1)]
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (int(axis),)
    return unary(lambda a: jnp.expand_dims(a, ax), x, name="unsqueeze")


def expand(x, shape, name=None):
    x = ensure_tensor(x)
    if isinstance(shape, Tensor):
        shape = [int(s) for s in shape.numpy().reshape(-1)]
    shape = [int(s._value) if isinstance(s, Tensor) else int(s) for s in shape]
    # paddle semantics: -1 means keep the input dim
    nd_in, nd_out = x.ndim, len(shape)
    full_shape = []
    for i, s in enumerate(shape):
        in_dim = x.shape[i - (nd_out - nd_in)] if i >= nd_out - nd_in else None
        full_shape.append(in_dim if s == -1 else s)
    return unary(lambda a: jnp.broadcast_to(a, tuple(full_shape)), x, name="expand")


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def expand_as(x, y, name=None):
    return expand(x, ensure_tensor(y).shape)


def tile(x, repeat_times, name=None):
    if isinstance(repeat_times, Tensor):
        repeat_times = [int(r) for r in repeat_times.numpy().reshape(-1)]
    reps = tuple(int(r.item()) if isinstance(r, Tensor) else int(r) for r in repeat_times)
    return unary(lambda a: jnp.tile(a, reps), x, name="tile")


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = ensure_tensor(x)
    nd = x.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0
    new_shape = x.shape[:s] + [-1] + x.shape[e + 1:]
    return unary(lambda a: jnp.reshape(a, tuple(new_shape)), x, name="flatten")


def flip(x, axis, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (int(axis),)
    return unary(lambda a: jnp.flip(a, axis=ax), x, name="flip")


def rot90(x, k=1, axes=(0, 1), name=None):
    return unary(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x, name="rot90")


def roll(x, shifts, axis=None, name=None):
    return unary(lambda a: jnp.roll(a, shifts, axis=axis), x, name="roll")


def gather(x, index, axis=0, name=None):
    """reference: paddle.gather — select rows of ``axis`` by 1-D index."""
    x = ensure_tensor(x)
    idx = ensure_tensor(index)._value
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return unary(lambda a: jnp.take(a, idx.reshape(-1) if idx.ndim > 1 else idx, axis=axis),
                 x, name="gather")


def gather_nd(x, index, name=None):
    x = ensure_tensor(x)
    idx = ensure_tensor(index)._value

    def fn(a):
        # index shape [..., k] indexes the first k dims of a
        k = idx.shape[-1]
        idx_tuple = tuple(idx[..., i] for i in range(k))
        return a[idx_tuple]

    return unary(fn, x, name="gather_nd")


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    arr = ensure_tensor(arr)
    idx = ensure_tensor(indices)._value
    return unary(lambda a: jnp.take_along_axis(a, idx, axis=axis), arr, name="take_along_axis")


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    arr = ensure_tensor(arr)
    idx = ensure_tensor(indices)._value
    vt = ensure_tensor(values)

    def fn(a, v):
        v = jnp.broadcast_to(v, idx.shape).astype(a.dtype)
        dims = tuple(
            jnp.broadcast_to(
                jnp.arange(idx.shape[d]).reshape((-1,) + (1,) * (idx.ndim - d - 1)), idx.shape
            )
            if d != axis % a.ndim
            else idx
            for d in range(a.ndim)
        )
        if reduce == "assign":
            return a.at[dims].set(v)
        if reduce == "add":
            return a.at[dims].add(v)
        if reduce in ("mul", "multiply"):
            return a.at[dims].multiply(v)
        raise ValueError(f"unsupported reduce: {reduce}")

    return apply_op(fn, [arr, vt], name="put_along_axis")


def index_select(x, index, axis=0, name=None):
    x = ensure_tensor(x)
    idx = ensure_tensor(index)._value
    return unary(lambda a: jnp.take(a, idx.reshape(-1), axis=axis), x, name="index_select")


def index_sample(x, index):
    x = ensure_tensor(x)
    idx = ensure_tensor(index)._value
    return unary(lambda a: jnp.take_along_axis(a, idx, axis=1), x, name="index_sample")


def masked_select(x, mask, name=None):
    """Note: output shape is data-dependent — not jittable; eager only
    (reference kernel has the same dynamic-shape nature)."""
    x = ensure_tensor(x)
    m = ensure_tensor(mask).numpy().astype(bool)
    flat_idx = jnp.asarray(m.reshape(-1).nonzero()[0])
    return unary(lambda a: jnp.take(a.reshape(-1), flat_idx), x, name="masked_select")


def masked_fill(x, mask, value, name=None):
    x = ensure_tensor(x)
    m = ensure_tensor(mask)._value
    v = value.item() if isinstance(value, Tensor) and value.size == 1 else value
    if isinstance(v, Tensor):
        return apply_op(lambda a, val: jnp.where(m, val.astype(a.dtype), a), [x, v], name="masked_fill")
    return unary(lambda a: jnp.where(m, jnp.asarray(v, a.dtype), a), x, name="masked_fill")


def where(condition, x=None, y=None, name=None):
    cond = ensure_tensor(condition)._value
    if x is None and y is None:
        return nonzero(Tensor(cond), as_tuple=True)
    xt, yt = ensure_tensor(x), ensure_tensor(y)
    return apply_op(lambda a, b: jnp.where(cond, a, b), [xt, yt], name="where")


def nonzero(x, as_tuple=False):
    arr = ensure_tensor(x).numpy()
    import numpy as np

    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i.reshape(-1, 1))) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1)))


def scatter(x, index, updates, overwrite=True, name=None):
    """reference: paddle.scatter — write ``updates`` rows at ``index`` along dim 0."""
    x = ensure_tensor(x)
    idx = ensure_tensor(index)._value.reshape(-1)
    upd = ensure_tensor(updates)

    def fn(a, u):
        if overwrite:
            return a.at[idx].set(u.astype(a.dtype))
        return a.at[idx].set(0.0).at[idx].add(u.astype(a.dtype))

    return apply_op(fn, [x, upd], name="scatter")


def scatter_nd_add(x, index, updates, name=None):
    x = ensure_tensor(x)
    idx = ensure_tensor(index)._value
    upd = ensure_tensor(updates)

    def fn(a, u):
        k = idx.shape[-1]
        idx_tuple = tuple(idx[..., i] for i in range(k))
        return a.at[idx_tuple].add(u.astype(a.dtype))

    return apply_op(fn, [x, upd], name="scatter_nd_add")


def index_put(x, indices, value, accumulate=False, name=None):
    x = ensure_tensor(x)
    idx_tuple = tuple(ensure_tensor(i)._value for i in indices)
    v = ensure_tensor(value)

    def fn(a, val):
        if accumulate:
            return a.at[idx_tuple].add(val.astype(a.dtype))
        return a.at[idx_tuple].set(val.astype(a.dtype))

    return apply_op(fn, [x, v], name="index_put")


def slice(input, axes, starts, ends, name=None):
    x = ensure_tensor(input)

    def _v(v):
        return int(v.item()) if isinstance(v, Tensor) else int(v)

    def fn(a):
        out = a
        for ax, s, e in zip(axes, starts, ends):
            out = jax.lax.slice_in_dim(out, _v(s), min(_v(e), out.shape[ax]), axis=ax)
        return out

    return unary(fn, x, name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = ensure_tensor(x)

    import builtins

    def fn(a):
        sl = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            sl[ax] = builtins.slice(int(s), int(e), int(st))
        return a[tuple(sl)]

    return unary(fn, x, name="strided_slice")


def crop(x, shape=None, offsets=None, name=None):
    x = ensure_tensor(x)
    offs = [0] * x.ndim if offsets is None else [int(o) for o in offsets]
    shp = [int(s) if int(s) != -1 else x.shape[i] - offs[i] for i, s in enumerate(shape)]
    return unary(lambda a: jax.lax.dynamic_slice(a, offs, shp), x, name="crop")


def cast(x, dtype):
    """reference: phi CastKernel. float->float/complex casts carry gradient
    (cast-back vjp); anything else is non-differentiable."""
    x = ensure_tensor(x)
    dt = dtypes.convert_dtype(dtype)
    import numpy as np

    diff = dtypes.is_floating(dt) and dtypes.is_floating(np.dtype(x.dtype))
    return unary(lambda a: a.astype(dt), x, differentiable=diff, name="cast")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    if isinstance(pad, Tensor):
        pad = [int(p) for p in pad.numpy().reshape(-1)]
    pad = [int(p) for p in pad]
    nd = x.ndim

    if len(pad) == 2 * nd:
        pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle NCHW/NCL/NCDHW convention: pad applies to spatial dims, last-first order
        n_spatial = len(pad) // 2
        pairs = [(0, 0)] * nd
        if data_format.startswith("NC"):
            spatial = list(range(2, 2 + n_spatial))
        else:
            spatial = list(range(1, 1 + n_spatial))
        for i, dim in enumerate(spatial):
            pairs[dim] = (pad[2 * i], pad[2 * i + 1])

    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]

    def fn(a):
        if jmode == "constant":
            return jnp.pad(a, pairs, mode="constant", constant_values=value)
        return jnp.pad(a, pairs, mode=jmode)

    return unary(fn, x, name="pad")


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    x = ensure_tensor(x)
    if isinstance(k, Tensor):
        k = int(k.item())
    ax = -1 if axis is None else int(axis)

    def fn(a):
        arr = jnp.moveaxis(a, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(arr, k)
        else:
            vals, idx = jax.lax.top_k(-arr, k)
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx.astype(jnp.int64), -1, ax)

    out = apply_op(fn, [x], name="topk")
    return out[0], out[1]


def sort(x, axis=-1, descending=False, name=None):
    def fn(a):
        s = jnp.sort(a, axis=axis)
        return jnp.flip(s, axis=axis) if descending else s

    return unary(fn, x, name="sort")


def argsort(x, axis=-1, descending=False, name=None):
    def fn(a):
        idx = jnp.argsort(a, axis=axis)
        if descending:
            idx = jnp.flip(idx, axis=axis)
        return idx.astype(jnp.int64)

    return unary(fn, x, differentiable=False, name="argsort")


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    seq = ensure_tensor(sorted_sequence)._value
    v = ensure_tensor(values)
    side = "right" if right else "left"
    dt = jnp.int32 if out_int32 else jnp.int64
    return unary(lambda a: jnp.searchsorted(seq, a, side=side).astype(dt), v,
                 differentiable=False, name="searchsorted")


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None,
           dtype="int64", name=None):
    """Data-dependent output shape: eager only (host round-trip), like the
    reference's UniqueKernel."""
    import numpy as np

    arr = ensure_tensor(x).numpy()
    res = np.unique(arr, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    dt = dtypes.convert_dtype(dtype)
    return tuple(Tensor(jnp.asarray(r if i == 0 else r.astype(dt))) for i, r in enumerate(res))


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    import numpy as np

    arr = ensure_tensor(x).numpy()
    if axis is None:
        arr = arr.reshape(-1)
        change = np.concatenate([[True], arr[1:] != arr[:-1]])
    else:
        raise NotImplementedError("unique_consecutive with axis is not supported yet")
    out = arr[change]
    rets = [Tensor(jnp.asarray(out))]
    if return_inverse:
        inv = np.cumsum(change) - 1
        rets.append(Tensor(jnp.asarray(inv.astype(np.int64))))
    if return_counts:
        idx = np.nonzero(change)[0]
        counts = np.diff(np.concatenate([idx, [arr.size]]))
        rets.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return rets[0] if len(rets) == 1 else tuple(rets)


def repeat_interleave(x, repeats, axis=None, name=None):
    x = ensure_tensor(x)
    if isinstance(repeats, Tensor):
        repeats = repeats._value
    return unary(lambda a: jnp.repeat(a, repeats, axis=axis), x, name="repeat_interleave")


def numel(x, name=None):
    return Tensor(jnp.asarray(ensure_tensor(x).size, jnp.int64))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """reference: paddle.shard_index (used by sharded embedding)."""
    x = ensure_tensor(input)
    shard_size = (index_num + nshards - 1) // nshards

    def fn(a):
        in_shard = (a // shard_size) == shard_id
        return jnp.where(in_shard, a % shard_size, ignore_value)

    return unary(fn, x, differentiable=False, name="shard_index")


def as_complex(x, name=None):
    return unary(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x, name="as_complex")


def as_real(x, name=None):
    return unary(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), x, name="as_real")


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return unary(lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2), x, name="diagonal")


def tensordot(x, y, axes=2, name=None):
    xt, yt = ensure_tensor(x), ensure_tensor(y)
    return apply_op(lambda a, b: jnp.tensordot(a, b, axes=axes), [xt, yt], name="tensordot")
