"""Fused multi-tensor AdamW as a Pallas TPU kernel.

Reference parity: paddle/phi/kernels/gpu/fused_adam_kernel.cu (multi-tensor
Adam: one launch updates every parameter chunk) — the reference motivation is
amortizing per-tensor kernel-launch overhead.

TPU framing: inside a jitted train step there are no per-tensor launches to
amortize (XLA already fuses the elementwise updates), so the only possible
win is scheduling: one Pallas kernel streams w/m/v/g through VMEM in a
single pass with explicit double-buffering instead of whatever fusion
grouping XLA picks across 100+ parameter tensors. Whether that wins is an
empirical question — tools/bench_adamw.py measures it on chip, and the
optimizer only routes through this kernel if it measured faster
(the VERDICT r2 #6 contract: keep it only with a measured win).

Layout: the caller flattens all params into ONE fp32 vector per state
(w, m, v, grad) — the multi-tensor part — padded to a multiple of the
(8, 128) f32 tile and viewed [rows, 1024].

RETIRED from the hot path (r4, measured on v5e at 355M params with chained
data-dependent timing): XLA 14.9ms (667 GB/s, ~81% of HBM peak) vs this
kernel 42.9ms (232 GB/s). The update is purely memory-bound and XLA's
fusion already streams it near roofline. The r4 run was later found to
have timed a crippled 16x1024 blocking (alignment bug in the harness),
and the intended 256x1024 design point turns out not to compile on v5e
at all (exceeds scoped VMEM, r5) — the honest A/B runs at the largest
compilable blocking via ``block_rows`` (tools/bench_adamw.py sweeps it).
Kept as reference code and for the A/B harness; optimizers use the XLA
path.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    _HAS_PLTPU = False

__all__ = ["fused_adamw_flat"]

LANE = 1024          # flat view: [rows, 1024] f32
# 128x1024 f32 = 0.5MB per operand block: 4 block inputs + 3 block
# outputs (the lr/bc scalars live in SMEM) double-buffered ~= 7MB,
# inside v5e's 16MB scoped VMEM. The original 256-row design point never
# compiled on real v5e — 16.79M > 16M scoped-vmem limit, measured r5 —
# so 256 exists only as a sweep point on hardware with more headroom.
BLOCK_ROWS = 128


def _interpret() -> bool:
    return os.environ.get("PADDLE_TPU_PALLAS_INTERPRET") == "1"


def _adamw_kernel(w_ref, m_ref, v_ref, g_ref, lr_ref, bc1_ref, bc2_ref,
                  wo_ref, mo_ref, vo_ref, *, beta1, beta2, eps,
                  weight_decay):
    # bias corrections bc{1,2} = 1 - beta^t arrive precomputed: Mosaic has
    # no lowering for math.powf (measured on-chip failure, r4), and a
    # scalar pow belongs on the XLA side anyway.
    w = w_ref[...]
    m = m_ref[...]
    v = v_ref[...]
    g = g_ref[...]
    lr = lr_ref[0, 0]
    bc1 = bc1_ref[0, 0]
    bc2 = bc2_ref[0, 0]
    b1 = jnp.float32(beta1)
    b2 = jnp.float32(beta2)
    m_new = b1 * m + (1.0 - b1) * g
    v_new = b2 * v + (1.0 - b2) * g * g
    update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + jnp.float32(eps))
    wo_ref[...] = w - lr * (update + jnp.float32(weight_decay) * w)
    mo_ref[...] = m_new
    vo_ref[...] = v_new


def fused_adamw_flat(w, m, v, g, lr, step, *, block_rows=None,
                     beta1=0.9, beta2=0.999,
                     eps=1e-8, weight_decay=0.01):
    """One AdamW step over flat fp32 vectors. Returns (w', m', v').

    w/m/v/g: [N] f32 (N padded to 8·1024 by the caller or here);
    lr: scalar f32; step: scalar f32 (1-based).
    """
    n = w.shape[0]
    pad = (-n) % (8 * LANE)
    if pad:
        w, m, v, g = (jnp.pad(x, (0, pad)) for x in (w, m, v, g))
    rows = w.shape[0] // LANE
    shape2 = (rows, LANE)
    w2, m2, v2, g2 = (x.reshape(shape2) for x in (w, m, v, g))
    br = min(block_rows or BLOCK_ROWS, rows)
    while rows % br:
        br //= 2
    br = max(br, 1)
    grid = (rows // br,)

    lr2 = jnp.full((1, 1), lr, jnp.float32)
    t_f = jnp.asarray(step, jnp.float32)
    bc1 = jnp.full((1, 1), 1.0 - jnp.float32(beta1) ** t_f, jnp.float32)
    bc2 = jnp.full((1, 1), 1.0 - jnp.float32(beta2) ** t_f, jnp.float32)

    # index maps must return int32 built INSIDE the lambda: under
    # jax_enable_x64 a python-int literal traces as i64 (Mosaic refuses to
    # legalize it), and a precomputed array would be a captured constant
    def _z():
        return jnp.asarray(0, jnp.int32)

    blk = pl.BlockSpec((br, LANE), lambda i: (i, _z()))
    scal = pl.BlockSpec((1, 1), lambda i: (_z(), _z()),
                        memory_space=pltpu.SMEM) \
        if (_HAS_PLTPU and not _interpret()) \
        else pl.BlockSpec((1, 1), lambda i: (_z(), _z()))
    wo, mo, vo = pl.pallas_call(
        functools.partial(_adamw_kernel, beta1=beta1, beta2=beta2, eps=eps,
                          weight_decay=weight_decay),
        grid=grid,
        in_specs=[blk, blk, blk, blk, scal, scal, scal],
        out_specs=[blk, blk, blk],
        out_shape=[jax.ShapeDtypeStruct(shape2, jnp.float32)] * 3,
        interpret=_interpret(),
    )(w2, m2, v2, g2, lr2, bc1, bc2)
    out = (wo.reshape(-1), mo.reshape(-1), vo.reshape(-1))
    if pad:
        out = tuple(x[:n] for x in out)
    return out


def xla_adamw_flat(w, m, v, g, lr, step, *, beta1=0.9, beta2=0.999,
                   eps=1e-8, weight_decay=0.01):
    """The same update as plain XLA ops — the A/B baseline."""
    b1, b2 = jnp.float32(beta1), jnp.float32(beta2)
    m_new = b1 * m + (1.0 - b1) * g
    v_new = b2 * v + (1.0 - b2) * g * g
    bc1 = 1.0 - jnp.power(b1, jnp.float32(step))
    bc2 = 1.0 - jnp.power(b2, jnp.float32(step))
    update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + jnp.float32(eps))
    w_new = w - lr * (update + jnp.float32(weight_decay) * w)
    return w_new, m_new, v_new
