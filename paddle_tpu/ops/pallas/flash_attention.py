"""Flash attention as a Pallas TPU kernel.

TPU-native replacement for the reference's flash-attn integration
(paddle/phi/kernels/gpu/flash_attn_kernel.cu:213): online-softmax attention
tiled over VMEM blocks so the [S, S] score matrix never materializes in HBM.

Layout: paddle flash-attn layout [batch, seq, heads, head_dim] at the API
boundary; internally [batch*heads, seq, head_dim] with a (bh, q_block,
k_block) grid. The k loop is the innermost grid dim — TPU grids run
sequentially, so VMEM scratch (acc, running max m, running sum l) carries
across k steps (the standard TPU flash pattern).

Backward: jax.custom_vjp whose bwd recomputes attention with the pure-XLA
reference math and differentiates it — numerically identical, keeps the
Pallas fast path for inference/forward; a fused Pallas bwd can replace it
without API change.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend (absent on some CPU-only builds)
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    _HAS_PLTPU = False

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
NEG_INF = -1e30


def _i32(x):
    # index maps must stay int32: under jax_enable_x64 a python-int literal
    # traces as i64, which Mosaic refuses to legalize
    return jnp.asarray(x, jnp.int32)


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                 causal: bool, scale: float, block_q: int, block_k: int,
                 seq_q: int, seq_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    neg_inf = jnp.float32(NEG_INF)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref[...])
        m_ref[...] = jnp.full_like(m_ref[...], neg_inf)
        l_ref[...] = jnp.zeros_like(l_ref[...])

    q = q_ref[0].astype(jnp.float32) * jnp.float32(scale)  # [bq, d]
    k = k_ref[0].astype(jnp.float32)  # [bk, d]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))  # [bq, bk]

    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    mask = k_pos < seq_k  # padded keys
    if causal:
        mask = mask & (q_pos + (seq_k - seq_q) >= k_pos)
    s = jnp.where(mask, s, neg_inf)

    m_prev = m_ref[...]  # [bq, 128] replicated
    l_prev = l_ref[...]
    m_cur = jnp.max(s, axis=1, keepdims=True)  # [bq, 1]
    m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
    alpha = jnp.exp(m_prev - m_new)  # [bq, 128]
    p = jnp.exp(s - m_new[:, :1])  # [bq, bk]
    l_new = alpha * l_prev + jnp.broadcast_to(
        jnp.sum(p, axis=1, keepdims=True), l_prev.shape)
    v = v_ref[0].astype(jnp.float32)  # [bk, d]
    pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())))  # [bq, d]
    acc_ref[...] = acc_ref[...] * alpha[:, :1] + pv
    m_ref[...] = m_new
    l_ref[...] = l_new

    @pl.when(ki == nk - 1)
    def _finish():
        denom = jnp.maximum(l_ref[...][:, :1], jnp.float32(1e-30))
        o_ref[0] = (acc_ref[...] / denom).astype(o_ref.dtype)


def _flash_fwd_bhsd(q, k, v, causal: bool, scale: float,
                    block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K):
    """q,k,v: [BH, S, D] → out [BH, Sq, D]."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq = min(block_q, max(128, 1 << (sq - 1).bit_length()) if sq < block_q else block_q)
    bq = min(bq, block_q)
    bk = min(block_k, max(128, 1 << (sk - 1).bit_length()) if sk < block_k else block_k)
    pad_q = (-sq) % bq
    pad_k = (-sk) % bk
    qp = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0))) if pad_q else q
    kp = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0))) if pad_k else k
    vp = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0))) if pad_k else v
    nq = qp.shape[1] // bq
    nk = kp.shape[1] // bk

    grid = (bh, nq, nk)
    out = pl.pallas_call(
        functools.partial(_attn_kernel, causal=causal, scale=scale,
                          block_q=bq, block_k=bk, seq_q=sq, seq_k=sk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, _i32(0))),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, _i32(0))),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, _i32(0))),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, _i32(0))),
        out_shape=jax.ShapeDtypeStruct((bh, qp.shape[1], d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
    )(qp, kp, vp)
    return out[:, :sq]


def _ref_attention_bshd(q, k, v, causal: bool, scale: float):
    """Pure-XLA reference (same math), used for the backward pass."""
    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kh = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vh = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    if causal:
        sq_, sk_ = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq_, sk_), bool), sk_ - sq_)
        logits = jnp.where(cm, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention(q, k, v, causal: bool, scale: float):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    qf = jnp.swapaxes(q, 1, 2).reshape(b * h, sq, d)
    kf = jnp.swapaxes(k, 1, 2).reshape(b * h, sk, d)
    vf = jnp.swapaxes(v, 1, 2).reshape(b * h, sk, d)
    of = _flash_fwd_bhsd(qf, kf, vf, causal, scale)
    return jnp.swapaxes(of.reshape(b, h, sq, d), 1, 2)


def _fwd(q, k, v, causal, scale):
    return _flash_attention(q, k, v, causal, scale), (q, k, v)


def _bwd(causal, scale, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q_, k_, v_: _ref_attention_bshd(q_, k_, v_, causal, scale),
                     q, k, v)
    return vjp(g)


_flash_attention.defvjp(_fwd, _bwd)


def flash_attention_bshd(q, k, v, causal: bool = False, scale: float = None):
    """Flash attention, paddle layout [B, S, H, D]."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if not _HAS_PLTPU:
        return _ref_attention_bshd(q, k, v, causal, scale)
    return _flash_attention(q, k, v, causal, scale)
