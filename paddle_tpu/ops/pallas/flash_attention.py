"""Flash attention (forward AND backward) as Pallas TPU kernels.

TPU-native replacement for the reference's flash-attn integration
(paddle/phi/kernels/gpu/flash_attn_kernel.cu:213 — fwd+bwd both registered):
online-softmax attention tiled over VMEM blocks so the [S, S] score matrix
never materializes in HBM, in either direction.

Layout: paddle flash-attn layout [batch, seq, heads, head_dim] at the API
boundary; internally [batch*heads, seq, head_dim]. TPU grids run
sequentially over the innermost dim, so VMEM scratch accumulators carry
across that dim (the standard TPU flash pattern):

- forward: grid (bh, nq, nk) — k innermost; carries (acc, running max m,
  running sum l); emits O and the logsumexp LSE = m + log l (the residual
  that makes a flash backward possible).
- dq kernel: grid (bh, nq, nk) — k innermost; recomputes p from (q, k, LSE)
  per block and accumulates dq = scale * Σ_j ds·k.
- dkv kernel: grid (bh, nk, nq) — q innermost; accumulates
  dv = Σ_i pᵀ·do and dk = scale * Σ_i dsᵀ·q.

where ds = p ∘ (do·vᵀ − Δ) and Δ = rowsum(do ∘ o) is precomputed in XLA
(elementwise — no [S,S]). LSE/Δ ride in [*, bq, 128]-lane-replicated blocks,
the layout jax's own TPU kernels use for row statistics.

Set PADDLE_TPU_PALLAS_INTERPRET=1 to run the kernels in pallas interpret
mode (CPU) — used by the test suite to exercise the real kernel code paths
without a TPU.
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend (absent on some CPU-only builds)
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    _HAS_PLTPU = False

# Measured blocks (v5e, r4 sweeps): every config beats XLA, but the two
# r4 sweeps disagree on the best S=1024 blocks — quick sweep: (1024,1024)
# 1.17ms; full sweep: (1024,512) 1.73ms with (1024,1024) at 2.20ms — i.e.
# the spread between large-block configs is within run-to-run noise.
# (1024, 1024) is the default pending a higher-rep tie-break
# (tools/bench_flash.py --s 1024 --reps N); _pick_block clamps to S below
# 1024, landing on the measured-best (512, 512) at S=512.
DEFAULT_BLOCK_Q = int(os.environ.get("PADDLE_TPU_FLASH_BQ", 1024))
DEFAULT_BLOCK_K = int(os.environ.get("PADDLE_TPU_FLASH_BK", 1024))
NEG_INF = -1e30
LANES = 128


def _interpret() -> bool:
    return os.environ.get("PADDLE_TPU_PALLAS_INTERPRET") == "1"


def _compiler_params():
    """Mosaic dimension semantics: batch×head and the q-block axis are
    parallel (no cross-iteration carries), the innermost axis is 'arbitrary'
    (the online-softmax / accumulator carry rides it). Without this Mosaic
    assumes every grid dim may carry state and serializes the whole grid."""
    if _interpret() or not _HAS_PLTPU:
        return {}
    sem = ("parallel", "parallel", "arbitrary")
    cp = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams", None)
    if cp is not None:
        try:
            return {"compiler_params": cp(dimension_semantics=sem)}
        except TypeError:  # pragma: no cover - older ctor signature
            pass
    return {"compiler_params": dict(mosaic=dict(dimension_semantics=sem))}


def _i32(x):
    # index maps must stay int32: under jax_enable_x64 a python-int literal
    # traces as i64, which Mosaic refuses to legalize
    return jnp.asarray(x, jnp.int32)


def _pick_block(seq: int, block: int) -> int:
    if seq < block:
        return min(block, max(128, 1 << (seq - 1).bit_length()))
    return block


def _keep_mask(seed, bh, qi, ki, block_q: int, block_k: int,
               drop_p: float):
    """Deterministic dropout keep-mask for score block (bh, qi, ki).

    Counter-based hash (xorshift-multiply rounds) on the GLOBAL element
    coordinates in plain i32 jnp ops: the same (seed, batch-head, row,
    col) always yields the same bit, so the dq and dkv kernels reproduce
    the forward's mask exactly — regardless of their different grid
    orders or block shapes — with no PRNG-state plumbing, and it runs
    under interpret mode (pltpu.prng_seed has no CPU lowering).

    ``seed`` is a DATA value (f32 scalar holding an int < 2^24, exact in
    f32): under StaticFunction tracing the framework RNG key is traced
    state, so the seed cannot be a static python int."""
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    seed_i = seed.astype(jnp.int32) if hasattr(seed, "astype") \
        else jnp.int32(seed)
    x = (rows * jnp.int32(-1640531527)          # 0x9E3779B9
         ^ cols * jnp.int32(-2048144789)        # 0x85EBCA6B
         ^ (seed_i + bh * jnp.int32(668265263)))  # 0x27D4EB2F
    x = x ^ (x >> 15)
    x = x * jnp.int32(-2045495917)              # 0x85EBCA77^... odd const
    x = x ^ (x >> 13)
    x = x * jnp.int32(-1028477387)              # 0xC2B2AE35
    x = x ^ (x >> 16)
    u = (x & jnp.int32(0xFFFFFF)).astype(jnp.float32) / 16777216.0
    return u >= jnp.float32(drop_p)


# ───────────────────────────── forward ─────────────────────────────


def _attn_kernel(q_ref, k_ref, v_ref, seed_ref, kp_ref, o_ref, lse_ref,
                 acc_ref, m_ref, l_ref, *,
                 causal: bool, scale: float, block_q: int, block_k: int,
                 seq_q: int, seq_k: int, drop_p: float = 0.0,
                 has_kpad: bool = False):
    bh = pl.program_id(0)  # read at kernel top: program_id inside a
    qi = pl.program_id(1)  # pl.when body escapes the interpret context
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    neg_inf = jnp.float32(NEG_INF)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref[...])
        m_ref[...] = jnp.full_like(m_ref[...], neg_inf)
        l_ref[...] = jnp.zeros_like(l_ref[...])

    # causal: skip k-blocks entirely above the diagonal (the grid is
    # rectangular, so roughly half the blocks are dead weight otherwise)
    needed = (qi * block_q + (block_q - 1) + (seq_k - seq_q)
              >= ki * block_k) if causal else (ki >= 0)

    @pl.when(needed)
    def _body():
        # bf16 inputs + fp32 accumulation: the MXU's native mode. Casting
        # inputs up to f32 first would fall off the fast path entirely.
        q = q_ref[0]  # [bq, d]
        k = k_ref[0]  # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT) * jnp.float32(scale)

        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < seq_k  # padded keys
        if has_kpad:
            # caller-supplied per-key padding mask (f32 0/1, [1, bk])
            mask = mask & (kp_ref[0] > 0.5)[None, :]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = mask & (q_pos + (seq_k - seq_q) >= k_pos)
        s = jnp.where(mask, s, neg_inf)

        m_prev = m_ref[...]  # [bq, 128] replicated
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=1, keepdims=True)  # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        alpha = jnp.exp(m_prev - m_new)  # [bq, 128]
        p = jnp.exp(s - m_new[:, :1])  # [bq, bk]
        # a row with ZERO valid keys in every block so far has m_new still
        # at neg_inf, so exp(s - m_new) = exp(0) = 1 for masked positions —
        # zero them so such rows emit 0 (l clamps to 1e-30 in _finish),
        # consistent with the backward kernels' p=0 reconstruction
        p = jnp.where(mask, p, 0.0)
        l_new = alpha * l_prev + jnp.broadcast_to(
            jnp.sum(p, axis=1, keepdims=True), l_prev.shape)
        v = v_ref[0]  # [bk, d]
        if drop_p > 0.0:
            # after-softmax dropout: l (the softmax denominator) uses the
            # UNmasked p, so mask∘(p/l) == (mask∘p)/l — apply to the pv
            # accumulation only
            keep = _keep_mask(seed_ref[0, 0], bh, qi, ki,
                              block_q, block_k, drop_p)
            p = jnp.where(keep, p, 0.0) / jnp.float32(1.0 - drop_p)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)  # [bq, d]
        acc_ref[...] = acc_ref[...] * alpha[:, :1] + pv
        m_ref[...] = m_new
        l_ref[...] = l_new

    @pl.when(ki == nk - 1)
    def _finish():
        l_fin = jnp.maximum(l_ref[...], jnp.float32(1e-30))
        o_ref[0] = (acc_ref[...] / l_fin[:, :1]).astype(o_ref.dtype)
        # logsumexp residual for the flash backward
        lse_ref[0] = m_ref[...] + jnp.log(l_fin)


def _scalar_spec():
    """(1,1) scalar block: SMEM on the real TPU backend, plain VMEM-ish
    block under interpret (SMEM has no interpret support)."""
    if _HAS_PLTPU and not _interpret():
        return pl.BlockSpec((1, 1), lambda *_: (_i32(0), _i32(0)),
                            memory_space=pltpu.SMEM)
    return pl.BlockSpec((1, 1), lambda *_: (_i32(0), _i32(0)))


def _flash_fwd_bhsd(q, k, v, causal: bool, scale: float,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    drop_p: float = 0.0, drop_seed=0, kpad=None,
                    kpad_heads: int = 1, vma=None):
    """q,k,v: [BH, S, D] → (out [BH, Sq, D], lse [BH, Sq] f32).
    ``vma``: varying-mesh-axes metadata for the out_shapes — required when
    the kernel runs inside a shard_map manual region (the vma checker
    rejects ShapeDtypeStructs without it).
    ``kpad``: optional per-key keep mask [B, Sk] f32 0/1 (key padding);
    ``kpad_heads`` is H, so block b of the [B·H] grid reads row b // H —
    no H-fold mask copy is ever materialized."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq = _pick_block(sq, block_q)
    bk = _pick_block(sk, block_k)
    pad_q = (-sq) % bq
    pad_k = (-sk) % bk
    qp = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0))) if pad_q else q
    kp = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0))) if pad_k else k
    vp = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0))) if pad_k else v
    nq = qp.shape[1] // bq
    nk = kp.shape[1] // bk

    grid = (bh, nq, nk)
    seed2 = jnp.full((1, 1), drop_seed, jnp.float32)
    has_kpad = kpad is not None
    if has_kpad:
        kp2 = jnp.pad(kpad, ((0, 0), (0, pad_k))) if pad_k else kpad
        _h = kpad_heads
        kp_spec = pl.BlockSpec((1, bk), lambda b, i, j: (b // _i32(_h), j))
    else:
        kp2 = jnp.ones((1, bk), jnp.float32)
        kp_spec = pl.BlockSpec((1, bk), lambda b, i, j: (_i32(0), _i32(0)))
    out, lse = pl.pallas_call(
        functools.partial(_attn_kernel, causal=causal, scale=scale,
                          block_q=bq, block_k=bk, seq_q=sq, seq_k=sk,
                          drop_p=drop_p, has_kpad=has_kpad),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, _i32(0))),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, _i32(0))),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, _i32(0))),
            _scalar_spec(),
            kp_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, _i32(0))),
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, _i32(0))),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, qp.shape[1], d), q.dtype,
                                 **({"vma": vma} if vma else {})),
            jax.ShapeDtypeStruct((bh, qp.shape[1], LANES), jnp.float32,
                                 **({"vma": vma} if vma else {})),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
        ],
        interpret=_interpret(),
        **_compiler_params(),
    )(qp, kp, vp, seed2, kp2)
    return out[:, :sq], lse[:, :sq, 0]


# ───────────────────────────── backward ─────────────────────────────


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, seed_ref,
               kp_ref, dq_ref, dq_acc, *, causal: bool, scale: float,
               block_q: int, block_k: int, seq_q: int, seq_k: int,
               drop_p: float = 0.0, has_kpad: bool = False):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc[...])

    needed = (qi * block_q + (block_q - 1) + (seq_k - seq_q)
              >= ki * block_k) if causal else (ki >= 0)

    @pl.when(needed)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]   # [bq, 1]
        dlt = dlt_ref[0][:, :1]   # [bq, 1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT) * jnp.float32(scale)
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < seq_k
        if has_kpad:
            mask = mask & (kp_ref[0] > 0.5)[None, :]
        if causal:
            mask = mask & (q_pos + (seq_k - seq_q) >= k_pos)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)  # [bq, bk] f32

        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)  # [bq, bk]
        if drop_p > 0.0:
            # dL/dp routes only through kept positions (same mask as fwd);
            # note Δ = rowsum(do∘o) already equals rowsum(p∘dp_eff)
            keep = _keep_mask(seed_ref[0, 0], bh, qi, ki,
                              block_q, block_k, drop_p)
            dp = jnp.where(keep, dp, 0.0) / jnp.float32(1.0 - drop_p)
        ds = (p * (dp - dlt)).astype(k.dtype)
        dq_acc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = (dq_acc[...] * jnp.float32(scale)).astype(dq_ref.dtype)


def _dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, dlt_ref, seed_ref,
                kp_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, causal: bool,
                scale: float, block_q: int, block_k: int, seq_q: int,
                seq_k: int, drop_p: float = 0.0, has_kpad: bool = False):
    bh = pl.program_id(0)
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc[...])
        dv_acc[...] = jnp.zeros_like(dv_acc[...])

    needed = (qi * block_q + (block_q - 1) + (seq_k - seq_q)
              >= kj * block_k) if causal else (qi >= 0)

    @pl.when(needed)
    def _body():
        k = k_ref[0]
        v = v_ref[0]
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]
        dlt = dlt_ref[0][:, :1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT) * jnp.float32(scale)
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        # padded q rows must not contribute to dk/dv sums
        mask = (k_pos < seq_k) & (q_pos < seq_q)
        if has_kpad:
            mask = mask & (kp_ref[0] > 0.5)[None, :]
        if causal:
            mask = mask & (q_pos + (seq_k - seq_q) >= k_pos)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)  # [bq, bk] f32
        if drop_p > 0.0:
            # same (seed, b, row, col) hash as the fwd — the dkv grid
            # iterates (b, kj, qi) but the mask depends only on global
            # coordinates, so order is irrelevant
            keep = _keep_mask(seed_ref[0, 0], bh, qi, kj,
                              block_q, block_k, drop_p)
            inv = jnp.float32(1.0 - drop_p)
            p_eff = jnp.where(keep, p, 0.0) / inv
        else:
            keep, inv, p_eff = None, None, p
        pl_ = p_eff.astype(do.dtype)

        # dv += p_effᵀ · do : contract the bq dim (dropout: out = p_eff·v)
        dv_acc[...] += jax.lax.dot_general(
            pl_, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)  # [bq, bk]
        if drop_p > 0.0:
            dp = jnp.where(keep, dp, 0.0) / inv
        ds = (p * (dp - dlt)).astype(q.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = (dk_acc[...] * jnp.float32(scale)).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_bhsd(q, k, v, o, lse, do, causal: bool, scale: float,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    drop_p: float = 0.0, drop_seed=0, kpad=None,
                    kpad_heads: int = 1):
    """All [BH, S, D] (lse [BH, Sq]) → (dq, dk, dv)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq = _pick_block(sq, block_q)
    bk = _pick_block(sk, block_k)
    pad_q = (-sq) % bq
    pad_k = (-sk) % bk

    # Δ = rowsum(do ∘ o): pure elementwise+reduce, XLA fuses it — no [S,S]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    def padq(x):
        return jnp.pad(x, ((0, 0), (0, pad_q), (0, 0))) if pad_q else x

    def padk(x):
        return jnp.pad(x, ((0, 0), (0, pad_k), (0, 0))) if pad_k else x

    qp, dop = padq(q), padq(do)
    kp, vp = padk(k), padk(v)
    # row statistics ride lane-replicated [BH, Sqp, 128] blocks
    lse_b = jnp.broadcast_to(
        (jnp.pad(lse, ((0, 0), (0, pad_q))) if pad_q else lse)[..., None],
        (bh, sq + pad_q, LANES))
    dlt_b = jnp.broadcast_to(
        (jnp.pad(delta, ((0, 0), (0, pad_q))) if pad_q else delta)[..., None],
        (bh, sq + pad_q, LANES))

    nq = qp.shape[1] // bq
    nk = kp.shape[1] // bk
    has_kpad = kpad is not None
    kw = dict(causal=causal, scale=scale, block_q=bq, block_k=bk,
              seq_q=sq, seq_k=sk, drop_p=drop_p, has_kpad=has_kpad)
    seed2 = jnp.full((1, 1), drop_seed, jnp.float32)
    if has_kpad:
        kp2 = jnp.pad(kpad, ((0, 0), (0, pad_k))) if pad_k else kpad
        _h = kpad_heads
        kp_spec_q = pl.BlockSpec((1, bk),
                                 lambda b, i, j: (b // _i32(_h), j))
        kp_spec_k = pl.BlockSpec((1, bk),
                                 lambda b, j, i: (b // _i32(_h), j))
    else:
        kp2 = jnp.ones((1, bk), jnp.float32)
        kp_spec_q = pl.BlockSpec((1, bk),
                                 lambda b, i, j: (_i32(0), _i32(0)))
        kp_spec_k = pl.BlockSpec((1, bk),
                                 lambda b, j, i: (_i32(0), _i32(0)))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **kw),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, _i32(0))),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, _i32(0))),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, _i32(0))),
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, _i32(0))),
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, _i32(0))),
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, _i32(0))),
            _scalar_spec(),
            kp_spec_q,
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, _i32(0))),
        out_shape=jax.ShapeDtypeStruct((bh, qp.shape[1], d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=_interpret(),
        **_compiler_params(),
    )(qp, kp, vp, dop, lse_b, dlt_b, seed2, kp2)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, **kw),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, _i32(0))),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, _i32(0))),
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, _i32(0))),
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, _i32(0))),
            pl.BlockSpec((1, bq, LANES), lambda b, j, i: (b, i, _i32(0))),
            pl.BlockSpec((1, bq, LANES), lambda b, j, i: (b, i, _i32(0))),
            _scalar_spec(),
            kp_spec_k,
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, _i32(0))),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, _i32(0))),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, kp.shape[1], d), k.dtype),
            jax.ShapeDtypeStruct((bh, kp.shape[1], d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=_interpret(),
        **_compiler_params(),
    )(kp, vp, qp, dop, lse_b, dlt_b, seed2, kp2)

    return dq[:, :sq], dk[:, :sk], dv[:, :sk]


# ───────────────────────────── public op ─────────────────────────────


def _ref_attention_bshd(q, k, v, causal: bool, scale: float):
    """Pure-XLA reference (same math), used off-TPU."""
    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kh = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vh = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    if causal:
        sq_, sk_ = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq_, sk_), bool), sk_ - sq_)
        logits = jnp.where(cm, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def _to_bh(x):
    b, s, h, d = x.shape
    return jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)


def _from_bh(x, b, h):
    bh, s, d = x.shape
    return jnp.swapaxes(x.reshape(b, h, s, d), 1, 2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_attention(q, k, v, drop_seed, causal: bool, scale: float,
                     block_q: int, block_k: int, drop_p: float = 0.0):
    # drop_seed is an f32 scalar OPERAND (position 3): under StaticFunction
    # tracing the framework RNG is traced state, so the seed cannot be a
    # static python int without retracing per step
    o, _ = _fwd(q, k, v, drop_seed, causal, scale, block_q, block_k, drop_p)
    return o


def _fwd(q, k, v, drop_seed, causal, scale, block_q, block_k, drop_p=0.0):
    b, sq, h, d = q.shape
    of, lse = _flash_fwd_bhsd(_to_bh(q), _to_bh(k), _to_bh(v), causal, scale,
                              block_q=block_q, block_k=block_k,
                              drop_p=drop_p, drop_seed=drop_seed)
    o = _from_bh(of, b, h)
    return o, (q, k, v, drop_seed, o, lse)


def _bwd(causal, scale, block_q, block_k, drop_p, res, g):
    q, k, v, drop_seed, o, lse = res
    b, sq, h, d = q.shape
    dq, dk, dv = _flash_bwd_bhsd(
        _to_bh(q), _to_bh(k), _to_bh(v), _to_bh(o), lse, _to_bh(g),
        causal, scale, block_q=block_q, block_k=block_k,
        drop_p=drop_p, drop_seed=drop_seed)
    return (_from_bh(dq, b, h), _from_bh(dk, b, h), _from_bh(dv, b, h),
            jnp.zeros_like(drop_seed))


_flash_attention.defvjp(_fwd, _bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_attention_kpad(q, k, v, drop_seed, kpad, causal: bool,
                          scale: float, block_q: int, block_k: int,
                          drop_p: float = 0.0):
    """Key-padding variant: ``kpad`` [B, Sk] f32 0/1 rides as an operand
    (separate custom_vjp so the unmasked hot path's signature stays
    untouched). The kernels index row b // H — do NOT H-fold the mask;
    a [B*H, Sk] array would be silently mis-read (rows 0..B-1 only)."""
    o, _ = _fwd_kpad(q, k, v, drop_seed, kpad, causal, scale, block_q,
                     block_k, drop_p)
    return o


def _fwd_kpad(q, k, v, drop_seed, kpad, causal, scale, block_q, block_k,
              drop_p=0.0):
    b, sq, h, d = q.shape
    of, lse = _flash_fwd_bhsd(_to_bh(q), _to_bh(k), _to_bh(v), causal, scale,
                              block_q=block_q, block_k=block_k,
                              drop_p=drop_p, drop_seed=drop_seed, kpad=kpad,
                              kpad_heads=h)
    o = _from_bh(of, b, h)
    return o, (q, k, v, drop_seed, kpad, o, lse)


def _bwd_kpad(causal, scale, block_q, block_k, drop_p, res, g):
    q, k, v, drop_seed, kpad, o, lse = res
    b, sq, h, d = q.shape
    dq, dk, dv = _flash_bwd_bhsd(
        _to_bh(q), _to_bh(k), _to_bh(v), _to_bh(o), lse, _to_bh(g),
        causal, scale, block_q=block_q, block_k=block_k,
        drop_p=drop_p, drop_seed=drop_seed, kpad=kpad, kpad_heads=h)
    return (_from_bh(dq, b, h), _from_bh(dk, b, h), _from_bh(dv, b, h),
            jnp.zeros_like(drop_seed), jnp.zeros_like(kpad))


_flash_attention_kpad.defvjp(_fwd_kpad, _bwd_kpad)


def flash_attention_bshd(q, k, v, causal: bool = False, scale: float = None,
                         block_q: int = None, block_k: int = None,
                         dropout_p: float = 0.0, dropout_seed: int = 0,
                         key_padding_mask=None):
    """Flash attention, paddle layout [B, S, H, D]. Fwd and bwd are both
    Pallas flash kernels (no [S,S] materialization in either direction).
    Block sizes default to the measured-best ladder (PADDLE_TPU_FLASH_BQ/BK
    env overrides; explicit args win — the sweep harness uses them).

    ``dropout_p``: after-softmax attention dropout INSIDE the kernel (the
    reference's flash_attn dropout — flash_attn_kernel.cu takes a
    dropout rate). The keep-mask is a counter-based hash of the global
    (seed, batch-head, row, col), so fwd and both bwd kernels reproduce
    it exactly without materializing an [S, S] mask. ``dropout_seed`` is
    DATA (int or traced scalar < 2^24; exact in the f32 it rides in), so
    a fresh per-step seed costs no retrace."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if not _HAS_PLTPU:
        if dropout_p > 0.0 or key_padding_mask is not None:
            raise NotImplementedError(
                "flash_attention_bshd dropout/key-padding requires the "
                "pallas TPU backend (this build lacks "
                "jax.experimental.pallas.tpu); silently ignoring them "
                "would be worse")
        return _ref_attention_bshd(q, k, v, causal, scale)
    seed_f = jnp.asarray(dropout_seed, jnp.float32)
    if key_padding_mask is not None:
        # [B, Sk] bool/0-1 keep mask — the kernels index row b // H, no
        # H-fold copy is materialized (nor saved in the vjp residuals)
        kpad = key_padding_mask.astype(jnp.float32)
        return _flash_attention_kpad(q, k, v, seed_f, kpad, causal, scale,
                                     block_q or DEFAULT_BLOCK_Q,
                                     block_k or DEFAULT_BLOCK_K,
                                     float(dropout_p))
    return _flash_attention(q, k, v, seed_f, causal, scale,
                            block_q or DEFAULT_BLOCK_Q,
                            block_k or DEFAULT_BLOCK_K,
                            float(dropout_p))
