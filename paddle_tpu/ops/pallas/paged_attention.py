"""Ragged paged-attention decode kernel (Pallas TPU) + pure-jnp fallback.

TPU-native kernel for continuous-batching decode (PAPERS.md: "Ragged
Paged Attention", arxiv 2604.15464): each live sequence owns a list of
fixed-size KV pages scattered through a shared pool, described by a
per-sequence block table. One query token per sequence attends over its
own ragged page list — no per-sequence dense cache, no re-layout when
sequences join or retire mid-decode.

Layout (serving/kv_cache.py owns the pool):

- ``q``            [B, num_heads, head_dim]      — one decode token per seq
- ``k/v pool``     [num_pages, page_size, num_kv_heads, head_dim]
- ``block_tables`` [B, pages_per_seq] int32      — page ids, 0-padded (page 0
  is the pool's reserved null page, never allocated to a sequence)
- ``seq_lens``     [B] int32                     — tokens written so far

Kernel shape (style of ops/pallas/flash_attention.py): grid
``(B, num_kv_heads, pages_per_seq)`` with the page axis innermost carrying
the online-softmax state in VMEM scratch; the block table and seq lens ride
in as SCALAR-PREFETCH operands (``pltpu.PrefetchScalarGridSpec``) so the
k/v BlockSpec index maps can DMA exactly the pages each sequence names —
the "ragged" part: no dense [B, max_len] gather ever materializes.

The pure-jnp fallback (``ref_paged_attention``) is the same math as the
dense decode path (models/llama.py cached_attn): softmax in f32 over the
gathered pages with masked lanes at -1e30 — tier-1 CPU tests drive the
engine through this path and assert token-for-token equality with dense
``generate()``. Set PADDLE_TPU_PALLAS_INTERPRET=1 to run the real kernel
on CPU (interpret mode), as the flash kernels do.

**Ragged (mixed query-length) form** — ``ragged_paged_attention``: the
unified serving step (engine.py) batches decode slots (q_len 1) and
prompt chunks (q_len up to the token budget) in ONE launch by
flattening every query token into a row of a ``[T, ...]`` grid: a
slot's chunk contributes one row per token, each carrying the slot's
block table and its own absolute position. Per-row ``seq_lens`` =
position + 1 masks later keys, so a chunk token attends to the shared
pool's KV — its own earlier chunk tokens included, because the step
scatters the whole chunk's KV before the gather — exactly causally.
Raggedness is therefore DATA (row→table mapping), not shape: one
compiled program per token-grid bucket serves every prefill/decode mix
(PAPERS.md, arXiv 2604.15464 — the same "queries of every length in
one kernel" contract, expressed on the decode kernel's grid).
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp

try:  # pallas TPU backend (absent on some CPU-only builds)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pl = None
    pltpu = None
    _HAS_PLTPU = False

__all__ = ["paged_attention", "ragged_paged_attention",
           "ref_paged_attention"]

NEG_INF = -1e30
LANES = 128


def _interpret() -> bool:
    return os.environ.get("PADDLE_TPU_PALLAS_INTERPRET") == "1"


# ───────────────────────── pure-jnp fallback ─────────────────────────


def ref_paged_attention(q, k_pool, v_pool, block_tables, seq_lens,
                        scale: float = None, k_scale=None, v_scale=None):
    """Gather-based paged attention, pure jnp — the CPU/equivalence path.

    Math-identical to the dense cached_attn (einsum in f32, -1e30 masked
    lanes, softmax over the key axis): a masked key contributes exactly 0
    to every sum, so outputs match the dense decode bit-for-bit on the
    positions both paths share.

    ``k_scale``/``v_scale`` (``[num_pages, page_size, nkv]`` f32, both or
    neither) arm int8-page dequantization: gathered blocks are widened
    per-block (``q * scale``) right here in the reduction — the full
    bf16/f32 page array is never materialized, mirroring the in-kernel
    dequant of the Pallas path.
    """
    B, nh, hd = q.shape
    nkv = k_pool.shape[2]
    page = k_pool.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(hd)
    groups = nh // nkv
    # [B, pages_per_seq, page, nkv, hd] -> [B, K, nkv, hd]
    k = k_pool[block_tables].reshape(B, -1, nkv, hd)
    v = v_pool[block_tables].reshape(B, -1, nkv, hd)
    if k_scale is not None:
        ks = k_scale[block_tables].reshape(B, -1, nkv)
        vs = v_scale[block_tables].reshape(B, -1, nkv)
        k = k.astype(jnp.float32) * ks[..., None]
        v = v.astype(jnp.float32) * vs[..., None]
    if groups > 1:  # GQA: repeat kv per query group (same as dense path)
        k = jnp.repeat(k, groups, axis=2)
        v = jnp.repeat(v, groups, axis=2)
    qf = q.astype(jnp.float32)
    s = jnp.einsum("bhd,bkhd->bhk", qf, k.astype(jnp.float32)) * scale
    pos = jnp.arange(k.shape[1], dtype=jnp.int32)[None, :]  # [1, K]
    valid = pos < seq_lens.astype(jnp.int32)[:, None]       # [B, K]
    s = jnp.where(valid[:, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhk,bkhd->bhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


# ───────────────────────── pallas kernel ─────────────────────────


def _paged_attn_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, *rest,
                       scale: float, page_size: int, groups: int,
                       quantized: bool = False):
    """One (sequence b, kv head h, page j) step of online-softmax decode.

    bt_ref/len_ref are the scalar-prefetched block table and seq lens —
    already consumed by the k/v index maps; len_ref masks the tail of the
    last live page here. q block is the head group [groups, hd]; scratch
    carries (acc, m, l) across the page axis (innermost, 'arbitrary').

    ``quantized`` (a Python-time flag, so the unquantized trace is
    byte-identical to before) threads two extra per-page scale blocks
    (``ks_ref``/``vs_ref``, [1, page, 1]) and widens the int8 k/v blocks
    in VMEM right before the dot — the dequant never touches HBM.
    """
    if quantized:
        ks_ref, vs_ref = rest[0], rest[1]
        o_ref, acc_ref, m_ref, l_ref = rest[2:]
    else:
        ks_ref = vs_ref = None
        o_ref, acc_ref, m_ref, l_ref = rest
    b = pl.program_id(0)
    j = pl.program_id(2)
    npages = pl.num_programs(2)

    neg_inf = jnp.float32(NEG_INF)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref[...])
        m_ref[...] = jnp.full_like(m_ref[...], neg_inf)
        l_ref[...] = jnp.zeros_like(l_ref[...])

    seq_len = len_ref[b]
    # ragged early-out: pages past the sequence's length are dead weight
    # (their block-table entries are the null page) — skip the whole block
    @pl.when(j * page_size < seq_len)
    def _body():
        q = q_ref[0, 0]  # [groups, hd]
        k = k_ref[0, :, 0, :]  # [page, hd]
        v = v_ref[0, :, 0, :]
        if quantized:  # in-kernel dequant: int8 block × per-slot scale
            k = k.astype(jnp.float32) * ks_ref[0, :, 0][:, None]
            v = v.astype(jnp.float32) * vs_ref[0, :, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * jnp.float32(scale)
        # mask the tail of the last live page
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (q.shape[0], page_size), 1)
        mask = pos < seq_len
        s = jnp.where(mask, s, neg_inf)

        m_prev = m_ref[...]  # [groups, LANES] replicated
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :1])
        p = jnp.where(mask, p, 0.0)
        l_ref[...] = alpha * l_prev + jnp.broadcast_to(
            jnp.sum(p, axis=1, keepdims=True), l_prev.shape)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha[:, :1] + pv
        m_ref[...] = m_new

    @pl.when(j == npages - 1)
    def _finish():
        l_fin = jnp.maximum(l_ref[...], jnp.float32(1e-30))
        o_ref[0, 0] = (acc_ref[...] / l_fin[:, :1]).astype(o_ref.dtype)


def _paged_attention_pallas(q, k_pool, v_pool, block_tables, seq_lens,
                            scale: float, k_scale=None, v_scale=None):
    B, nh, hd = q.shape
    num_pages, page_size, nkv, _ = k_pool.shape
    groups = nh // nkv
    pages_per_seq = block_tables.shape[1]
    quantized = k_scale is not None
    # q regrouped so each kv head's query group is one contiguous block
    qg = q.reshape(B, nkv, groups, hd)

    bt = block_tables.astype(jnp.int32)
    sl = seq_lens.astype(jnp.int32)

    kv_spec = pl.BlockSpec((1, page_size, 1, hd),
                           lambda b, h, j, bt_ref, len_ref:
                           (bt_ref[b, j], 0, h, 0))
    in_specs = [
        pl.BlockSpec((1, 1, groups, hd),
                     lambda b, h, j, bt_ref, len_ref: (b, h, 0, 0)),
        kv_spec,
        kv_spec,
    ]
    operands = [qg, k_pool, v_pool]
    if quantized:
        # per-slot scale blocks ride the same page-indexed DMA pattern
        sc_spec = pl.BlockSpec((1, page_size, 1),
                               lambda b, h, j, bt_ref, len_ref:
                               (bt_ref[b, j], 0, h))
        in_specs += [sc_spec, sc_spec]
        operands += [k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # block_tables, seq_lens
        grid=(B, nkv, pages_per_seq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, groups, hd),
                               lambda b, h, j, bt_ref, len_ref: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((groups, hd), jnp.float32),
            pltpu.VMEM((groups, LANES), jnp.float32),
            pltpu.VMEM((groups, LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_attn_kernel, scale=scale,
                          page_size=page_size, groups=groups,
                          quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, nkv, groups, hd), q.dtype),
        interpret=_interpret(),
    )(bt, sl, *operands)
    return out.reshape(B, nh, hd)


# ───────────────────────── public op ─────────────────────────


def paged_attention(q, k_pool, v_pool, block_tables, seq_lens,
                    scale: float = None, use_kernel: bool = None,
                    k_scale=None, v_scale=None):
    """Ragged paged-attention decode: one query token per sequence over its
    page list. ``use_kernel=None`` picks the Pallas kernel on TPU backends
    (or under PADDLE_TPU_PALLAS_INTERPRET=1) and the jnp gather fallback
    elsewhere — both compute the identical masked-softmax math, so the
    serving engine's numerics don't depend on the backend.

    ``k_scale``/``v_scale`` (pass both or neither; f32
    ``[num_pages, page_size, nkv]``) switch the pools to int8 pages with
    per-slot dequant applied inside the reduction on BOTH backends."""
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be passed together")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if use_kernel is None:
        use_kernel = _HAS_PLTPU and (
            _interpret()
            or jax.default_backend() in ("tpu", "axon"))
    if use_kernel and _HAS_PLTPU:
        return _paged_attention_pallas(q, k_pool, v_pool, block_tables,
                                       seq_lens, scale,
                                       k_scale=k_scale, v_scale=v_scale)
    return ref_paged_attention(q, k_pool, v_pool, block_tables, seq_lens,
                               scale, k_scale=k_scale, v_scale=v_scale)


def ragged_paged_attention(q, k_pool, v_pool, row_block_tables, row_lens,
                           scale: float = None, use_kernel: bool = None,
                           k_scale=None, v_scale=None):
    """Mixed query-length paged attention over a FLATTENED token grid
    (module docstring, "Ragged form"): ``q`` is ``[T, nh, hd]`` — one
    row per query token across every slot this step, decode tokens and
    prompt-chunk tokens alike. ``row_block_tables`` ``[T, pages]``
    repeats a slot's block table for each of its rows; ``row_lens``
    ``[T]`` is each row's absolute position + 1 (keys at or past the
    row's own position are masked, which is what makes an in-chunk
    token causal over its chunk-mates' freshly scattered KV).

    Contract: the caller has ALREADY scattered this step's KV for every
    row into the pool (the unified step writes first, attends second —
    the decode step's own idiom, generalized). Each row then reduces
    over its named pages exactly like a decode query, so the kernel grid
    (``(T, kv_heads, pages)``, scalar-prefetched tables, online-softmax
    scratch) serves the ragged batch unchanged — per-row early-out over
    ``row_lens`` is what keeps a 1-token decode row from paying a long
    prompt's page walk."""
    return paged_attention(q, k_pool, v_pool, row_block_tables, row_lens,
                           scale=scale, use_kernel=use_kernel,
                           k_scale=k_scale, v_scale=v_scale)
