"""Pallas TPU kernel tier.

reference parity: the reference's fused/native kernel layer —
FlashAttention (paddle/phi/kernels/gpu/flash_attn_kernel.cu:213, external lib
via cmake/external/flashattn.cmake) and the CUTLASS memory-efficient attention
(phi/kernels/fusion/cutlass/). Here the fused kernels are Pallas TPU kernels
(VMEM-tiled, MXU matmuls); non-TPU platforms fall back to pure-XLA reference
math in the callers.
"""
from . import flash_attention  # noqa: F401
