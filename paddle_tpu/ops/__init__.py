"""paddle_tpu.ops — the functional op library.

reference parity: the PHI kernel surface (paddle/phi/kernels/) exposed through
python/paddle/tensor/*. At import time, ops are monkey-patched onto Tensor as
methods and operator overloads — the counterpart of the reference's
``eager_math_op_patch.cc`` + tensor method patching
(python/paddle/tensor/__init__.py).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..autograd.engine import apply_op
from ..tensor import Tensor
from . import creation, extras, linalg, logic, manipulation, math, random, stat
from ._apply import binary, ensure_tensor, unary
from .creation import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403

__all__ = (
    creation.__all__ + extras.__all__ + linalg.__all__ + logic.__all__
    + manipulation.__all__ + math.__all__ + random.__all__ + stat.__all__
    + ["getitem", "setitem"]
)


# ---------------------------------------------------------------- indexing
def _prep_index(item):
    """Convert Tensor indices to jax arrays, keep slices/ints/None/Ellipsis."""
    if isinstance(item, tuple):
        return tuple(_prep_index(i) for i in item)
    if isinstance(item, Tensor):
        return item._value
    if isinstance(item, (list,)):
        return jnp.asarray(item)
    return item


def _check_int_bounds(shape, item):
    """Reference/numpy semantics: out-of-range CONCRETE int indices raise
    IndexError. jax silently CLAMPS them — r5 found `for v in tensor`
    never terminating because of exactly this; a user typo like x[5] on a
    size-3 axis deserves the same loudness as numpy. Applies only to
    plain Python ints (static shapes make the check valid under tracing);
    slices keep Python clamping, and any array/Tensor index disables the
    check for the whole subscript (advanced indexing keeps documented jax
    gather semantics, incl. bool masks consuming several axes)."""
    # NB: builtins `any`/`all`/`sum` are SHADOWED here by the paddle
    # reduction ops (star-imports above) — this function avoids them
    items = item if isinstance(item, tuple) else (item,)
    for i in items:
        if isinstance(i, (Tensor, np.ndarray, jnp.ndarray, list)):
            return
    # None (newaxis) and scalar bools (0-d masks, numpy semantics) ADD an
    # axis and consume none — both are excluded from axis tracking
    positional = [i for i in items
                  if i is not None and not isinstance(i, (bool, np.bool_))]
    ndim = len(shape)
    remaining = 0
    for i in positional:
        if i is not Ellipsis:
            remaining += 1
    axis = 0
    for i in positional:
        if i is Ellipsis:
            axis = ndim - remaining
            continue
        remaining -= 1
        if (isinstance(i, int) and not isinstance(i, bool)
                and 0 <= axis < ndim):
            dim = shape[axis]
            if not -dim <= i < dim:
                raise IndexError(f"index {i} is out of bounds for axis "
                                 f"{axis} with size {dim}")
        axis += 1


def getitem(x, item):
    _check_int_bounds(x.shape, item)
    idx = _prep_index(item)
    return unary(lambda a: a[idx], x, name="getitem")


def setitem(x, item, value):
    """In-place indexed write (reference: eager __setitem__ / set_value op).
    Routes through the tape via inplace_rebind so autograd stays correct."""
    from ..autograd.engine import inplace_rebind

    _check_int_bounds(x.shape, item)
    idx = _prep_index(item)
    if isinstance(value, Tensor):
        out = apply_op(lambda a, v: a.at[idx].set(v.astype(a.dtype)), [x, value], name="setitem")
    else:
        out = unary(lambda a: a.at[idx].set(jnp.asarray(value).astype(a.dtype)), x, name="setitem")
    return inplace_rebind(x, out)


# ----------------------------------------------- Tensor method/op patching
def _patch_tensor():
    import builtins

    T = Tensor

    # operators
    T.__add__ = lambda s, o: math.add(s, o)
    T.__radd__ = lambda s, o: math.add(o, s)
    T.__sub__ = lambda s, o: math.subtract(s, o)
    T.__rsub__ = lambda s, o: math.subtract(o, s)
    T.__mul__ = lambda s, o: math.multiply(s, o)
    T.__rmul__ = lambda s, o: math.multiply(o, s)
    T.__truediv__ = lambda s, o: math.divide(s, o)
    T.__rtruediv__ = lambda s, o: math.divide(o, s)
    T.__floordiv__ = lambda s, o: math.floor_divide(s, o)
    T.__mod__ = lambda s, o: math.remainder(s, o)
    T.__pow__ = lambda s, o: math.pow(s, o)
    T.__rpow__ = lambda s, o: math.pow(o, s)
    T.__matmul__ = lambda s, o: math.matmul(s, o)
    T.__rmatmul__ = lambda s, o: math.matmul(o, s)
    T.__neg__ = lambda s: math.neg(s)
    T.__abs__ = lambda s: math.abs(s)
    T.__invert__ = lambda s: logic.bitwise_not(s)
    T.__eq__ = lambda s, o: logic.equal(s, o)
    T.__ne__ = lambda s, o: logic.not_equal(s, o)
    T.__lt__ = lambda s, o: logic.less_than(s, o)
    T.__le__ = lambda s, o: logic.less_equal(s, o)
    T.__gt__ = lambda s, o: logic.greater_than(s, o)
    T.__ge__ = lambda s, o: logic.greater_equal(s, o)
    # reference maps &,|,^,~ to the bitwise ops (python/paddle/tensor/__init__.py)
    T.__and__ = lambda s, o: logic.bitwise_and(s, o)
    T.__or__ = lambda s, o: logic.bitwise_or(s, o)
    T.__xor__ = lambda s, o: logic.bitwise_xor(s, o)
    # linalg/meta methods the reference patches onto Tensor
    from .. import linalg as _linalg_facade

    T.cond = lambda s, p=None, name=None: _linalg_facade.cond(s, p)
    T.multi_dot = lambda s, xs, name=None: _linalg_facade.multi_dot([s] + list(xs))
    T.lu_unpack = lambda s, y, unpack_ludata=True, unpack_pivots=True, \
        name=None: _linalg_facade.lu_unpack(s, y, unpack_ludata, unpack_pivots)
    T.is_tensor = lambda s: True
    T.create_parameter = staticmethod(
        lambda *a, **k: __import__(
            "paddle_tpu.framework.core_api", fromlist=["create_parameter"]
        ).create_parameter(*a, **k))
    T.create_tensor = staticmethod(
        lambda dtype="float32", name=None, persistable=False: T(
            __import__("jax.numpy", fromlist=["zeros"]).zeros((), dtype)))

    T.__getitem__ = getitem
    T.__setitem__ = setitem
    T.__hash__ = lambda s: id(s)

    # methods (paddle patches ~200; we patch everything in __all__ whose first
    # arg is a tensor, under both the op name and common aliases)
    method_sources = [creation, extras, linalg, logic, manipulation, math,
                      random, stat]
    skip = {
        "to_tensor", "zeros", "ones", "full", "empty", "arange", "linspace",
        "logspace", "eye", "meshgrid", "tril_indices", "triu_indices",
        "uniform", "gaussian", "normal", "standard_normal", "randn", "rand",
        "randint", "randperm",
    }
    for mod in method_sources:
        for name in mod.__all__:
            if name in skip or hasattr(T, name):
                continue
            fn = getattr(mod, name)
            if callable(fn):
                setattr(T, name, fn)

    # aliases matching paddle Tensor methods
    T.add = math.add
    T.add_ = math.add_
    T.subtract = math.subtract
    T.multiply = math.multiply
    T.divide = math.divide
    T.matmul = math.matmul
    T.dim = lambda s: s.ndim
    T.rank = lambda s: Tensor(jnp.asarray(s.ndim))
    T.mean = math.mean
    T.sum = math.sum
    T.max = math.max
    T.min = math.min
    T.prod = math.prod
    T.reshape = manipulation.reshape
    T.transpose = manipulation.transpose
    # x.T reverses all dims (reference: fluid/framework.py:2015 Variable.T)
    T.T = property(lambda s: manipulation.transpose(
        s, list(range(s.ndim))[::-1]))
    T.unsqueeze = manipulation.unsqueeze
    T.squeeze = manipulation.squeeze
    T.flatten = manipulation.flatten
    T.scale = math.scale
    T.pow = math.pow
    T.exp = math.exp
    T.log = math.log
    T.sqrt = math.sqrt
    T.rsqrt = math.rsqrt
    T.tanh = math.tanh
    T.sigmoid = math.sigmoid
    T.abs = math.abs
    T.clip = math.clip
    T.norm = linalg.norm
    T.argmax = math.argmax
    T.argmin = math.argmin
    T.cumsum = math.cumsum
    T.topk = manipulation.topk
    T.sort = manipulation.sort
    T.argsort = manipulation.argsort
    T.gather = manipulation.gather
    T.cast = manipulation.cast
    T.astype = manipulation.cast
    T.expand = manipulation.expand
    T.tile = manipulation.tile
    T.split = manipulation.split
    T.chunk = manipulation.chunk
    T.concat = staticmethod(manipulation.concat)
    T.equal = logic.equal
    T.allclose = math.allclose


_patch_tensor()
