"""Op-application helpers.

TPU-native counterpart of the reference's PHI kernel dispatch
(``paddle/phi/api/lib/kernel_dispatch.cc`` + ``kernel_factory.h:324``): here
"kernel selection" collapses — every op is one pure jax function and XLA owns
device placement/fusion. What remains is the uniform glue: normalize inputs to
Tensors, route through the autograd tape (autograd/engine.py:apply_op), and
keep python scalars as static attrs so they compile into the XLA program
instead of becoming device transfers.
"""
from __future__ import annotations

import numbers

import jax.numpy as jnp
import numpy as np

from ..autograd.engine import apply_op
from ..tensor import Tensor


def ensure_tensor(x, ref: Tensor = None) -> Tensor:
    if isinstance(x, Tensor):
        return x
    if isinstance(x, numbers.Number) or isinstance(x, (np.ndarray, list, tuple)):
        arr = np.asarray(x)
        if ref is not None and arr.dtype in (np.float64, np.int64) and np.issubdtype(
            np.asarray(ref._value).dtype if not hasattr(ref._value, "dtype") else ref._value.dtype,
            np.inexact,
        ):
            arr = arr.astype(ref._value.dtype)
        return Tensor(jnp.asarray(arr))
    return Tensor(jnp.asarray(x))


def unary(fn, x, attrs=None, differentiable=True, name=""):
    x = ensure_tensor(x)
    return apply_op(fn, [x], attrs, differentiable=differentiable, name=name or fn.__name__)


def binary(fn, x, y, attrs=None, differentiable=True, name=""):
    """Binary op; python scalars stay scalars (weak-typed, no promotion surprises)."""
    if isinstance(x, Tensor) and isinstance(y, numbers.Number):
        return apply_op(lambda a: fn(a, y, **(attrs or {})), [x], None,
                        differentiable=differentiable, name=name or fn.__name__)
    if isinstance(y, Tensor) and isinstance(x, numbers.Number):
        return apply_op(lambda b: fn(x, b, **(attrs or {})), [y], None,
                        differentiable=differentiable, name=name or fn.__name__)
    xt = ensure_tensor(x, ref=y if isinstance(y, Tensor) else None)
    yt = ensure_tensor(y, ref=x if isinstance(x, Tensor) else None)
    return apply_op(fn, [xt, yt], attrs, differentiable=differentiable, name=name or fn.__name__)
