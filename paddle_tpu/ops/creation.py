"""Tensor creation ops (reference: python/paddle/tensor/creation.py,
phi kernels full/empty/arange/eye/tril/triu)."""
from __future__ import annotations

import jax.numpy as jnp

from .. import dtypes
from ..autograd.engine import apply_op
from ..tensor import Tensor, to_tensor
from ._apply import ensure_tensor, unary

__all__ = [
    "to_tensor", "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "empty_like", "arange", "linspace", "logspace", "eye",
    "diag", "diagflat", "tril", "triu", "meshgrid", "assign", "clone",
    "tril_indices", "triu_indices", "complex",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy().reshape(-1))
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s) for s in shape)


def zeros(shape, dtype="float32", name=None):
    return Tensor(jnp.zeros(_shape(shape), dtypes.convert_dtype(dtype)))


def ones(shape, dtype="float32", name=None):
    return Tensor(jnp.ones(_shape(shape), dtypes.convert_dtype(dtype)))


def full(shape, fill_value, dtype="float32", name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return Tensor(jnp.full(_shape(shape), fill_value, dtypes.convert_dtype(dtype)))


def empty(shape, dtype="float32", name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.zeros_like(x._value, dtype=dtypes.convert_dtype(dtype)))


def ones_like(x, dtype=None, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.ones_like(x._value, dtype=dtypes.convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.full_like(x._value, fill_value, dtype=dtypes.convert_dtype(dtype)))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v

    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = "int64" if all(isinstance(v, int) for v in (start, end, step)) else "float32"
    return Tensor(jnp.arange(start, end, step, dtype=dtypes.convert_dtype(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v

    return Tensor(
        jnp.linspace(_v(start), _v(stop), int(_v(num)), dtype=dtypes.convert_dtype(dtype or "float32"))
    )


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v

    return Tensor(
        jnp.logspace(_v(start), _v(stop), int(_v(num)), base=_v(base),
                     dtype=dtypes.convert_dtype(dtype or "float32"))
    )


def eye(num_rows, num_columns=None, dtype="float32", name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=dtypes.convert_dtype(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    x = ensure_tensor(x)

    def fn(a):
        if a.ndim == 1 and padding_value != 0:
            n = a.shape[0] + abs(offset)
            base = jnp.full((n, n), padding_value, a.dtype)
            return base.at[jnp.arange(a.shape[0]), jnp.arange(a.shape[0]) + offset].set(a) \
                if offset >= 0 else base.at[jnp.arange(a.shape[0]) - offset, jnp.arange(a.shape[0])].set(a)
        return jnp.diag(a, k=offset)

    return unary(fn, x, name="diag")


def diagflat(x, offset=0, name=None):
    return unary(lambda a: jnp.diagflat(a, k=offset), x, name="diagflat")


def tril(x, diagonal=0, name=None):
    return unary(lambda a: jnp.tril(a, k=diagonal), x, name="tril")


def triu(x, diagonal=0, name=None):
    return unary(lambda a: jnp.triu(a, k=diagonal), x, name="triu")


def tril_indices(row, col, offset=0, dtype="int64", name=None):
    r, c = jnp.tril_indices(row, k=offset, m=col)
    dt = dtypes.convert_dtype(dtype)
    return Tensor(jnp.stack([r.astype(dt), c.astype(dt)]))


def triu_indices(row, col=None, offset=0, dtype="int64", name=None):
    r, c = jnp.triu_indices(row, k=offset, m=col if col is not None else row)
    dt = dtypes.convert_dtype(dtype)
    return Tensor(jnp.stack([r.astype(dt), c.astype(dt)]))


def meshgrid(*args, name=None):
    ts = [ensure_tensor(a) for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    return apply_op(lambda *arrs: tuple(jnp.meshgrid(*arrs, indexing="ij")), ts, name="meshgrid")


def assign(x, output=None):
    """reference: paddle.assign (copy)."""
    x = ensure_tensor(x)
    out = unary(lambda a: a + 0 if a.dtype != jnp.bool_ else a, x, name="assign")
    if output is not None:
        output._set_value(out._value)
        return output
    return out


def clone(x, name=None):
    return assign(x)


def complex(real, imag, name=None):
    import jax.lax

    from ._apply import binary

    return binary(lambda r, i: jax.lax.complex(r, i), real, imag, name="complex")
