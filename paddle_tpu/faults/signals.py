"""Shared signal-handler scope — ONE install/uninstall discipline for
every clean-exit path in the package.

Training (``CheckpointManager.save_on_signal``: SIGTERM → checkpoint →
exit 0) and serving (``Router.install_signal_handlers``: SIGTERM →
drain → seal-WAL → exit 0) react to the same preemption notice; before
this module each grew its own handler bookkeeping. The factored core is
deliberately tiny: :func:`install_signal_handler` snapshots the previous
handlers and returns a :class:`SignalScope` whose ``uninstall()`` is
IDEMPOTENT and swallows the only two errors restoration can
legitimately hit (not the main thread / interpreter tearing down) —
the part that is easy to get subtly wrong twice.

Scopes nest LIFO like the handlers they shadow: installing a second
scope snapshots the first's handler, and uninstalling in reverse order
restores the chain exactly (the double-install regression test in
tests/test_wal.py pins this). Stdlib-only, like the rest of
``paddle_tpu.faults``.
"""
from __future__ import annotations

import signal as _signal
from typing import Callable, Dict, Tuple

__all__ = ["SignalScope", "install_signal_handler"]


class SignalScope:
    """Uninstaller for a batch of installed signal handlers.

    ``uninstall()`` restores the handlers that were live at install
    time, exactly once — a second call is a no-op (the snapshot is
    consumed), and restoration failures that only mean "this thread/
    interpreter can no longer touch signals" (ValueError, OSError) are
    swallowed: teardown must never raise out of a ``finally``. Also a
    context manager (``__exit__`` uninstalls)."""

    def __init__(self, prev: Dict):
        self._prev = prev

    def uninstall(self) -> None:
        prev, self._prev = self._prev, {}
        for sig, handler in prev.items():
            try:
                _signal.signal(sig, handler)
            except (ValueError, OSError):  # not main thread / torn down
                pass

    def __enter__(self) -> "SignalScope":
        return self

    def __exit__(self, *exc) -> None:
        self.uninstall()


def install_signal_handler(handler: Callable,
                           signals: Tuple = (_signal.SIGTERM,)
                           ) -> SignalScope:
    """Install ``handler(signum, frame)`` for each signal in ``signals``
    and return the :class:`SignalScope` that restores the previous
    handlers. Main-thread only, like any Python signal handler. The
    handler owns its exit semantics (checkpoint-then-``sys.exit(0)``,
    drain-then-seal, ...); this function owns only the install/restore
    bookkeeping, so every caller gets the same idempotent teardown."""
    scope = SignalScope({})
    for sig in signals:
        scope._prev[sig] = _signal.signal(sig, handler)
    return scope
