"""Train sentinel: anomaly detection + automatic rollback-and-skip.

The training-side twin of the serving resilience layer (docs/RESILIENCE.md
"Self-healing training"): a llama7b-scale run must not burn a day of TPU
time because one poisoned batch sent the loss to NaN at 3am. The sentinel
watches per-step health scalars — loss, global grad-norm, finite flags —
that the train step already produces (one stacked host fetch, the same
sync the loss read costs; zero extra compiles), and answers every step
with a deterministic verdict:

- ``OK``       apply the update; after a healthy window, mark the state
               *last-known-good* (in-memory snapshot, and a committed
               ``CheckpointManager`` step when one is bound);
- ``SKIP``     suppress the update (the optimizer's ``_found_inf`` skip
               path — the same traceable no-op GradScaler uses) and
               advance data past the suspect batch;
- ``ROLLBACK`` restore the last-known-good step (checksum-verified
               ``CheckpointManager.restore`` when bound), quarantine the
               batch window consumed since the mark, and use the
               dataloader's sample-exact position to skip deterministically
               past it; after ``lr_reramp_after`` rollbacks into the same
               region the skip widens and the LR re-ramps;
- abort        ``SentinelAbort`` carrying the anomaly journal once a
               region keeps failing (``abort_after_rollbacks``) or no
               rollback target exists.

Detectors (evaluated in order; the first match names the anomaly):

1. ``nonfinite_loss``  — loss is NaN/inf;
2. ``nonfinite_grad``  — the global grad-norm is non-finite;
3. ``loss_spike``      — robust z-score over a rolling median/MAD window
                         exceeds ``z_threshold`` (median/MAD, not
                         mean/std: a spike must not inflate its own
                         baseline);
4. ``grad_spike``      — same statistic over the grad-norm series;
5. ``divergence``      — the loss EWMA exceeds ``divergence_factor`` ×
                         the best (lowest) EWMA seen — the slow-creep
                         failure no single-step test catches.

Anomalous steps never enter the rolling baselines, so a burst cannot
teach the detector that burst losses are normal.

The journal and the full escalation state ride ``state_dict()`` — pure
python scalars, so inside a checkpoint they land in ``scalars.json`` and
a preempted run resumes mid-incident with its memory intact (counters,
region rollback counts, quarantine bookkeeping).

Wiring: ``Model.fit(sentinel=TrainSentinel(...))`` guards the hapi loop;
``sentinel.guard(step_fn)`` guards any custom loop (the wrapper owns
backward + optimizer + rollback). A bound :class:`StepWatchdog` makes a
hung/over-threshold train step trip ``health()`` (→ ``/healthz`` via
``MetricsServer(health_cb=sentinel.health)``) and — when a manager is
bound — checkpoint-and-abort so the scheduler can restart the job.

Module imports stay stdlib + paddle_tpu.metrics (the faults-package
contract); jax / checkpoint / tensor machinery is imported lazily inside
the methods that train loops call.
"""
from __future__ import annotations

import json
import math
import statistics
from collections import deque
from typing import Any, Callable, Dict, List, NamedTuple, Optional

from .. import metrics
from .injection import declare_point, point
from .watchdog import StepWatchdog

__all__ = [
    "Action", "SentinelAbort", "SentinelConfig", "StepReport",
    "TrainSentinel",
]

declare_point(
    "train.step",
    "top of one sentinel-guarded train step (Model._sentinel_batch / "
    "sentinel.guard wrapper): delay_s simulates a hung step -> watchdog; "
    "raise_ kills the step")
declare_point(
    "train.grads",
    "after backward, before the health-scalar fetch in a guarded step: "
    "call= poisons gradients (seeded NaN injection -> skip/rollback "
    "drills, tools/chaos_train.py scenarios 6-8)")

_REG = metrics.get_registry()
_M_ANOMALIES = _REG.counter(
    "paddle_tpu_train_anomalies_total",
    "Train-step anomalies detected by the sentinel", labels=("kind",))
_M_ROLLBACKS = _REG.counter(
    "paddle_tpu_train_rollbacks_total",
    "Sentinel rollbacks to the last-known-good step")
_M_SKIPPED = _REG.counter(
    "paddle_tpu_train_skipped_batches_total",
    "Batches whose update the sentinel suppressed (skip-batch) or "
    "quarantined past (rollback skip-forward)")
_M_LAST_GOOD = _REG.gauge(
    "paddle_tpu_train_last_good_step",
    "Newest step marked last-known-good by the sentinel")
_M_LOSS = _REG.histogram(
    "paddle_tpu_train_loss",
    "Per-step training loss seen by the sentinel (finite samples only)")
_M_GNORM = _REG.histogram(
    "paddle_tpu_train_grad_norm",
    "Per-step global gradient norm seen by the sentinel (finite only)")
_M_RERAMPS = _REG.counter(
    "paddle_tpu_train_lr_reramps_total",
    "LR re-ramps triggered by repeated rollbacks into one region")
_M_ABORTS = _REG.counter(
    "paddle_tpu_train_aborts_total",
    "Sentinel aborts by reason", labels=("reason",))
_M_STALLS = _REG.counter(
    "paddle_tpu_train_watchdog_trips_total",
    "Train-step watchdog trip episodes (hung/over-threshold steps)")


class Action:
    """Sentinel verdicts (plain strings so they journal/JSON cleanly)."""

    OK = "ok"
    SKIP = "skip"
    ROLLBACK = "rollback"


class SentinelAbort(RuntimeError):
    """The sentinel gave up: the escalation ladder is exhausted (or a
    watchdog stall demanded checkpoint-and-exit). Carries the anomaly
    ``journal`` (most recent last) and the machine-readable ``reason`` —
    the actionable incident report, not just a traceback."""

    def __init__(self, reason: str, journal: List[Dict], detail: str = ""):
        self.reason = str(reason)
        self.journal = list(journal)
        tail = journal[-3:]
        msg = f"train sentinel abort ({reason})"
        if detail:
            msg += f": {detail}"
        if tail:
            msg += "; journal tail: " + json.dumps(tail)
        super().__init__(msg)


class StepReport(NamedTuple):
    """What one ``guard()``-wrapped step did."""

    action: str           # Action.OK / SKIP / ROLLBACK
    loss: float
    grad_norm: float
    rolled_back: bool     # True => the data iterator must be rebuilt
    info: Optional[Dict]  # rollback details (target step, skipped, ...)


class SentinelConfig:
    """Detector + escalation knobs (all deterministic; no wall clocks).

    ``healthy_window`` consecutive healthy steps arm a last-known-good
    mark; ``mark_every`` (default ``healthy_window``) is the minimum step
    spacing between marks. ``skip_limit`` consecutive anomalies are
    handled as skip-batch before escalating to rollback; the
    ``lr_reramp_after``-th rollback into the same region re-ramps the LR
    (float LRs only) and widens the quarantine skip by ``widen_factor``;
    the ``abort_after_rollbacks``-th raises :class:`SentinelAbort`.
    """

    def __init__(self, *, window: int = 32, min_history: int = 8,
                 z_threshold: float = 8.0, grad_z_threshold: float = 8.0,
                 ewma_alpha: float = 0.05, divergence_factor: float = 3.0,
                 healthy_window: int = 8, mark_every: Optional[int] = None,
                 skip_limit: int = 2, lr_reramp_after: int = 2,
                 abort_after_rollbacks: int = 4, reramp_factor: float = 0.1,
                 reramp_steps: int = 20, widen_factor: int = 2,
                 quarantine_pad: int = 0, max_unrecoverable_skips: int = 8,
                 journal_limit: int = 256, abort_on_stall: bool = True):
        if window < 2 or min_history < 2:
            raise ValueError("window and min_history must be >= 2")
        if healthy_window < 1 or skip_limit < 0:
            raise ValueError("healthy_window >= 1 and skip_limit >= 0")
        if not 0.0 < ewma_alpha <= 1.0:
            raise ValueError("ewma_alpha must be in (0, 1]")
        if divergence_factor <= 1.0:
            # factor 1.0 makes the divergence margin zero: every
            # fluctuation above the best-ever EWMA would be an incident
            raise ValueError("divergence_factor must be > 1.0")
        if abort_after_rollbacks < 1 or lr_reramp_after < 1:
            raise ValueError("rollback escalation thresholds must be >= 1")
        if widen_factor < 1 or reramp_steps < 1:
            raise ValueError("widen_factor and reramp_steps must be >= 1")
        if not 0.0 < reramp_factor <= 1.0:
            raise ValueError("reramp_factor must be in (0, 1]")
        self.window = int(window)
        self.min_history = int(min_history)
        self.z_threshold = float(z_threshold)
        self.grad_z_threshold = float(grad_z_threshold)
        self.ewma_alpha = float(ewma_alpha)
        self.divergence_factor = float(divergence_factor)
        self.healthy_window = int(healthy_window)
        self.mark_every = int(mark_every if mark_every is not None
                              else healthy_window)
        self.skip_limit = int(skip_limit)
        self.lr_reramp_after = int(lr_reramp_after)
        self.abort_after_rollbacks = int(abort_after_rollbacks)
        self.reramp_factor = float(reramp_factor)
        self.reramp_steps = int(reramp_steps)
        self.widen_factor = int(widen_factor)
        self.quarantine_pad = int(quarantine_pad)
        self.max_unrecoverable_skips = int(max_unrecoverable_skips)
        self.journal_limit = int(journal_limit)
        self.abort_on_stall = bool(abort_on_stall)


def _finite(v) -> bool:
    try:
        return math.isfinite(float(v))
    except (TypeError, ValueError):
        return False


def _jsonable(v):
    """Journal values must survive strict JSON (scalars.json): non-finite
    floats become their repr strings."""
    if isinstance(v, float) and not math.isfinite(v):
        return repr(v)
    return v


def _robust_z(value: float, series) -> float:
    """|value - median| / (1.4826·MAD + floors): outlier-resistant scale,
    with a relative + absolute floor so a near-constant baseline (MAD≈0)
    doesn't turn numeric dust into an incident."""
    med = statistics.median(series)
    mad = statistics.median([abs(x - med) for x in series])
    scale = 1.4826 * mad + 1e-3 * abs(med) + 1e-12
    return abs(value - med) / scale


class TrainSentinel:
    """Guards a train loop: detect → skip → rollback-and-skip → re-ramp →
    abort, with exactly-once accounting and a persistent journal. See the
    module docstring for the state machine; docs/RESILIENCE.md for the
    operator view."""

    OK = Action.OK
    SKIP = Action.SKIP
    ROLLBACK = Action.ROLLBACK

    def __init__(self, config: Optional[SentinelConfig] = None,
                 watchdog: Optional[StepWatchdog] = None, **overrides):
        if config is not None and overrides:
            raise ValueError("pass config= or keyword overrides, not both")
        self.config = config or SentinelConfig(**overrides)
        self.watchdog = watchdog
        # bound training objects (bind()); all optional until rollback
        self._model = None
        self._optimizer = None
        self._dataloader = None
        self._manager = None
        # detector baselines
        c = self.config
        self._loss_win: deque = deque(maxlen=c.window)
        self._gnorm_win: deque = deque(maxlen=c.window)
        self._ewma: Optional[float] = None
        self._best_ewma: Optional[float] = None
        # escalation state
        self.global_step = 0
        self._epoch: Optional[int] = None
        self._healthy_streak = 0
        self._anomaly_streak = 0
        self._batches_since_mark = 0
        self._mark: Optional[Dict[str, Any]] = None
        self._last_good_step: Optional[int] = None
        self._region_step: Optional[int] = None
        self._region_rollbacks = 0
        self._reramp: Optional[Dict[str, float]] = None
        self._pending_mark = False
        # exactly-once python mirrors of the process-wide counters (the
        # registry is shared across sentinels; tests and state_dict need
        # THIS incident's numbers)
        self.anomalies: Dict[str, int] = {}
        self.rollbacks = 0
        self.skipped_batches = 0
        self.aborts = 0
        self.stalls = 0
        self._journal: List[Dict] = []

    # ------------------------------------------------------------ binding
    def bind(self, model=None, optimizer=None, dataloader=None,
             manager=None, prune_future: bool = True) -> "TrainSentinel":
        """Attach the live training objects rollback needs. With a
        ``CheckpointManager``, marks become committed steps and rollback
        restores checksum-verified; ``prune_future`` deletes committed
        marks AHEAD of ``self.global_step`` — they belong to a timeline a
        coarser resume (fit's epoch-granular restore) already rewound
        behind, and restoring one would fast-forward params into the
        future of the data stream."""
        self._model = model if model is not None else self._model
        self._optimizer = (optimizer if optimizer is not None
                           else self._optimizer)
        self._dataloader = (dataloader if dataloader is not None
                            else self._dataloader)
        if manager is not None:
            self._manager = manager
            if prune_future:
                for s in manager.all_steps():
                    if s > self.global_step:
                        manager.delete_step(s)
            # restore-then-bind (fit's order): set_state_dict ran without a
            # manager, so the newest committed mark must be re-acquired
            # here or a mid-incident resume would have no rollback target
            if self._mark is None:
                self._reacquire_mark()
        return self

    def _reacquire_mark(self) -> None:
        if self._manager is None:
            return
        steps = [s for s in self._manager.all_steps()
                 if s <= self.global_step]
        if steps:
            # epoch=None: which epoch the committed mark belongs to is
            # unknown until its state is read — rollback() derives the
            # true epoch from the RESTORED dataloader, never from here
            self._mark = {"step": steps[-1], "epoch": None,
                          "data": None, "state": None}

    # ----------------------------------------------------- step protocol
    def begin_step(self) -> None:
        """Bracket the guarded step for the watchdog (any-thread
        ``stalled_now`` makes a live hang visible to ``health()``)."""
        if self.watchdog is not None:
            self.watchdog.begin_step()

    def observe(self, loss, grad_norm=None, grads_finite: bool = True,
                ) -> str:
        """One step's verdict from its health scalars. Detection runs
        BEFORE the update is applied, so ``SKIP`` can suppress it; the
        caller reports back through :meth:`after_update` (OK/SKIP) or
        :meth:`rollback` (ROLLBACK)."""
        if self.watchdog is not None:
            if self.watchdog.end_step():
                self._on_stall()
        loss = float(loss)
        gnorm = None if grad_norm is None else float(grad_norm)
        if _finite(loss):
            _M_LOSS.observe(loss)
        if gnorm is not None and _finite(gnorm):
            _M_GNORM.observe(gnorm)

        kind = self._detect(loss, gnorm, grads_finite)
        if kind is None:
            self._note_healthy(loss, gnorm)
            return Action.OK
        return self._escalate(kind, loss, gnorm)

    def after_update(self, applied: bool) -> None:
        """Advance the step clock after the caller applied (OK) or
        suppressed (SKIP) the update; commits a pending last-known-good
        mark — post-update state, which is what rollback must restore."""
        self.global_step += 1
        self._batches_since_mark += 1
        if applied and self._pending_mark:
            self._pending_mark = False
            self.mark()

    # ---------------------------------------------------------- detectors
    def _detect(self, loss: float, gnorm: Optional[float],
                grads_finite: bool) -> Optional[str]:
        c = self.config
        if not _finite(loss):
            return "nonfinite_loss"
        if not grads_finite or (gnorm is not None and not _finite(gnorm)):
            return "nonfinite_grad"
        if (len(self._loss_win) >= c.min_history
                and _robust_z(loss, self._loss_win) > c.z_threshold):
            return "loss_spike"
        if (gnorm is not None and len(self._gnorm_win) >= c.min_history
                and _robust_z(gnorm, self._gnorm_win) > c.grad_z_threshold):
            return "grad_spike"
        if self._best_ewma is not None:
            tentative = ((1.0 - c.ewma_alpha) * self._ewma
                         + c.ewma_alpha * loss)
            # margin formulation (== factor × best for positive best):
            # stays sound when the loss is negative or bottoms near zero —
            # `tentative > factor * best` flips meaning for best <= 0
            best = self._best_ewma
            margin = (c.divergence_factor - 1.0) * max(abs(best), 1e-6)
            if tentative > best + margin:
                return "divergence"
        return None

    def _note_healthy(self, loss: float, gnorm: Optional[float]) -> None:
        c = self.config
        self._loss_win.append(loss)
        if gnorm is not None:
            self._gnorm_win.append(gnorm)
        self._ewma = (loss if self._ewma is None
                      else (1.0 - c.ewma_alpha) * self._ewma
                      + c.ewma_alpha * loss)
        if len(self._loss_win) >= c.min_history:
            self._best_ewma = (self._ewma if self._best_ewma is None
                               else min(self._best_ewma, self._ewma))
        self._anomaly_streak = 0
        self._healthy_streak += 1
        self._tick_reramp()
        if (self._healthy_streak >= c.healthy_window
                and self._batches_since_mark + 1 >= c.mark_every):
            # +1: the mark lands in after_update, once THIS step applied
            self._pending_mark = True

    # --------------------------------------------------------- escalation
    def _escalate(self, kind: str, loss: float,
                  gnorm: Optional[float]) -> str:
        c = self.config
        self._healthy_streak = 0
        self._anomaly_streak += 1
        self._pending_mark = False
        _M_ANOMALIES.labels(kind=kind).inc()
        self.anomalies[kind] = self.anomalies.get(kind, 0) + 1
        entry = self._journal_event(
            "anomaly", kind=kind, loss=_jsonable(loss),
            grad_norm=_jsonable(gnorm), streak=self._anomaly_streak)
        if self._anomaly_streak <= c.skip_limit:
            entry["action"] = Action.SKIP
            self.skipped_batches += 1
            _M_SKIPPED.inc()
            return Action.SKIP
        if not self._can_rollback():
            if self._anomaly_streak >= c.skip_limit + c.max_unrecoverable_skips:
                entry["action"] = "abort"
                self._abort("no_rollback_target",
                            "anomalies persist and no last-known-good mark "
                            "exists to roll back to")
            entry["action"] = Action.SKIP
            self.skipped_batches += 1
            _M_SKIPPED.inc()
            return Action.SKIP
        target = self._mark["step"]
        if (self._region_step == target
                and self._region_rollbacks >= c.abort_after_rollbacks):
            entry["action"] = "abort"
            self._abort("rollback_limit",
                        f"{self._region_rollbacks} rollbacks into the "
                        f"region after step {target} did not clear the "
                        f"anomaly")
        entry["action"] = Action.ROLLBACK
        return Action.ROLLBACK

    def _can_rollback(self) -> bool:
        return self._mark is not None

    def rollback(self) -> Dict[str, Any]:
        """Restore the last-known-good mark and queue a deterministic
        skip past the quarantined batch window. Returns
        ``{"step", "epoch", "skipped", "region_rollbacks"}`` — the caller
        must rebuild its data iterator (fit restarts the epoch loop;
        ``guard()`` reports ``rolled_back=True``)."""
        if not self._can_rollback():
            self._abort("no_rollback_target",
                        "rollback requested with no mark")
        c = self.config
        mark = self._mark
        target = int(mark["step"])
        # restore FIRST: verification may fall back to an older committed
        # step, and every piece of bookkeeping below must key on the step
        # actually restored, not the one we hoped for
        actual = self._restore_mark_state(target, mark)
        if self._region_step == actual:
            self._region_rollbacks += 1
        else:
            self._region_step = actual
            self._region_rollbacks = 1
        # quarantine window: every batch consumed since the TARGET mark,
        # plus the batch that triggered this verdict (after_update never
        # ran for it), plus — on a fallback restore — the one-batch-per-
        # step stretch between the actual and target marks, so the skip
        # still lands past the anomaly from the older data position
        window = self._batches_since_mark + 1 + max(0, target - actual)
        # the lr_reramp_after-th rollback into one region starts widening:
        # the region is visibly larger than the window observed so far
        widen = c.widen_factor ** max(
            0, self._region_rollbacks - c.lr_reramp_after + 1)
        skip = window * widen + c.quarantine_pad

        if self._dataloader is not None and hasattr(self._dataloader,
                                                    "advance_batches"):
            self._dataloader.advance_batches(skip)
        self.rollbacks += 1
        _M_ROLLBACKS.inc()
        self.skipped_batches += skip
        _M_SKIPPED.inc(skip)
        reramped = False
        if self._region_rollbacks >= c.lr_reramp_after:
            reramped = self._start_reramp()
        # the restored DATALOADER knows the true epoch the mark was taken
        # in — a mark re-acquired after resume carries epoch=None, and
        # stamping the resume-time epoch would desync fit's epoch counter
        # from the replayed data stream
        mark_epoch = mark.get("epoch")
        if self._dataloader is not None and hasattr(self._dataloader,
                                                    "state_dict"):
            try:
                mark_epoch = int(
                    self._dataloader.state_dict().get("epoch", mark_epoch))
            except Exception:
                pass
        info = {
            "step": actual,
            "epoch": mark_epoch,
            "skipped": int(skip),
            "region_rollbacks": self._region_rollbacks,
            "reramped": reramped,
        }
        self._journal_event(
            "rollback", target=actual, window=int(window),
            skipped=int(skip), region_rollbacks=self._region_rollbacks,
            reramped=reramped, data=mark.get("data"),
            fallback_from=(target if actual != target else None))
        self.global_step = actual
        self._batches_since_mark = 0
        self._anomaly_streak = 0
        self._healthy_streak = 0
        self._pending_mark = False
        return info

    def _restore_mark_state(self, target: int, mark: Dict) -> int:
        """Restore the mark's state into the bound objects; returns the
        step ACTUALLY restored (an older one when the target's committed
        step failed verification and restore fell back)."""
        from ..checkpoint import restore_train_state

        state, actual = None, target
        if self._manager is not None:
            try:
                state, _ = self._manager.restore(target)
            except Exception:
                # the mark's committed step failed verification (or went
                # missing): fall back to the newest valid older step,
                # then to the in-memory snapshot
                try:
                    state, actual = self._manager.restore()
                    mark["step"] = actual
                    mark["data"] = None  # position belonged to the target
                except Exception:
                    state = None
        if state is None:
            state = mark.get("state")
        if state is None:
            self._abort("rollback_failed",
                        f"no restorable state for mark step {target}")
        restore_train_state(state, model=self._model,
                            optimizer=self._optimizer,
                            dataloader=self._dataloader)
        return actual

    def _start_reramp(self) -> bool:
        opt = self._optimizer
        if opt is None:
            return False
        restarted = self._reramp is not None
        try:
            # a ramp already in flight keeps its ORIGINAL base — repeated
            # rollbacks must restart the ramp, not compound the reduction
            base = (self._reramp["base"] if restarted else opt.get_lr())
            opt.set_lr(base * self.config.reramp_factor)
        except (RuntimeError, AttributeError):
            # LRScheduler-driven optimizer: the schedule owns the LR; the
            # widened skip still applies, journal records the decision
            self._journal_event("lr_reramp_skipped",
                                reason="scheduler-driven lr")
            return False
        self._reramp = {"base": float(base),
                        "remaining": self.config.reramp_steps,
                        "total": self.config.reramp_steps}
        if not restarted:  # a restart extends THIS ramp, not a new event
            _M_RERAMPS.inc()
        self._journal_event("lr_reramp", base=float(base),
                            factor=self.config.reramp_factor,
                            steps=self.config.reramp_steps,
                            restarted=restarted or None)
        return True

    def _tick_reramp(self) -> None:
        r = self._reramp
        if r is None or self._optimizer is None:
            return
        r["remaining"] -= 1
        frac = 1.0 - max(0, r["remaining"]) / r["total"]
        f = self.config.reramp_factor
        try:
            self._optimizer.set_lr(r["base"] * (f + (1.0 - f) * frac))
        except (RuntimeError, AttributeError):
            self._reramp = None
            return
        if r["remaining"] <= 0:
            self._reramp = None

    def _abort(self, reason: str, detail: str = "") -> None:
        self.aborts += 1
        _M_ABORTS.labels(reason=reason).inc()
        self._journal_event("abort", reason=reason, detail=detail)
        raise SentinelAbort(reason, self._journal, detail)

    # --------------------------------------------------------------- marks
    def mark(self, force: bool = False) -> Optional[int]:
        """Capture the CURRENT state as last-known-good. Called
        automatically after a healthy window; ``force=True`` marks
        regardless (fit uses it at epoch starts via :meth:`note_epoch`).
        Returns the marked step, or None when nothing is bound to
        capture."""
        if self._model is None and self._optimizer is None:
            return None
        if not force and self._anomaly_streak:
            return None
        from ..checkpoint import capture_train_state

        # lazy per-param accumulators must exist in the snapshot: a mark
        # taken before the first update (the step-0 init mark) would
        # otherwise capture an EMPTY optimizer state, and restoring it
        # would leave post-mark moments in place (set_state_dict only
        # overwrites keys present in the state)
        if self._optimizer is not None and hasattr(
                self._optimizer, "_materialize_accumulators"):
            try:
                self._optimizer._materialize_accumulators()
            except Exception:
                pass
        state = capture_train_state(
            model=self._model, optimizer=self._optimizer,
            dataloader=self._dataloader, step=self.global_step,
            sentinel=self)
        data_pos = None
        if self._dataloader is not None and hasattr(self._dataloader,
                                                    "state_dict"):
            data_pos = dict(self._dataloader.state_dict())
        mark: Dict[str, Any] = {"step": self.global_step,
                                "epoch": self._epoch, "data": data_pos,
                                "state": None}
        if self._manager is not None:
            try:
                self._manager.save_if_absent(self.global_step, state)
            except Exception:
                # durability is best-effort; the in-memory snapshot keeps
                # rollback possible even when the disk is unhappy
                mark["state"] = _detach_state(state)
        else:
            mark["state"] = _detach_state(state)
        self._mark = mark
        self._last_good_step = self.global_step
        _M_LAST_GOOD.set(self.global_step)
        self._batches_since_mark = 0
        return self.global_step

    def note_epoch(self, epoch: int) -> None:
        """fit's epoch-boundary hook: records the epoch for journal/mark
        bookkeeping and takes a mark when eligible — at step 0 the init
        state is trivially good; later boundaries mark only when the
        healthy-window contract is met (mid-incident boundaries keep the
        previous mark, so a rollback may legitimately land in the prior
        epoch)."""
        self._epoch = int(epoch)
        if self.global_step == 0 and self._mark is None:
            self.mark(force=True)
        elif (self._anomaly_streak == 0
              and self._healthy_streak >= self.config.healthy_window):
            self.mark()

    @property
    def last_good_step(self) -> Optional[int]:
        return self._last_good_step

    # ------------------------------------------------------------ journal
    def _journal_event(self, event: str, **fields) -> Dict:
        entry = {"event": event, "step": int(self.global_step)}
        if self._epoch is not None:
            entry["epoch"] = int(self._epoch)
        if (self._dataloader is not None and "data" not in fields
                and hasattr(self._dataloader, "state_dict")):
            try:
                entry["data"] = dict(self._dataloader.state_dict())
            except Exception:
                pass
        entry.update({k: _jsonable(v) for k, v in fields.items()
                      if v is not None})
        self._journal.append(entry)
        if len(self._journal) > self.config.journal_limit:
            del self._journal[:-self.config.journal_limit]
        return entry

    def journal(self) -> List[Dict]:
        """The incident log, oldest first (bounded to
        ``journal_limit``)."""
        return [dict(e) for e in self._journal]

    # ----------------------------------------------------- watchdog/health
    def _on_stall(self) -> None:
        self.stalls += 1
        _M_STALLS.inc()
        self._journal_event("stall",
                            threshold_s=self.watchdog.stall_threshold_s)
        if not self.config.abort_on_stall:
            return
        if self._manager is not None and (self._model is not None
                                          or self._optimizer is not None):
            # checkpoint-and-exit: persist the CURRENT state (pre-verdict
            # params are one over-long step past last-known-good, not
            # anomalous) so the restarted job loses nothing
            from ..checkpoint import capture_train_state

            try:
                self._manager.save_if_absent(
                    self.global_step,
                    capture_train_state(
                        model=self._model, optimizer=self._optimizer,
                        dataloader=self._dataloader, step=self.global_step,
                        sentinel=self))
            except Exception:
                pass
        self._abort("stall", "train step exceeded the watchdog threshold")

    def health(self) -> Dict[str, Any]:
        """``MetricsServer(health_cb=sentinel.health)`` payload: degraded
        while a step is live-hung / the watchdog is tripped / an incident
        is open."""
        degraded = bool(self._anomaly_streak)
        if self.watchdog is not None:
            degraded = degraded or self.watchdog.status() != "ok"
        return {
            "status": "degraded" if degraded else "ok",
            "last_good_step": self._last_good_step,
            "step": self.global_step,
            "anomaly_streak": self._anomaly_streak,
            "rollbacks": self.rollbacks,
            "skipped_batches": self.skipped_batches,
        }

    # ------------------------------------------------------- guard wrapper
    def guard(self, step_fn: Callable, optimizer=None) -> Callable:
        """Wrap a custom train step. ``step_fn(*args, **kw)`` runs
        forward + loss and returns the scalar loss Tensor (grads NOT yet
        computed, optimizer NOT yet stepped) — the wrapper owns backward,
        the single health-scalar fetch, the verdict, the (possibly
        suppressed) optimizer step, and rollback. Returns a
        :class:`StepReport`; ``report.rolled_back`` means the caller must
        rebuild its data iterator (the restored dataloader has the
        quarantine skip queued)."""
        opt = optimizer if optimizer is not None else self._optimizer
        if opt is None:
            raise ValueError("guard() needs an optimizer (argument or "
                             "bind(optimizer=...))")

        def guarded(*args, **kwargs) -> StepReport:
            self.begin_step()
            point("train.step")
            loss = step_fn(*args, **kwargs)
            if isinstance(loss, (tuple, list)):
                loss = loss[0]
            loss.backward()
            point("train.grads")
            loss_v, gnorm, finite = _grad_health(loss, opt)
            action = self.observe(loss_v, gnorm, grads_finite=finite)
            if action == Action.OK:
                opt.step()
                opt.clear_grad()
                self.after_update(True)
                return StepReport(action, loss_v, gnorm, False, None)
            if action == Action.SKIP:
                _suppress_update(opt)
                opt.clear_grad()
                self.after_update(False)
                return StepReport(action, loss_v, gnorm, False, None)
            opt.clear_grad()
            info = self.rollback()
            return StepReport(action, loss_v, gnorm, True, info)

        guarded.__name__ = getattr(step_fn, "__name__", "train_step")
        return guarded

    # --------------------------------------------------------- persistence
    def state_dict(self) -> Dict[str, Any]:
        """Pure-python scalars (one JSON blob), so inside a checkpoint the
        whole escalation state + journal land in ``scalars.json`` and a
        preempted run resumes mid-incident with exact counters."""
        payload = {
            "global_step": self.global_step,
            "epoch": self._epoch,
            "healthy_streak": self._healthy_streak,
            "anomaly_streak": self._anomaly_streak,
            "batches_since_mark": self._batches_since_mark,
            "last_good_step": self._last_good_step,
            "region_step": self._region_step,
            "region_rollbacks": self._region_rollbacks,
            "reramp": self._reramp,
            "loss_win": list(self._loss_win),
            "gnorm_win": list(self._gnorm_win),
            "ewma": self._ewma,
            "best_ewma": self._best_ewma,
            "anomalies": dict(self.anomalies),
            "rollbacks": self.rollbacks,
            "skipped_batches": self.skipped_batches,
            "aborts": self.aborts,
            "stalls": self.stalls,
            "journal": self._journal,
        }
        return {"version": 1, "json": json.dumps(payload)}

    def set_state_dict(self, state: Dict[str, Any]) -> None:
        payload = json.loads(state["json"]) if "json" in state else dict(state)
        c = self.config
        self.global_step = int(payload.get("global_step", 0))
        ep = payload.get("epoch")
        self._epoch = None if ep is None else int(ep)
        self._healthy_streak = int(payload.get("healthy_streak", 0))
        self._anomaly_streak = int(payload.get("anomaly_streak", 0))
        self._batches_since_mark = int(payload.get("batches_since_mark", 0))
        self._last_good_step = payload.get("last_good_step")
        self._region_step = payload.get("region_step")
        self._region_rollbacks = int(payload.get("region_rollbacks", 0))
        self._reramp = payload.get("reramp")
        self._loss_win = deque(payload.get("loss_win", ()), maxlen=c.window)
        self._gnorm_win = deque(payload.get("gnorm_win", ()),
                                maxlen=c.window)
        self._ewma = payload.get("ewma")
        self._best_ewma = payload.get("best_ewma")
        self.anomalies = dict(payload.get("anomalies", {}))
        self.rollbacks = int(payload.get("rollbacks", 0))
        self.skipped_batches = int(payload.get("skipped_batches", 0))
        self.aborts = int(payload.get("aborts", 0))
        self.stalls = int(payload.get("stalls", 0))
        self._journal = list(payload.get("journal", []))
        if self._last_good_step is not None:
            _M_LAST_GOOD.set(self._last_good_step)
        # marks are NOT serialized here (they are the checkpoints
        # themselves): a manager-bound resume re-acquires the newest
        # committed mark lazily; in-memory-only resume re-marks after the
        # next healthy window
        self._mark = None
        self._pending_mark = False
        self._reacquire_mark()

    load_state_dict = set_state_dict


def _detach_state(state):
    """Deep-detach a capture_train_state dict for an IN-MEMORY mark:
    ``model.state_dict()`` returns the LIVE Parameter objects, whose
    payload cell ``Optimizer.step`` mutates in place via ``_set_value`` —
    holding them directly would make rollback restore current params into
    themselves (a silent no-op). Wrapping the current (immutable) jax
    array in a fresh Tensor is a true point-in-time snapshot; non-tensor
    leaves (ints, floats, strings) are already immutable."""
    from ..tensor import Tensor

    def snap(v):
        if isinstance(v, dict):
            return {k: snap(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return type(v)(snap(x) for x in v)
        if hasattr(v, "_value"):
            return Tensor(v._value)
        return v

    return snap(state)


def _grad_health(loss, optimizer):
    """(loss, global grad-norm, grads_finite) with ONE host fetch: the
    scalars are stacked device-side, so guarding costs the same sync the
    loss read already pays. Lazy jax import keeps the faults package
    importable without it."""
    import jax.numpy as jnp
    import numpy as np

    sq = None
    for p in optimizer._parameter_list or []:
        if p.grad is None or getattr(p, "stop_gradient", False):
            continue
        g = p.grad._value.astype(jnp.float32)
        s = jnp.sum(g * g)
        sq = s if sq is None else sq + s
    gsq = sq if sq is not None else jnp.float32(0.0)
    lv = loss._value.astype(jnp.float32) if hasattr(loss, "_value") \
        else jnp.float32(loss)
    stats = jnp.stack([lv.reshape(()), jnp.sqrt(gsq),
                       jnp.isfinite(gsq).astype(jnp.float32)])
    host = np.asarray(stats, dtype=np.float64)
    return float(host[0]), float(host[1]), bool(host[2])


def _suppress_update(optimizer) -> None:
    """Skip-batch via the optimizer's own ``_found_inf`` no-op path (the
    traceable skip GradScaler uses), tagged so the AMP skip counter
    doesn't claim sentinel skips."""
    import jax.numpy as jnp

    from ..tensor import Tensor

    optimizer._found_inf = Tensor(jnp.bool_(True))
    optimizer._found_inf_origin = "sentinel"
    optimizer.step()
