"""paddle_tpu.faults — fault injection + the resilience primitives.

A serving system is only as good as its worst step: this package turns
failure modes into *tested contracts* (docs/RESILIENCE.md). Four pieces:

- **Fault points** (injection.py): production code marks failure-prone
  sites with ``faults.point("serving.kv_alloc")`` — free until a test
  arms a fault there with the ``faults.inject(...)`` context manager
  (raise-once / raise-every-N / latency / resource-exhaustion / host
  callback, on deterministic seeded schedules). Every firing counts in
  ``paddle_tpu_faults_injected_total{point}``.
- **retry** (retry.py): exponential backoff + seeded jitter, injectable
  sleep, optional deadline bound. The final failure re-raises unchanged.
- **Deadline** (deadline.py): an absolute time budget on an injectable
  clock — the currency of request timeouts and retry bounds.
- **LockSanitizer** (sanitizer.py): opt-in instrumented lock wrapper —
  per-thread acquisition stacks, live lock-order-inversion and
  non-reentrant re-acquisition detection, per-lock hold/wait
  histograms. The runtime half of tpulint's TPL007-009; chaos drills
  switch it on.
- **StepWatchdog** (watchdog.py): trips on an over-threshold engine
  step, detects live hangs from any thread (``stalled_now``), recovers
  after N healthy steps — the state behind ``/healthz`` degraded mode.
- **TrainSentinel** (sentinel.py): self-healing training — anomaly
  detectors over per-step health scalars (non-finite loss/grad, robust
  z-score spikes, divergence EWMA) feeding a deterministic escalation
  ladder: skip-batch → rollback to the last-known-good checkpoint with a
  quarantine skip-forward → LR re-ramp + widened skip → abort with an
  actionable journal. Wired into ``Model.fit(sentinel=...)``; guards any
  loop via ``sentinel.guard(step_fn)``.

Chaos drill in one breath:

    from paddle_tpu import faults

    with faults.inject("serving.decode_step", delay_s=0.05):
        engine.step()               # watchdog trips; /healthz -> 503
    engine.run()                    # recovers after healthy steps

Stdlib + paddle_tpu.metrics only — importable from every layer without
jax or import cycles, so tier-1 tests stay hermetic and fast.
"""
from .deadline import Deadline, DeadlineExceeded
from .injection import (CallbackError, FaultInjected, FaultSpec,
                        ResourceExhausted, active_faults, declare_point,
                        inject, known_points, point, reset)
from .retry import backoff_delays, retry
from .sanitizer import LockSanitizer, LockViolation
from .signals import SignalScope, install_signal_handler
from .sentinel import (Action, SentinelAbort, SentinelConfig, StepReport,
                       TrainSentinel)
from .watchdog import StepWatchdog

__all__ = [
    "Action", "CallbackError", "Deadline", "DeadlineExceeded",
    "FaultInjected", "FaultSpec", "LockSanitizer", "LockViolation",
    "ResourceExhausted", "SentinelAbort", "SentinelConfig", "SignalScope",
    "StepReport", "StepWatchdog", "TrainSentinel",
    "active_faults", "backoff_delays", "declare_point", "inject",
    "install_signal_handler", "known_points", "point", "reset", "retry",
]
