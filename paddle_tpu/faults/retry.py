"""Generic retry with exponential backoff + deterministic jitter.

The recovery half of the faults package: transient failures (a compile
hiccup, a flaky RPC, an injected drill) are retried on a seeded backoff
schedule — deterministic for a fixed seed, so chaos tests replay
bit-identically and never sleep wall-clock time they didn't budget
(``sleep=`` is injectable). A :class:`~.deadline.Deadline` bounds the
whole retry loop: no attempt starts past it, and no backoff sleeps
through it.
"""
from __future__ import annotations

import random
import time
from typing import Callable, Iterator, Optional, Tuple, Type

from .. import metrics
from .deadline import Deadline, DeadlineExceeded

__all__ = ["backoff_delays", "retry"]

_M_RETRIES = metrics.get_registry().counter(
    "paddle_tpu_faults_retries_total",
    "Retry attempts taken after a retryable failure (first tries not "
    "counted)")


def backoff_delays(attempts: int, *, base_delay_s: float = 0.05,
                   factor: float = 2.0, max_delay_s: float = 2.0,
                   jitter: float = 0.5, seed: int = 0) -> Iterator[float]:
    """Yield the ``attempts - 1`` sleep durations between attempts:
    ``base * factor**k`` capped at ``max_delay_s``, each scaled by a
    seeded uniform draw from ``[1-jitter, 1+jitter]`` (decorrelates
    thundering-herd retries; deterministic per seed)."""
    if attempts < 1:
        raise ValueError("attempts must be >= 1")
    if not 0.0 <= jitter < 1.0:
        raise ValueError("jitter must be in [0, 1)")
    rng = random.Random(seed)
    for k in range(attempts - 1):
        d = min(base_delay_s * factor ** k, max_delay_s)
        if jitter:
            d *= rng.uniform(1.0 - jitter, 1.0 + jitter)
        yield min(d, max_delay_s)


def retry(fn: Callable, *, attempts: int = 3,
          retry_on: Tuple[Type[BaseException], ...] = (Exception,),
          base_delay_s: float = 0.05, factor: float = 2.0,
          max_delay_s: float = 2.0, jitter: float = 0.5, seed: int = 0,
          deadline: Optional[Deadline] = None,
          sleep: Callable[[float], None] = time.sleep,
          on_retry: Optional[Callable[[int, BaseException], None]] = None):
    """Call ``fn()`` up to ``attempts`` times; backoff between failures.

    The final failure re-raises the original exception unchanged (no
    wrapper type to unwrap). A ``deadline`` turns exhaustion-by-time into
    :class:`DeadlineExceeded` with the last failure chained as cause.
    ``on_retry(attempt_index, exc)`` observes each scheduled retry.
    """
    delays = list(backoff_delays(attempts, base_delay_s=base_delay_s,
                                 factor=factor, max_delay_s=max_delay_s,
                                 jitter=jitter, seed=seed))
    last: Optional[BaseException] = None
    for attempt in range(attempts):
        if deadline is not None and deadline.expired():
            raise DeadlineExceeded(
                f"retry deadline exceeded after {attempt} attempt(s)"
            ) from last
        try:
            return fn()
        except retry_on as e:
            last = e
            if attempt == attempts - 1:
                raise
            _M_RETRIES.inc()
            if on_retry is not None:
                on_retry(attempt, e)
            d = delays[attempt]
            if deadline is not None:
                d = min(d, max(deadline.remaining(), 0.0))
            if d > 0:
                sleep(d)
    raise AssertionError("unreachable")  # pragma: no cover
