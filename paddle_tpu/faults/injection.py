"""Deterministic fault injection: named points + scoped, seeded schedules.

The chaos half of resilience (docs/RESILIENCE.md): production code marks
failure-prone sites with ``faults.point("serving.decode_step")`` — a
no-op costing one empty-list check until a test or drill *arms* a fault
there:

    with faults.inject("serving.kv_alloc",
                       raise_=faults.ResourceExhausted, times=1):
        engine.run()          # ONE allocation fails; the engine must
                              # quarantine the victim and keep draining

Schedules compose from ``times`` (fire at most N times), ``every``
(every Nth eligible hit), ``after`` (skip the first N hits), and ``p``
(seeded probability gate) — all deterministic for a fixed seed, so a
chaos run replays bit-identically. Modes compose too: ``call=`` runs a
host callback (e.g. poison a KV page), ``delay_s=`` injects latency,
``raise_=`` throws (class or instance) — in that order, so one spec can
corrupt state AND stall AND fail.

Hermetic by construction: ``inject`` is a context manager over a
process-global spec list; on exit the spec is disarmed, so tier-1 tests
can't leak faults into each other. Every firing increments
``paddle_tpu_faults_injected_total{point}`` — chaos tests assert the
telemetry alongside the behavior.

Stdlib + paddle_tpu.metrics only: importable from every layer without
jax or import cycles.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, List, Optional

from .. import metrics

__all__ = [
    "CallbackError", "FaultInjected", "FaultSpec", "ResourceExhausted",
    "active_faults", "declare_point", "inject", "known_points", "point",
    "reset",
]


class FaultInjected(RuntimeError):
    """Default exception raised by an armed ``raise_`` fault — a distinct
    type so handlers (and test asserts) can tell drills from real bugs."""


class ResourceExhausted(FaultInjected):
    """Canned resource-exhaustion simulation (page pool, HBM, fds)."""


class CallbackError(RuntimeError):
    """A user-supplied callback raised; the original is chained as
    ``__cause__``. Raised by ``CompletionAPI._chunk_cb`` so the engine's
    callback isolation can attribute the failure to user code."""


_lock = threading.RLock()  # tpulint: lock=faults.catalog
_active: List["FaultSpec"] = []
_catalog: Dict[str, str] = {}

_M_INJECTED = metrics.get_registry().counter(
    "paddle_tpu_faults_injected_total",
    "Faults fired by the injection framework", labels=("point",))


class FaultSpec:
    """One armed fault: where (``point``), what (``call``/``delay_s``/
    ``raise_``), when (``times``/``every``/``after``/``p`` + ``seed``)."""

    __slots__ = ("point", "raise_", "delay_s", "call", "times", "every",
                 "after", "p", "hits", "fired", "_rng")

    def __init__(self, point: str, *, raise_=None, delay_s: float = 0.0,
                 call: Optional[Callable[[], None]] = None,
                 times: Optional[int] = None, every: int = 1,
                 after: int = 0, p: Optional[float] = None, seed: int = 0):
        if raise_ is None and not delay_s and call is None:
            raise ValueError("armed fault must do something: pass raise_, "
                             "delay_s, and/or call")
        if every < 1:
            raise ValueError("every must be >= 1")
        if p is not None and not 0.0 <= p <= 1.0:
            raise ValueError("p must be in [0, 1]")
        self.point = str(point)
        self.raise_ = raise_
        self.delay_s = float(delay_s)
        self.call = call
        self.times = None if times is None else int(times)
        self.every = int(every)
        self.after = int(after)
        self.p = p
        self.hits = 0     # point() evaluations seen
        self.fired = 0    # times actually fired
        self._rng = random.Random(seed) if p is not None else None

    def _advance_hit(self) -> bool:
        """Advance the schedule one hit and report eligibility (caller
        holds the module lock). ``fired`` is NOT marked here — it is
        claimed at execution time, so a batch-mate spec that raises
        first can never strand this spec's accounting."""
        self.hits += 1
        if self.hits <= self.after:
            return False
        if (self.hits - self.after - 1) % self.every != 0:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        if self._rng is not None and self._rng.random() >= self.p:
            return False
        return True

    def _claim_fire(self) -> bool:
        """Claim one firing against the ``times`` cap (caller holds the
        module lock); False if a concurrent point() used it up."""
        if self.times is not None and self.fired >= self.times:
            return False
        self.fired += 1
        return True

    def __repr__(self) -> str:
        mode = []
        if self.call is not None:
            mode.append("call")
        if self.delay_s:
            mode.append(f"delay={self.delay_s}")
        if self.raise_ is not None:
            mode.append("raise")
        return (f"FaultSpec({self.point!r}, {'+'.join(mode)}, "
                f"fired={self.fired}, hits={self.hits})")


def point(name: str) -> None:
    """Named fault site. Free when nothing is armed — one empty-list
    check, no lock — so production hot paths can afford it."""
    if not _active:
        return
    with _lock:
        eligible = [spec for spec in _active
                    if spec.point == name and spec._advance_hit()]
    # every eligible spec runs its call/delay and counts, even when an
    # earlier spec also wants to raise — the (first) raise is deferred
    # to the end so one armed exception can't strand a batch-mate
    # spec's accounting or side effects
    pending: Optional[BaseException] = None
    for spec in eligible:
        with _lock:
            if not spec._claim_fire():
                continue
        _M_INJECTED.labels(point=name).inc()
        if spec.call is not None:
            spec.call()
        if spec.delay_s:
            time.sleep(spec.delay_s)
        if spec.raise_ is not None and pending is None:
            exc = spec.raise_
            if isinstance(exc, type):
                exc = exc(f"fault injected at point {name!r}")
            pending = exc
    if pending is not None:
        raise pending


class inject:
    """Context manager arming one :class:`FaultSpec` for its scope.

    ``with faults.inject("serving.decode_step", delay_s=0.05): ...``
    The spec object is returned (``as spec``) so tests can assert
    ``spec.fired``. Nesting arms multiple specs; exit disarms exactly
    the one this scope armed.
    """

    def __init__(self, point: str, **kw):
        self.spec = FaultSpec(point, **kw)

    def __enter__(self) -> FaultSpec:
        with _lock:
            _active.append(self.spec)
        return self.spec

    def __exit__(self, *exc) -> None:
        with _lock:
            try:
                _active.remove(self.spec)
            except ValueError:
                pass


def active_faults() -> List[FaultSpec]:
    """Currently armed specs (copy)."""
    with _lock:
        return list(_active)


def reset() -> None:
    """Disarm everything — belt-and-braces test teardown."""
    with _lock:
        _active.clear()


def declare_point(name: str, description: str = "") -> str:
    """Register a fault point in the catalog (docs/RESILIENCE.md is the
    human copy; ``known_points()`` the live one). Call at import time
    next to the subsystem that owns the ``point()`` site."""
    with _lock:
        _catalog[str(name)] = str(description)
    return name


def known_points() -> Dict[str, str]:
    """Declared fault points: name -> description."""
    with _lock:
        return dict(_catalog)
