"""LockSanitizer — runtime lock-order + reentrancy checking (opt-in).

tpulint's TPL007-009 prove lock discipline *statically* over declared
locks; this module is the dynamic half of the same contract: wrap the
locks you care about, run the workload (chaos drills do), then
``assert_clean()``. Three violation kinds:

- **order-inversion**: some thread acquired A then B while another
  acquisition path (any thread, this process) went B then A — the
  classic deadlock precondition, caught even when the interleaving
  never actually deadlocks in this run.
- **canonical-order**: an acquisition contradicts the declared fleet
  order (docs/RESILIENCE.md: router -> engine -> scheduler -> pool;
  registry and faults locks are leaf-only).
- **non-reentrant-reacquire**: a thread re-acquires a plain
  ``threading.Lock`` it already holds. The sanitizer raises
  ``RuntimeError`` instead of letting the test hang forever (RLocks
  re-enter silently, as designed).

Hold/wait time is exported per lock so a scrape shows *which* lock a
stall lives under::

    san = faults.LockSanitizer(order=("router", "engine"))
    router._lock = san.wrap(router._lock, "router")
    ... drive traffic ...
    san.assert_clean()

Stdlib + paddle_tpu.metrics only, like the rest of the package.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from .. import metrics

__all__ = ["LockSanitizer", "LockViolation"]

_RLOCK_TYPE = type(threading.RLock())

_M_HOLD = metrics.get_registry().histogram(
    "paddle_tpu_lock_hold_seconds",
    "Time a sanitized lock was held, per acquisition", labels=("lock",),
    buckets=(1e-5, 1e-4, 1e-3, 1e-2, 0.1, 1.0))
_M_WAIT = metrics.get_registry().histogram(
    "paddle_tpu_lock_wait_seconds",
    "Time a thread blocked waiting for a sanitized lock", labels=("lock",),
    buckets=(1e-5, 1e-4, 1e-3, 1e-2, 0.1, 1.0))
_M_VIOLATIONS = metrics.get_registry().counter(
    "paddle_tpu_lock_order_violations_total",
    "Lock-discipline violations observed by LockSanitizer")


@dataclass(frozen=True)
class LockViolation:
    """One observed lock-discipline violation (deduplicated by
    ``(kind, locks)`` — the first witness wins)."""
    kind: str            # order-inversion | canonical-order | leaf-holds
    #                    # | non-reentrant-reacquire
    locks: Tuple[str, ...]
    thread: str
    detail: str

    def __str__(self) -> str:
        return (f"[{self.kind}] {' -> '.join(self.locks)} "
                f"(thread {self.thread}): {self.detail}")


@dataclass
class _HeldEntry:
    name: str
    t_acquired: float
    depth: int = 1       # RLock re-entry depth


class LockSanitizer:
    """Wrap locks, observe every acquisition, detect ordering hazards.

    ``order`` is the canonical acquisition sequence (outermost first);
    acquiring an earlier-ranked lock while holding a later-ranked one is
    a violation even if no reverse path was ever observed. ``leaves``
    are locks that must never be held across *any* other sanitized
    acquisition (the registry and faults locks in this repo).
    """

    def __init__(self, order: Sequence[str] = (),
                 leaves: Sequence[str] = ()):
        self._order: Dict[str, int] = {n: i for i, n in enumerate(order)}
        self._leaves = frozenset(leaves)
        self._meta = threading.Lock()  # tpulint: lock=faults.sanitizer
        # observed acquisition edges: (held, acquired) -> first witness
        self._edges: Dict[Tuple[str, str], str] = {}
        self._seen: set = set()        # violation dedup keys
        self.violations: List[LockViolation] = []
        self._tls = threading.local()

    # -- wiring -----------------------------------------------------------
    def wrap(self, lock, name: str) -> "_SanitizedLock":
        """Return a drop-in proxy for ``lock`` that reports to this
        sanitizer. Idempotent on already-wrapped locks."""
        if isinstance(lock, _SanitizedLock):
            return lock
        return _SanitizedLock(self, lock, name)

    def attach(self, obj, attr: str, name: Optional[str] = None):
        """``obj.attr = wrap(obj.attr)``; returns the original lock so a
        drill can restore it in ``finally`` (process-global locks stay
        usable after the drill)."""
        original = getattr(obj, attr)
        setattr(obj, attr, self.wrap(original, name or attr))
        return original

    # -- results ----------------------------------------------------------
    def report(self) -> str:
        with self._meta:
            vs = list(self.violations)
        if not vs:
            return "LockSanitizer: clean"
        lines = [f"LockSanitizer: {len(vs)} violation(s)"]
        lines += [f"  {v}" for v in vs]
        return "\n".join(lines)

    def assert_clean(self) -> None:
        with self._meta:
            vs = list(self.violations)
        if vs:
            raise AssertionError(self.report())

    # -- internals --------------------------------------------------------
    def _stack(self) -> List[_HeldEntry]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _record(self, kind: str, locks: Tuple[str, ...],
                detail: str) -> None:
        # direction-agnostic dedup: the a->b and b->a reports of one
        # inversion are the same hazard — the first witness carries
        # both sites in its detail
        key = (kind, tuple(sorted(locks)))
        with self._meta:
            if key in self._seen:
                return
            self._seen.add(key)
            self.violations.append(LockViolation(
                kind, locks, threading.current_thread().name, detail))
        _M_VIOLATIONS.inc()

    def _before_acquire(self, name: str, reentrant: bool) -> None:
        """Runs in the acquiring thread BEFORE the real acquire — so a
        guaranteed deadlock (non-reentrant re-acquire) raises instead of
        hanging the suite."""
        stack = self._stack()
        held_names = [e.name for e in stack]
        if name in held_names:
            if reentrant:
                return          # RLock re-entry: legal, no edges
            self._record(
                "non-reentrant-reacquire", (name, name),
                f"thread already holds non-reentrant lock `{name}`")
            raise RuntimeError(
                f"LockSanitizer: re-acquiring non-reentrant lock "
                f"{name!r} on the same thread would deadlock")
        me = threading.current_thread().name
        for held in held_names:
            if held == name:
                continue
            if held in self._leaves:
                self._record(
                    "leaf-holds", (held, name),
                    f"leaf-only lock `{held}` held while acquiring "
                    f"`{name}`")
            ra, rb = self._order.get(held), self._order.get(name)
            if ra is not None and rb is not None and rb < ra:
                self._record(
                    "canonical-order", (held, name),
                    f"acquired `{name}` (rank {rb}) while holding "
                    f"`{held}` (rank {ra}); canonical order is "
                    f"{tuple(self._order)}")
            witness = f"thread {me}: {held} -> {name}"
            with self._meta:
                self._edges.setdefault((held, name), witness)
                reverse = self._edges.get((name, held))
            if reverse is not None:
                self._record(
                    "order-inversion", (held, name),
                    f"{witness} inverts previously observed {reverse}")

    def _after_acquire(self, name: str, waited: float) -> None:
        stack = self._stack()
        for e in stack:
            if e.name == name:   # RLock re-entry
                e.depth += 1
                return
        stack.append(_HeldEntry(name, time.monotonic()))
        _M_WAIT.labels(lock=name).observe(waited)

    def _on_release(self, name: str) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i].name == name:
                stack[i].depth -= 1
                if stack[i].depth == 0:
                    held = time.monotonic() - stack[i].t_acquired
                    del stack[i]
                    _M_HOLD.labels(lock=name).observe(held)
                return
        # release without a tracked acquire (lock handed across
        # threads): not an ordering hazard, just untracked — ignore.


class _SanitizedLock:
    """Duck-typed stand-in for ``threading.Lock``/``RLock`` — supports
    ``with``, ``acquire(blocking, timeout)``, ``release`` and
    ``locked``, reporting every transition to its sanitizer."""

    def __init__(self, sanitizer: LockSanitizer, inner, name: str):
        self._sanitizer = sanitizer
        self._inner = inner
        self._name = name
        self._reentrant = isinstance(inner, _RLOCK_TYPE)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._sanitizer._before_acquire(self._name, self._reentrant)
        t0 = time.monotonic()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._sanitizer._after_acquire(
                self._name, time.monotonic() - t0)
        return ok

    def release(self) -> None:
        self._inner.release()
        self._sanitizer._on_release(self._name)

    def locked(self) -> bool:
        fn = getattr(self._inner, "locked", None)
        if fn is not None:
            return fn()
        # RLock grows .locked() only in newer CPythons; owned-by-me is
        # the closest honest answer for the duck type
        return bool(self._inner._is_owned())

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<SanitizedLock {self._name!r} over {self._inner!r}>"
