"""Step watchdog: detect stalled engine steps and hold a degraded state.

The health half of the faults package (docs/RESILIENCE.md has the state
machine): the engine brackets each step with ``begin_step()`` /
``end_step(duration)``. An over-threshold step *trips* the watchdog
(``end_step`` returns True exactly on the healthy→tripped transition, so
``watchdog_trips_total`` counts episodes, not slow steps); it recovers
after ``recovery_steps`` consecutive healthy steps. ``stalled_now()``
answers from ANY thread — a ``/healthz`` scrape sees a step that is
still running past the threshold as degraded without waiting for it to
return, which is the only way to observe a genuinely hung step in-band.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

__all__ = ["StepWatchdog"]


class StepWatchdog:
    """Trip on a step slower than ``stall_threshold_s``; recover after
    ``recovery_steps`` consecutive healthy steps. ``clock=`` injectable
    for deterministic tests."""

    def __init__(self, stall_threshold_s: float = 30.0,
                 recovery_steps: int = 3,
                 clock: Callable[[], float] = time.monotonic):
        if stall_threshold_s <= 0:
            raise ValueError("stall_threshold_s must be > 0")
        if recovery_steps < 1:
            raise ValueError("recovery_steps must be >= 1")
        self.stall_threshold_s = float(stall_threshold_s)
        self.recovery_steps = int(recovery_steps)
        self._clock = clock
        self._lock = threading.Lock()  # tpulint: lock=watchdog
        self._in_step_since: Optional[float] = None
        self._tripped = False
        self._healthy_streak = 0
        self._trips = 0

    # -- engine-thread protocol -------------------------------------------
    def begin_step(self) -> None:
        with self._lock:
            self._in_step_since = self._clock()

    def end_step(self, duration_s: Optional[float] = None) -> bool:
        """Record one finished step; returns True only on a NEW trip
        (healthy→tripped transition). ``duration_s=None`` measures from
        the matching ``begin_step``."""
        with self._lock:
            t0, self._in_step_since = self._in_step_since, None
            if duration_s is None:
                duration_s = 0.0 if t0 is None else self._clock() - t0
            if duration_s > self.stall_threshold_s:
                self._healthy_streak = 0
                newly = not self._tripped
                self._tripped = True
                if newly:
                    self._trips += 1
                return newly
            if self._tripped:
                self._healthy_streak += 1
                if self._healthy_streak >= self.recovery_steps:
                    self._tripped = False
                    self._healthy_streak = 0
            return False

    # -- any-thread queries -----------------------------------------------
    def stalled_now(self) -> bool:
        """True while a step is CURRENTLY running past the threshold —
        the live-hang detector a health scrape relies on."""
        with self._lock:
            return (self._in_step_since is not None
                    and self._clock() - self._in_step_since
                    > self.stall_threshold_s)

    @property
    def tripped(self) -> bool:
        return self._tripped

    @property
    def trips(self) -> int:
        """Trip episodes since construction (not slow-step count)."""
        return self._trips

    def status(self) -> str:
        """``"ok"`` | ``"degraded"`` (tripped, or a step is live-hung)."""
        return "degraded" if (self._tripped or self.stalled_now()) else "ok"
