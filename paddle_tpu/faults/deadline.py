"""Deadline: an absolute time budget on an injectable clock.

The serving stack's cancellation currency (docs/RESILIENCE.md): a
request carries ``Deadline(deadline_s)`` from enqueue; the engine sweeps
expired deadlines every step and retires them with
``finish_reason="timeout"``. ``clock=`` is injectable so tests drive
expiry deterministically (a fake clock, or a negative budget for
"already expired") instead of sleeping.
"""
from __future__ import annotations

import math
import time
from typing import Callable, Optional

__all__ = ["Deadline", "DeadlineExceeded"]


class DeadlineExceeded(TimeoutError):
    """A time budget ran out (``Deadline.check`` / ``retry(deadline=)``)."""


class Deadline:
    """Absolute expiry instant computed at construction: ``seconds=None``
    never expires (``Deadline.never()``); ``seconds<=0`` is already
    expired. Monotonic by default — wall-clock jumps don't cancel work."""

    __slots__ = ("_t_end", "_clock")

    def __init__(self, seconds: Optional[float] = None, *,
                 clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._t_end = None if seconds is None else clock() + float(seconds)

    @classmethod
    def never(cls) -> "Deadline":
        return cls(None)

    @property
    def unbounded(self) -> bool:
        return self._t_end is None

    def remaining(self) -> float:
        """Seconds left (may be negative once expired; +inf if unbounded)."""
        if self._t_end is None:
            return math.inf
        return self._t_end - self._clock()

    def expired(self) -> bool:
        return self._t_end is not None and self._clock() >= self._t_end

    def check(self, what: str = "") -> None:
        """Raise :class:`DeadlineExceeded` if the budget ran out."""
        if self.expired():
            raise DeadlineExceeded(
                f"deadline exceeded{' in ' + what if what else ''} "
                f"(over by {-self.remaining():.3f}s)")

    def __repr__(self) -> str:
        if self._t_end is None:
            return "Deadline(never)"
        return f"Deadline(remaining={self.remaining():.3f}s)"
