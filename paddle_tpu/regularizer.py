"""paddle.regularizer — weight-decay regularizers attached to params or
optimizers (reference: python/paddle/regularizer.py L1Decay/L2Decay).

The optimizer base already applies these at gradient time
(optimizer/optimizer.py); this module is the public spelling.
"""
from .optimizer.optimizer import _L1Decay as L1Decay  # noqa: F401
from .optimizer.optimizer import _L2Decay as L2Decay  # noqa: F401

__all__ = ["L1Decay", "L2Decay"]
