"""paddle.dataset — legacy reader-style dataset loaders.

Reference parity: ``python/paddle/dataset/`` (mnist, cifar, imdb,
uci_housing, ... exposing ``train()``/``test()`` readers). Thin facade
over the first-class datasets in ``paddle_tpu.vision.datasets`` and
``paddle_tpu.text``, re-shaped to the legacy contract: each loader is a
zero-arg callable yielding samples. The zero-egress gating (local cache
or FileNotFoundError) is inherited from those implementations.
"""
from __future__ import annotations

import numpy as np

__all__ = ["mnist", "cifar", "uci_housing", "imdb"]


class _ReaderModule:
    """Builds train()/test() readers over a Dataset class lazily."""

    def __init__(self, factory, train_kw, test_kw):
        self._factory = factory
        self._train_kw = train_kw
        self._test_kw = test_kw

    def _reader(self, kw):
        def reader():
            ds = self._factory(**kw)
            for i in range(len(ds)):
                yield ds[i]

        return reader

    def train(self):
        return self._reader(self._train_kw)

    def test(self):
        return self._reader(self._test_kw)


def _mnist_factory(**kw):
    from ..vision.datasets import MNIST

    return MNIST(**kw)


def _cifar_factory(**kw):
    from ..vision.datasets import Cifar10

    return Cifar10(**kw)


mnist = _ReaderModule(_mnist_factory, {"mode": "train"}, {"mode": "test"})
cifar = _ReaderModule(_cifar_factory, {"mode": "train"}, {"mode": "test"})


class _UciHousing:
    def train(self):
        from ..text import UCIHousing

        ds = UCIHousing(mode="train")
        return lambda: iter(ds)

    def test(self):
        from ..text import UCIHousing

        ds = UCIHousing(mode="test")
        return lambda: iter(ds)


class _Imdb:
    def train(self, word_idx=None):
        from ..text import Imdb

        ds = Imdb(mode="train")
        return lambda: iter(ds)

    def test(self, word_idx=None):
        from ..text import Imdb

        ds = Imdb(mode="test")
        return lambda: iter(ds)


uci_housing = _UciHousing()
imdb = _Imdb()
