"""paddle_tpu.checkpoint — crash-consistent checkpointing + auto-resume.

The training-side half of the resilience story (docs/RESILIENCE.md): on
preemptible TPU fleets a job must survive SIGTERM without losing work,
and a crash mid-save must never cost the *previous* checkpoint either.

- :class:`CheckpointManager` (manager.py): step-versioned directory with
  an atomic commit protocol — scratch dir, fsynced shard files, a COMMIT
  marker carrying per-file CRC32s written last, then one atomic rename.
  ``latest_step()`` only ever sees committed steps; ``restore()``
  verifies checksums and quarantines corrupt steps, falling back to the
  newest valid one; retention GC keeps the last K.
- ``save_on_signal()`` / ``restore_or_init()``: preemption-aware resume —
  checkpoint-and-exit on SIGTERM, one call to pick the run back up.
- ``capture_train_state`` / ``restore_train_state`` (state.py): params +
  optimizer moments + RNG key + dataloader position (epoch + offset), so
  resumed training is sample-exact and token-for-token identical to an
  uninterrupted run (proved by tools/chaos_train.py).

Ten-second tour::

    from paddle_tpu import checkpoint

    mgr = checkpoint.CheckpointManager("/ckpts/run7", max_to_keep=3)
    res = mgr.restore_or_init()
    start = 0
    if res.restored:
        start = checkpoint.restore_train_state(
            res.state, model=net, optimizer=opt, dataloader=loader) + 1
    step = start - 1   # bound BEFORE the handler can fire
    scope = mgr.save_on_signal(
        lambda: (step, checkpoint.capture_train_state(
            model=net, optimizer=opt, dataloader=loader, step=step)))
    for step in range(start, total_steps):
        train_step(...)
        mgr.save(step, checkpoint.capture_train_state(..., step=step),
                 async_save=True)
    checkpoint.wait()   # async saves are durable only after this returns

The sharded file format underneath is ``distributed.checkpoint`` —
cross-topology resume (save under one mesh, load under another) works
through the same ``shardings=``/``target=`` arguments.
"""
from ..distributed.checkpoint import AsyncHandle, CheckpointError, wait
from .manager import (CheckpointManager, CheckpointNotFoundError,
                      RestoreResult)
from .state import (capture_train_state, restore_train_state,
                    rng_state_dict, set_rng_state_dict)

__all__ = [
    "AsyncHandle", "CheckpointError", "CheckpointManager",
    "CheckpointNotFoundError", "RestoreResult", "capture_train_state",
    "restore_train_state", "rng_state_dict", "set_rng_state_dict", "wait",
]
