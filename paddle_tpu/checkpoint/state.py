"""Train-state capture/restore: params + optimizer + RNG + data position.

Sample-exact resume needs more than parameters: the optimizer moments, the
global RNG key, and where the dataloader was (epoch + batch/sample offset).
``capture_train_state`` gathers all of it into one nested dict the
:class:`~paddle_tpu.checkpoint.CheckpointManager` can commit atomically;
``restore_train_state`` pushes it back. Any object with
``state_dict``/``set_state_dict`` works for ``model`` (an ``nn.Layer``;
for a ``hapi.Model`` pass ``model.network`` or use
``Model.save_checkpoint``/``Model.restore_checkpoint``).

RNG keys are typed jax PRNG arrays — not numpy-serializable directly —
so they travel as their ``jax.random.key_data`` uint32 payload and are
rebuilt with ``wrap_key_data`` on restore.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..generator import default_generator, get_rng_state, set_rng_state
from ..tensor import Tensor

__all__ = [
    "capture_train_state", "restore_train_state", "rng_state_dict",
    "set_rng_state_dict",
]


def rng_state_dict() -> Dict[str, Any]:
    """Serialize the global generator: the device key as its uint32
    key-data, plus the base seed — host-side epoch-seeded shuffling
    (io/sampler.py) derives from ``(seed, epoch)``, so resume must restore
    the seed or the replayed epochs would shuffle differently."""
    key = get_rng_state()
    try:
        data = jax.random.key_data(key)
    except (TypeError, AttributeError):  # already a raw uint32 key array
        data = key
    return {"key_data": Tensor(np.asarray(jax.device_get(data),
                                          dtype=np.uint32)),
            "seed": int(default_generator.seed())}


def set_rng_state_dict(state: Dict[str, Any]) -> None:
    """Rebuild and install the global RNG key (and base seed) from
    :func:`rng_state_dict` output (values may be Tensors fresh off a
    checkpoint load)."""
    if "seed" in state:
        # restore the base seed WITHOUT resetting the key (manual_seed
        # would): the key is restored explicitly below
        default_generator._seed = int(state["seed"])
    data = state["key_data"]
    if isinstance(data, Tensor):
        data = data.numpy()
    arr = jnp.asarray(np.asarray(data, dtype=np.uint32))
    set_rng_state(jax.random.wrap_key_data(arr))


def capture_train_state(model=None, optimizer=None, dataloader=None,
                        step: Optional[int] = None,
                        extra: Optional[Dict] = None,
                        sentinel=None) -> Dict[str, Any]:
    """One nested dict holding everything resume needs. Omitted pieces are
    simply absent; ``step`` rides along as an exact python int.
    ``sentinel`` (a :class:`~paddle_tpu.faults.TrainSentinel`) contributes
    its journal + escalation state — pure scalars, so they land in the
    checkpoint's ``scalars.json`` and a preempted run resumes mid-incident
    with its anomaly memory intact."""
    state: Dict[str, Any] = {"rng": rng_state_dict()}
    if model is not None:
        state["model"] = model.state_dict()
    if optimizer is not None:
        state["optimizer"] = optimizer.state_dict()
    if dataloader is not None:
        state["dataloader"] = dataloader.state_dict()
    if sentinel is not None:
        state["sentinel"] = sentinel.state_dict()
    if step is not None:
        state["step"] = int(step)
    if extra:
        state["extra"] = dict(extra)
    return state


def restore_train_state(state: Dict[str, Any], model=None, optimizer=None,
                        dataloader=None, sentinel=None) -> Optional[int]:
    """Push a :func:`capture_train_state` dict back into live objects and
    return the saved ``step`` (None if it wasn't captured). ``sentinel``
    is only restored when passed — a sentinel-driven ROLLBACK restores
    params/optimizer/data from a mark but must keep its own live incident
    state (region counts, journal), so rollback calls this without it."""
    if "rng" in state:
        set_rng_state_dict(state["rng"])
    if model is not None and "model" in state:
        model.set_state_dict(state["model"])
    if optimizer is not None and "optimizer" in state:
        optimizer.set_state_dict(state["optimizer"])
    if dataloader is not None and "dataloader" in state:
        dataloader.set_state_dict(state["dataloader"])
    if sentinel is not None and "sentinel" in state:
        sentinel.set_state_dict(state["sentinel"])
    step = state.get("step")
    return None if step is None else int(step)
