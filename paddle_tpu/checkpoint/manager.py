"""Orbax-style CheckpointManager: crash-consistent commit + auto-resume.

The commit protocol (docs/RESILIENCE.md "Checkpoint commit protocol"):

1. every save targets a scratch directory ``step_<N>.tmp-<nonce>`` — never
   the published name;
2. shard files + the shard manifest land there via
   ``distributed.checkpoint.save_state_dict`` (each file fsynced, manifest
   last — see that module's ordering contract);
3. pure-python scalar leaves (epoch counters, dataloader offsets, LR
   scheduler floats) are split into ``scalars.json`` so they round-trip
   with exact types instead of as 0-d arrays;
4. a ``COMMIT`` marker carrying per-file sizes + CRC32s is written last
   (tmp + fsync + atomic replace), then the whole directory is atomically
   renamed to ``step_<N>`` and the parent directory fsynced.

A step therefore exists to readers *only* if every byte it references was
durable first. ``latest_step()`` never sees a partial save; ``restore()``
re-verifies the COMMIT checksums and quarantines any step that fails
(renamed ``corrupt-step_<N>-<nonce>``), falling back to the newest valid
step. Retention GC keeps the last ``max_to_keep`` committed steps.

Preemption: ``save_on_signal()`` installs SIGTERM/SIGINT handlers that
checkpoint synchronously and exit cleanly — the preemptible-TPU story.
``restore_or_init()`` is the one-call resume entry point.

Fault points: ``ckpt.commit`` fires before the COMMIT-marker write and
again before the publish rename (``times=1`` kills the marker,
``times=1, after=1`` kills the rename); the write/fsync/manifest points
live in ``distributed.checkpoint``. Every phase is drilled by
``tools/chaos_train.py`` and tests/test_checkpoint_manager.py.

Multi-host note: every process writes its own shards into ONE shared
scratch directory (``step_<N>.tmp-shared``) and only process 0 commits —
the caller owns the cross-host barrier between the workers' ``save()``
returning and process 0's. Process 0's COMMIT digests cover only the
files it wrote itself; other hosts' shards publish unverified (their
sizes/CRCs are not visible to p0 at commit time on this codebase).
"""
from __future__ import annotations

import json
import os
import re
import shutil
import signal as _signal
import sys
import threading
import time
import zlib
from contextlib import contextmanager
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax

from .. import faults, metrics
from ..distributed import checkpoint as dist_ckpt
from ..distributed.checkpoint import (AsyncHandle, CheckpointError,
                                      _atomic_json_write, _flatten,
                                      _fsync_dir, _unflatten)

__all__ = [
    "CheckpointManager", "CheckpointNotFoundError", "RestoreResult",
]

_COMMIT_FILE = "COMMIT"
_SCALARS_FILE = "scalars.json"
_STEP_RE = re.compile(r"^step_(\d+)$")
_TMP_RE = re.compile(r"^step_\d+\.tmp-")
_CORRUPT_PREFIX = "corrupt-"

# scratch dirs with an in-flight writer, process-wide: stale-tmp sweeping
# must never reap a LIVE async save's directory, including one started by
# a DIFFERENT CheckpointManager instance on the same directory (e.g. two
# successive Model.save_checkpoint calls each build their own manager)
_LIVE_TMP: set = set()
_LIVE_TMP_LOCK = threading.RLock()  # tpulint: lock=ckpt.live_tmp (reentrant: see _pending_lock's note)

faults.declare_point(
    "ckpt.commit",
    "CheckpointManager commit: fires before the COMMIT-marker write and "
    "again before the publish rename (times=1 kills the marker; "
    "times=1, after=1 kills the rename)")

_REG = metrics.get_registry()
_M_SAVE_SECONDS = _REG.histogram(
    "paddle_tpu_ckpt_save_seconds",
    "Checkpoint save wall time, snapshot through commit", labels=("mode",))
_M_LAST_STEP = _REG.gauge(
    "paddle_tpu_ckpt_last_committed_step",
    "Newest step whose COMMIT marker is published")
_M_SAVES = _REG.counter(
    "paddle_tpu_ckpt_saves_total",
    "Checkpoint save attempts by result", labels=("result",))
_M_CORRUPT = _REG.counter(
    "paddle_tpu_ckpt_corrupt_total",
    "Checkpoint steps quarantined after failing COMMIT verification")
_M_FALLBACK = _REG.counter(
    "paddle_tpu_ckpt_restore_fallback_total",
    "Restores that skipped a corrupt newest step for an older valid one")
_M_GC = _REG.counter(
    "paddle_tpu_ckpt_gc_deleted_total",
    "Committed steps deleted by retention GC")


class CheckpointNotFoundError(FileNotFoundError):
    """No committed (and verifiable) checkpoint step exists."""


class RestoreResult(NamedTuple):
    """What ``restore_or_init`` found: the state (or the caller's default),
    the committed step it came from (None when initializing fresh), and
    whether anything was restored."""

    state: Any
    step: Optional[int]
    restored: bool


def _step_name(step: int) -> str:
    return f"step_{step:08d}"


def _split_state(state: Dict) -> Tuple[Dict, Dict]:
    """Partition flat leaves into array-like (npy shard path) and pure
    python scalars (json path — exact int/float/bool/str/None round-trip,
    which sample-exact resume of epoch/offset counters depends on)."""
    arrays: Dict[str, Any] = {}
    scalars: Dict[str, Any] = {}
    for k, v in _flatten(state).items():
        if v is None or isinstance(v, (bool, int, float, str)):
            scalars[k] = v
        else:
            arrays[k] = v
    return arrays, scalars


def _drain_pending(timeout_s: float) -> None:
    """Best-effort bounded join of all outstanding async saves (signal
    handler use: never re-raise, never block past the budget — the
    post-drain ``all_steps()`` check decides what still needs saving)."""
    with dist_ckpt._pending_lock:
        pending = list(dist_ckpt._pending)
    deadline = time.monotonic() + max(0.0, timeout_s)
    for h in pending:
        t = h._thread
        if t is not None:
            t.join(max(0.0, deadline - time.monotonic()))


def _file_digest(path: str) -> Tuple[int, int]:
    """(size, crc32) streamed in 1 MiB chunks."""
    size, crc = 0, 0
    with open(path, "rb") as fh:
        while True:
            chunk = fh.read(1 << 20)
            if not chunk:
                break
            size += len(chunk)
            crc = zlib.crc32(chunk, crc)
    return size, crc


class CheckpointManager:
    """Crash-consistent, step-versioned checkpoint directory.

    ::

        mgr = checkpoint.CheckpointManager(dir, max_to_keep=3)
        res = mgr.restore_or_init(default=init_state())
        for step in range(res.step + 1 if res.restored else 0, steps):
            train_step(...)
            mgr.save(step, capture_state(), async_save=True)
        checkpoint.wait()          # async saves durable only after this
    """

    def __init__(self, directory: str, max_to_keep: Optional[int] = 5,
                 process_index: Optional[int] = None):
        self.directory = str(directory)
        self.max_to_keep = max_to_keep
        self._process_index = process_index
        self.preempted = False  # set by the save_on_signal handler
        # serializes commit/GC phases; REENTRANT because the save_on_signal
        # handler runs on the main thread and may interrupt a save that is
        # inside its own locked commit — a plain Lock would self-deadlock
        self._save_lock = threading.RLock()  # tpulint: lock=ckpt.save
        os.makedirs(self.directory, exist_ok=True)

    # ------------------------------------------------------------- steps
    def all_steps(self) -> list:
        """Committed steps (COMMIT marker present), ascending. Scratch
        ``.tmp-`` and quarantined ``corrupt-`` directories are invisible."""
        steps = []
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return steps
        for name in entries:
            m = _STEP_RE.match(name)
            if m and os.path.isfile(
                    os.path.join(self.directory, name, _COMMIT_FILE)):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        """Newest committed step, or None. Only ever sees directories whose
        COMMIT marker was published by the atomic rename."""
        steps = self.all_steps()
        return steps[-1] if steps else None

    def step_path(self, step: int) -> str:
        return os.path.join(self.directory, _step_name(step))

    # -------------------------------------------------------------- save
    def save(self, step: int, state: Dict, async_save: bool = False
             ) -> AsyncHandle:
        """Persist ``state`` (nested dict of Tensors/arrays/python scalars)
        as committed step ``step``.

        Sync: raises on any failure; on return the step is durable.
        Async: device arrays are snapshotted to host before returning (the
        training loop may mutate params immediately); commit happens on the
        writer thread and the returned handle's ``wait()`` re-raises any
        failure — a step is durable only once ``wait()`` returned cleanly.
        """
        step = int(step)
        if step < 0:
            raise ValueError(f"checkpoint step must be >= 0, got {step}")
        if step in set(self.all_steps()):
            raise ValueError(
                f"step {step} is already committed in {self.directory}; "
                f"checkpoint steps are immutable once published")
        self._clean_stale_tmp()
        arrays, scalars = _split_state(state)
        # multi-host: every process writes into ONE shared scratch name
        # (a per-process nonce would strand non-zero processes' shards in
        # directories the commit rename never publishes) and only process
        # 0 commits — after the caller's cross-host barrier
        multi = jax.process_count() > 1
        pidx = (self._process_index if self._process_index is not None
                else jax.process_index())
        nonce = "shared" if multi else os.urandom(4).hex()
        tmpdir = os.path.join(self.directory,
                              f"{_step_name(step)}.tmp-{nonce}")
        with _LIVE_TMP_LOCK:
            _LIVE_TMP.add(tmpdir)
        os.makedirs(tmpdir, exist_ok=multi)
        t0 = time.perf_counter()
        mode = "async" if async_save else "sync"

        def finish(inner: AsyncHandle):
            try:
                inner.wait()  # re-raises the shard writer's failure
                if pidx != 0:
                    return  # workers publish shards only; process 0 commits
                digests = dict(inner.digests)
                digests[_SCALARS_FILE] = self._write_scalars(tmpdir, scalars)
                with self._commit_lock():
                    self._commit(tmpdir, step, digests)
                    _M_SAVE_SECONDS.labels(mode=mode).observe(
                        time.perf_counter() - t0)
                    # publish the DIRECTORY's latest, not this save's step:
                    # an out-of-order async commit (slow step 4 landing
                    # after step 5) must not walk the gauge backwards
                    _M_LAST_STEP.set(self.latest_step() or step)
                    _M_SAVES.labels(result="committed").inc()
                    self._gc()
            except BaseException:
                _M_SAVES.labels(result="failed").inc()
                raise
            finally:
                with _LIVE_TMP_LOCK:
                    _LIVE_TMP.discard(tmpdir)

        if async_save:
            # save_state_dict(async) snapshots shards to host eagerly on
            # THIS thread (may raise right here — device fetch, bad leaf);
            # the returned writer thread is then chained with the commit so
            # ordering (shards -> manifest -> COMMIT -> rename) holds end
            # to end.
            try:
                inner = dist_ckpt.save_state_dict(
                    arrays, tmpdir, async_save=True,
                    process_index=self._process_index)
            except BaseException:
                _M_SAVES.labels(result="failed").inc()
                with _LIVE_TMP_LOCK:
                    _LIVE_TMP.discard(tmpdir)
                raise
            return dist_ckpt._spawn_async(lambda: finish(inner))

        try:
            inner = dist_ckpt.save_state_dict(
                arrays, tmpdir, async_save=False,
                process_index=self._process_index)
        except BaseException:
            _M_SAVES.labels(result="failed").inc()
            with _LIVE_TMP_LOCK:
                _LIVE_TMP.discard(tmpdir)
            raise
        finish(inner)
        return AsyncHandle(None)

    def save_if_absent(self, step: int, state: Dict,
                       async_save: bool = False) -> Optional[AsyncHandle]:
        """:meth:`save` unless ``step`` is already committed — returns
        None then, and tolerates losing a commit race (a concurrent save
        publishing the step IS durability). The idempotent path shared by
        the preemption signal handler and the train sentinel's
        mark/emergency saves, where "someone already committed this step"
        is success, not an error."""
        step = int(step)
        if step in set(self.all_steps()):
            return None
        try:
            return self.save(step, state, async_save=async_save)
        except ValueError:
            if step in set(self.all_steps()):
                return None
            raise

    def delete_step(self, step: int) -> bool:
        """Remove one committed step. For callers that own their step
        semantics beyond retention GC — the train sentinel prunes marks
        AHEAD of a resumed timeline (an epoch-granular restore rewound
        behind them; restoring such a mark would fast-forward params past
        the data stream). Returns False when the step isn't committed."""
        path = self.step_path(int(step))
        if not os.path.isfile(os.path.join(path, _COMMIT_FILE)):
            return False
        with self._commit_lock():
            shutil.rmtree(path, ignore_errors=True)
        # an rmtree failure (EBUSY/EPERM on a network fs) must not report
        # success: a caller pruning stale-timeline marks would otherwise
        # believe a restorable step is gone
        return not os.path.isfile(os.path.join(path, _COMMIT_FILE))

    @contextmanager
    def _commit_lock(self, timeout_s: float = 30.0):
        """Commit/GC serialization with a liveness escape hatch: if the
        holder is wedged past ``timeout_s`` (stuck I/O mid-commit), the
        caller proceeds unserialized with a warning — losing strict
        ordering beats losing the checkpoint entirely (the signal handler
        especially must outrun the preemption grace period). Distinct
        saves touch distinct scratch dirs; the rename-collision guard in
        _commit keeps even a same-step race loud and consistent."""
        got = self._save_lock.acquire(timeout=timeout_s)
        if not got:
            sys.stderr.write(
                f"[paddle_tpu.checkpoint] commit lock not acquired within "
                f"{timeout_s}s (wedged save?); committing unserialized\n")
        try:
            yield
        finally:
            if got:
                self._save_lock.release()

    def _write_scalars(self, dirpath: str, scalars: Dict) -> Dict[str, int]:
        faults.point("ckpt.write")
        return _atomic_json_write(os.path.join(dirpath, _SCALARS_FILE),
                                  scalars)

    def _commit(self, tmpdir: str, step: int,
                digests: Optional[Dict] = None) -> None:
        """COMMIT marker (sizes + CRC32s of every file already durable in
        the scratch dir) then the atomic publish rename. Digests normally
        arrive from the writers (accumulated as the bytes streamed out);
        the fallback re-reads the directory."""
        files = dict(digests) if digests else {}
        if not files:
            for name in sorted(os.listdir(tmpdir)):
                path = os.path.join(tmpdir, name)
                if name == _COMMIT_FILE or not os.path.isfile(path):
                    continue
                size, crc = _file_digest(path)
                files[name] = {"size": size, "crc32": crc}
        payload = {"step": step, "format": 1, "files": files}

        faults.point("ckpt.commit")  # phase 1: marker write
        _atomic_json_write(os.path.join(tmpdir, _COMMIT_FILE), payload)

        faults.point("ckpt.commit")  # phase 2: publish rename
        final = self.step_path(step)
        try:
            os.rename(tmpdir, final)
        except OSError:
            if os.path.isfile(os.path.join(final, _COMMIT_FILE)):
                # lost a commit race: a concurrent save (e.g. an async save
                # racing the signal handler) already published this step —
                # drop our duplicate scratch and report it clearly
                shutil.rmtree(tmpdir, ignore_errors=True)
                raise ValueError(
                    f"step {step} was committed concurrently by another "
                    f"save; this save's scratch was discarded") from None
            raise
        _fsync_dir(self.directory)

    def _clean_stale_tmp(self) -> None:
        """Remove scratch dirs a crashed PREVIOUS process left behind
        (single-writer directories by contract — see class docstring).
        In-flight async saves of THIS manager are exempt."""
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return
        with _LIVE_TMP_LOCK:
            live = set(_LIVE_TMP)
        latest = self.latest_step()
        for name in entries:
            path = os.path.join(self.directory, name)
            if not _TMP_RE.match(name) or path in live:
                continue
            if name.endswith(".tmp-shared"):
                # may be live on ANOTHER host (multi-host shared fs): only
                # reap once the fleet has visibly moved past it — a step at
                # or below the latest commit can no longer be mid-save
                # under the barrier discipline, so its scratch is litter
                m = _STEP_RE.match(name.split(".tmp-")[0])
                if latest is None or (m and int(m.group(1)) > latest):
                    continue
            shutil.rmtree(path, ignore_errors=True)

    def _gc(self) -> None:
        if not self.max_to_keep or self.max_to_keep <= 0:
            return
        steps = self.all_steps()
        while len(steps) > self.max_to_keep:
            victim = steps.pop(0)
            shutil.rmtree(self.step_path(victim), ignore_errors=True)
            _M_GC.inc()

    # ----------------------------------------------------------- restore
    def verify(self, step: int) -> Tuple[bool, str]:
        """Re-check a committed step against its COMMIT record: every
        listed file must exist with matching size and CRC32."""
        return self._verify_dir(self.step_path(step))

    def _verify_dir(self, dirpath: str) -> Tuple[bool, str]:
        commit = os.path.join(dirpath, _COMMIT_FILE)
        try:
            with open(commit) as f:
                payload = json.load(f)
        except (OSError, ValueError) as exc:
            return False, f"unreadable COMMIT marker: {exc}"
        for name, rec in payload.get("files", {}).items():
            path = os.path.join(dirpath, name)
            if not os.path.isfile(path):
                return False, f"missing file {name!r}"
            size, crc = _file_digest(path)
            if size != rec.get("size"):
                return False, (f"size mismatch for {name!r}: "
                               f"{size} != {rec.get('size')}")
            if crc != rec.get("crc32"):
                return False, f"crc32 mismatch for {name!r}"
        return True, ""

    def _quarantine(self, step: int, reason: str) -> None:
        src = self.step_path(step)
        dst = os.path.join(
            self.directory,
            f"{_CORRUPT_PREFIX}{_step_name(step)}-{os.urandom(4).hex()}")
        try:
            os.rename(src, dst)
        except OSError:
            shutil.rmtree(src, ignore_errors=True)
        _M_CORRUPT.inc()
        sys.stderr.write(
            f"[paddle_tpu.checkpoint] quarantined step {step} "
            f"({reason}) -> {dst}\n")

    def restore(self, step: Optional[int] = None, shardings: Optional[Dict]
                = None, target: Optional[Dict] = None) -> Tuple[Dict, int]:
        """Load a committed step (newest by default), verifying checksums.

        A step that fails verification is quarantined and the next-newest
        one tried; returns ``(state, step)`` or raises
        :class:`CheckpointNotFoundError` when nothing valid remains.
        ``shardings``/``target`` re-place arrays exactly like
        ``distributed.checkpoint.load_state_dict``."""
        steps = self.all_steps()
        if step is not None:
            if int(step) not in steps:
                raise CheckpointNotFoundError(
                    f"step {step} is not committed in {self.directory}")
            candidates = [int(step)]
        else:
            candidates = sorted(steps, reverse=True)
        fell_back = False
        for s in candidates:
            ok, reason = self._verify_dir(self.step_path(s))
            if not ok:
                self._quarantine(s, reason)
                fell_back = True
                continue
            state = self._load_dir(self.step_path(s), shardings, target)
            if fell_back:
                _M_FALLBACK.inc()
            return state, s
        raise CheckpointNotFoundError(
            f"no valid committed checkpoint in {self.directory}"
            + (" (newest candidates were quarantined)" if fell_back else ""))

    def _load_dir(self, dirpath: str, shardings, target) -> Dict:
        loaded = dist_ckpt.load_state_dict(dirpath, shardings=shardings,
                                           target=target)
        flat = _flatten(loaded)
        scalars_path = os.path.join(dirpath, _SCALARS_FILE)
        if os.path.isfile(scalars_path):
            with open(scalars_path) as f:
                flat.update(json.load(f))
        return _unflatten(flat)

    def restore_or_init(self, default: Any = None,
                        shardings: Optional[Dict] = None,
                        target: Optional[Dict] = None) -> RestoreResult:
        """One-call auto-resume: the newest valid committed state, or
        ``default`` when the directory holds nothing restorable."""
        try:
            state, step = self.restore(shardings=shardings, target=target)
        except CheckpointNotFoundError:
            return RestoreResult(default, None, False)
        return RestoreResult(state, step, True)

    # ------------------------------------------------------- preemption
    def save_on_signal(self, state_fn: Callable[[], Tuple[int, Dict]],
                       signals: Tuple = (_signal.SIGTERM, _signal.SIGINT),
                       exit_on_save: bool = True,
                       drain_timeout_s: float = 10.0) -> "_SignalScope":
        """Install preemption handlers: on SIGTERM/SIGINT, call
        ``state_fn() -> (step, state)``, commit it synchronously, and (by
        default) exit 0 — a clean preemption the next job resumes from via
        ``restore_or_init``. In-flight async saves are drained first
        (bounded by ``drain_timeout_s`` — preemption notices carry a grace
        period, and a writer wedged on the lock our interrupted frame holds
        must not hang the handler). Returns a scope usable as a context
        manager; ``scope.uninstall()`` (or scope exit) restores the old
        handlers. Main-thread only, like any Python signal handler."""

        def _handler(signum, frame):
            self.preempted = True
            try:
                # drain in-flight async saves first: one of them may be
                # committing the very step we'd save (racing its rename),
                # and anything already queued should land before we exit
                _drain_pending(drain_timeout_s)
                step, state = state_fn()
                # a wedged async save may publish our step AFTER the
                # drain timed out — losing that race means the checkpoint
                # is durable, which is success here
                self.save_if_absent(int(step), state)
            finally:
                scope.uninstall()
            if exit_on_save:
                sys.exit(0)

        scope = faults.install_signal_handler(_handler, signals=signals)
        return scope


# The install/uninstall discipline lives in paddle_tpu.faults.signals now
# (shared with Router.install_signal_handlers); the old private name stays
# importable for callers that annotate against it.
_SignalScope = faults.SignalScope
