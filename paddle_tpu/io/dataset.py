"""Dataset types.

reference parity: python/paddle/fluid/dataloader/dataset.py (Dataset,
IterableDataset, TensorDataset, ComposeDataset, ChainDataset, ConcatDataset,
Subset, random_split).
"""
from __future__ import annotations

import bisect
from typing import Iterable, List, Sequence

import numpy as np

from ..tensor import Tensor

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "ConcatDataset", "Subset", "random_split",
]


class Dataset:
    """Map-style dataset (reference: dataloader/dataset.py Dataset)."""

    def __getitem__(self, idx):
        raise NotImplementedError(
            f"{type(self).__name__} must implement __getitem__"
        )

    def __len__(self):
        raise NotImplementedError(
            f"{type(self).__name__} must implement __len__"
        )


class IterableDataset(Dataset):
    """Stream-style dataset (reference: dataloader/dataset.py
    IterableDataset)."""

    def __iter__(self):
        raise NotImplementedError(
            f"{type(self).__name__} must implement __iter__"
        )

    def __getitem__(self, idx):
        raise TypeError("IterableDataset does not support indexing")

    def __len__(self):
        # TypeError (not RuntimeError) so list(ds) treats it as "no length
        # hint" instead of propagating
        raise TypeError("IterableDataset does not support len()")


class TensorDataset(Dataset):
    """Wraps equal-first-dim tensors; item i is the tuple of row i."""

    def __init__(self, tensors: Sequence):
        arrays = []
        for t in tensors:
            if isinstance(t, Tensor):
                arrays.append(t.numpy())
            else:
                arrays.append(np.asarray(t))
        n = arrays[0].shape[0]
        for a in arrays:
            assert a.shape[0] == n, "tensors must share dim 0 size"
        self._arrays = arrays

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self._arrays)

    def __len__(self):
        return self._arrays[0].shape[0]


class ComposeDataset(Dataset):
    """Zip of same-length datasets; fields concatenated."""

    def __init__(self, datasets: Sequence[Dataset]):
        assert datasets, "datasets must not be empty"
        self._datasets = list(datasets)
        n = len(self._datasets[0])
        for d in self._datasets:
            assert len(d) == n, "datasets must share length"

    def __len__(self):
        return len(self._datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self._datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    """Concatenation of iterable datasets."""

    def __init__(self, datasets: Sequence[IterableDataset]):
        self._datasets = list(datasets)

    def __iter__(self):
        for d in self._datasets:
            yield from d


class ConcatDataset(Dataset):
    """Concatenation of map-style datasets."""

    def __init__(self, datasets: Iterable[Dataset]):
        self.datasets = list(datasets)
        assert self.datasets, "datasets must not be empty"
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = 0 if ds_idx == 0 else self.cumulative_sizes[ds_idx - 1]
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset: Dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset: Dataset, lengths: Sequence, generator=None) -> List[Subset]:
    """reference: dataloader/dataset.py random_split (supports fractions)."""
    if all(isinstance(l, float) for l in lengths):
        if abs(sum(lengths) - 1.0) > 1e-6:
            raise ValueError(
                f"Fractional lengths must sum to 1, got {sum(lengths)}"
            )
        total = len(dataset)
        counts = [int(np.floor(total * f)) for f in lengths]
        rem = total - sum(counts)
        for i in range(rem):
            counts[i % len(counts)] += 1
        lengths = counts
    if sum(lengths) != len(dataset):
        raise ValueError(
            "Sum of input lengths does not equal the length of the input dataset!"
        )
    from ..generator import host_rng

    perm = host_rng().permutation(len(dataset)).tolist()
    out, offset = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[offset:offset + n]))
        offset += n
    return out
