"""Samplers.

reference parity: python/paddle/fluid/dataloader/sampler.py (Sampler,
SequenceSampler, RandomSampler, WeightedRandomSampler) and batch_sampler.py
(BatchSampler, DistributedBatchSampler).
"""
from __future__ import annotations

import itertools
from typing import Iterator, List, Optional, Sequence

import numpy as np

from ..generator import default_generator, host_rng

__all__ = [
    "Sampler", "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
    "BatchSampler", "DistributedBatchSampler", "SubsetRandomSampler",
]


def _epoch_rng(epoch, tag: int) -> np.random.Generator:
    """Epoch-deterministic RNG for randomized samplers whose epoch is
    pinned (checkpoint resume must replay the exact order). The per-class
    ``tag`` and the tuple shape give domain separation from host_rng()'s
    (seed, counter) space and from each other — two samplers sharing a
    seed and epoch must not draw in lockstep. epoch=None keeps the legacy
    free-running stream."""
    if epoch is None:
        return host_rng()
    return np.random.default_rng((default_generator.seed(), tag, epoch))


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source
        # set_epoch pins randomized samplers to an epoch-deterministic
        # stream — the contract checkpoint resume relies on: the same
        # (global seed, epoch) must yield the same order in both the
        # interrupted and the resumed run. None = legacy free-running RNG.
        self.epoch: Optional[int] = None

    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)

    def __iter__(self) -> Iterator[int]:
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        # reference: sampler.py rejects oversampling without replacement at
        # construction — failing here keeps __len__ honest for DataLoader
        # sizing instead of blowing up mid-epoch
        if (not replacement and num_samples is not None
                and generator is None and num_samples > len(data_source)):
            raise ValueError(
                "RandomSampler: num_samples should not exceed dataset "
                "length when replacement=False")
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.generator is not None:
            # bounded to num_samples (an unbounded generator must not make
            # the epoch infinite)
            yield from (int(i) for i in
                        itertools.islice(self.generator, self.num_samples))
            return
        rng = _epoch_rng(self.epoch, 0x5EED)
        if self.replacement:
            yield from rng.integers(0, n, size=self.num_samples).tolist()
        else:
            yield from rng.permutation(n)[:self.num_samples].tolist()

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices: Sequence[int]):
        super().__init__(None)
        self.indices = list(indices)

    def __iter__(self):
        rng = _epoch_rng(self.epoch, 0x5EEE)
        yield from (self.indices[i] for i in rng.permutation(len(self.indices)))

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights: Sequence[float], num_samples: int,
                 replacement: bool = True):
        super().__init__(None)
        self.weights = np.asarray(weights, dtype=np.float64)
        if num_samples <= 0:
            raise ValueError("num_samples should be a positive integer")
        if not replacement and num_samples > len(self.weights):
            raise ValueError(
                "num_samples should not be larger than weights length when "
                "replacement is False"
            )
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        rng = _epoch_rng(self.epoch, 0x5EEF)
        idx = rng.choice(len(p), size=self.num_samples, replace=self.replacement, p=p)
        yield from idx.tolist()

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """reference: dataloader/batch_sampler.py BatchSampler."""

    def __init__(self, dataset=None, sampler: Optional[Sampler] = None,
                 shuffle: bool = False, batch_size: int = 1,
                 drop_last: bool = False):
        super().__init__(dataset)
        if sampler is not None:
            assert dataset is None, "either dataset or sampler, not both"
            self.sampler = sampler
        else:
            assert dataset is not None, "either dataset or sampler must be given"
            self.sampler = (
                RandomSampler(dataset) if shuffle else SequenceSampler(dataset)
            )
        assert batch_size > 0, "batch_size should be a positive integer"
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.shuffle = shuffle

    def set_epoch(self, epoch: int) -> None:
        """Forwarded to the wrapped sampler: makes a shuffled epoch
        deterministic given (global seed, epoch) — checkpoint resume
        replays the exact same batch order."""
        super().set_epoch(epoch)
        if hasattr(self.sampler, "set_epoch"):
            self.sampler.set_epoch(epoch)

    def __iter__(self) -> Iterator[List[int]]:
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards batches across data-parallel ranks (reference:
    python/paddle/fluid/dataloader/batch_sampler.py DistributedBatchSampler).

    On TPU the same sampler serves jax.process-level sharding: each host
    loads only its shard and the global batch is assembled by the mesh
    sharding (distributed/dataloader wires this up)."""

    def __init__(self, dataset, batch_size: int, num_replicas: Optional[int] = None,
                 rank: Optional[int] = None, shuffle: bool = False,
                 drop_last: bool = False):
        self.dataset = dataset
        assert batch_size > 0
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.shuffle = shuffle
        if num_replicas is None or rank is None:
            from .. import distributed as dist

            num_replicas = num_replicas if num_replicas is not None else dist.get_world_size()
            rank = rank if rank is not None else dist.get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.default_rng(self.epoch)
            indices = rng.permutation(n).tolist()
            self.epoch += 1
        else:
            indices = list(range(n))
        # pad to be evenly divisible; cycle when total_size - n > n
        # (tiny dataset over many replicas)
        while len(indices) < self.total_size:
            indices += indices[: (self.total_size - len(indices))]
        # subsample for this rank
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch: int):
        self.epoch = epoch
