"""DataLoader.

reference parity: python/paddle/io.DataLoader (fluid/reader.py:311) +
fluid/dataloader/dataloader_iter.py (single-process and multi-worker prefetch
iterators with shared-memory queues).

TPU-first reshaping: the reference's multiprocess workers + shared-memory
blobs exist to keep CUDA-stream H2D copies off the Python loop. On TPU the
equivalent goal is keeping the XLA dispatch pipeline fed: batches are
assembled as host numpy arrays by a pool of prefetch worker threads (numpy
slicing/decoding releases the GIL) feeding a bounded queue, and transfer to
device HBM happens asynchronously on first use inside jit. num_workers>0
selects threaded prefetch; num_workers=0 is fully synchronous (debuggable),
matching the reference's semantics.
"""
from __future__ import annotations

import itertools
import queue
import threading
import traceback
from multiprocessing import TimeoutError as _mp_TimeoutError
from typing import Any, Callable, Optional

import numpy as np

from ..tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn"]


def _end_epoch_once(it):
    """Advance the loader's epoch exactly once per exhausted iterator, no
    matter how many times next() is re-called on it."""
    if not getattr(it, "_epoch_noted", False):
        it._epoch_noted = True
        it._loader._note_epoch_end()


def default_collate_fn(batch):
    """Stack samples into batch arrays (reference:
    fluid/dataloader/collate.py default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s.numpy()) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.number)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(group)) for group in transposed]
    raise TypeError(f"batch data must be tensor/ndarray/number/dict/list, got {type(sample)}")


class _SingleProcessIter:
    def __init__(self, loader: "DataLoader"):
        self._loader = loader
        # lazy: the sampler streams batch-by-batch (an epoch of a 100M
        # sample dataset must not materialize millions of index lists);
        # only the prefetch iterators need the whole list up front
        self._it = loader._epoch_index_iter()

    def __iter__(self):
        return self

    def __next__(self):
        try:
            indices = next(self._it)
        except StopIteration:
            _end_epoch_once(self)
            raise
        batch = self._loader._fetch(indices)
        self._loader._note_batch(len(indices))
        return batch


class _ThreadedPrefetchIter:
    """Bounded-queue prefetch over worker threads; preserves batch order."""

    def __init__(self, loader: "DataLoader"):
        self._loader = loader
        self._indices = loader._epoch_indices()
        capacity = max(2, loader.prefetch_factor * loader.num_workers)
        self._results: dict = {}
        self._results_lock = threading.Condition()
        self._next_out = 0
        self._next_in = 0
        self._in_lock = threading.Lock()
        self._capacity = capacity
        self._shutdown = False
        self._workers = [
            threading.Thread(target=self._work, args=(wid,), daemon=True)
            for wid in range(loader.num_workers)
        ]
        for w in self._workers:
            w.start()

    def _work(self, worker_id: int):
        init_err = None
        try:
            if self._loader.worker_init_fn is not None:
                self._loader.worker_init_fn(worker_id)
        except Exception:
            # must not die silently: claim batches and deliver the error,
            # otherwise the consumer waits forever on the missing index
            init_err = traceback.format_exc()
        while True:
            with self._in_lock:
                i = self._next_in
                if i >= len(self._indices):
                    return
                self._next_in += 1
            if init_err is not None:
                payload = (i, None, init_err)
            else:
                try:
                    batch = self._loader._fetch(self._indices[i])
                    payload = (i, batch, None)
                except Exception:  # propagate to consumer
                    payload = (i, None, traceback.format_exc())
            with self._results_lock:
                while (not self._shutdown and
                       i - self._next_out >= self._capacity):
                    self._results_lock.wait(0.1)
                if self._shutdown:
                    return
                self._results[i] = payload
                self._results_lock.notify_all()

    def __iter__(self):
        return self

    def __next__(self):
        if self._next_out >= len(self._indices):
            self.close()
            _end_epoch_once(self)
            raise StopIteration
        with self._results_lock:
            while self._next_out not in self._results:
                self._results_lock.wait()
            i, batch, err = self._results.pop(self._next_out)
            self._next_out += 1
            self._results_lock.notify_all()
        if err is not None:
            self.close()
            raise RuntimeError(f"DataLoader worker failed:\n{err}")
        self._loader._note_batch(len(self._indices[i]))
        return batch

    def close(self):
        with self._results_lock:
            self._shutdown = True
            self._results_lock.notify_all()

    def __del__(self):
        self.close()


_process_worker_state: dict = {}


def _process_worker_init(dataset, init_fn, num_workers=1, id_counter=None):
    """Pool initializer: runs once per worker process (dataset pickled once,
    not per batch). Worker ids come from a shared counter, NOT
    mp.current_process()._identity — that is a parent-global counter that
    never resets, so a second epoch's pool would see ids N..2N-1 and any
    dataset sharding by worker id would silently go wrong."""
    _process_worker_state["dataset"] = dataset
    _process_worker_state["num_workers"] = num_workers
    if id_counter is not None:
        with id_counter.get_lock():
            wid = id_counter.value
            id_counter.value += 1
    else:
        import multiprocessing as mp

        ident = mp.current_process()._identity
        wid = (ident[0] - 1) if ident else 0
    _process_worker_state["worker_id"] = wid % max(num_workers, 1)
    if init_fn is not None:
        init_fn(_process_worker_state["worker_id"])


def _process_fetch(indices):
    ds = _process_worker_state["dataset"]
    return [ds[i] for i in indices]


def _workers_crash_looping(pool, seen_pids, num_workers):
    """True when the pool is respawning dead-on-arrival workers. A healthy
    pool keeps a stable set of worker PIDs for its whole life; a worker
    whose initializer (or spawn import) dies is silently replaced by
    mp.Pool with a fresh process — forever — so the submitted tasks never
    run and every result.get() blocks. Distinct-PID churn past 3x the
    pool size is that loop, not a slow dataset."""
    for p in getattr(pool, "_pool", None) or []:
        if p.pid is not None:
            seen_pids.add(p.pid)
    return len(seen_pids) > 3 * max(num_workers, 1)


class _ProcessPoolIter:
    """Multiprocess sample fetching (reference: dataloader_iter.py's
    _DataLoaderIterMultiProcess — worker subprocesses + shared queues).

    Workers decode samples in parallel OS processes (no GIL: the fix for
    ImageNet-style decode+augment that thread workers cannot parallelize,
    VERDICT r1 weak #7); the parent applies collate so jax arrays never
    cross the process boundary. ``spawn`` start method: forking a
    jax-initialized parent is a deadlock hazard."""

    def __init__(self, loader: "DataLoader"):
        import multiprocessing as mp
        from collections import deque

        self._loader = loader
        self._indices = loader._epoch_indices()
        ctx = mp.get_context("spawn")
        self._pool = ctx.Pool(
            loader.num_workers, initializer=_process_worker_init,
            initargs=(loader.dataset, loader.worker_init_fn,
                      loader.num_workers, ctx.Value("i", 0)))
        # bounded in-flight via apply_async (Pool.imap's task-feeder thread
        # drains the whole input eagerly — no backpressure, epoch-sized
        # result buildup); prefetch_factor * workers stays the cap like the
        # thread iterator and the reference's outstanding_capacity
        self._capacity = max(2, loader.prefetch_factor * loader.num_workers)
        self._pending = deque()
        self._next_submit = 0
        self._seen_pids: set = set()
        self._fill()

    def _fill(self):
        while (self._next_submit < len(self._indices)
               and len(self._pending) < self._capacity):
            self._pending.append(self._pool.apply_async(
                _process_fetch, (self._indices[self._next_submit],)))
            self._next_submit += 1

    def __iter__(self):
        return self

    def __next__(self):
        if not self._pending:
            self.close()
            _end_epoch_once(self)
            raise StopIteration
        res = self._pending.popleft()
        while True:
            try:
                samples = res.get(timeout=1.0)
                break
            except _mp_TimeoutError:
                if _workers_crash_looping(self._pool, self._seen_pids,
                                          self._loader.num_workers):
                    self.close()
                    raise RuntimeError(
                        "process dataloader: worker processes are "
                        f"crash-looping ({len(self._seen_pids)} distinct "
                        f"workers spawned for {self._loader.num_workers} "
                        "slots) — worker init failed; see worker stderr")
            except Exception:
                self.close()
                raise
        self._fill()
        collate = self._loader.collate_fn or default_collate_fn
        batch = collate(samples)
        self._loader._note_batch(len(samples))
        return batch

    def close(self):
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.terminate()
            pool.join()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _shm_worker_init(dataset, init_fn, channel_name, num_workers=1,
                     id_counter=None):
    _process_worker_init(dataset, init_fn, num_workers, id_counter)
    from .shm_channel import ShmChannel

    _process_worker_state["channel"] = ShmChannel(channel_name, create=False)


def _shm_fetch(seq, indices):
    ds = _process_worker_state["dataset"]
    samples = [ds[i] for i in indices]
    _process_worker_state["channel"].put((seq, samples))
    return seq  # tiny ack through the Pool pipe; payload rode the shm ring


class _ShmProcessPoolIter:
    """Process workers + shared-memory batch transport (reference:
    use_shared_memory=True in dataloader_iter.py — decoded batches travel
    through a native shm ring, paddle_tpu/native/src/shm_ring.cc, so the
    Pool result pipe carries only sequence-number acks)."""

    def __init__(self, loader: "DataLoader"):
        import multiprocessing as mp
        from collections import deque

        from .shm_channel import ShmChannel

        # attribute defaults first: a partially-constructed iterator must
        # still close() cleanly (and unlink the shm segment)
        self._loader = loader
        self._pool = None
        self._channel = None
        self._indices = loader._epoch_indices()
        self._capacity = max(2, loader.prefetch_factor * loader.num_workers)
        self._pending = deque()
        self._next_submit = 0
        self._next_seq = 0  # next batch owed to the consumer, in order
        self._stash = {}    # out-of-order batches parked by seq
        self._seen_pids: set = set()
        try:
            self._channel = ShmChannel()  # owner: unlinked on close
            ctx = mp.get_context("spawn")
            self._pool = ctx.Pool(
                loader.num_workers, initializer=_shm_worker_init,
                initargs=(loader.dataset, loader.worker_init_fn,
                          self._channel.name, loader.num_workers,
                          ctx.Value("i", 0)))
            self._fill()
        except Exception:
            self.close()
            raise

    def _fill(self):
        while (self._next_submit < len(self._indices)
               and len(self._pending) < self._capacity):
            self._pending.append(self._pool.apply_async(
                _shm_fetch,
                (self._next_submit, self._indices[self._next_submit])))
            self._next_submit += 1

    def __iter__(self):
        return self

    def _reap_acks(self):
        """Surface worker exceptions from any FINISHED acks without
        blocking. Never block on an ack: the worker behind it may itself
        be blocked pushing into a full ring that only we can drain."""
        while self._pending and self._pending[0].ready():
            ack = self._pending.popleft()
            try:
                ack.get()
            except Exception:
                self.close()
                raise
            self._fill()

    def __next__(self):
        if self._next_seq >= len(self._indices):
            self.close()
            _end_epoch_once(self)
            raise StopIteration
        want = self._next_seq
        while want not in self._stash:
            self._reap_acks()
            try:
                # draining the ring is the priority (it is the workers'
                # backpressure); short timeout so ack errors surface too
                seq, samples = self._channel.get(timeout=1.0)
                self._stash[seq] = samples
            except TimeoutError:
                if not self._pending and want not in self._stash:
                    self.close()
                    raise RuntimeError(
                        "shm dataloader: workers ended without producing "
                        f"batch {want}")
                if _workers_crash_looping(self._pool, self._seen_pids,
                                          self._loader.num_workers):
                    self.close()
                    raise RuntimeError(
                        "shm dataloader: worker processes are crash-looping "
                        f"({len(self._seen_pids)} distinct workers spawned "
                        f"for {self._loader.num_workers} slots) — worker "
                        "init failed; see worker stderr")
        samples = self._stash.pop(want)
        self._next_seq += 1
        collate = self._loader.collate_fn or default_collate_fn
        batch = collate(samples)
        self._loader._note_batch(len(samples))
        return batch

    def close(self):
        pool, self._pool = getattr(self, "_pool", None), None
        if pool is not None:
            pool.terminate()
            pool.join()
        chan, self._channel = getattr(self, "_channel", None), None
        if chan is not None:
            chan.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _IterableDatasetIter:
    def __init__(self, loader: "DataLoader"):
        self._loader = loader
        self._it = iter(loader.dataset)
        # resume for iterable datasets = skip-by-consume: the stream is
        # re-iterated from the top and the already-served prefix discarded
        # (sample-exact iff the iterable is deterministic); counters reset
        # to what this iterator actually skipped
        skip = loader._consume_resume_batches()
        loader._batches_served = loader._samples_served = 0
        for b in _chunks_consumed(self._it, skip, loader.batch_size,
                                  loader.drop_last):
            loader._batches_served += 1
            loader._samples_served += len(b)

    def __iter__(self):
        return self

    def __next__(self):
        batch = list(itertools.islice(self._it, self._loader.batch_size))
        if not batch:
            _end_epoch_once(self)
            raise StopIteration
        if self._loader.drop_last and len(batch) < self._loader.batch_size:
            _end_epoch_once(self)
            raise StopIteration
        collate = self._loader.collate_fn or default_collate_fn
        out = collate(batch)
        self._loader._note_batch(len(batch))
        return out


def _chunks_consumed(it, n_batches, batch_size, drop_last):
    """Pull (and discard) the first ``n_batches`` batches of an iterable
    stream, yielding them so the caller can count skipped samples."""
    for _ in range(n_batches):
        batch = list(itertools.islice(it, batch_size))
        if not batch or (drop_last and len(batch) < batch_size):
            return
        yield batch


class DataLoader:
    """reference: paddle.io.DataLoader (fluid/reader.py:311).

    Examples:
        >>> class Squares(paddle.io.Dataset):
        ...     def __len__(self):
        ...         return 8
        ...     def __getitem__(self, i):
        ...         return np.float32(i), np.float32(i * i)
        >>> loader = paddle.io.DataLoader(Squares(), batch_size=4)
        >>> xs, ys = next(iter(loader))
        >>> xs.shape
        [4]
    """

    def __init__(
        self,
        dataset: Dataset,
        feed_list=None,
        places=None,
        return_list: bool = True,
        batch_sampler: Optional[BatchSampler] = None,
        batch_size: Optional[int] = 1,
        shuffle: bool = False,
        drop_last: bool = False,
        collate_fn: Optional[Callable] = None,
        num_workers: int = 0,
        use_buffer_reader: bool = True,
        prefetch_factor: int = 2,
        use_shared_memory: bool = True,
        timeout: int = 0,
        worker_init_fn: Optional[Callable] = None,
        persistent_workers: bool = False,
        worker_mode: str = "thread",
    ):
        del feed_list, places, return_list  # static-graph-only args
        del use_buffer_reader, timeout, persistent_workers
        self.dataset = dataset
        self.collate_fn = collate_fn
        self.num_workers = max(0, int(num_workers))
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        # shared-memory transport for process workers (reference default):
        # batches ride a native shm ring instead of the Pool result pipe
        self.use_shared_memory = bool(use_shared_memory)
        if worker_mode not in ("thread", "process"):
            raise ValueError("worker_mode must be 'thread' or 'process'")
        # 'thread' suits tokenized/numpy batches (zero pickling constraints);
        # 'process' is the reference's subprocess model for GIL-bound decode
        # (dataset must be picklable; see _ProcessPoolIter)
        self.worker_mode = worker_mode
        self._is_iterable = isinstance(dataset, IterableDataset)
        self.drop_last = drop_last
        # checkpoint-resume position: epoch + batches/samples consumed this
        # epoch (docs/RESILIENCE.md). _resume_batches is the pending skip
        # the NEXT __iter__ applies; counters advance as batches are
        # *consumed* (not prefetched), so state_dict() mid-epoch is exact.
        self._epoch = 0
        self._batches_served = 0
        self._samples_served = 0
        self._resume_batches = 0
        # epoch-driving (set_epoch per __iter__) applies only to a sampler
        # the loader built itself: a user-provided batch_sampler keeps full
        # control of its own epoch/shuffle stream (the reference pattern of
        # calling DistributedBatchSampler.set_epoch by hand every epoch)
        self._owns_batch_sampler = False
        if self._is_iterable:
            assert batch_sampler is None, (
                "batch_sampler is not supported for IterableDataset"
            )
            self.batch_size = batch_size or 1
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        else:
            assert batch_size is not None and batch_size > 0
            self.batch_size = batch_size
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)
            self._owns_batch_sampler = True

    def _fetch(self, indices):
        samples = [self.dataset[i] for i in indices]
        collate = self.collate_fn or default_collate_fn
        return collate(samples)

    # ------------------------------------------------- checkpoint resume
    def set_epoch(self, epoch: int):
        """Pin the epoch (shuffle stream + resume bookkeeping). Called
        automatically at each __iter__; call manually to replay or skip
        epochs. Epoch-seeded shuffling means the same (global seed, epoch)
        always yields the same batch order — the property checkpoint
        resume needs."""
        self._epoch = int(epoch)

    def state_dict(self) -> dict:
        """Exact stream position: epoch + batches/samples consumed within
        it. Goes inside a checkpoint (see paddle_tpu.checkpoint
        capture_train_state) so resume continues from the next sample."""
        return {"epoch": self._epoch, "batch": self._batches_served,
                "sample": self._samples_served}

    def set_state_dict(self, state: dict):
        """Resume from a :meth:`state_dict` position: the next __iter__
        replays the saved epoch's order and skips the already-consumed
        prefix, so the first batch served is exactly the one the
        interrupted run would have seen next."""
        self._epoch = int(state.get("epoch", 0))
        self._batches_served = int(state.get("batch", 0))
        self._samples_served = int(state.get("sample", 0))
        self._resume_batches = self._batches_served

    load_state_dict = set_state_dict

    def advance_batches(self, n: int):
        """Queue ``n`` ADDITIONAL batches to skip at the next
        ``__iter__``, on top of any pending resume position — the train
        sentinel's rollback primitive: restore the last-known-good
        position via :meth:`set_state_dict`, then advance past the
        quarantined window so the replay deterministically trains only on
        the batches a clean run would have (docs/RESILIENCE.md
        "Self-healing training"). A skip running past the epoch's end
        simply ends the epoch (quarantine clamps at the boundary)."""
        n = int(n)
        if n < 0:
            raise ValueError(f"advance_batches needs n >= 0, got {n}")
        self._resume_batches += n

    def _epoch_index_iter(self):
        """Lazy batch-index stream for the current epoch, the resume skip
        already consumed. The newest iterator owns the position: counters
        reset to its start offset, so an abandoned mid-epoch iterator
        can't leave stale batch/sample counts behind in state_dict()."""
        if self._owns_batch_sampler and hasattr(
                self.batch_sampler, "set_epoch"):
            self.batch_sampler.set_epoch(self._epoch)
        it = iter(self.batch_sampler)
        skip = self._consume_resume_batches()
        served = samples = 0
        for b in itertools.islice(it, skip):
            served += 1
            samples += len(b)
        self._batches_served = served
        self._samples_served = samples
        return it

    def _epoch_indices(self):
        """Materialized form of :meth:`_epoch_index_iter` for the prefetch
        iterators, which need random access for ordered multi-worker
        scheduling."""
        return list(self._epoch_index_iter())

    def _consume_resume_batches(self) -> int:
        skip, self._resume_batches = self._resume_batches, 0
        return skip

    def _note_batch(self, n_samples: int):
        self._batches_served += 1
        self._samples_served += int(n_samples)

    def _note_epoch_end(self):
        self._epoch += 1
        self._batches_served = 0
        self._samples_served = 0
        self._resume_batches = 0

    def __iter__(self):
        if self._is_iterable:
            return _IterableDatasetIter(self)
        if self.num_workers > 0:
            if self.worker_mode == "process":
                if self.use_shared_memory:
                    saved_resume = self._resume_batches
                    try:
                        return _ShmProcessPoolIter(self)
                    except Exception:  # shm unavailable: fall back to pipes
                        # the failed iterator may already have consumed the
                        # resume skip — restore it for the fallback
                        self._resume_batches = saved_resume
                return _ProcessPoolIter(self)
            return _ThreadedPrefetchIter(self)
        return _SingleProcessIter(self)

    def __len__(self):
        if self._is_iterable:
            raise TypeError("length of IterableDataset loader is unknown")
        return len(self.batch_sampler)

    def __call__(self):
        return self.__iter__()
