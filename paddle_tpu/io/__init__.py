"""paddle_tpu.io — datasets, samplers, DataLoader.

reference parity: paddle.io (python/paddle/io/, fluid/reader.py:311,
fluid/dataloader/).
"""
from .dataloader import DataLoader, default_collate_fn
from .dataset import (
    ChainDataset, ComposeDataset, ConcatDataset, Dataset, IterableDataset,
    Subset, TensorDataset, random_split,
)
from .sampler import (
    BatchSampler, DistributedBatchSampler, RandomSampler, Sampler,
    SequenceSampler, SubsetRandomSampler, WeightedRandomSampler,
)

__all__ = [
    "DataLoader", "default_collate_fn",
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "ConcatDataset", "Subset", "random_split",
    "Sampler", "SequenceSampler", "RandomSampler", "SubsetRandomSampler",
    "WeightedRandomSampler", "BatchSampler", "DistributedBatchSampler",
]


class WorkerInfo:
    """Identity of the current DataLoader worker (reference:
    fluid/dataloader/worker.py get_worker_info)."""

    def __init__(self, id: int, num_workers: int, dataset=None):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset

    def __repr__(self):
        return (f"WorkerInfo(id={self.id}, "
                f"num_workers={self.num_workers})")


def get_worker_info():
    """Inside a process worker: this worker's (id, num_workers, dataset);
    in the main process: None (reference contract)."""
    from .dataloader import _process_worker_state

    st = _process_worker_state
    if "dataset" not in st:
        return None
    return WorkerInfo(st.get("worker_id", 0), st.get("num_workers", 1),
                      st.get("dataset"))


__all__ += ["get_worker_info", "WorkerInfo"]
