"""paddle_tpu.io — datasets, samplers, DataLoader.

reference parity: paddle.io (python/paddle/io/, fluid/reader.py:311,
fluid/dataloader/).
"""
from .dataloader import DataLoader, default_collate_fn
from .dataset import (
    ChainDataset, ComposeDataset, ConcatDataset, Dataset, IterableDataset,
    Subset, TensorDataset, random_split,
)
from .sampler import (
    BatchSampler, DistributedBatchSampler, RandomSampler, Sampler,
    SequenceSampler, SubsetRandomSampler, WeightedRandomSampler,
)

__all__ = [
    "DataLoader", "default_collate_fn",
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "ConcatDataset", "Subset", "random_split",
    "Sampler", "SequenceSampler", "RandomSampler", "SubsetRandomSampler",
    "WeightedRandomSampler", "BatchSampler", "DistributedBatchSampler",
]
