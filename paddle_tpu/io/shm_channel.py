"""Shared-memory batch channel for multiprocess DataLoader workers.

Reference parity: the DataLoader's ``use_shared_memory=True`` transport
(``mmap_allocator.cc`` + ``_convert_to_tensor_list``): decoded numpy
batches move worker→trainer through a native shm ring
(paddle_tpu/native/src/shm_ring.cc) instead of the multiprocessing
result-queue pipe. Serialization is pickle protocol 5 with out-of-band
buffers, so ndarray payload bytes are written into shm exactly once and
reconstructed as zero-copy views on the consumer side.
"""
from __future__ import annotations

import ctypes
import os
import pickle
import struct
from typing import Any, Optional

from ..native import load_library

__all__ = ["ShmChannel"]

_lib = None


def _native():
    global _lib
    if _lib is None:
        lib = load_library("shm_ring")
        lib.pd_shm_ring_create.restype = ctypes.c_void_p
        lib.pd_shm_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                           ctypes.c_int]
        lib.pd_shm_ring_push.restype = ctypes.c_int
        lib.pd_shm_ring_push.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
            ctypes.c_double]
        lib.pd_shm_ring_pop.restype = ctypes.c_int64
        lib.pd_shm_ring_pop.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_double]
        lib.pd_shm_ring_free_buf.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        lib.pd_shm_ring_used.restype = ctypes.c_uint64
        lib.pd_shm_ring_used.argtypes = [ctypes.c_void_p]
        lib.pd_shm_ring_close.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


class ShmChannel:
    """Multi-producer single-consumer object channel over one shm ring."""

    DEFAULT_CAPACITY = 256 << 20  # overridable: PADDLE_TPU_SHM_CAPACITY_MB

    def __init__(self, name: Optional[str] = None,
                 capacity_bytes: Optional[int] = None, create: bool = True):
        if capacity_bytes is None:
            mb = os.environ.get("PADDLE_TPU_SHM_CAPACITY_MB")
            capacity_bytes = (int(mb) << 20) if mb else self.DEFAULT_CAPACITY
        self.name = name or f"/pt_dl_{os.getpid()}_{id(self):x}"
        self._h = _native().pd_shm_ring_create(
            self.name.encode(), capacity_bytes, 1 if create else 0)
        if not self._h:
            raise RuntimeError(
                f"ShmChannel: could not {'create' if create else 'open'} "
                f"shm ring {self.name!r}")

    # -- object transport ----------------------------------------------------
    def put(self, obj: Any, timeout: float = 300.0) -> None:
        bufs = []
        meta = pickle.dumps(obj, protocol=5, buffer_callback=bufs.append)
        # assemble ONE contiguous frame in a bytearray, then hand its
        # buffer to the ring without further copies (the ring's memcpy
        # into shm is the only remaining copy)
        frame = bytearray()
        frame += struct.pack("<I", len(meta))
        frame += meta
        for b in bufs:
            raw = b.raw()
            frame += struct.pack("<Q", raw.nbytes)
            frame += raw
        arr = (ctypes.c_uint8 * len(frame)).from_buffer(frame)
        rc = _native().pd_shm_ring_push(self._h, arr, len(frame), timeout)
        if rc == -2:
            raise ValueError(
                f"batch of {len(frame)} bytes exceeds the shm ring capacity; "
                "set PADDLE_TPU_SHM_CAPACITY_MB higher, lower the batch "
                "size, or pass use_shared_memory=False to DataLoader")
        if rc == -1:
            raise TimeoutError("ShmChannel.put: ring full past timeout "
                               "(consumer stalled?)")
        if rc != 0:
            raise RuntimeError(f"ShmChannel.put failed (rc={rc})")

    def get(self, timeout: float = 300.0) -> Any:
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = _native().pd_shm_ring_pop(self._h, ctypes.byref(out), timeout)
        if n == -1:
            raise TimeoutError("ShmChannel.get: ring empty past timeout")
        if n < 0:
            raise RuntimeError(f"ShmChannel.get failed (rc={n})")
        try:
            payload = ctypes.string_at(out, n)
        finally:
            _native().pd_shm_ring_free_buf(out)
        (meta_len,) = struct.unpack_from("<I", payload, 0)
        off = 4 + meta_len
        meta = payload[4:off]
        buffers = []
        view = memoryview(payload)
        while off < n:
            (blen,) = struct.unpack_from("<Q", payload, off)
            off += 8
            buffers.append(view[off:off + blen])
            off += blen
        return pickle.loads(meta, buffers=buffers)

    def qsize_bytes(self) -> int:
        return int(_native().pd_shm_ring_used(self._h))

    def close(self) -> None:
        if self._h:
            _native().pd_shm_ring_close(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
