"""HuggingFace checkpoint → paddle_tpu model weight conversion.

Reference-ecosystem parity: PaddleNLP's ``from_pretrained`` converters
(torch state dict → paddle params, with the per-architecture transpose
and layout fixes). Zero-egress: takes an in-memory ``state_dict`` (from
``torch.load`` on a local file, or a live ``transformers`` model's
``.state_dict()``) — no hub download path.

Layout rules encoded here:
- torch ``nn.Linear`` stores ``[out, in]``; this framework's ``nn.Linear``
  stores ``[in, out]`` → transpose.
- HF Llama q/k projections are stored for the rotate-half (half-split)
  rope convention; this framework's rope is interleaved (Meta layout,
  llama.py:_apply_rope). The inverse of the transformers conversion
  permute restores interleaved rows, so logits match exactly.
- HF GPT-2 uses ``Conv1D`` (already ``[in, out]``) → no transpose; its
  fused ``c_attn`` maps 1:1 onto this framework's fused ``qkv_proj``.

Every ``load_hf_*`` asserts exact shape agreement and returns the list of
consumed keys; unconsumed non-buffer keys raise (a silently half-loaded
checkpoint is worse than an error).
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

__all__ = ["load_hf_llama", "load_hf_gpt2", "load_hf_bert"]


def _np(v) -> np.ndarray:
    if hasattr(v, "detach"):  # torch tensor, incl. bf16 (numpy lacks bf16)
        v = v.detach().cpu().float().numpy()
    return np.asarray(v, dtype=np.float32)


def _set(param, value: np.ndarray, name: str):
    if tuple(param.shape) != tuple(value.shape):
        raise ValueError(f"{name}: checkpoint shape {value.shape} != "
                         f"model shape {tuple(param.shape)}")
    from ..autograd.engine import no_grad

    with no_grad():
        param._set_value(value.astype(np.float32))


def _assert_tied(head: np.ndarray, emb: np.ndarray) -> None:
    """A tied model can only absorb a checkpoint whose head IS the
    embedding; silently dropping a distinct trained head would corrupt
    logits with no error."""
    if head.shape != emb.shape or not np.array_equal(head, emb):
        raise ValueError(
            "checkpoint has an untied lm_head.weight but the target model "
            "ties word embeddings — rebuild with tie_word_embeddings=False")


def _unpermute_rope(w: np.ndarray, n_heads: int) -> np.ndarray:
    """[out, in] HF rotate-half rows → interleaved (Meta) rows; inverse of
    transformers' convert_llama_weights_to_hf permute."""
    out, inn = w.shape
    return (w.reshape(n_heads, 2, out // n_heads // 2, inn)
            .transpose(0, 2, 1, 3).reshape(out, inn))


class _SD:
    """Tracks consumed keys so leftovers are loud."""

    def __init__(self, state_dict: Dict):
        self.d = dict(state_dict)
        self.used = set()

    def take(self, key: str) -> np.ndarray:
        if key not in self.d:
            raise KeyError(f"checkpoint is missing {key!r} "
                           f"(has {len(self.d)} keys)")
        self.used.add(key)
        return _np(self.d[key])

    def finish(self, ignore_substrings=("rotary_emb.inv_freq",
                                        "masked_bias", ".attn.bias",
                                        "position_ids")) -> List[str]:
        left = [k for k in self.d if k not in self.used
                and not any(s in k for s in ignore_substrings)]
        if left:
            raise ValueError(
                f"{len(left)} checkpoint keys were not consumed (first 8): "
                f"{left[:8]} — architecture/config mismatch?")
        return sorted(self.used)


def load_hf_llama(model, state_dict: Dict) -> List[str]:
    """Load a HF ``LlamaForCausalLM`` state dict into
    :class:`paddle_tpu.models.LlamaForCausalLM`. Returns consumed keys."""
    cfg = model.config
    nh, nkv = cfg.num_heads, cfg.num_key_value_heads
    sd = _SD(state_dict)

    _set(model.llama.embed_tokens.weight, sd.take("model.embed_tokens.weight"),
         "embed_tokens")
    for i, layer in enumerate(model.llama.layers):
        p = f"model.layers.{i}."
        a = layer.self_attn
        _set(a.q_proj.weight,
             _unpermute_rope(sd.take(p + "self_attn.q_proj.weight"), nh).T,
             p + "q_proj")
        _set(a.k_proj.weight,
             _unpermute_rope(sd.take(p + "self_attn.k_proj.weight"), nkv).T,
             p + "k_proj")
        _set(a.v_proj.weight, sd.take(p + "self_attn.v_proj.weight").T,
             p + "v_proj")
        _set(a.o_proj.weight, sd.take(p + "self_attn.o_proj.weight").T,
             p + "o_proj")
        _set(layer.mlp.gate_proj.weight,
             sd.take(p + "mlp.gate_proj.weight").T, p + "gate_proj")
        _set(layer.mlp.up_proj.weight,
             sd.take(p + "mlp.up_proj.weight").T, p + "up_proj")
        _set(layer.mlp.down_proj.weight,
             sd.take(p + "mlp.down_proj.weight").T, p + "down_proj")
        _set(layer.input_layernorm.weight,
             sd.take(p + "input_layernorm.weight"), p + "input_ln")
        _set(layer.post_attention_layernorm.weight,
             sd.take(p + "post_attention_layernorm.weight"), p + "post_ln")
    _set(model.llama.norm.weight, sd.take("model.norm.weight"), "norm")
    if model.lm_head is not None:
        key = ("lm_head.weight" if "lm_head.weight" in sd.d
               else "model.embed_tokens.weight")
        _set(model.lm_head.weight, sd.take(key).T, "lm_head")
    elif "lm_head.weight" in sd.d:
        _assert_tied(sd.take("lm_head.weight"),
                     _np(sd.d["model.embed_tokens.weight"]))
    return sd.finish()


def load_hf_gpt2(model, state_dict: Dict,
                 expect_gelu_new: bool = True) -> List[str]:
    """Load a HF ``GPT2LMHeadModel`` state dict into
    :class:`paddle_tpu.models.GPTForCausalLM` (Conv1D: no transpose).

    Real GPT-2 checkpoints use the tanh-approximate gelu ("gelu_new"), so
    the target must be built with ``GPTConfig(gelu_approximate=True)`` —
    enforced here because the resulting ~1e-3 logits drift would be
    silent. Pass ``expect_gelu_new=False`` for a checkpoint whose HF
    config says ``activation_function="gelu"``."""
    if expect_gelu_new and not model.config.gelu_approximate:
        raise ValueError(
            "HF gpt2 checkpoints use gelu_new (tanh approximation); build "
            "the model with GPTConfig(gelu_approximate=True), or pass "
            "expect_gelu_new=False if the source config used exact gelu")
    sd = _SD(state_dict)
    gpt = model.gpt

    _set(gpt.embeddings.weight, sd.take("transformer.wte.weight"), "wte")
    _set(gpt.position_embeddings.weight, sd.take("transformer.wpe.weight"),
         "wpe")
    for i, layer in enumerate(gpt.layers):
        p = f"transformer.h.{i}."
        _set(layer.ln1.weight, sd.take(p + "ln_1.weight"), p + "ln1.w")
        _set(layer.ln1.bias, sd.take(p + "ln_1.bias"), p + "ln1.b")
        _set(layer.attn.qkv_proj.weight, sd.take(p + "attn.c_attn.weight"),
             p + "qkv.w")
        _set(layer.attn.qkv_proj.bias, sd.take(p + "attn.c_attn.bias"),
             p + "qkv.b")
        _set(layer.attn.out_proj.weight, sd.take(p + "attn.c_proj.weight"),
             p + "attn_out.w")
        _set(layer.attn.out_proj.bias, sd.take(p + "attn.c_proj.bias"),
             p + "attn_out.b")
        _set(layer.ln2.weight, sd.take(p + "ln_2.weight"), p + "ln2.w")
        _set(layer.ln2.bias, sd.take(p + "ln_2.bias"), p + "ln2.b")
        _set(layer.mlp.fc1.weight, sd.take(p + "mlp.c_fc.weight"),
             p + "fc1.w")
        _set(layer.mlp.fc1.bias, sd.take(p + "mlp.c_fc.bias"), p + "fc1.b")
        _set(layer.mlp.fc2.weight, sd.take(p + "mlp.c_proj.weight"),
             p + "fc2.w")
        _set(layer.mlp.fc2.bias, sd.take(p + "mlp.c_proj.bias"), p + "fc2.b")
    _set(gpt.ln_f.weight, sd.take("transformer.ln_f.weight"), "ln_f.w")
    _set(gpt.ln_f.bias, sd.take("transformer.ln_f.bias"), "ln_f.b")
    if getattr(model, "lm_head", None) is not None:
        key = ("lm_head.weight" if "lm_head.weight" in sd.d
               else "transformer.wte.weight")
        _set(model.lm_head.weight, sd.take(key).T, "lm_head")
    elif "lm_head.weight" in sd.d:
        _assert_tied(sd.take("lm_head.weight"),
                     _np(sd.d["transformer.wte.weight"]))
    return sd.finish()


def load_hf_bert(model, state_dict: Dict,
                 layer_norm_eps: float = 1e-12) -> List[str]:
    """Load a HF ``BertModel`` (or the ``bert.`` submodule of a
    ``BertFor*`` head model — head weights are ignored) state dict into
    :class:`paddle_tpu.models.BertModel` (torch Linear: transpose).
    ``layer_norm_eps`` must be the HF config's value (HF default 1e-12);
    it is applied to every LayerNorm so hidden states match exactly."""
    sd = _SD(state_dict)
    emb = model.embeddings

    def tk(k):
        # accept both bare BertModel ("embeddings...") and BertFor* dumps
        # ("bert.embeddings...")
        return sd.take(k if k in sd.d else "bert." + k)

    _set(emb.word_embeddings.weight,
         tk("embeddings.word_embeddings.weight"), "word_emb")
    _set(emb.position_embeddings.weight,
         tk("embeddings.position_embeddings.weight"), "pos_emb")
    _set(emb.token_type_embeddings.weight,
         tk("embeddings.token_type_embeddings.weight"), "type_emb")
    _set(emb.layer_norm.weight, tk("embeddings.LayerNorm.weight"), "emb_ln.w")
    _set(emb.layer_norm.bias, tk("embeddings.LayerNorm.bias"), "emb_ln.b")
    # the TransformerEncoderLayer default eps is 1e-5 — align to the HF
    # checkpoint's so hidden states match to float tolerance
    eps = layer_norm_eps
    emb.layer_norm._epsilon = eps
    for i, layer in enumerate(model.encoder.layers):
        p = f"encoder.layer.{i}."

        def lin(dst, src, tag):
            _set(dst.weight, tk(p + src + ".weight").T, p + tag + ".w")
            _set(dst.bias, tk(p + src + ".bias"), p + tag + ".b")

        lin(layer.self_attn.q_proj, "attention.self.query", "q")
        lin(layer.self_attn.k_proj, "attention.self.key", "k")
        lin(layer.self_attn.v_proj, "attention.self.value", "v")
        lin(layer.self_attn.out_proj, "attention.output.dense", "attn_out")
        _set(layer.norm1.weight,
             tk(p + "attention.output.LayerNorm.weight"), p + "attn_ln.w")
        _set(layer.norm1.bias,
             tk(p + "attention.output.LayerNorm.bias"), p + "attn_ln.b")
        lin(layer.linear1, "intermediate.dense", "fc1")
        lin(layer.linear2, "output.dense", "fc2")
        _set(layer.norm2.weight, tk(p + "output.LayerNorm.weight"),
             p + "ffn_ln.w")
        _set(layer.norm2.bias, tk(p + "output.LayerNorm.bias"),
             p + "ffn_ln.b")
        layer.norm1._epsilon = eps
        layer.norm2._epsilon = eps
    if getattr(model, "pooler", None) is not None \
            and ("pooler.dense.weight" in sd.d
                 or "bert.pooler.dense.weight" in sd.d):
        _set(model.pooler.weight, tk("pooler.dense.weight").T, "pooler.w")
        _set(model.pooler.bias, tk("pooler.dense.bias"), "pooler.b")
    # BertFor* dumps carry task-head keys this BertModel has no slot for
    return sd.finish(ignore_substrings=("position_ids", "cls.",
                                        "classifier."))
