"""paddle_tpu.models — the transformer model zoo (flagship benchmark models).

The reference ships its LLM zoo out-of-tree (PaddleNLP); the BASELINE.json
north-star configs (GPT-3 1.3B DP+TP, Llama-2 7B 4D hybrid, BERT-base) make
these first-class here. Vision models live in paddle_tpu.vision.models.
"""
from .gpt import GPTConfig, GPTForCausalLM, GPTModel, gpt_tiny, gpt3_1_3b  # noqa: F401

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "gpt_tiny", "gpt3_1_3b"]
from .llama import (  # noqa: F401,E402
    LlamaConfig, LlamaForCausalLM, LlamaModel, llama_tiny,
)

__all__ += ["LlamaConfig", "LlamaModel", "LlamaForCausalLM", "llama_tiny"]
from .bert import (  # noqa: F401,E402
    BertConfig, BertForPretraining, BertForSequenceClassification, BertModel,
    bert_base, bert_tiny,
)

__all__ += ["BertConfig", "BertModel", "BertForPretraining",
            "BertForSequenceClassification", "bert_tiny", "bert_base"]
from .convert_hf import load_hf_llama, load_hf_gpt2, load_hf_bert  # noqa: F401,E402

__all__ += ["load_hf_llama", "load_hf_gpt2", "load_hf_bert"]
