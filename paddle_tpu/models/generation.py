"""Autoregressive generation with KV caches — shared by the LLM zoo.

Reference ecosystem parity: PaddleNLP's GenerationMixin.generate (the
reference repo ships only ops; the LLM zoo is first-class here,
models/__init__.py).

TPU-native shape: ONE compiled prefill program (prompt length) and ONE
compiled decode program reused for every step. The cache write position
rides in as DATA (``lax.dynamic_update_slice`` with a tensor index), so
there is no per-position recompilation; greedy (temperature=0) or
temperature/top-k sampling runs inside the compiled step via
``jax.random.categorical`` on a threaded PRNG key.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..ops._apply import apply_op, ensure_tensor
from ..tensor import Tensor

__all__ = ["GenerationMixin"]


class GenerationMixin:
    """Requires on the host class:
    - ``_decode_trunk()`` → trunk module whose forward accepts
      ``(ids, caches=..., cur_len=...)`` and returns (hidden, new_caches)
    - ``logits(hidden)`` → [B, S, V]
    - ``_cache_spec()`` → (num_layers, cached_heads, head_dim)
    - ``config.max_position_embeddings``
    """

    @staticmethod
    def _sample(logits_row, temperature, top_k, key):
        """One sampling step, pure jnp: [B, V] logits -> [B] token ids."""
        if temperature == 0.0:
            return jnp.argmax(logits_row, axis=-1).astype(jnp.int32)
        logits_row = logits_row / jnp.float32(max(temperature, 1e-6))
        if top_k:
            kth = jnp.sort(logits_row, axis=-1)[:, -int(top_k)][:, None]
            logits_row = jnp.where(logits_row < kth, -1e30, logits_row)
        return jax.random.categorical(key, logits_row,
                                      axis=-1).astype(jnp.int32)

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 1.0, top_k: int = 0,
                 eos_token_id: Optional[int] = None, seed: int = 0):
        """Returns [B, prompt+generated] token ids (generation stops early
        when every row emitted ``eos_token_id``)."""
        import numpy as np

        from .. import jit
        from ..autograd.engine import no_grad

        cfg = self.config
        trunk = self._decode_trunk()
        n_layers, nh_c, hd = self._cache_spec()
        ids = ensure_tensor(input_ids)
        B, S0 = ids.shape
        total = S0 + max_new_tokens
        if total > cfg.max_position_embeddings:
            raise ValueError(
                f"prompt {S0} + max_new_tokens {max_new_tokens} exceeds "
                f"max_position_embeddings {cfg.max_position_embeddings}")
        was_training = self.training
        self.eval()

        def step_fn(tok, cur, key, *flat_caches):
            caches = [(flat_caches[2 * i], flat_caches[2 * i + 1])
                      for i in range(n_layers)]
            with no_grad():
                hidden, ncs = trunk(tok, caches=caches, cur_len=cur)
                logits = self.logits(hidden)
            last = apply_op(lambda lv: lv[:, -1, :].astype(jnp.float32),
                            [ensure_tensor(logits)], name="last_logits")
            nxt = apply_op(
                lambda lv, kv: self._sample(lv, temperature, top_k, kv),
                [last, ensure_tensor(key)], name="sample")
            flat = [t for c in ncs for t in c]
            return (nxt, *flat)

        # compiled prefill/decode are cached on the model per signature:
        # repeated generate() calls pay tracing+compilation once
        gen_key = (B, S0, total, float(temperature), int(top_k))
        cache_map = getattr(self, "_generation_programs", None)
        if cache_map is None:
            cache_map = self._generation_programs = {}
        progs = cache_map.get(gen_key)
        if progs is None:
            progs = (jit.StaticFunction(step_fn, observe=[self],
                                        warmup=False, dy2static=False),
                     jit.StaticFunction(step_fn, observe=[self],
                                        warmup=False, dy2static=False))
            cache_map[gen_key] = progs
        prefill, decode = progs

        flat = [t for _ in range(n_layers)
                for t in (Tensor(jnp.zeros((B, total, nh_c, hd),
                                           jnp.float32)),
                          Tensor(jnp.zeros((B, total, nh_c, hd),
                                           jnp.float32)))]
        rng_key = jax.random.PRNGKey(seed)
        out = [np.asarray(ids.numpy())]

        k0, rng_key = jax.random.split(rng_key)
        res = prefill(ids, Tensor(jnp.zeros((), jnp.int32)), Tensor(k0),
                      *flat)
        nxt, flat = res[0], list(res[1:])
        tokens = np.asarray(nxt.numpy()).reshape(B, 1)
        out.append(tokens)

        for step in range(1, max_new_tokens):
            if eos_token_id is not None and np.all(tokens == eos_token_id):
                break
            k, rng_key = jax.random.split(rng_key)
            res = decode(Tensor(jnp.asarray(tokens, jnp.int32)),
                         Tensor(jnp.asarray(S0 + step - 1, jnp.int32)),
                         Tensor(k), *flat)
            nxt, flat = res[0], list(res[1:])
            tokens = np.asarray(nxt.numpy()).reshape(B, 1)
            out.append(tokens)

        if was_training:
            self.train()
        return Tensor(jnp.asarray(np.concatenate(out, axis=1)))
