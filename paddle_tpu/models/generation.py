"""Autoregressive generation with KV caches — shared by the LLM zoo.

Reference ecosystem parity: PaddleNLP's GenerationMixin.generate (the
reference repo ships only ops; the LLM zoo is first-class here,
models/__init__.py).

TPU-native shape: ONE compiled prefill program (prompt length) and ONE
compiled decode program reused for every step. The cache write position
rides in as DATA (``lax.dynamic_update_slice`` with a tensor index), so
there is no per-position recompilation; greedy (temperature=0) or
temperature/top-k sampling runs inside the compiled step via
``jax.random.categorical`` on a threaded PRNG key.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..ops._apply import apply_op, ensure_tensor
from ..tensor import Tensor

__all__ = ["GenerationMixin"]


class GenerationMixin:
    """Requires on the host class:
    - ``_decode_trunk()`` → trunk module whose forward accepts
      ``(ids, caches=..., cur_len=...)`` and returns (hidden, new_caches)
    - ``logits(hidden)`` → [B, S, V]
    - ``_cache_spec()`` → (num_layers, cached_heads, head_dim)
    - ``config.max_position_embeddings``
    """

    @staticmethod
    def _sample(logits_row, temperature, top_k, key):
        """One sampling step, pure jnp: [B, V] logits -> [B] token ids."""
        if temperature == 0.0:
            return jnp.argmax(logits_row, axis=-1).astype(jnp.int32)
        logits_row = logits_row / jnp.float32(max(temperature, 1e-6))
        if top_k:
            kth = jnp.sort(logits_row, axis=-1)[:, -int(top_k)][:, None]
            logits_row = jnp.where(logits_row < kth, -1e30, logits_row)
        return jax.random.categorical(key, logits_row,
                                      axis=-1).astype(jnp.int32)

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 1.0, top_k: int = 0,
                 eos_token_id: Optional[int] = None, seed: int = 0,
                 device_loop: Optional[bool] = None,
                 return_stats: bool = False):
        """Returns [B, prompt+generated] token ids (generation stops early
        when every row emitted ``eos_token_id``).

        ``return_stats=True`` returns ``(ids, stats)`` instead, where
        ``stats`` is ``{"n_gen": tokens generated per row (incl. eos
        padding), "stop_reason": "eos" | "length"}`` — "eos" when every
        row finished on ``eos_token_id`` before the token budget ran out.
        The serving engine and the early-stop tests assert on it; the
        default keeps the old single-tensor return shape.

        EOS semantics (both loops, PaddleNLP/HF style): a row that emits
        ``eos_token_id`` is frozen — every later position in that row is
        filled with ``eos_token_id`` — and generation stops once ALL rows
        have finished (or at ``max_new_tokens``).

        ``device_loop``: run the whole decode as ONE compiled program — a
        ``lax.while_loop`` whose carry holds the token buffer, KV caches,
        PRNG key, and per-row done flags — instead of one host-driven
        call per token. On TPU the host loop pays a device↔host round trip per
        token (~63ms through the axon tunnel — more than the decode step
        itself); the device loop pays one. Default: on for TPU backends,
        off elsewhere (the host loop is easier to debug and can stop the
        moment EOS lands instead of at the compiled cond check).
        """
        import time

        import numpy as np

        from .. import jit, metrics
        from ..autograd.engine import no_grad

        _gen_t0 = time.perf_counter()
        cfg = self.config
        trunk = self._decode_trunk()
        n_layers, nh_c, hd = self._cache_spec()
        ids = ensure_tensor(input_ids)
        B, S0 = ids.shape
        total = S0 + max_new_tokens
        if total > cfg.max_position_embeddings:
            raise ValueError(
                f"prompt {S0} + max_new_tokens {max_new_tokens} exceeds "
                f"max_position_embeddings {cfg.max_position_embeddings}")
        was_training = self.training
        self.eval()
        if device_loop is None:
            # "axon" is the tunneled-TPU PJRT platform name
            device_loop = jax.default_backend() in ("tpu", "axon")

        def step_fn(tok, cur, key, *flat_caches):
            caches = [(flat_caches[2 * i], flat_caches[2 * i + 1])
                      for i in range(n_layers)]
            with no_grad():
                hidden, ncs = trunk(tok, caches=caches, cur_len=cur)
                logits = self.logits(hidden)
            last = apply_op(lambda lv: lv[:, -1, :].astype(jnp.float32),
                            [ensure_tensor(logits)], name="last_logits")
            nxt = apply_op(
                lambda lv, kv: self._sample(lv, temperature, top_k, kv),
                [last, ensure_tensor(key)], name="sample")
            flat = [t for c in ncs for t in c]
            return (nxt, *flat)

        step_fn.__name__ = "generate_step"  # jit_compiles_total{fn=...}

        # compiled prefill/decode are cached on the model per signature:
        # repeated generate() calls pay tracing+compilation once
        gen_key = (B, S0, total, float(temperature), int(top_k))
        cache_map = getattr(self, "_generation_programs", None)
        if cache_map is None:
            cache_map = self._generation_programs = {}
        progs = cache_map.get(gen_key)
        if progs is None:
            progs = (jit.StaticFunction(step_fn, observe=[self],
                                        warmup=False, dy2static=False),
                     jit.StaticFunction(step_fn, observe=[self],
                                        warmup=False, dy2static=False))
            cache_map[gen_key] = progs
        prefill, decode = progs

        flat = [t for _ in range(n_layers)
                for t in (Tensor(jnp.zeros((B, total, nh_c, hd),
                                           jnp.float32)),
                          Tensor(jnp.zeros((B, total, nh_c, hd),
                                           jnp.float32)))]
        rng_key = jax.random.PRNGKey(seed)
        out = [np.asarray(ids.numpy())]

        k0, rng_key = jax.random.split(rng_key)
        res = prefill(ids, Tensor(jnp.zeros((), jnp.int32)), Tensor(k0),
                      *flat)
        nxt, flat = res[0], list(res[1:])
        tokens = np.asarray(nxt.numpy()).reshape(B, 1)
        out.append(tokens)

        if device_loop and max_new_tokens > 1:
            # eos rides in as DATA (sentinel -1 = none): one compiled
            # program serves every stop id
            loop_key = ("loop",) + gen_key
            loop = cache_map.get(loop_key)
            if loop is None:
                loop = jit.StaticFunction(
                    self._make_device_loop(trunk, n_layers, B, S0,
                                           max_new_tokens, temperature,
                                           top_k),
                    observe=[self], warmup=False, dy2static=False)
                cache_map[loop_key] = loop
            k, rng_key = jax.random.split(rng_key)
            eos_t = Tensor(jnp.int32(eos_token_id
                                     if eos_token_id is not None else -1),
                           stop_gradient=True)
            buf, n_gen, all_done = loop(nxt, Tensor(k), eos_t, *flat)
            # one batched fetch — each host sync costs a tunnel round trip
            buf_v, n_v, done_v = jax.device_get(
                (buf._value, n_gen._value, all_done._value))
            out[-1] = np.asarray(buf_v)[:, :int(n_v)]
            stopped_on_eos = bool(done_v)
        else:
            done = (tokens[:, 0] == eos_token_id) if eos_token_id is not None \
                else np.zeros(B, bool)
            for step in range(1, max_new_tokens):
                if eos_token_id is not None and done.all():
                    break
                k, rng_key = jax.random.split(rng_key)
                res = decode(Tensor(jnp.asarray(tokens, jnp.int32)),
                             Tensor(jnp.asarray(S0 + step - 1, jnp.int32)),
                             Tensor(k), *flat)
                nxt, flat = res[0], list(res[1:])
                tokens = np.asarray(nxt.numpy()).reshape(B, 1)
                if eos_token_id is not None:
                    # frozen rows keep emitting eos (HF/PaddleNLP padding)
                    tokens = np.where(done[:, None], eos_token_id, tokens)
                    done = done | (tokens[:, 0] == eos_token_id)
                out.append(tokens)
            stopped_on_eos = bool(eos_token_id is not None and done.all())

        if was_training:
            self.train()
        ids_out = Tensor(jnp.asarray(np.concatenate(out, axis=1)))
        reg = metrics.get_registry()
        reg.histogram(
            "paddle_tpu_generate_seconds",
            "Whole dense generate() call (prefill + all decode steps, "
            "compile included on the first signature)",
        ).observe(time.perf_counter() - _gen_t0)
        reg.counter(
            "paddle_tpu_generate_tokens_total",
            "Tokens emitted by dense generate() across all rows",
        ).inc(B * (int(ids_out.shape[1]) - S0))
        if not return_stats:
            return ids_out
        stats = {"n_gen": int(ids_out.shape[1]) - S0,
                 "stop_reason": "eos" if stopped_on_eos else "length"}
        return ids_out, stats

    def _make_device_loop(self, trunk, n_layers, B, S0, max_new_tokens,
                          temperature, top_k):
        """Build the whole-decode-in-one-program fn: carry = (token buffer
        [B, max_new_tokens], count, PRNG key, stop, *flat KV caches);
        stops at the buffer end or when every row has emitted ``eos``
        (per-row freeze: finished rows pad with eos — the host loop's
        exact semantics). ``eos`` is a data operand (-1 = no stop id) so
        one program serves every stop id."""
        from ..autograd.engine import no_grad

        def loop_fn(first_tok, key, eos, *flat_caches):
            def run(tok0_v, key_v, eos_v, *cache_vals):
                eos_i = eos_v.astype(jnp.int32).reshape(())
                buf0 = jnp.zeros((B, max_new_tokens), jnp.int32)
                z0 = jnp.int32(0)
                buf0 = jax.lax.dynamic_update_slice(
                    buf0, tok0_v.reshape(B, 1).astype(jnp.int32), (z0, z0))

                def cond(carry):
                    i, done = carry[1], carry[3]
                    return (i < max_new_tokens) & ~(
                        (eos_i >= 0) & jnp.all(done))

                def body(carry):
                    buf, i, kv, done = (carry[0], carry[1], carry[2],
                                        carry[3])
                    cvals = carry[4:]
                    z = jnp.int32(0)  # literal ints trace i64 under x64
                    tok = jax.lax.dynamic_slice(buf, (z, i - 1), (B, 1))
                    caches = [(Tensor(cvals[2 * l], stop_gradient=True),
                               Tensor(cvals[2 * l + 1], stop_gradient=True))
                              for l in range(n_layers)]
                    with no_grad():
                        hidden, ncs = trunk(
                            Tensor(tok, stop_gradient=True), caches=caches,
                            cur_len=Tensor(S0 + i - 1, stop_gradient=True))
                        logits = self.logits(hidden)
                    last = logits._value[:, -1, :].astype(jnp.float32)
                    kv, sub = jax.random.split(kv)
                    nxt = self._sample(last, temperature, top_k, sub)
                    # frozen rows keep emitting eos (HF/PaddleNLP padding)
                    nxt = jnp.where((eos_i >= 0) & done, eos_i, nxt)
                    done = done | ((eos_i >= 0) & (nxt == eos_i))
                    buf = jax.lax.dynamic_update_slice(
                        buf, nxt.reshape(B, 1), (z, i))
                    new_cvals = tuple(t._value for c in ncs for t in c)
                    return (buf, i + 1, kv, done) + new_cvals

                done0 = (eos_i >= 0) & (tok0_v.astype(jnp.int32).reshape(B)
                                        == eos_i)
                init = (buf0, jnp.int32(1), key_v, done0, *cache_vals)
                fin = jax.lax.while_loop(cond, body, init)
                # token buffer, count generated, all-rows-hit-eos flag
                return fin[0], fin[1], jnp.all(fin[3])

            return apply_op(run, [ensure_tensor(first_tok),
                                  ensure_tensor(key), ensure_tensor(eos),
                                  *[ensure_tensor(c) for c in flat_caches]],
                            name="generate_device_loop")

        loop_fn.__name__ = "generate_device_loop"
        return loop_fn
