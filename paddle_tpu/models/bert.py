"""BERT model family — bidirectional encoder with MLM + NSP heads.

Reference parity: the BERT-base pretraining config in BASELINE.json (the
reference trains it via PaddleNLP's bert modeling on the fleet stack).
Built on this framework's own nn.TransformerEncoder; the pretraining
heads follow the original BERT recipe: masked-LM head tied to the token
embedding + next-sentence binary head over the pooled [CLS].
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from .. import nn
from ..autograd.engine import apply_op
from ..nn import functional as F
from ..ops._apply import ensure_tensor

__all__ = ["BertConfig", "BertModel", "BertForPretraining",
           "BertForSequenceClassification", "bert_tiny", "bert_base"]


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    layer_norm_epsilon: float = 1e-12
    initializer_range: float = 0.02

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size
        if self.hidden_size % self.num_heads:
            raise ValueError("num_heads must divide hidden_size")


def bert_tiny(**kw) -> BertConfig:
    cfg = dict(vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
               max_position_embeddings=128, hidden_dropout_prob=0.0,
               attention_dropout_prob=0.0)
    cfg.update(kw)
    return BertConfig(**cfg)


def bert_base(**kw) -> BertConfig:
    return BertConfig(**kw)


def _normal(std):
    return nn.ParamAttr(initializer=nn.initializer.Normal(mean=0.0, std=std))


class BertEmbeddings(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        std = config.initializer_range
        self.word_embeddings = nn.Embedding(
            config.vocab_size, config.hidden_size, weight_attr=_normal(std))
        self.position_embeddings = nn.Embedding(
            config.max_position_embeddings, config.hidden_size,
            weight_attr=_normal(std))
        self.token_type_embeddings = nn.Embedding(
            config.type_vocab_size, config.hidden_size,
            weight_attr=_normal(std))
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_epsilon)
        self.drop_p = config.hidden_dropout_prob

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        import jax.numpy as jnp

        from ..tensor import Tensor

        ids = ensure_tensor(input_ids)
        B, S = ids.shape
        if position_ids is None:
            position_ids = Tensor(
                jnp.arange(S, dtype=jnp.int32)[None, :].repeat(B, axis=0),
                stop_gradient=True)
        if token_type_ids is None:
            token_type_ids = Tensor(jnp.zeros((B, S), jnp.int32),
                                    stop_gradient=True)
        x = (self.word_embeddings(ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        x = self.layer_norm(x)
        if self.drop_p and self.training:
            x = F.dropout(x, self.drop_p)
        return x


class BertModel(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        layer = nn.TransformerEncoderLayer(
            config.hidden_size, config.num_heads, config.intermediate_size,
            dropout=config.hidden_dropout_prob,
            attn_dropout=config.attention_dropout_prob,
            act_dropout=0.0, activation="gelu", normalize_before=False)
        self.encoder = nn.TransformerEncoder(layer, config.num_layers)
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size,
                                weight_attr=_normal(config.initializer_range))

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        if attention_mask is not None:
            # [B, S] 1/0 mask → BOOL [B, 1, 1, S] (True = attend): the
            # form scaled_dot_product_attention consumes natively and
            # the one that routes padded batches onto the flash kernel
            # (additive -1e4 bias would fall back to naive [S,S] math)
            am = ensure_tensor(attention_mask)

            def to_bool(m):
                import jax.numpy as jnp

                return (m[:, None, None, :].astype(jnp.float32) > 0.5)

            attention_mask = apply_op(to_bool, [am], name="bert_attn_mask")
        sequence_output = self.encoder(x, src_mask=attention_mask)
        pooled = F.tanh(self.pooler(sequence_output[:, 0]))
        return sequence_output, pooled


class BertForPretraining(nn.Layer):
    """MLM (tied decoder) + NSP heads, summed loss (original recipe)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.bert = BertModel(config)
        std = config.initializer_range
        self.transform = nn.Linear(config.hidden_size, config.hidden_size,
                                   weight_attr=_normal(std))
        self.transform_norm = nn.LayerNorm(config.hidden_size,
                                           epsilon=config.layer_norm_epsilon)
        self.nsp_head = nn.Linear(config.hidden_size, 2,
                                  weight_attr=_normal(std))

    def mlm_logits(self, sequence_output):
        h = self.transform_norm(F.gelu(self.transform(sequence_output)))
        w = self.bert.embeddings.word_embeddings.weight
        return apply_op(lambda hh, ww: hh @ ww.T,
                        [ensure_tensor(h), ensure_tensor(w)],
                        name="tied_mlm_head")

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_lm_labels=None, next_sentence_labels=None):
        seq, pooled = self.bert(input_ids, token_type_ids,
                                attention_mask=attention_mask)
        mlm = self.mlm_logits(seq)
        nsp = self.nsp_head(pooled)
        if masked_lm_labels is None:
            return mlm, nsp
        # label -100 marks unmasked positions (ignored)
        mlm_loss = F.cross_entropy(
            mlm.reshape((-1, self.config.vocab_size)),
            ensure_tensor(masked_lm_labels).reshape((-1,)),
            ignore_index=-100)
        loss = mlm_loss
        if next_sentence_labels is not None:
            loss = loss + F.cross_entropy(
                nsp, ensure_tensor(next_sentence_labels).reshape((-1,)))
        return (mlm, nsp), loss


class BertForSequenceClassification(nn.Layer):
    def __init__(self, config: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes,
                                    weight_attr=_normal(
                                        config.initializer_range))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        _, pooled = self.bert(input_ids, token_type_ids,
                              attention_mask=attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is None:
            return logits
        return logits, F.cross_entropy(logits,
                                       ensure_tensor(labels).reshape((-1,)))
