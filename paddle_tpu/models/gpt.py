"""GPT model family — the flagship causal-LM benchmark model.

Reference parity: the GPT pattern models used by the reference's hybrid
-parallel tests (``test/legacy_test/auto_parallel_gpt_model.py``) and the
fused-transformer surface (``incubate/nn/layer/fused_transformer.py:192``).

TPU-native design:
- pre-LN decoder blocks whose matmuls are MXU-shaped (hidden sizes multiples
  of 128); attention via the Pallas flash kernel (ops/pallas/flash_attention)
  with an XLA sdpa fallback;
- tensor parallelism by construction: when the active mesh has mp>1 the QKV /
  MLP projections are Column/RowParallelLinear and the vocab embedding is
  VocabParallelEmbedding — same module code, sharding annotations compiled in;
- sequence parallelism: activations optionally sharded over the 'sep' axis on
  the sequence dim (GSPMD inserts the boundary collectives);
- weight tying between embedding and LM head (SharedLayerDesc semantics —
  single parameter cell, gradients accumulate on one tape leaf).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import nn
from ..nn import functional as F
from ..distributed import topology
from ..distributed.sharding_api import shard_tensor
from ..ops._apply import apply_op, ensure_tensor
from .generation import GenerationMixin
from ..tensor import Tensor

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "gpt_tiny", "gpt3_1_3b"]


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304          # 50257 padded to a multiple of 128 (MXU)
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    # tanh-approximate gelu (HF gpt2's "gelu_new") — set when loading HF
    # gpt2 checkpoints (models/convert_hf.py) so logits match exactly
    gelu_approximate: bool = False
    use_flash_attention: bool = True
    sequence_parallel: bool = False
    tie_word_embeddings: bool = True
    pp_num_microbatches: Optional[int] = None  # default: pp degree
    # activation recompute per decoder block (fleet.recompute → jax.remat):
    # trades ~1/3 more FLOPs for O(layers) less live activation memory —
    # the standard lever for batching past HBM on one chip
    recompute: bool = False
    # remat policy (fleet/recompute.py _POLICIES): None/'full' recomputes
    # everything; 'dots' saves matmul outputs and recomputes only the cheap
    # VPU elementwise ops — most of the memory for a few % of step time
    recompute_policy: Optional[str] = None
    # fused chunked linear+CE (ops/fused_loss.py): never materializes the
    # [B·S, V] logits — O(N·V) loss memory drops to O(N·chunk), unlocking
    # larger per-chip batches. forward(labels=...) then returns (None, loss)
    # since full logits are deliberately never formed.
    fused_loss: bool = False

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size
        if self.hidden_size % self.num_heads:
            raise ValueError("num_heads must divide hidden_size")


def gpt_tiny(**kw) -> "GPTConfig":
    cfg = dict(vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
               max_position_embeddings=128, hidden_dropout_prob=0.0,
               attention_dropout_prob=0.0)
    cfg.update(kw)
    return GPTConfig(**cfg)


def gpt3_1_3b(**kw) -> "GPTConfig":
    """BASELINE.json north-star config: GPT-3 XL 1.3B."""
    cfg = dict(vocab_size=50304, hidden_size=2048, num_layers=24, num_heads=16,
               max_position_embeddings=2048)
    cfg.update(kw)
    return GPTConfig(**cfg)


def _mesh_mp() -> int:
    return topology.axis_size("mp")


def _mesh_pp() -> int:
    return topology.axis_size("pp")


def _normal_init(std):
    from ..nn import initializer as I

    return I.Normal(mean=0.0, std=std)


class GPTAttention(nn.Layer):
    """Causal self-attention. QKV column-parallel (heads sharded over mp),
    output row-parallel — the Megatron layout the reference's
    ColumnParallelLinear/RowParallelLinear exist for (mp_layers.py:173,343).
    """

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.cfg = config
        h, nh = config.hidden_size, config.num_heads
        self.head_dim = h // nh
        mp = _mesh_mp()
        if nh % mp:
            raise ValueError(f"num_heads {nh} not divisible by mp {mp}")
        std = config.initializer_range
        proj_std = std / math.sqrt(2 * config.num_layers)
        if mp > 1:
            from ..distributed.fleet import ColumnParallelLinear, RowParallelLinear

            self.qkv_proj = ColumnParallelLinear(
                h, 3 * h, gather_output=False,
                weight_attr=nn.ParamAttr(initializer=_normal_init(std)))
            self.out_proj = RowParallelLinear(
                h, h, input_is_parallel=True,
                weight_attr=nn.ParamAttr(initializer=_normal_init(proj_std)))
        else:
            self.qkv_proj = nn.Linear(
                h, 3 * h, weight_attr=nn.ParamAttr(initializer=_normal_init(std)))
            self.out_proj = nn.Linear(
                h, h, weight_attr=nn.ParamAttr(initializer=_normal_init(proj_std)))
        self.attn_drop_p = config.attention_dropout_prob

    def forward(self, x, cache=None, cur_len=None):
        B, S, H = x.shape
        nh, hd = self.cfg.num_heads, self.head_dim
        qkv = self.qkv_proj(x)  # [B, S, 3H] (H possibly mp-sharded)

        def split_heads(v):
            # [B, S, 3H] -> 3 x [B, S, nh, hd]; head dim is the sharded one,
            # so reshape keeps shards intact ([..., nh/mp, hd] per shard)
            q, k, v_ = jnp.split(v, 3, axis=-1)
            return tuple(t.reshape(B, S, nh, hd) for t in (q, k, v_))

        q, k, v = apply_op(split_heads, [ensure_tensor(qkv)], name="split_heads")
        if cache is not None:
            # KV-cache decode path (generation): write this call's k/v at
            # cur_len and attend over the whole buffer with a position mask.
            # cur_len is a TENSOR so one compiled step serves every position.
            k_buf, v_buf = cache
            scale = 1.0 / math.sqrt(hd)

            def cached_attn(qv, kv, vv, kb, vb, cl):
                cl = cl.astype(jnp.int32).reshape(())
                z = jnp.int32(0)
                start = (z, cl, z, z)
                kb = jax.lax.dynamic_update_slice(kb, kv.astype(kb.dtype),
                                                  start)
                vb = jax.lax.dynamic_update_slice(vb, vv.astype(vb.dtype),
                                                  start)
                L = kb.shape[1]
                qh = jnp.swapaxes(qv, 1, 2)            # [B, nh, S, hd]
                kh = jnp.swapaxes(kb, 1, 2)            # [B, nh, L, hd]
                vh = jnp.swapaxes(vb, 1, 2)
                s = jnp.einsum("bhqd,bhkd->bhqk", qh,
                               kh.astype(qh.dtype)) * scale
                rows = cl + jnp.arange(S)[:, None]     # absolute q positions
                cols = jnp.arange(L)[None, :]
                mask = cols <= rows                    # causal over buffer
                s = jnp.where(mask[None, None], s, -1e30)
                p = jax.nn.softmax(s, axis=-1)
                ctx = jnp.einsum("bhqk,bhkd->bhqd", p, vh.astype(p.dtype))
                return jnp.swapaxes(ctx, 1, 2), kb, vb

            ctx, new_k, new_v = apply_op(
                cached_attn,
                [q, k, v, ensure_tensor(k_buf), ensure_tensor(v_buf),
                 ensure_tensor(cur_len)],
                name="cached_attention")
            merged = apply_op(lambda t: t.reshape(B, S, nh * hd),
                              [ensure_tensor(ctx)], name="merge_heads")
            return self.out_proj(merged), (new_k, new_v)
        mesh = topology.get_mesh()
        if (self.cfg.sequence_parallel and mesh is not None
                and "sep" in mesh.axis_names and mesh.shape["sep"] > 1
                and not (self.attn_drop_p and self.training)):
            # long-context path: exact ring attention over the 'sep' axis —
            # q stays resident, k/v stream around the ring (ppermute), so
            # no device ever holds the full sequence (SURVEY §7 step 6)
            from ..distributed.ring_attention import ring_attention

            ctx = ring_attention(q, k, v, causal=True, mesh=mesh)
        elif self.cfg.use_flash_attention:
            ctx = F.flash_attention(q, k, v, causal=True,
                                    dropout=self.attn_drop_p if self.training else 0.0)
        else:
            ctx = F.scaled_dot_product_attention(
                q, k, v, is_causal=True,
                dropout_p=self.attn_drop_p if self.training else 0.0)
        if isinstance(ctx, tuple):
            ctx = ctx[0]
        merged = apply_op(lambda t: t.reshape(B, S, nh * hd),
                          [ensure_tensor(ctx)], name="merge_heads")
        return self.out_proj(merged)

    def forward_paged(self, x, positions, block_tables, k_pool, v_pool,
                      adapters=None, layer_idx=0, k_scale=None,
                      v_scale=None):
        """Paged-KV ragged step (serving engine): one QUERY TOKEN per
        row — decode tokens and prompt-chunk tokens alike (the unified
        step's flattened grid; ops/pallas/paged_attention.py "Ragged
        form") — KV write hook scattering into the page pool at per-row
        positions, then ragged paged attention over each row's block
        table masked at the row's own position. Position embeddings were
        already added at the trunk level (GPTModel.forward_paged).

        ``adapters`` (docs/SERVING.md "Multi-LoRA adapters"): per-row
        gathered LoRA stacks ``{site: (A, B)}``; GPT's fused QKV takes
        ONE delta on the concatenated [B, 1, 3H] output (the delta
        splits with it), out_proj one on the merged context.

        ``k_scale``/``v_scale`` arm int8 KV pages exactly as in
        LlamaAttention.forward_paged: quantize-on-write in the scatter,
        in-kernel dequant in attention, cache tuple grows to
        ``(k, v, k_scale, v_scale)`` — a static Python branch, not a new
        program."""
        from ..ops.pallas.paged_attention import ragged_paged_attention
        from ..quantization.observers import quantize_kv

        B = x.shape[0]
        nh, hd = self.cfg.num_heads, self.head_dim
        scale = 1.0 / math.sqrt(hd)
        quantized = k_scale is not None
        qkv = self.qkv_proj(x)  # [B, 1, 3H]
        if adapters is not None:
            from ..serving.adapters import lora_delta

            qkv = qkv + lora_delta(x, *adapters["qkv_proj"], layer_idx)

        def paged_step(qkv_v, kp, vp, bt, pos, *scales):
            pos = pos.astype(jnp.int32).reshape(B)
            bt = bt.astype(jnp.int32)
            page_size = kp.shape[1]
            qv, kv, vv = jnp.split(qkv_v, 3, axis=-1)
            nh_l = qv.shape[-1] // hd
            qh = qv.reshape(B, nh_l, hd)
            kh = kv.reshape(B, nh_l, hd)
            vh = vv.reshape(B, nh_l, hd)
            page_ids = bt[jnp.arange(B), pos // page_size]
            offs = pos % page_size
            if scales:
                ks, vs = scales
                kq, ksc = quantize_kv(kh)
                vq, vsc = quantize_kv(vh)
                kp = kp.at[page_ids, offs].set(kq)
                vp = vp.at[page_ids, offs].set(vq)
                ks = ks.at[page_ids, offs].set(ksc)
                vs = vs.at[page_ids, offs].set(vsc)
                ctx = ragged_paged_attention(qh, kp, vp, bt, pos + 1,
                                             scale=scale, k_scale=ks,
                                             v_scale=vs)
                return ctx.reshape(B, 1, nh_l * hd), kp, vp, ks, vs
            kp = kp.at[page_ids, offs].set(kh.astype(kp.dtype))
            vp = vp.at[page_ids, offs].set(vh.astype(vp.dtype))
            ctx = ragged_paged_attention(qh, kp, vp, bt, pos + 1,
                                         scale=scale)
            return ctx.reshape(B, 1, nh_l * hd), kp, vp

        operands = [ensure_tensor(qkv), ensure_tensor(k_pool),
                    ensure_tensor(v_pool), ensure_tensor(block_tables),
                    ensure_tensor(positions)]
        if quantized:
            operands += [ensure_tensor(k_scale), ensure_tensor(v_scale)]
        merged, *new_cache = apply_op(
            paged_step, operands, name="gpt_paged_attention")
        out = self.out_proj(merged)
        if adapters is not None:
            from ..serving.adapters import lora_delta

            out = out + lora_delta(merged, *adapters["out_proj"],
                                   layer_idx)
        return out, tuple(new_cache)


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h, ff = config.hidden_size, config.intermediate_size
        mp = _mesh_mp()
        std = config.initializer_range
        proj_std = std / math.sqrt(2 * config.num_layers)
        if mp > 1:
            from ..distributed.fleet import ColumnParallelLinear, RowParallelLinear

            self.fc1 = ColumnParallelLinear(
                h, ff, gather_output=False,
                weight_attr=nn.ParamAttr(initializer=_normal_init(std)))
            self.fc2 = RowParallelLinear(
                ff, h, input_is_parallel=True,
                weight_attr=nn.ParamAttr(initializer=_normal_init(proj_std)))
        else:
            self.fc1 = nn.Linear(h, ff, weight_attr=nn.ParamAttr(
                initializer=_normal_init(std)))
            self.fc2 = nn.Linear(ff, h, weight_attr=nn.ParamAttr(
                initializer=_normal_init(proj_std)))
        self._gelu_approx = config.gelu_approximate

    def forward(self, x, adapters=None, layer_idx=0):
        if adapters is None:
            return self.fc2(F.gelu(self.fc1(x),
                                   approximate=self._gelu_approx))
        from ..serving.adapters import lora_delta

        h = self.fc1(x) + lora_delta(x, *adapters["fc1"], layer_idx)
        a = F.gelu(h, approximate=self._gelu_approx)
        return self.fc2(a) + lora_delta(a, *adapters["fc2"], layer_idx)


class GPTDecoderLayer(nn.Layer):
    """Pre-LN block: x + attn(ln1(x)); x + mlp(ln2(x))."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        eps = config.layer_norm_epsilon
        self.ln1 = nn.LayerNorm(config.hidden_size, epsilon=eps)
        self.attn = GPTAttention(config)
        self.ln2 = nn.LayerNorm(config.hidden_size, epsilon=eps)
        self.mlp = GPTMLP(config)
        self.drop_p = config.hidden_dropout_prob

    def forward(self, x, cache=None, cur_len=None):
        if cache is not None:
            h, new_cache = self.attn(self.ln1(x), cache=cache,
                                     cur_len=cur_len)
            x = x + h
            x = x + self.mlp(self.ln2(x))
            return x, new_cache
        h = self.attn(self.ln1(x))
        if self.drop_p and self.training:
            h = F.dropout(h, self.drop_p)
        x = x + h
        h = self.mlp(self.ln2(x))
        if self.drop_p and self.training:
            h = F.dropout(h, self.drop_p)
        return x + h

    def forward_paged(self, x, positions, block_tables, k_pool, v_pool,
                      adapters=None, layer_idx=0, k_scale=None,
                      v_scale=None):
        h, nc = self.attn.forward_paged(self.ln1(x), positions,
                                        block_tables, k_pool, v_pool,
                                        adapters=adapters,
                                        layer_idx=layer_idx,
                                        k_scale=k_scale, v_scale=v_scale)
        x = x + h
        return x + self.mlp(self.ln2(x), adapters=adapters,
                            layer_idx=layer_idx), nc


class GPTModel(nn.Layer):
    """Transformer trunk: embeddings → N decoder blocks → final LN."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        mp = _mesh_mp()
        std = config.initializer_range
        if mp > 1:
            from ..distributed.fleet import VocabParallelEmbedding

            self.embeddings = VocabParallelEmbedding(
                config.vocab_size, config.hidden_size,
                weight_attr=nn.ParamAttr(initializer=_normal_init(std)))
        else:
            self.embeddings = nn.Embedding(
                config.vocab_size, config.hidden_size,
                weight_attr=nn.ParamAttr(initializer=_normal_init(std)))
        self.position_embeddings = nn.Embedding(
            config.max_position_embeddings, config.hidden_size,
            weight_attr=nn.ParamAttr(initializer=_normal_init(std)))
        pp = _mesh_pp()
        self._pp = pp
        if pp > 1:
            # stage-stacked blocks: the 1F1B scan+ppermute schedule compiles
            # into the forward (distributed/fleet/pipeline_schedule.py)
            if config.hidden_dropout_prob or config.attention_dropout_prob:
                raise ValueError(
                    "pp>1 uses lax.scan-stacked blocks whose dropout would "
                    "reuse one PRNG key per scan; set dropout probs to 0")
            from ..distributed.fleet.pipeline_schedule import (
                StackedPipelineBlocks,
            )

            self.layers = StackedPipelineBlocks(
                lambda: GPTDecoderLayer(config), config.num_layers)
        else:
            self.layers = nn.LayerList([GPTDecoderLayer(config)
                                        for _ in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_epsilon)
        self.drop_p = config.hidden_dropout_prob

    def _seq_parallel(self, x):
        mesh = topology.get_mesh()
        if (not self.config.sequence_parallel or mesh is None
                or "sep" not in mesh.axis_names or mesh.shape["sep"] == 1):
            return x
        # activations sharded on the sequence dim over 'sep'
        def fn(v):
            return jax.lax.with_sharding_constraint(
                v, NamedSharding(mesh, P(None, "sep", None)))

        return apply_op(fn, [ensure_tensor(x)], name="seq_parallel_constraint")

    def forward(self, input_ids, position_ids=None, caches=None,
                cur_len=None):
        ids = ensure_tensor(input_ids)
        B, S = ids.shape
        if caches is not None:
            if self._pp > 1:
                raise NotImplementedError(
                    "KV-cache decode requires pp=1 (generation is a "
                    "single-program path; pipeline decode is out of scope)")
            # absolute positions: cur_len .. cur_len+S-1 (a tensor, so one
            # compiled decode step serves every position)
            position_ids = apply_op(
                lambda cl: (jnp.arange(S, dtype=jnp.int32)[None, :]
                            + cl.astype(jnp.int32)).repeat(B, axis=0),
                [ensure_tensor(cur_len)], name="decode_positions")
            x = self.embeddings(ids) + self.position_embeddings(position_ids)
            new_caches = []
            for layer, cache in zip(self.layers, caches):
                x, nc = layer(x, cache=cache, cur_len=cur_len)
                new_caches.append(nc)
            return self.ln_f(x), new_caches
        if position_ids is None:
            pos_val = jnp.arange(S, dtype=jnp.int32)[None, :].repeat(B, axis=0)
            position_ids = Tensor(pos_val, stop_gradient=True)
        x = self.embeddings(ids) + self.position_embeddings(position_ids)
        if self.drop_p and self.training:
            x = F.dropout(x, self.drop_p)
        x = self._seq_parallel(x)
        if self._pp > 1:
            if self.config.recompute:
                import warnings

                warnings.warn(
                    "GPTConfig.recompute is subsumed under pp>1: the "
                    "pipeline schedule already remats each stage block "
                    "(fleet/pipeline_schedule.py); the flag adds nothing",
                    stacklevel=2)
            x = self.layers(
                x, num_microbatches=self.config.pp_num_microbatches or self._pp)
        elif self.config.recompute:
            from ..distributed.fleet.recompute import recompute as _rc

            for layer in self.layers:
                x = _rc(layer, x, policy=self.config.recompute_policy)
        else:
            for layer in self.layers:
                x = layer(x)
        return self.ln_f(x)

    def forward_paged(self, input_ids, positions, block_tables, caches,
                      adapters=None):
        """Paged decode trunk (serving engine): ``input_ids`` [B, 1],
        ``positions`` [B] per-row absolute positions (the learned position
        embedding is gathered per row — the paged counterpart of the
        cur_len-offset decode_positions), ``caches`` a per-layer list of
        (k_pool, v_pool) page pools — or (k_pool, v_pool, k_scales,
        v_scales) for int8 pages. ``adapters``: per-row gathered LoRA
        stacks ``{site: (A, B)}`` applied at every projection per layer
        (zero for slot-0 rows). Returns (hidden, new_caches)."""
        if self._pp > 1:
            raise NotImplementedError(
                "paged decode requires pp=1 (same single-program scope as "
                "KV-cache decode)")
        ids = ensure_tensor(input_ids)
        pos_ids = apply_op(
            lambda p: p.astype(jnp.int32).reshape(-1, 1),
            [ensure_tensor(positions)], name="paged_positions")
        x = self.embeddings(ids) + self.position_embeddings(pos_ids)
        new_caches = []
        for li, (layer, cache) in enumerate(zip(self.layers, caches)):
            kp, vp = cache[0], cache[1]
            ks = cache[2] if len(cache) > 2 else None
            vs = cache[3] if len(cache) > 2 else None
            x, nc = layer.forward_paged(x, positions, block_tables, kp, vp,
                                        adapters=adapters, layer_idx=li,
                                        k_scale=ks, v_scale=vs)
            new_caches.append(nc)
        return self.ln_f(x), new_caches


class GPTForCausalLM(nn.Layer, GenerationMixin):
    """LM head on the trunk; weight-tied to the input embedding by default
    (one parameter cell — SharedLayerDesc semantics without the allreduce).
    ``generate()`` comes from GenerationMixin (KV-cache decode).
    """

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)
        else:
            self.lm_head = None

    def logits(self, hidden):
        if self.lm_head is not None:
            return self.lm_head(hidden)
        w = self.gpt.embeddings.weight  # [V, H] (possibly mp-sharded on V)
        return apply_op(lambda h, wv: h @ wv.T,
                        [ensure_tensor(hidden), w], name="matmul")

    def forward(self, input_ids, position_ids=None, labels=None):
        hidden = self.gpt(input_ids, position_ids)
        if labels is not None and self.config.fused_loss \
                and self.lm_head is None and _mesh_mp() == 1:
            from ..ops.fused_loss import fused_linear_cross_entropy

            H = self.config.hidden_size
            loss = apply_op(
                lambda h, w, y: fused_linear_cross_entropy(
                    h.reshape(-1, H), w, y.reshape(-1)),
                [ensure_tensor(hidden), self.gpt.embeddings.weight,
                 ensure_tensor(labels)],
                name="fused_linear_cross_entropy")
            return None, loss
        logits = self.logits(hidden)
        if labels is None:
            return logits
        mp = _mesh_mp()
        V = self.config.vocab_size
        flat_logits = logits.reshape([-1, V])
        flat_labels = ensure_tensor(labels).reshape([-1])
        if mp > 1:
            from ..distributed.fleet import ParallelCrossEntropy

            loss = ParallelCrossEntropy()(flat_logits, flat_labels)
            from ..ops import math as _math

            return logits, _math.mean(loss)
        loss = F.cross_entropy(flat_logits, flat_labels)
        return logits, loss

    # ------------------------------------------------- generation hooks
    def _decode_trunk(self):
        if self.gpt._pp > 1:
            raise NotImplementedError("generate requires pp=1")
        return self.gpt

    def _cache_spec(self):
        cfg = self.config
        return (cfg.num_layers, cfg.num_heads,
                cfg.hidden_size // cfg.num_heads)

    def lora_sites(self):
        """The AdapterStore contract (serving/adapters.py): ordered
        ``(site, in_dim, out_dim)`` triples plus the layer count. GPT's
        QKV is FUSED, so one ``qkv_proj`` site covers all three with a
        [H → 3H] delta that splits alongside the base projection.
        Dims are unsharded — multi-LoRA serving assumes mp=1."""
        cfg = self.config
        h, ff = cfg.hidden_size, cfg.intermediate_size
        sites = [("qkv_proj", h, 3 * h), ("out_proj", h, h),
                 ("fc1", h, ff), ("fc2", ff, h)]
        return sites, cfg.num_layers
