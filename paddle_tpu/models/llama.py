"""Llama model family — RoPE + RMSNorm + SwiGLU + grouped-query attention.

Reference parity: PaddleNLP's llama modeling (the reference framework's
flagship decoder family; the 4-D-parallel pretraining target in
BASELINE.md). TPU-first construction mirrors models/gpt.py: Megatron
column/row-parallel projections over the 'mp' mesh axis, optional ring
attention over 'sep' for long context, per-block recompute, and a fully
traceable forward so the whole train step compiles to one XLA program.

GQA: ``num_key_value_heads < num_heads`` shrinks the KV projections and
repeats KV per query group — on TPU this is a gather-free
``jnp.repeat`` on the head axis that XLA fuses into the attention
matmuls.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from .. import nn
from ..autograd.engine import apply_op
from ..distributed import topology
from ..nn import functional as F
from ..ops._apply import ensure_tensor
from ..tensor import Tensor
from .generation import GenerationMixin

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM", "llama_tiny"]


def _mesh_dim(name: str) -> int:
    mesh = topology.get_mesh()
    if mesh is None or name not in mesh.axis_names:
        return 1
    return mesh.shape[name]


def _normal_init(std: float):
    return nn.initializer.Normal(mean=0.0, std=std)


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 2048
    num_layers: int = 22
    num_heads: int = 16
    num_key_value_heads: Optional[int] = None  # None → MHA; < heads → GQA
    intermediate_size: Optional[int] = None
    max_position_embeddings: int = 2048
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    initializer_range: float = 0.02
    use_flash_attention: bool = True
    sequence_parallel: bool = False
    tie_word_embeddings: bool = False
    recompute: bool = False
    # remat policy (fleet/recompute.py _POLICIES): None/'full' recomputes
    # everything; 'dots' saves matmul outputs, recomputing only elementwise
    recompute_policy: Optional[str] = None
    # chunked linear+CE (ops/fused_loss.py): never materializes the
    # [B·S, V] logits; forward(labels=...) returns (None, loss).
    # mp==1 only — under tensor parallelism the vocab shard math belongs to
    # ParallelCrossEntropy; forward warns and uses the dense path there.
    fused_loss: bool = False

    def __post_init__(self):
        if self.intermediate_size is None:
            # llama convention: 8/3 * h rounded up to a multiple of 256
            self.intermediate_size = ((int(8 * self.hidden_size / 3) + 255)
                                      // 256) * 256
        if self.num_key_value_heads is None:
            self.num_key_value_heads = self.num_heads
        if self.hidden_size % self.num_heads:
            raise ValueError("num_heads must divide hidden_size")
        if self.num_heads % self.num_key_value_heads:
            raise ValueError("num_key_value_heads must divide num_heads")


def llama_tiny(**kw) -> LlamaConfig:
    cfg = dict(vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
               num_key_value_heads=2, max_position_embeddings=128)
    cfg.update(kw)
    return LlamaConfig(**cfg)


# ------------------------------------------------------------------ RoPE


def _rope_tables(seq: int, dim: int, theta: float):
    """cos/sin tables [S, dim/2] (precomputed per forward; XLA hoists the
    constant computation out of the step)."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, dim, 2, dtype=jnp.float32)
                                / dim))
    t = jnp.arange(seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # [S, dim/2]
    return jnp.cos(freqs), jnp.sin(freqs)


def _apply_rope(x, cos, sin):
    """x: [B, S, H, D] — rotate pairs (x_even, x_odd)."""
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    c = cos[None, :, None, :].astype(x.dtype)
    s = sin[None, :, None, :].astype(x.dtype)
    out_even = x1 * c - x2 * s
    out_odd = x1 * s + x2 * c
    return jnp.stack([out_even, out_odd], axis=-1).reshape(x.shape)


# ------------------------------------------------------------- attention


class LlamaAttention(nn.Layer):
    """RoPE + GQA causal attention; q/k/v column-parallel over 'mp',
    output row-parallel (mp_layers.py layout, like GPTAttention)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.cfg = config
        h = config.hidden_size
        nh, nkv = config.num_heads, config.num_key_value_heads
        self.head_dim = h // nh
        mp = _mesh_dim("mp")
        if nh % mp or nkv % mp:
            raise ValueError(f"heads ({nh}) and kv heads ({nkv}) must be "
                             f"divisible by mp degree {mp}")
        std = config.initializer_range
        proj_std = std / math.sqrt(2 * config.num_layers)
        q_out = nh * self.head_dim
        kv_out = nkv * self.head_dim
        if mp > 1:
            from ..distributed.fleet import (ColumnParallelLinear,
                                             RowParallelLinear)

            def col(n_out, s):
                return ColumnParallelLinear(
                    h, n_out, gather_output=False, has_bias=False,
                    weight_attr=nn.ParamAttr(initializer=_normal_init(s)))

            self.q_proj = col(q_out, std)
            self.k_proj = col(kv_out, std)
            self.v_proj = col(kv_out, std)
            self.o_proj = RowParallelLinear(
                q_out, h, input_is_parallel=True, has_bias=False,
                weight_attr=nn.ParamAttr(initializer=_normal_init(proj_std)))
        else:
            def lin(n_out, s):
                return nn.Linear(h, n_out, bias_attr=False,
                                 weight_attr=nn.ParamAttr(
                                     initializer=_normal_init(s)))

            self.q_proj = lin(q_out, std)
            self.k_proj = lin(kv_out, std)
            self.v_proj = lin(kv_out, std)
            self.o_proj = nn.Linear(q_out, h, bias_attr=False,
                                    weight_attr=nn.ParamAttr(
                                        initializer=_normal_init(proj_std)))

    def forward_paged(self, x, positions, block_tables, k_pool, v_pool,
                      adapters=None, layer_idx=0, k_scale=None,
                      v_scale=None):
        """Paged-KV ragged step (serving engine): one QUERY TOKEN per
        row — a decode slot's next token, or one token of a prompt
        chunk (the unified step flattens mixed per-slot query lengths
        into rows; ops/pallas/paged_attention.py "Ragged form").

        ``x`` [T, 1, H]; ``positions`` [T] per-row absolute positions;
        the KV write hook scatters every row's rope'd k/v into the page
        its block-table row names at ``positions``, then ragged paged
        attention runs each row over its page list masked at the row's
        own position — which is what makes chunk rows causal over their
        freshly written chunk-mates. Returns (out [T, 1, H], new_k_pool,
        new_v_pool) — same rope tables and masked-softmax math as the
        dense cached_attn path, so paged serving is token-compatible
        with ``generate()``.

        ``adapters`` (docs/SERVING.md "Multi-LoRA adapters"): per-row
        gathered LoRA stacks ``{site: (A, B)}`` — each projection adds
        its ``lora_delta`` at ``layer_idx``; rows on adapter slot 0 add
        an exact zero, keeping non-adapter tenants bit-identical.

        ``k_scale``/``v_scale`` (both or neither — int8 pages,
        docs/SERVING.md "KV page tiers & quantization"): the write hook
        quantizes each row's k/v per-slot (quantization/observers.py
        absmax rule) and scatters codes + scales; attention dequantizes
        in-kernel. The cache tuple returned grows to
        ``(k, v, k_scale, v_scale)`` — a static Python branch, so the
        unquantized trace is unchanged and quantization rides as dtype +
        extra operands, never a new program.
        """
        from ..ops.pallas.paged_attention import ragged_paged_attention
        from ..quantization.observers import quantize_kv

        B = x.shape[0]
        cfg = self.cfg
        hd = self.head_dim
        scale = 1.0 / math.sqrt(hd)
        max_pos = cfg.max_position_embeddings
        quantized = k_scale is not None

        q = self.q_proj(x)
        k = self.k_proj(x)
        v = self.v_proj(x)
        if adapters is not None:
            from ..serving.adapters import lora_delta

            q = q + lora_delta(x, *adapters["q_proj"], layer_idx)
            k = k + lora_delta(x, *adapters["k_proj"], layer_idx)
            v = v + lora_delta(x, *adapters["v_proj"], layer_idx)

        def paged_step(qv, kv, vv, kp, vp, bt, pos, *scales):
            pos = pos.astype(jnp.int32).reshape(B)
            bt = bt.astype(jnp.int32)
            page_size = kp.shape[1]
            nh_l = qv.shape[-1] // hd
            nkv_l = kv.shape[-1] // hd
            qh = qv.reshape(B, nh_l, hd)
            kh = kv.reshape(B, nkv_l, hd)
            vh = vv.reshape(B, nkv_l, hd)
            cos_f, sin_f = _rope_tables(max_pos, hd, cfg.rope_theta)
            cos = cos_f[pos][:, None, :]  # [B, 1, hd/2] per-row positions
            sin = sin_f[pos][:, None, :]

            def rope_rows(t):
                t1, t2 = t[..., 0::2], t[..., 1::2]
                return jnp.stack([t1 * cos - t2 * sin,
                                  t1 * sin + t2 * cos],
                                 axis=-1).reshape(t.shape)

            qh = rope_rows(qh)
            kh = rope_rows(kh)
            # KV write hook: page = block_table[pos // page_size], slot =
            # pos % page_size. Inactive slots carry all-zero block tables,
            # landing their writes on the pool's reserved null page 0.
            page_ids = bt[jnp.arange(B), pos // page_size]
            offs = pos % page_size
            if scales:
                ks, vs = scales
                kq, ksc = quantize_kv(kh)
                vq, vsc = quantize_kv(vh)
                kp = kp.at[page_ids, offs].set(kq)
                vp = vp.at[page_ids, offs].set(vq)
                ks = ks.at[page_ids, offs].set(ksc)
                vs = vs.at[page_ids, offs].set(vsc)
                ctx = ragged_paged_attention(qh, kp, vp, bt, pos + 1,
                                             scale=scale, k_scale=ks,
                                             v_scale=vs)
                return ctx.reshape(B, 1, nh_l * hd), kp, vp, ks, vs
            kp = kp.at[page_ids, offs].set(kh.astype(kp.dtype))
            vp = vp.at[page_ids, offs].set(vh.astype(vp.dtype))
            ctx = ragged_paged_attention(qh, kp, vp, bt, pos + 1,
                                         scale=scale)
            return ctx.reshape(B, 1, nh_l * hd), kp, vp

        operands = [ensure_tensor(q), ensure_tensor(k), ensure_tensor(v),
                    ensure_tensor(k_pool), ensure_tensor(v_pool),
                    ensure_tensor(block_tables), ensure_tensor(positions)]
        if quantized:
            operands += [ensure_tensor(k_scale), ensure_tensor(v_scale)]
        merged, *new_cache = apply_op(
            paged_step, operands, name="llama_paged_attention")
        out = self.o_proj(merged)
        if adapters is not None:
            from ..serving.adapters import lora_delta

            out = out + lora_delta(merged, *adapters["o_proj"], layer_idx)
        return out, tuple(new_cache)

    def forward(self, x, cache=None, cur_len=None):
        B, S, _ = x.shape
        cfg = self.cfg
        hd = self.head_dim
        nh, nkv = cfg.num_heads, cfg.num_key_value_heads
        groups = nh // nkv

        q = self.q_proj(x)
        k = self.k_proj(x)
        v = self.v_proj(x)

        if cache is not None:
            # KV-cache decode: rope at ABSOLUTE positions (tables for the
            # full buffer, sliced at cur_len), write k/v into the buffer,
            # attend with a position mask. See models/generation.py.
            k_buf, v_buf = cache
            L = k_buf.shape[1]
            scale = 1.0 / math.sqrt(hd)

            def cached_attn(qv, kv, vv, kb, vb, cl):
                cl = cl.astype(jnp.int32).reshape(())
                z = jnp.int32(0)
                nh_l = qv.shape[-1] // hd
                nkv_l = kv.shape[-1] // hd
                qh = qv.reshape(B, S, nh_l, hd)
                kh = kv.reshape(B, S, nkv_l, hd)
                vh = vv.reshape(B, S, nkv_l, hd)
                cos_f, sin_f = _rope_tables(L, hd, cfg.rope_theta)
                cos = jax.lax.dynamic_slice(cos_f, (cl, z),
                                            (S, cos_f.shape[1]))
                sin = jax.lax.dynamic_slice(sin_f, (cl, z),
                                            (S, sin_f.shape[1]))
                qh = _apply_rope(qh, cos, sin)
                kh = _apply_rope(kh, cos, sin)
                # cache stores PRE-repeat kv heads (nkv): repeating at read
                # time keeps GQA's memory saving (the whole point of GQA)
                kb = jax.lax.dynamic_update_slice(
                    kb, kh.astype(kb.dtype), (z, cl, z, z))
                vb = jax.lax.dynamic_update_slice(
                    vb, vh.astype(vb.dtype), (z, cl, z, z))
                kr, vr = kb, vb
                if groups > 1:
                    kr = jnp.repeat(kb, groups, axis=2)
                    vr = jnp.repeat(vb, groups, axis=2)
                qt = jnp.swapaxes(qh, 1, 2)
                kt = jnp.swapaxes(kr, 1, 2)
                vt = jnp.swapaxes(vr, 1, 2)
                s = jnp.einsum("bhqd,bhkd->bhqk", qt,
                               kt.astype(qt.dtype)) * scale
                rows = cl + jnp.arange(S)[:, None]
                cols = jnp.arange(L)[None, :]
                s = jnp.where((cols <= rows)[None, None], s, -1e30)
                p = jax.nn.softmax(s, axis=-1)
                ctx = jnp.swapaxes(
                    jnp.einsum("bhqk,bhkd->bhqd", p, vt.astype(p.dtype)),
                    1, 2)
                return ctx.reshape(B, S, nh_l * hd), kb, vb

            merged, new_k, new_v = apply_op(
                cached_attn,
                [ensure_tensor(q), ensure_tensor(k), ensure_tensor(v),
                 ensure_tensor(k_buf), ensure_tensor(v_buf),
                 ensure_tensor(cur_len)],
                name="llama_cached_attention")
            return self.o_proj(merged), (new_k, new_v)

        def shape_rope_repeat(qv, kv, vv):
            # per-shard head counts (mp shards the head axis)
            nh_l = qv.shape[-1] // hd
            nkv_l = kv.shape[-1] // hd
            qh = qv.reshape(B, S, nh_l, hd)
            kh = kv.reshape(B, S, nkv_l, hd)
            vh = vv.reshape(B, S, nkv_l, hd)
            cos, sin = _rope_tables(S, hd, cfg.rope_theta)
            qh = _apply_rope(qh, cos, sin)
            kh = _apply_rope(kh, cos, sin)
            if groups > 1:  # GQA: repeat kv heads per query group
                kh = jnp.repeat(kh, groups, axis=2)
                vh = jnp.repeat(vh, groups, axis=2)
            return qh, kh, vh

        q, k, v = apply_op(shape_rope_repeat,
                           [ensure_tensor(q), ensure_tensor(k),
                            ensure_tensor(v)], name="llama_rope_gqa")

        mesh = topology.get_mesh()
        if (cfg.sequence_parallel and mesh is not None
                and "sep" in mesh.axis_names and mesh.shape["sep"] > 1):
            from ..distributed.ring_attention import ring_attention

            ctx = ring_attention(q, k, v, causal=True, mesh=mesh)
        elif cfg.use_flash_attention:
            ctx = F.flash_attention(q, k, v, causal=True)
        else:
            ctx = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        if isinstance(ctx, tuple):
            ctx = ctx[0]
        merged = apply_op(lambda t: t.reshape(B, S, t.shape[2] * hd),
                          [ensure_tensor(ctx)], name="merge_heads")
        return self.o_proj(merged)


class LlamaMLP(nn.Layer):
    """SwiGLU: down(silu(gate(x)) * up(x)); gate/up column-parallel,
    down row-parallel."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        h, ff = config.hidden_size, config.intermediate_size
        mp = _mesh_dim("mp")
        std = config.initializer_range
        proj_std = std / math.sqrt(2 * config.num_layers)
        if mp > 1:
            from ..distributed.fleet import (ColumnParallelLinear,
                                             RowParallelLinear)

            self.gate_proj = ColumnParallelLinear(
                h, ff, gather_output=False, has_bias=False,
                weight_attr=nn.ParamAttr(initializer=_normal_init(std)))
            self.up_proj = ColumnParallelLinear(
                h, ff, gather_output=False, has_bias=False,
                weight_attr=nn.ParamAttr(initializer=_normal_init(std)))
            self.down_proj = RowParallelLinear(
                ff, h, input_is_parallel=True, has_bias=False,
                weight_attr=nn.ParamAttr(initializer=_normal_init(proj_std)))
        else:
            self.gate_proj = nn.Linear(h, ff, bias_attr=False,
                                       weight_attr=nn.ParamAttr(
                                           initializer=_normal_init(std)))
            self.up_proj = nn.Linear(h, ff, bias_attr=False,
                                     weight_attr=nn.ParamAttr(
                                         initializer=_normal_init(std)))
            self.down_proj = nn.Linear(ff, h, bias_attr=False,
                                       weight_attr=nn.ParamAttr(
                                           initializer=_normal_init(proj_std)))

    def forward(self, x, adapters=None, layer_idx=0):
        if adapters is None:
            return self.down_proj(F.silu(self.gate_proj(x))
                                  * self.up_proj(x))
        from ..serving.adapters import lora_delta

        g = self.gate_proj(x) + lora_delta(x, *adapters["gate_proj"],
                                           layer_idx)
        u = self.up_proj(x) + lora_delta(x, *adapters["up_proj"],
                                         layer_idx)
        a = F.silu(g) * u
        return self.down_proj(a) + lora_delta(a, *adapters["down_proj"],
                                              layer_idx)


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        eps = config.rms_norm_eps
        self.input_layernorm = nn.RMSNorm(config.hidden_size, epsilon=eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size,
                                                   epsilon=eps)
        self.mlp = LlamaMLP(config)

    def forward(self, x, cache=None, cur_len=None):
        if cache is not None:
            h, nc = self.self_attn(self.input_layernorm(x), cache=cache,
                                   cur_len=cur_len)
            x = x + h
            return x + self.mlp(self.post_attention_layernorm(x)), nc
        x = x + self.self_attn(self.input_layernorm(x))
        return x + self.mlp(self.post_attention_layernorm(x))

    def forward_paged(self, x, positions, block_tables, k_pool, v_pool,
                      adapters=None, layer_idx=0, k_scale=None,
                      v_scale=None):
        h, nc = self.self_attn.forward_paged(
            self.input_layernorm(x), positions, block_tables, k_pool,
            v_pool, adapters=adapters, layer_idx=layer_idx,
            k_scale=k_scale, v_scale=v_scale)
        x = x + h
        return x + self.mlp(self.post_attention_layernorm(x),
                            adapters=adapters, layer_idx=layer_idx), nc


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        std = config.initializer_range
        mp = _mesh_dim("mp")
        if mp > 1:
            from ..distributed.fleet import VocabParallelEmbedding

            self.embed_tokens = VocabParallelEmbedding(
                config.vocab_size, config.hidden_size,
                weight_attr=nn.ParamAttr(initializer=_normal_init(std)))
        else:
            self.embed_tokens = nn.Embedding(
                config.vocab_size, config.hidden_size,
                weight_attr=nn.ParamAttr(initializer=_normal_init(std)))
        self.layers = nn.LayerList([LlamaDecoderLayer(config)
                                    for _ in range(config.num_layers)])
        self.norm = nn.RMSNorm(config.hidden_size,
                               epsilon=config.rms_norm_eps)

    def _seq_parallel(self, x):
        """Pin the residual stream's sequence dim to the 'sep' axis (same
        pattern as GPTModel._seq_parallel) — without this, ring attention's
        shard_map boundary would reshard activations every layer."""
        import jax

        mesh = topology.get_mesh()
        if (not self.config.sequence_parallel or mesh is None
                or "sep" not in mesh.axis_names or mesh.shape["sep"] == 1):
            return x

        def fn(v):
            from jax.sharding import NamedSharding, PartitionSpec as P

            return jax.lax.with_sharding_constraint(
                v, NamedSharding(mesh, P(None, "sep", None)))

        return apply_op(fn, [ensure_tensor(x)],
                        name="seq_parallel_constraint")

    def forward(self, input_ids, caches=None, cur_len=None):
        x = self.embed_tokens(ensure_tensor(input_ids))
        if caches is not None:
            new_caches = []
            for layer, cache in zip(self.layers, caches):
                x, nc = layer(x, cache=cache, cur_len=cur_len)
                new_caches.append(nc)
            return self.norm(x), new_caches
        x = self._seq_parallel(x)
        if self.config.recompute:
            from ..distributed.fleet.recompute import recompute as _rc

            for layer in self.layers:
                x = _rc(layer, x, policy=self.config.recompute_policy)
        else:
            for layer in self.layers:
                x = layer(x)
        return self.norm(x)

    def forward_paged(self, input_ids, positions, block_tables, caches,
                      adapters=None):
        """Paged decode trunk (serving engine): ``input_ids`` [B, 1],
        ``positions`` [B], ``caches`` a per-layer list of (k_pool, v_pool)
        page pools — or (k_pool, v_pool, k_scales, v_scales) for int8
        pages (the scale arrays thread through to the in-kernel dequant
        and come back updated in ``new_caches``). ``adapters``: per-row
        gathered LoRA stacks ``{site: (A [T, L, r, in], B [T, L, out,
        r])}`` applied at every projection site per layer (zero for
        slot-0 rows). Returns (hidden [B, 1, H], new_caches)."""
        x = self.embed_tokens(ensure_tensor(input_ids))
        new_caches = []
        for li, (layer, cache) in enumerate(zip(self.layers, caches)):
            kp, vp = cache[0], cache[1]
            ks = cache[2] if len(cache) > 2 else None
            vs = cache[3] if len(cache) > 2 else None
            x, nc = layer.forward_paged(x, positions, block_tables, kp, vp,
                                        adapters=adapters, layer_idx=li,
                                        k_scale=ks, v_scale=vs)
            new_caches.append(nc)
        return self.norm(x), new_caches


class LlamaForCausalLM(nn.Layer, GenerationMixin):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = nn.Linear(
                config.hidden_size, config.vocab_size, bias_attr=False,
                weight_attr=nn.ParamAttr(
                    initializer=_normal_init(config.initializer_range)))

    def logits(self, hidden):
        if self.lm_head is not None:
            return self.lm_head(hidden)
        w = self.llama.embed_tokens.weight
        return apply_op(lambda h, e: h @ e.T,
                        [ensure_tensor(hidden), ensure_tensor(w)],
                        name="tied_lm_head")

    def _decode_trunk(self):
        return self.llama

    def _cache_spec(self):
        cfg = self.config
        # pre-repeat kv heads: GQA's memory saving applies to the cache too
        return (cfg.num_layers, cfg.num_key_value_heads,
                cfg.hidden_size // cfg.num_heads)

    def lora_sites(self):
        """The AdapterStore contract (serving/adapters.py): ordered
        ``(site, in_dim, out_dim)`` triples for every projection the
        paged trunk offers a LoRA delta at, plus the layer count.
        Dims are the UNSHARDED shapes — multi-LoRA serving assumes the
        single-program (mp=1) serving path."""
        cfg = self.config
        hd = cfg.hidden_size // cfg.num_heads
        h = cfg.hidden_size
        q_out = cfg.num_heads * hd
        kv_out = cfg.num_key_value_heads * hd
        ff = cfg.intermediate_size
        sites = [("q_proj", h, q_out), ("k_proj", h, kv_out),
                 ("v_proj", h, kv_out), ("o_proj", q_out, h),
                 ("gate_proj", h, ff), ("up_proj", h, ff),
                 ("down_proj", ff, h)]
        return sites, cfg.num_layers

    def forward(self, input_ids, labels=None):
        hidden = self.llama(input_ids)
        if labels is not None and self.config.fused_loss:
            if _mesh_dim("mp") > 1:
                import warnings

                warnings.warn(
                    "LlamaConfig.fused_loss is mp==1 only (vocab-sharded "
                    "loss is ParallelCrossEntropy's job); using the dense "
                    "path — expect the [B·S, V] logits memory peak",
                    stacklevel=2)
            else:
                from ..ops.fused_loss import fused_linear_cross_entropy

                w = self.lm_head.weight if self.lm_head is not None \
                    else self.llama.embed_tokens.weight
                H = self.config.hidden_size
                # lm_head.weight is [H, V] (Linear layout); fused CE wants
                # [V, H]; the tied embedding is [V, H] already
                needs_t = self.lm_head is not None
                loss = apply_op(
                    lambda h, wv, y: fused_linear_cross_entropy(
                        h.reshape(-1, H), wv.T if needs_t else wv,
                        y.reshape(-1)),
                    [ensure_tensor(hidden), ensure_tensor(w),
                     ensure_tensor(labels)],
                    name="fused_linear_cross_entropy")
                return None, loss
        logits = self.logits(hidden)
        if labels is None:
            return logits
        loss = F.cross_entropy(
            logits.reshape((-1, self.config.vocab_size)),
            ensure_tensor(labels).reshape((-1,)))
        return logits, loss
