"""Audio frequency-domain helpers (reference:
python/paddle/audio/functional/functional.py + window.py)."""
from __future__ import annotations

import math
from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

from ..ops._apply import ensure_tensor, unary
from ..tensor import Tensor

__all__ = [
    "hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
    "compute_fbank_matrix", "power_to_db", "create_dct", "get_window",
]


def hz_to_mel(freq, htk: bool = False):
    """reference: functional.py hz_to_mel (slaney default)."""
    scalar = not isinstance(freq, Tensor)
    f = np.asarray(freq.numpy()) if isinstance(freq, Tensor) \
        else np.asarray(freq, "float64")
    if htk:
        mel = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mel = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mel = np.where(f >= min_log_hz,
                       min_log_mel + np.log(np.maximum(f, 1e-10)
                                            / min_log_hz) / logstep, mel)
    return float(mel) if scalar and mel.ndim == 0 else Tensor(
        jnp.asarray(mel.astype("float32")), stop_gradient=True)


def mel_to_hz(mel, htk: bool = False):
    """reference: functional.py mel_to_hz."""
    scalar = not isinstance(mel, Tensor)
    m = np.asarray(mel.numpy()) if isinstance(mel, Tensor) \
        else np.asarray(mel, "float64")
    if htk:
        f = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        f = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        f = np.where(m >= min_log_mel,
                     min_log_hz * np.exp(logstep * (m - min_log_mel)), f)
    return float(f) if scalar and f.ndim == 0 else Tensor(
        jnp.asarray(f.astype("float32")), stop_gradient=True)


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False,
                    dtype="float32"):
    """reference: functional.py mel_frequencies."""
    lo, hi = hz_to_mel(f_min, htk), hz_to_mel(f_max, htk)
    mels = np.linspace(lo, hi, n_mels)
    f = np.asarray(mel_to_hz(Tensor(jnp.asarray(
        mels.astype("float32"))), htk).numpy())
    return Tensor(jnp.asarray(f.astype(dtype)), stop_gradient=True)


def fft_frequencies(sr: int, n_fft: int, dtype="float32"):
    """reference: functional.py fft_frequencies."""
    return Tensor(jnp.linspace(0, sr / 2, 1 + n_fft // 2).astype(dtype),
                  stop_gradient=True)


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max: Optional[float] = None,
                         htk: bool = False, norm: Union[str, float] = "slaney",
                         dtype="float32"):
    """reference: functional.py compute_fbank_matrix — [n_mels, 1+n_fft//2]
    triangular mel filter bank."""
    f_max = f_max or sr / 2.0
    fft_f = np.asarray(fft_frequencies(sr, n_fft, "float64").numpy())
    mel_f = np.asarray(mel_frequencies(n_mels + 2, f_min, f_max, htk,
                                       "float64").numpy())
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fft_f[None, :]
    weights = np.zeros((n_mels, len(fft_f)))
    for i in range(n_mels):
        lower = -ramps[i] / fdiff[i]
        upper = ramps[i + 2] / fdiff[i + 1]
        weights[i] = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights *= enorm[:, None]
    elif isinstance(norm, (int, float)):
        w_norm = np.linalg.norm(weights, ord=norm, axis=-1, keepdims=True)
        weights = weights / np.maximum(w_norm, 1e-10)
    return Tensor(jnp.asarray(weights.astype(dtype)), stop_gradient=True)


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: Optional[float] = 80.0, name=None):
    """reference: functional.py power_to_db — 10*log10 with top_db floor."""
    if amin <= 0:
        raise ValueError("amin must be strictly positive")
    if ref_value <= 0:
        raise ValueError("ref_value must be strictly positive")

    def fn(s):
        log_spec = 10.0 * jnp.log10(jnp.maximum(s, amin))
        log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
        if top_db is not None:
            if top_db < 0:
                raise ValueError("top_db must be non-negative")
            log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
        return log_spec

    return unary(fn, ensure_tensor(spect), name="power_to_db")


def create_dct(n_mfcc: int, n_mels: int, norm: Optional[str] = "ortho",
               dtype="float32"):
    """reference: functional.py create_dct — [n_mels, n_mfcc] DCT-II basis."""
    n = np.arange(n_mels, dtype="float64")
    k = np.arange(n_mfcc, dtype="float64")[None, :]
    dct = np.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        dct[:, 0] *= 1.0 / math.sqrt(2.0)
        dct *= math.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return Tensor(jnp.asarray(dct.astype(dtype)), stop_gradient=True)


def get_window(window: Union[str, tuple], win_length: int,
               fftbins: bool = True, dtype="float32"):
    """reference: audio/functional/window.py get_window."""
    import scipy.signal as sps

    w = sps.get_window(window, win_length, fftbins=fftbins)
    return Tensor(jnp.asarray(w.astype(dtype)), stop_gradient=True)
