"""Stdlib audio backend (reference: audio/backends/wave_backend.py plus the
soundfile backend's format coverage — the reference loads 8/16/24/32-bit PCM
and float WAVs via soundfile; this zero-egress build parses the RIFF
container directly so the same encodings round-trip without external deps).

Encodings: PCM_U8, PCM_S (16/24/32-bit), PCM_F (float32/float64).
"""
from __future__ import annotations

import struct
import wave as _wave
from dataclasses import dataclass

import numpy as np

__all__ = ["AudioInfo", "info", "load", "save", "get_current_backend",
           "list_available_backends", "set_backend"]

_FMT_PCM = 1
_FMT_FLOAT = 3


@dataclass
class AudioInfo:
    sample_rate: int
    num_samples: int
    num_channels: int
    bits_per_sample: int
    encoding: str = "PCM_S"


_current = "wave"


def list_available_backends():
    return ["wave"]


def get_current_backend() -> str:
    return _current


def set_backend(backend_name: str) -> None:
    global _current
    if backend_name not in list_available_backends():
        raise NotImplementedError(
            f"audio backend {backend_name!r} unavailable; only the stdlib "
            "'wave' backend ships in this zero-egress image")
    _current = backend_name


def _read_riff(filepath: str):
    """Parse a RIFF/WAVE file: returns (fmt_tag, channels, sample_rate,
    bits, raw data bytes). Handles PCM and IEEE-float fmt chunks, which the
    stdlib wave module rejects."""
    with open(filepath, "rb") as f:
        riff, _, wav = struct.unpack("<4sI4s", f.read(12))
        if riff != b"RIFF" or wav != b"WAVE":
            raise ValueError(f"{filepath!r} is not a RIFF/WAVE file")
        fmt = None
        data = None
        while True:
            hdr = f.read(8)
            if len(hdr) < 8:
                break
            cid, size = struct.unpack("<4sI", hdr)
            body = f.read(size)
            if size % 2:
                f.read(1)  # chunks are word-aligned
            if cid == b"fmt ":
                tag, ch, sr, _, _, bits = struct.unpack("<HHIIHH", body[:16])
                if tag == 0xFFFE and size >= 40:  # WAVE_FORMAT_EXTENSIBLE
                    tag = struct.unpack("<H", body[24:26])[0]
                fmt = (tag, ch, sr, bits)
            elif cid == b"data":
                data = body
        if fmt is None or data is None:
            raise ValueError(f"{filepath!r}: missing fmt/data chunk")
        return (*fmt, data)


def _decode(tag, ch, bits, raw, normalize):
    if tag == _FMT_FLOAT:
        dt = "<f4" if bits == 32 else "<f8"
        data = np.frombuffer(raw, dtype=dt).reshape(-1, ch)
        return data.astype(np.float32) if normalize else data
    if bits == 8:  # unsigned
        data = np.frombuffer(raw, dtype=np.uint8).reshape(-1, ch)
        return (data.astype(np.float32) - 128.0) / 128.0 if normalize \
            else data
    if bits == 16:
        data = np.frombuffer(raw, dtype="<i2").reshape(-1, ch)
        return data.astype(np.float32) / 32768.0 if normalize else data
    if bits == 24:
        b = np.frombuffer(raw, dtype=np.uint8).reshape(-1, 3)
        val = (b[:, 0].astype(np.int32) | (b[:, 1].astype(np.int32) << 8)
               | (b[:, 2].astype(np.int32) << 16))
        val = np.where(val & 0x800000, val - (1 << 24), val)
        data = val.reshape(-1, ch)
        return data.astype(np.float32) / float(1 << 23) if normalize \
            else data
    if bits == 32:
        data = np.frombuffer(raw, dtype="<i4").reshape(-1, ch)
        return data.astype(np.float32) / float(1 << 31) if normalize \
            else data
    raise NotImplementedError(f"unsupported PCM bit depth {bits}")


def _encoding_name(tag, bits):
    if tag == _FMT_FLOAT:
        return "PCM_F"
    return "PCM_U" if bits == 8 else "PCM_S"


def info(filepath: str) -> AudioInfo:
    tag, ch, sr, bits, data = _read_riff(filepath)
    frame = ch * (bits // 8)
    return AudioInfo(sample_rate=sr, num_samples=len(data) // frame,
                     num_channels=ch, bits_per_sample=bits,
                     encoding=_encoding_name(tag, bits))


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True):
    """Returns (Tensor [C, N] or [N, C], sample_rate)."""
    from ...tensor import Tensor
    import jax.numpy as jnp

    tag, ch, sr, bits, raw = _read_riff(filepath)
    data = _decode(tag, ch, bits, raw, normalize)
    if frame_offset:
        data = data[frame_offset:]
    if num_frames >= 0:
        data = data[:num_frames]
    arr = data.T if channels_first else data
    return Tensor(jnp.asarray(arr)), sr


def save(filepath: str, src, sample_rate: int,
         channels_first: bool = True, encoding: str = "PCM_S",
         bits_per_sample: int = 16) -> None:
    data = np.asarray(src.numpy() if hasattr(src, "numpy") else src)
    if data.ndim == 1:
        data = data[:, None]
    elif channels_first:
        data = data.T
    ch = data.shape[1]

    if encoding == "PCM_F":
        bits = 32 if bits_per_sample not in (32, 64) else bits_per_sample
        payload = data.astype("<f4" if bits == 32 else "<f8").tobytes()
        tag = _FMT_FLOAT
    else:
        bits = bits_per_sample
        if data.dtype.kind == "f":
            data = np.clip(data, -1.0, 1.0)
            if bits == 8:
                q = (data * 127.0 + 128.0).astype(np.uint8)
            elif bits == 16:
                q = (data * 32767.0).astype("<i2")
            elif bits == 24:
                q = (data * float((1 << 23) - 1)).astype(np.int32)
            elif bits == 32:
                q = (data * float((1 << 31) - 1)).astype("<i4")
            else:
                raise NotImplementedError(
                    f"unsupported bits_per_sample {bits}")
        else:
            # integer input: cast to the declared sample width so the
            # payload matches the header's block align
            if bits == 8:
                q = data.astype(np.uint8)
            elif bits == 16:
                q = data.astype("<i2")
            elif bits in (24, 32):
                q = data.astype(np.int32 if bits == 24 else "<i4")
            else:
                raise NotImplementedError(
                    f"unsupported bits_per_sample {bits}")
        if bits == 24:
            v = q.astype(np.int32).reshape(-1)
            payload = np.stack([v & 0xFF, (v >> 8) & 0xFF,
                                (v >> 16) & 0xFF],
                               axis=-1).astype(np.uint8).tobytes()
        else:
            payload = np.ascontiguousarray(q).tobytes()
        tag = _FMT_PCM

    block = ch * (bits // 8)
    with open(filepath, "wb") as f:
        f.write(b"RIFF")
        f.write(struct.pack("<I", 36 + len(payload)))
        f.write(b"WAVE")
        f.write(struct.pack("<4sIHHIIHH", b"fmt ", 16, tag, ch,
                            sample_rate, sample_rate * block, block, bits))
        f.write(struct.pack("<4sI", b"data", len(payload)))
        f.write(payload)
