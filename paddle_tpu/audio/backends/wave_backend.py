"""Stdlib-wave audio backend (reference: audio/backends/wave_backend.py
— 16-bit PCM WAV read/write without external deps)."""
from __future__ import annotations

import wave as _wave
from dataclasses import dataclass

import numpy as np

__all__ = ["AudioInfo", "info", "load", "save", "get_current_backend",
           "list_available_backends", "set_backend"]


@dataclass
class AudioInfo:
    sample_rate: int
    num_samples: int
    num_channels: int
    bits_per_sample: int
    encoding: str = "PCM_S"


_current = "wave"


def list_available_backends():
    return ["wave"]


def get_current_backend() -> str:
    return _current


def set_backend(backend_name: str) -> None:
    global _current
    if backend_name not in list_available_backends():
        raise NotImplementedError(
            f"audio backend {backend_name!r} unavailable; only the stdlib "
            "'wave' backend ships in this zero-egress image")
    _current = backend_name


def info(filepath: str) -> AudioInfo:
    with _wave.open(filepath, "rb") as f:
        return AudioInfo(sample_rate=f.getframerate(),
                         num_samples=f.getnframes(),
                         num_channels=f.getnchannels(),
                         bits_per_sample=f.getsampwidth() * 8)


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True):
    """Returns (Tensor [C, N] or [N, C], sample_rate)."""
    from ...tensor import Tensor
    import jax.numpy as jnp

    with _wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        n = f.getnframes()
        ch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(min(frame_offset, n))
        count = n - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(count)
    if width != 2:
        raise NotImplementedError("wave backend reads 16-bit PCM only")
    data = np.frombuffer(raw, dtype="<i2").reshape(-1, ch)
    if normalize:
        data = data.astype(np.float32) / 32768.0
    arr = data.T if channels_first else data
    return Tensor(jnp.asarray(arr)), sr


def save(filepath: str, src, sample_rate: int,
         channels_first: bool = True, encoding: str = "PCM_S",
         bits_per_sample: int = 16) -> None:
    if bits_per_sample != 16:
        raise NotImplementedError("wave backend writes 16-bit PCM only")
    data = np.asarray(src.numpy() if hasattr(src, "numpy") else src)
    if channels_first:
        data = data.T
    if data.dtype.kind == "f":
        data = np.clip(data, -1.0, 1.0)
        data = (data * 32767.0).astype("<i2")
    with _wave.open(filepath, "wb") as f:
        f.setnchannels(data.shape[1] if data.ndim > 1 else 1)
        f.setsampwidth(2)
        f.setframerate(sample_rate)
        f.writeframes(data.astype("<i2").tobytes())
