"""audio.backends — audio file IO (reference: audio/backends/ — the
'wave' backend built on the stdlib wave module; soundfile optional)."""
from .wave_backend import AudioInfo, get_current_backend, info, list_available_backends, load, save, set_backend  # noqa: F401,E501

__all__ = ["info", "load", "save", "AudioInfo", "get_current_backend",
           "list_available_backends", "set_backend"]
