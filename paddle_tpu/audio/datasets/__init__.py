"""audio.datasets (reference: audio/datasets/ — ESC50/TESS download-based
corpora). Zero-egress: constructors raise with the local-files recipe,
matching the text datasets' contract."""
__all__ = ["ESC50", "TESS"]


class _ZeroEgressAudioDataset:
    def __init__(self, *a, **k):
        raise RuntimeError(
            f"{type(self).__name__} downloads its corpus from the network; "
            "this environment is zero-egress. Provide local WAV files and "
            "wrap them with paddle_tpu.io.Dataset + audio.backends.load.")


class ESC50(_ZeroEgressAudioDataset):
    pass


class TESS(_ZeroEgressAudioDataset):
    pass
