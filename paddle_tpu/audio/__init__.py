"""paddle.audio parity: feature extractors + functional frequency tools.

Reference parity: python/paddle/audio/ — ``functional`` (hz_to_mel,
mel_to_hz, mel_frequencies, fft_frequencies, compute_fbank_matrix,
power_to_db, create_dct) and ``features`` (Spectrogram, MelSpectrogram,
LogMelSpectrogram, MFCC layers) built on the stft from paddle.signal.
The dataset/backend IO tier is out of scope in a zero-egress image.
"""
from . import backends, datasets, features, functional  # noqa: F401
from .backends import info, load, save  # noqa: F401

__all__ = ["features", "functional", "backends", "datasets",
           "load", "info", "save"]
