"""Audio feature layers (reference:
python/paddle/audio/features/layers.py — Spectrogram, MelSpectrogram,
LogMelSpectrogram, MFCC)."""
from __future__ import annotations

from typing import Optional, Union

from .. import ops
from ..nn.layer_base import Layer
from ..ops._apply import ensure_tensor
from ..signal import stft
from . import functional as AF

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    """reference: features/layers.py Spectrogram — |STFT|^power."""

    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.fft_window = AF.get_window(window, self.win_length, dtype=dtype)

    def forward(self, x):
        spec = stft(ensure_tensor(x), n_fft=self.n_fft,
                    hop_length=self.hop_length, win_length=self.win_length,
                    window=self.fft_window, center=self.center,
                    pad_mode=self.pad_mode)
        mag = ops.abs(spec)
        if self.power != 1.0:
            mag = mag ** self.power
        return mag


class MelSpectrogram(Layer):
    """reference: features/layers.py MelSpectrogram."""

    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: Union[str, float] = "slaney",
                 dtype: str = "float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        dtype)
        self.fbank_matrix = AF.compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max,
            htk=htk, norm=norm, dtype=dtype)

    def forward(self, x):
        spec = self._spectrogram(x)  # [..., bins, frames]
        return ops.matmul(self.fbank_matrix, spec)


class LogMelSpectrogram(Layer):
    """reference: features/layers.py LogMelSpectrogram."""

    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: Union[str, float] = "slaney",
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None, dtype: str = "float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return AF.power_to_db(mel, ref_value=self.ref_value, amin=self.amin,
                              top_db=self.top_db)


class MFCC(Layer):
    """reference: features/layers.py MFCC — DCT over log-mel."""

    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: Union[str, float] = "slaney",
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None, dtype: str = "float32"):
        super().__init__()
        if n_mfcc > n_mels:
            raise ValueError("n_mfcc cannot be larger than n_mels")
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.dct_matrix = AF.create_dct(n_mfcc, n_mels, dtype=dtype)

    def forward(self, x):
        logmel = self._log_melspectrogram(x)  # [..., n_mels, frames]
        return ops.matmul(ops.t(self.dct_matrix), logmel)
