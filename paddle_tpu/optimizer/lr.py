"""Learning-rate schedulers.

reference parity: python/paddle/optimizer/lr.py (20+ scheduler classes over an
``LRScheduler`` base with step/get_lr/state_dict). Schedulers are pure-Python
host-side state — the lr enters the compiled step as a scalar argument, so
stepping the scheduler never triggers recompilation.
"""
from __future__ import annotations

import math
from typing import Callable, List, Optional, Sequence, Union

__all__ = [
    "LRScheduler", "NoamDecay", "PiecewiseDecay", "NaturalExpDecay",
    "InverseTimeDecay", "PolynomialDecay", "LinearWarmup", "ExponentialDecay",
    "MultiStepDecay", "StepDecay", "LambdaDecay", "ReduceOnPlateau",
    "CosineAnnealingDecay", "MultiplicativeDecay", "OneCycleLR", "CyclicLR",
    "LinearLR", "CosineAnnealingWarmRestarts",
]


class LRScheduler:
    """Base scheduler (reference: python/paddle/optimizer/lr.py LRScheduler)."""

    def __init__(self, learning_rate: float = 0.1, last_epoch: int = -1,
                 verbose: bool = False):
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.verbose = verbose
        self.last_lr = self.base_lr
        self.step()

    def __call__(self) -> float:
        return self.last_lr

    def step(self, epoch: Optional[int] = None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self.get_lr()
        if self.verbose:
            print(f"Epoch {self.last_epoch}: {type(self).__name__} set learning rate to {self.last_lr}.")

    def get_lr(self) -> float:
        raise NotImplementedError

    # progress state only — hyperparameters belong to the constructor, so a
    # resume with a new schedule config is not silently overwritten
    # (reference: lr.py keys = ['last_epoch', 'last_lr'])
    _state_keys = ("last_epoch", "last_lr")

    def state_dict(self) -> dict:
        return {k: self.__dict__[k] for k in self._state_keys if k in self.__dict__}

    def set_state_dict(self, state: dict):
        for k in self._state_keys:
            if k in state:
                self.__dict__[k] = state[k]

    load_state_dict = set_state_dict


class NoamDecay(LRScheduler):
    """lr = d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)."""

    def __init__(self, d_model, warmup_steps, learning_rate=1.0, last_epoch=-1,
                 verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 1)
        a = step ** -0.5
        b = step * (self.warmup_steps ** -1.5)
        return self.base_lr * (self.d_model ** -0.5) * min(a, b)


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries: Sequence[int], values: Sequence[float],
                 last_epoch=-1, verbose=False):
        self.boundaries = list(boundaries)
        self.values = list(values)
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self):
        for i, b in enumerate(self.boundaries):
            if self.last_epoch < b:
                return self.values[i]
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * self.last_epoch)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = self.last_epoch
        decay_steps = self.decay_steps
        if self.cycle:
            div = math.ceil(step / decay_steps) if step > 0 else 1
            decay_steps = decay_steps * max(div, 1)
        else:
            step = min(step, decay_steps)
        frac = (1 - step / decay_steps) ** self.power
        return (self.base_lr - self.end_lr) * frac + self.end_lr


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 last_epoch=-1, verbose=False):
        self.lr_sched = learning_rate if isinstance(learning_rate, LRScheduler) else None
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        base = learning_rate if not isinstance(learning_rate, LRScheduler) else end_lr
        super().__init__(float(base), last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch < self.warmup_steps:
            return (self.end_lr - self.start_lr) * self.last_epoch / max(
                self.warmup_steps, 1) + self.start_lr
        if self.lr_sched is not None:
            # explicit-epoch step keeps get_lr idempotent (calling it twice,
            # or jumping via step(epoch=N), lands on the same inner state)
            self.lr_sched.step(self.last_epoch - self.warmup_steps)
            return self.lr_sched()
        return self.base_lr

    def state_dict(self):
        sd = super().state_dict()
        if self.lr_sched is not None:
            sd["LinearWarmup_LR"] = self.lr_sched.state_dict()
        return sd

    def set_state_dict(self, state):
        state = dict(state)
        inner = state.pop("LinearWarmup_LR", None)
        if inner is not None and self.lr_sched is not None:
            self.lr_sched.set_state_dict(inner)
        super().set_state_dict(state)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * (self.gamma ** self.last_epoch)


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones: Sequence[int], gamma=0.1,
                 last_epoch=-1, verbose=False):
        self.milestones = list(milestones)
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        n = sum(1 for m in self.milestones if m <= self.last_epoch)
        return self.base_lr * (self.gamma ** n)


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size: int, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * (self.gamma ** (self.last_epoch // self.step_size))


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda: Callable[[int], float],
                 last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(self.last_epoch)


class MultiplicativeDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda: Callable[[int], float],
                 last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        # incremental like the reference: last_lr * lambda(epoch), O(1)/step
        if self.last_epoch > 0:
            return self.last_lr * self.lr_lambda(self.last_epoch)
        return self.base_lr


class CosineAnnealingDecay(LRScheduler):
    """Cosine-annealed learning rate (reference: optimizer/lr.py
    CosineAnnealingDecay).

    Examples:
        >>> sched = paddle.optimizer.lr.CosineAnnealingDecay(0.1, T_max=10)
        >>> sched.get_lr()
        0.1
        >>> sched.step()
        >>> round(sched.get_lr(), 6) < 0.1
        True
    """

    def __init__(self, learning_rate, T_max: int, eta_min=0.0, last_epoch=-1,
                 verbose=False):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.eta_min + (self.base_lr - self.eta_min) * (
            1 + math.cos(math.pi * self.last_epoch / self.T_max)) / 2


class CosineAnnealingWarmRestarts(LRScheduler):
    def __init__(self, learning_rate, T_0: int, T_mult: int = 1, eta_min=0.0,
                 last_epoch=-1, verbose=False):
        self.T_0 = T_0
        self.T_mult = T_mult
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        epoch = max(self.last_epoch, 0)
        T_i, T_cur = self.T_0, epoch
        while T_cur >= T_i:
            T_cur -= T_i
            T_i *= self.T_mult if self.T_mult > 1 else 1
            if self.T_mult == 1:
                T_cur = epoch % self.T_0
                break
        return self.eta_min + (self.base_lr - self.eta_min) * (
            1 + math.cos(math.pi * T_cur / T_i)) / 2


class ReduceOnPlateau(LRScheduler):
    """reference: lr.py ReduceOnPlateau — metric-driven, step(metric)."""

    _state_keys = ("last_epoch", "last_lr", "cooldown_counter", "best",
                   "num_bad_epochs")

    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode="rel", cooldown=0, min_lr=0,
                 epsilon=1e-8, verbose=False):
        assert mode in ("min", "max")
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.epsilon = epsilon
        self.verbose = verbose
        self.base_lr = float(learning_rate)
        self.last_lr = self.base_lr
        self.last_epoch = 0
        self.cooldown_counter = 0
        self.best = None
        self.num_bad_epochs = 0

    def step(self, metrics, epoch=None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        metrics = float(metrics)
        if self.best is None or self._is_better(metrics, self.best):
            self.best = metrics
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad_epochs = 0
        elif self.num_bad_epochs > self.patience:
            new_lr = max(self.last_lr * self.factor, self.min_lr)
            if self.last_lr - new_lr > self.epsilon:
                self.last_lr = new_lr
                if self.verbose:
                    print(f"Epoch {self.last_epoch}: ReduceOnPlateau set learning rate to {new_lr}.")
            self.cooldown_counter = self.cooldown
            self.num_bad_epochs = 0

    def _is_better(self, cur, best):
        if self.mode == "min":
            if self.threshold_mode == "rel":
                return cur < best * (1 - self.threshold)
            return cur < best - self.threshold
        if self.threshold_mode == "rel":
            return cur > best * (1 + self.threshold)
        return cur > best + self.threshold

    def get_lr(self):
        return self.last_lr


class LinearLR(LRScheduler):
    def __init__(self, learning_rate, total_steps, start_factor=1.0 / 3,
                 end_factor=1.0, last_epoch=-1, verbose=False):
        self.total_steps = total_steps
        self.start_factor = start_factor
        self.end_factor = end_factor
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        t = min(self.last_epoch, self.total_steps)
        frac = self.start_factor + (self.end_factor - self.start_factor) * t / self.total_steps
        return self.base_lr * frac


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=0.0001, phase_pct=0.3,
                 anneal_strategy="cos", three_phase=False, last_epoch=-1,
                 verbose=False):
        self.max_lr = float(max_learning_rate)
        self.total_steps = total_steps
        self.initial_lr = self.max_lr / divide_factor
        self.end_lr = float(end_learning_rate)
        self.phase_pct = phase_pct
        self.anneal = anneal_strategy
        self.three_phase = three_phase
        super().__init__(self.initial_lr, last_epoch, verbose)

    def _interp(self, start, end, pct):
        if self.anneal == "cos":
            return end + (start - end) * (1 + math.cos(math.pi * pct)) / 2
        return (end - start) * pct + start

    def get_lr(self):
        step = min(self.last_epoch, self.total_steps)
        up_steps = float(self.phase_pct * self.total_steps) - 1
        if self.three_phase:
            down_steps = 2 * up_steps + 1
            if step <= up_steps:
                return self._interp(self.initial_lr, self.max_lr, step / max(up_steps, 1))
            if step <= down_steps:
                return self._interp(self.max_lr, self.initial_lr,
                                    (step - up_steps) / max(up_steps, 1))
            return self._interp(self.initial_lr, self.end_lr,
                                (step - down_steps) / max(self.total_steps - 1 - down_steps, 1))
        if step <= up_steps:
            return self._interp(self.initial_lr, self.max_lr, step / max(up_steps, 1))
        return self._interp(self.max_lr, self.end_lr,
                            (step - up_steps) / max(self.total_steps - 1 - up_steps, 1))


class CyclicLR(LRScheduler):
    def __init__(self, base_learning_rate, max_learning_rate, step_size_up,
                 step_size_down=None, mode="triangular", exp_gamma=1.0,
                 scale_fn=None, scale_mode="cycle", last_epoch=-1, verbose=False):
        self.max_lr = float(max_learning_rate)
        self.step_size_up = step_size_up
        self.step_size_down = step_size_down or step_size_up
        self.mode = mode
        self.exp_gamma = exp_gamma
        self._scale_fn = scale_fn
        self.scale_mode = scale_mode if scale_fn is not None else (
            "iterations" if mode == "exp_range" else "cycle")
        super().__init__(base_learning_rate, last_epoch, verbose)

    def _scale(self, x):
        if self._scale_fn is not None:
            return self._scale_fn(x)
        if self.mode == "triangular":
            return 1.0
        if self.mode == "triangular2":
            return 1.0 / (2 ** (x - 1))
        return self.exp_gamma ** x

    def get_lr(self):
        total = self.step_size_up + self.step_size_down
        cycle = math.floor(1 + self.last_epoch / total)
        iter_in_cycle = self.last_epoch - (cycle - 1) * total
        if iter_in_cycle <= self.step_size_up:
            pct = iter_in_cycle / self.step_size_up
        else:
            pct = 1 - (iter_in_cycle - self.step_size_up) / self.step_size_down
        amp = (self.max_lr - self.base_lr) * pct
        x = cycle if self.scale_mode == "cycle" else self.last_epoch
        return self.base_lr + amp * self._scale(x)
