"""paddle_tpu.optimizer — optimizers + LR schedulers.

reference parity: python/paddle/optimizer/ (Optimizer base optimizer.py:91,
SGD/Momentum/Adam/AdamW/Adamax/Adagrad/Adadelta/RMSProp/Lamb, lr.py
schedulers).
"""
from . import lr
from .optimizer import Optimizer
from .optimizers import (
    SGD, Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb, Momentum, RMSProp,
)

__all__ = [
    "lr", "Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax",
    "Adagrad", "Adadelta", "RMSProp", "Lamb",
]
