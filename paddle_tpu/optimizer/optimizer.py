"""Optimizer base class.

reference parity: python/paddle/optimizer/optimizer.py:91 (``Optimizer`` with
``step`` :1477, ``minimize`` :1391, ``_apply_optimize`` :1186, accumulator
machinery ``_add_accumulator``), reshaped TPU-first:

- Optimizer state ("accumulators") is a per-parameter dict of ``jax.Array``s,
  i.e. a pytree. The whole update is pure jnp code over (param, grad, accs),
  so a train step wrapped in ``paddle_tpu.jit`` compiles parameter updates
  into the same XLA program as forward+backward — the TPU counterpart of the
  reference's fused_adam multi-tensor kernel (phi/kernels/gpu/fused_adam_kernel.cu).
- In-place semantics (the reference's ``adamw_`` inplace ops) are realized by
  rebinding the Parameter's payload cell (``Tensor._set_value``), which the
  jit tracer records for functionalization.
"""
from __future__ import annotations

import time
from typing import Callable, Iterable, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

from ..tensor import Parameter, Tensor
from ..autograd import no_grad
from .lr import LRScheduler

__all__ = ["Optimizer"]


class _L2Decay:
    """L2 regularization added to the gradient (reference:
    python/paddle/regularizer.py L2Decay)."""

    def __init__(self, coeff: float):
        self.coeff = float(coeff)

    def __call__(self, param_value, grad_value):
        return grad_value + self.coeff * param_value


class _L1Decay:
    """reference: python/paddle/regularizer.py L1Decay."""

    def __init__(self, coeff: float):
        self.coeff = float(coeff)

    def __call__(self, param_value, grad_value):
        return grad_value + self.coeff * jnp.sign(param_value)


def _coerce_regularizer(weight_decay):
    if weight_decay is None:
        return None
    if callable(weight_decay):
        return weight_decay
    return _L2Decay(float(weight_decay))


class Optimizer:
    """Base optimizer (reference: python/paddle/optimizer/optimizer.py:91).

    Subclasses implement ``_update(param_value, grad_value, accs, lr)``
    returning ``(new_param_value, new_accs)`` — pure jnp, jit-traceable —
    and list their accumulator names/initializers in ``_accumulator_specs``.
    """

    # name -> init fn(param_value) for per-param state; subclasses override.
    _accumulator_specs: dict = {}

    def __init__(
        self,
        learning_rate: Union[float, LRScheduler] = 0.001,
        parameters: Optional[Iterable] = None,
        weight_decay=None,
        grad_clip=None,
        name: Optional[str] = None,
    ):
        # per-param overrides from the param-group API:
        # [{'params': [...], 'learning_rate': mult, 'weight_decay': wd}, ...]
        self._group_lr_mult: dict = {}    # param uid -> lr multiplier
        self._group_wd: dict = {}         # param uid -> regularizer
        if parameters is not None:
            parameters = list(parameters)
            if parameters and isinstance(parameters[0], dict):
                self._param_groups = parameters
                flat = []
                for g in parameters:
                    for p in g["params"]:
                        flat.append(p)
                        if "learning_rate" in g:
                            self._group_lr_mult[p._uid] = float(g["learning_rate"])
                        if "weight_decay" in g:
                            self._group_wd[p._uid] = _coerce_regularizer(
                                g["weight_decay"])
                parameters = flat
            else:
                self._param_groups = None
        else:
            self._param_groups = None
        self._parameter_list = parameters
        self._learning_rate = learning_rate
        self.regularization = _coerce_regularizer(weight_decay)
        self._grad_clip = grad_clip
        self._name = name or type(self).__name__
        # param uid -> {acc_name: jax.Array} (uid, not name: two params may
        # share a user-chosen name, and uid is already the group-override key)
        self._accumulators: dict = {}
        self._global_step = 0

    # -------------------------------------------------------------- lr plumbing
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value: float):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError(
                "optimizer's learning rate can't be set when it uses an LRScheduler"
            )
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler: LRScheduler):
        self._learning_rate = scheduler

    def _lr_value(self):
        """Current lr as a jnp scalar (traceable). Under paddle_tpu.jit the
        tracer installs ``_lr_override`` so the lr is a traced input of the
        compiled step — scheduler.step() between calls then needs no retrace."""
        override = getattr(self, "_lr_override", None)
        if override is not None:
            return override
        return jnp.asarray(self.get_lr(), dtype=jnp.float32)

    # ---------------------------------------------------------- accumulators
    def _materialize_accumulators(self):
        """Eagerly create all per-param state (normally lazy on first step) —
        lets paddle_tpu.jit compile a train step without an eager warm-up
        call (to_static(..., warmup=False))."""
        multi_precision = getattr(self, "_multi_precision", False)
        for p in self._parameter_list or []:
            if getattr(p, "trainable", True) and not p.stop_gradient:
                accs = self._get_accumulators(p)
                if multi_precision and p._value.dtype in (
                        jnp.bfloat16, jnp.float16) and "@master" not in accs:
                    accs["@master"] = p._value.astype(jnp.float32)

    def _get_accumulators(self, p: Parameter) -> dict:
        accs = self._accumulators.get(p._uid)
        if accs is None:
            accs = {
                name: init(p._value) for name, init in self._accumulator_specs.items()
            }
            self._accumulators[p._uid] = accs
        return accs

    # ---------------------------------------------------------------- update
    def _update(self, param_value, grad_value, accs: dict, lr):
        raise NotImplementedError

    def _param_lr(self, param) -> float:
        """Per-parameter lr multiplier (ParamAttr learning_rate × param-group
        learning_rate, reference: optimizer.py _create_param_lr)."""
        mult = float(getattr(param, "optimize_attr", {}).get("learning_rate", 1.0))
        return mult * self._group_lr_mult.get(param._uid, 1.0)

    def _param_regularizer(self, param):
        """Effective regularizer: per-param > per-group > optimizer-wide."""
        if getattr(param, "regularizer", None) is not None:
            return param.regularizer
        if param._uid in self._group_wd:
            return self._group_wd[param._uid]
        return self.regularization

    def _collect_params_grads(self):
        params = self._parameter_list
        if params is None:
            raise ValueError(
                "optimizer constructed without a parameter list; pass "
                "parameters=model.parameters()"
            )
        out = []
        for p in params:
            if p.stop_gradient or p.grad is None:
                continue
            if not getattr(p, "trainable", True):
                continue
            out.append((p, p.grad))
        return out

    @no_grad()
    def step(self):
        """Apply one optimizer update (reference: optimizer.py:1477).

        Two AMP hooks (paddle_tpu.amp):
        - master weights (``multi_precision``, reference: optimizer.py
          _create_master_weight): low-precision params keep an fp32 "master"
          accumulator that carries the true state; the param cell holds its
          down-cast.
        - ``_found_inf`` (set by GradScaler before step, reference:
          check_finite_and_unscale + update_loss_scaling ops): when the traced
          flag is true the whole update is a jnp.where no-op — the traceable
          equivalent of the reference's skip-step.

        Telemetry: each call lands in
        ``paddle_tpu_train_optimizer_step_seconds`` /
        ``..._steps_total``. Inside a jit-compiled train step this python
        body runs only at trace time, so the metrics then count *traces*
        (and time tracing), not executed steps — eager training gets
        per-step numbers (docs/OBSERVABILITY.md).
        """
        from .. import metrics

        _reg = metrics.get_registry()
        _t0 = time.perf_counter() if _reg.enabled else 0.0
        params_grads = self._collect_params_grads()
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = self._lr_value()
        found_inf = getattr(self, "_found_inf", None)
        if found_inf is not None and isinstance(found_inf, Tensor):
            found_inf = found_inf._value
        multi_precision = getattr(self, "_multi_precision", False)
        for p, g in params_grads:
            gv = g._value
            use_master = multi_precision and p._value.dtype in (
                jnp.bfloat16, jnp.float16)
            accs = self._get_accumulators(p)
            if use_master:
                if "@master" not in accs:
                    accs["@master"] = p._value.astype(jnp.float32)
                pv = accs["@master"]
                gv = gv.astype(jnp.float32)
            else:
                pv = p._value
                if gv.dtype != pv.dtype:
                    gv = gv.astype(pv.dtype)
            reg = self._param_regularizer(p)
            if reg is not None:
                gv = reg(pv, gv)
            plr = self._param_lr(p)
            new_val, new_accs = self._update(pv, gv, accs, lr * plr)
            if found_inf is not None:
                new_val = jnp.where(found_inf, pv, new_val)
                new_accs = {
                    k: jnp.where(found_inf, accs[k], v) if k in accs
                    and getattr(v, "shape", None) == getattr(accs[k], "shape", None)
                    else v
                    for k, v in new_accs.items()
                }
            if use_master:
                new_accs["@master"] = new_val
                p._set_value(new_val.astype(p._value.dtype))
            else:
                p._set_value(new_val)
            self._accumulators[p._uid] = new_accs
        if found_inf is not None:
            # the skip used to be silent; counted AFTER the update loop
            # so the blocking host read of the flag overlaps the already-
            # dispatched device work instead of serializing ahead of it.
            # bool() on a traced flag raises (under jit the skip is data-
            # dependent and the host can't observe it), so only eager
            # skips count — which is where GradScaler runs. Sentinel-
            # tagged skips count in paddle_tpu_train_skipped_batches_total
            # instead.
            try:
                skip_now = bool(found_inf)
            except Exception:
                skip_now = False
            if skip_now and getattr(self, "_found_inf_origin",
                                    "amp") == "amp":
                _reg.counter(
                    "paddle_tpu_amp_skipped_steps_total",
                    "Optimizer updates suppressed by the _found_inf skip "
                    "path (GradScaler non-finite gradients)").inc()
        self._found_inf = None  # consume-once: a stale flag must not freeze future steps
        self._found_inf_origin = "amp"  # consumed with the flag it tags
        self._global_step += 1
        # _t0 > 0 guard: if the registry was enabled mid-step, _t0 is the
        # 0.0 sentinel and observing perf_counter()-0 would poison the
        # histogram with an absolute-clock outlier
        if _reg.enabled and _t0 > 0.0:
            _reg.histogram(
                "paddle_tpu_train_optimizer_step_seconds",
                "One Optimizer.step(): clip + per-param updates"
            ).observe(time.perf_counter() - _t0)
            _reg.counter(
                "paddle_tpu_train_optimizer_steps_total",
                "Optimizer.step() calls (trace-time only under jit)").inc()

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        """reference: optimizer.py:1391 — in dygraph the reference's
        ``backward`` only *collects* grads already produced by a prior
        ``loss.backward()`` call; it never re-runs autodiff. Matching that
        contract here: callers must run ``loss.backward()`` first (the
        documented pattern), otherwise we raise instead of silently
        double-accumulating.

        Inside a ``static.program_guard`` this is DECLARATIVE (reference:
        static-graph minimize appends backward+opt ops to the Program): the
        loss/optimizer register with the program; the actual grads + step
        happen in Executor.run."""
        from ..static import _collect_parameters, _guard_stack

        if _guard_stack:
            prog = _guard_stack[-1][0]
            prog.loss = loss
            prog.optimizer = self
            if parameters is not None:
                plist = list(parameters)
            elif self._parameter_list is not None:
                plist = list(self._parameter_list)
            else:
                # static contract: minimize() without parameters= trains
                # every trainable var reachable from the loss
                plist = _collect_parameters(loss)
            if no_grad_set:
                frozen_ids = {id(p) for p in no_grad_set
                              if not isinstance(p, str)}
                frozen_names = {p for p in no_grad_set if isinstance(p, str)}
                plist = [p for p in plist
                         if id(p) not in frozen_ids
                         and getattr(p, "name", None) not in frozen_names]
            self._parameter_list = plist
            self._materialize_accumulators()
            return None, []
        if (self._parameter_list is not None
                and not any(p.grad is not None for p in self._parameter_list)):
            raise RuntimeError(
                "Optimizer.minimize found no gradients: call loss.backward() "
                "before minimize() (minimize only applies already-computed "
                "grads, matching the reference dygraph contract)")
        self.step()
        return None, self._collect_params_grads()

    @no_grad()
    def clear_grad(self, set_to_zero: bool = False):
        """reference: optimizer.py clear_grad."""
        if self._parameter_list is None:
            return
        for p in self._parameter_list:
            if set_to_zero and p.grad is not None:
                p.grad = Tensor(jnp.zeros_like(p.grad._value))
            else:
                p.grad = None

    clear_gradients = clear_grad

    # ------------------------------------------------------------ state dict
    def state_dict(self) -> dict:
        """Accumulators + LR scheduler state (reference: optimizer.py
        state_dict — accumulator tensors keyed by name).

        Keys are ``pos:{index}.{acc_name}`` where index is the parameter's
        position in the optimizer's parameter list — stable across processes,
        unlike auto-generated tensor names (tensor.py's process-global uid
        counter shifts between runs).
        """
        sd = {}
        pos_of = {p._uid: i for i, p in enumerate(self._parameter_list or [])}
        for uid, accs in self._accumulators.items():
            if uid not in pos_of:
                continue  # param no longer tracked by this optimizer
            for aname, val in accs.items():
                sd[f"pos:{pos_of[uid]}.{aname}"] = Tensor(val)
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        sd["@global_step"] = self._global_step
        return sd

    def set_state_dict(self, state_dict: dict):
        state_dict = dict(state_dict)
        lr_state = state_dict.pop("LR_Scheduler", None)
        if lr_state is not None and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(lr_state)
        self._global_step = int(state_dict.pop("@global_step", 0))
        params = self._parameter_list or []
        for key, val in state_dict.items():
            pkey, _, aname = key.rpartition(".")
            if not pkey or not pkey.startswith("pos:"):
                continue
            idx = int(pkey[4:])
            if idx >= len(params):
                raise KeyError(
                    f"optimizer state refers to parameter index {idx} but "
                    f"this optimizer has only {len(params)} parameters"
                )
            uid = params[idx]._uid
            arr = val._value if isinstance(val, Tensor) else jnp.asarray(val)
            self._accumulators.setdefault(uid, {})[aname] = arr

    load_state_dict = set_state_dict

    def __repr__(self):
        return f"{type(self).__name__}(lr={self.get_lr()})"
