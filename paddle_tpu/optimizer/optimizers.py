"""The optimizer family.

reference parity: python/paddle/optimizer/{sgd,momentum,adam,adamw,adamax,
adagrad,adadelta,rmsprop,lamb}.py. Each ``_update`` is a pure jnp function
over (param, grad, accumulators, lr) so the whole family jit-compiles into
the training step (the TPU equivalent of the reference's fused multi-tensor
CUDA kernels, e.g. phi/kernels/gpu/fused_adam_kernel.cu).
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .optimizer import Optimizer, _L2Decay

__all__ = [
    "SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad", "Adadelta",
    "RMSProp", "Lamb",
]


def _zeros_like(v):
    return jnp.zeros_like(v)


def _f32_scalar(x):
    return jnp.asarray(x, dtype=jnp.float32)


class SGD(Optimizer):
    """reference: python/paddle/optimizer/sgd.py."""

    def _update(self, p, g, accs, lr):
        return p - lr.astype(p.dtype) * g, accs


class Momentum(Optimizer):
    """reference: python/paddle/optimizer/momentum.py (supports Nesterov)."""

    _accumulator_specs = {"velocity": _zeros_like}

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _update(self, p, g, accs, lr):
        lr = lr.astype(p.dtype)
        mu = self._momentum
        v = mu * accs["velocity"] + g
        if self._use_nesterov:
            new_p = p - lr * (g + mu * v)
        else:
            new_p = p - lr * v
        return new_p, {"velocity": v}


class Adam(Optimizer):
    """reference: python/paddle/optimizer/adam.py. L2 weight_decay is coupled
    (added to the gradient by the base class)."""

    _accumulator_specs = {
        "moment1": _zeros_like,
        "moment2": _zeros_like,
        "beta1_pow": lambda v: _f32_scalar(1.0),
        "beta2_pow": lambda v: _f32_scalar(1.0),
    }

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _update(self, p, g, accs, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        b1p = accs["beta1_pow"] * b1
        b2p = accs["beta2_pow"] * b2
        m = b1 * accs["moment1"] + (1 - b1) * g
        v = b2 * accs["moment2"] + (1 - b2) * g * g
        lr_t = (lr * jnp.sqrt(1 - b2p) / (1 - b1p)).astype(p.dtype)
        new_p = p - lr_t * m / (jnp.sqrt(v) + eps)
        return new_p, {"moment1": m, "moment2": v, "beta1_pow": b1p, "beta2_pow": b2p}


class AdamW(Adam):
    """reference: python/paddle/optimizer/adamw.py — decoupled weight decay
    applied directly to the parameter, gated by apply_decay_param_fun.

    Examples:
        >>> model = paddle.nn.Linear(4, 2)
        >>> opt = paddle.optimizer.AdamW(learning_rate=1e-2,
        ...                              parameters=model.parameters())
        >>> x = paddle.to_tensor(np.ones((3, 4), "float32"))
        >>> loss = model(x).mean()
        >>> loss.backward()
        >>> opt.step()
        >>> opt.clear_grad()
    """

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name)
        self._coeff = float(weight_decay) if weight_decay is not None else 0.0
        # AdamW decay is DECOUPLED: reinterpret param-group weight_decay
        # (parsed as coupled-L2 regularizers by the base) as per-param
        # decoupled coefficients. A custom callable regularizer has no
        # decoupled interpretation — it stays a coupled grad-transform.
        self._decay_by_uid = {}
        kept = {}
        for uid, reg in self._group_wd.items():
            if isinstance(reg, _L2Decay):
                self._decay_by_uid[uid] = reg.coeff
            else:
                kept[uid] = reg
        self._group_wd = kept
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio
        self._current_param_name = None
        self._current_param_uid = None

    def _param_lr(self, param):
        self._current_param_name = param.name
        self._current_param_uid = param._uid
        base = super()._param_lr(param)
        if self._lr_ratio is not None:
            base *= float(self._lr_ratio(param))
        return base

    def _update(self, p, g, accs, lr):
        decay = self._decay_by_uid.get(self._current_param_uid, self._coeff)
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(
                self._current_param_name):
            decay = 0.0
        if decay:
            p = p * (1 - lr.astype(p.dtype) * decay)
        return super()._update(p, g, accs, lr)


class Adamax(Optimizer):
    """reference: python/paddle/optimizer/adamax.py (infinity-norm Adam)."""

    _accumulator_specs = {
        "moment": _zeros_like,
        "inf_norm": _zeros_like,
        "beta1_pow": lambda v: _f32_scalar(1.0),
    }

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _update(self, p, g, accs, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        b1p = accs["beta1_pow"] * b1
        m = b1 * accs["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * accs["inf_norm"], jnp.abs(g))
        new_p = p - (lr / (1 - b1p)).astype(p.dtype) * m / (u + eps)
        return new_p, {"moment": m, "inf_norm": u, "beta1_pow": b1p}


class Adagrad(Optimizer):
    """reference: python/paddle/optimizer/adagrad.py."""

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        init = float(initial_accumulator_value)
        self._accumulator_specs = {
            "moment": lambda v: jnp.full_like(v, init),
        }

    def _update(self, p, g, accs, lr):
        moment = accs["moment"] + g * g
        new_p = p - lr.astype(p.dtype) * g / (jnp.sqrt(moment) + self._epsilon)
        return new_p, {"moment": moment}


class Adadelta(Optimizer):
    """reference: python/paddle/optimizer/adadelta.py."""

    _accumulator_specs = {
        "avg_squared_grad": _zeros_like,
        "avg_squared_update": _zeros_like,
    }

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon, self._rho = epsilon, rho

    def _update(self, p, g, accs, lr):
        rho, eps = self._rho, self._epsilon
        sg = rho * accs["avg_squared_grad"] + (1 - rho) * g * g
        update = -jnp.sqrt((accs["avg_squared_update"] + eps) / (sg + eps)) * g
        su = rho * accs["avg_squared_update"] + (1 - rho) * update * update
        new_p = p + lr.astype(p.dtype) * update
        return new_p, {"avg_squared_grad": sg, "avg_squared_update": su}


class RMSProp(Optimizer):
    """reference: python/paddle/optimizer/rmsprop.py (centered option)."""

    _accumulator_specs = {
        "mean_square": _zeros_like,
        "mean_grad": _zeros_like,
        "momentum_acc": _zeros_like,
    }

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _update(self, p, g, accs, lr):
        rho, eps = self._rho, self._epsilon
        ms = rho * accs["mean_square"] + (1 - rho) * g * g
        mg = accs["mean_grad"]
        if self._centered:
            mg = rho * mg + (1 - rho) * g
            denom = jnp.sqrt(ms - mg * mg + eps)
        else:
            denom = jnp.sqrt(ms + eps)
        mom = self._momentum * accs["momentum_acc"] + lr.astype(p.dtype) * g / denom
        new_p = p - mom
        return new_p, {"mean_square": ms, "mean_grad": mg, "momentum_acc": mom}


class Lamb(Optimizer):
    """reference: python/paddle/optimizer/lamb.py — layerwise-adaptive Adam
    with trust-ratio scaling (used for large-batch BERT)."""

    _accumulator_specs = {
        "moment1": _zeros_like,
        "moment2": _zeros_like,
        "beta1_pow": lambda v: _f32_scalar(1.0),
        "beta2_pow": lambda v: _f32_scalar(1.0),
    }

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._lamb_weight_decay = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn
        self._current_param = None

    def _param_lr(self, param):
        self._current_param = param
        return super()._param_lr(param)

    def _update(self, p, g, accs, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        b1p = accs["beta1_pow"] * b1
        b2p = accs["beta2_pow"] * b2
        m = b1 * accs["moment1"] + (1 - b1) * g
        v = b2 * accs["moment2"] + (1 - b2) * g * g
        m_hat = m / (1 - b1p)
        v_hat = v / (1 - b2p)
        decay = self._lamb_weight_decay
        if self._exclude_fn is not None and self._exclude_fn(self._current_param):
            decay = 0.0
        r = m_hat / (jnp.sqrt(v_hat) + eps) + decay * p
        w_norm = jnp.linalg.norm(p.astype(jnp.float32))
        r_norm = jnp.linalg.norm(r.astype(jnp.float32))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = p - (lr * trust).astype(p.dtype) * r
        return new_p, {"moment1": m, "moment2": v, "beta1_pow": b1p, "beta2_pow": b2p}
