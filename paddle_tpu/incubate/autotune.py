"""paddle.incubate.autotune — runtime tuning switches.

Reference parity: ``python/paddle/incubate/autotune.py`` (``set_config``
accepting {"kernel": {...}, "layout": {...}, "dataloader": {...}}, backed
by phi's autotune cache ``paddle/phi/kernels/autotune/``). On TPU the
kernel-level search belongs to XLA's autotuner, so "kernel" maps to the
Pallas attention dispatch (block-size selection is static today;
enable=False routes attention off the Pallas kernel entirely), "layout"
is a no-op acknowledgment (XLA owns layout assignment), and "dataloader"
tunes DataLoader prefetch depth.
"""
from __future__ import annotations

import json
from typing import Optional, Union

__all__ = ["set_config", "autotune_status"]

_status = {
    "kernel": {"enable": True},
    "layout": {"enable": False},
    "dataloader": {"enable": False, "tuning_steps": 25},
}


def set_config(config: Optional[Union[dict, str]] = None) -> None:
    """Enable/disable autotune domains. ``config`` is a dict or a path to
    a JSON file; ``None`` enables everything (reference behavior)."""
    global _status
    if config is None:
        _status["kernel"]["enable"] = True
        _status["layout"]["enable"] = True
        _status["dataloader"]["enable"] = True
    else:
        if isinstance(config, str):
            with open(config) as f:
                config = json.load(f)
        if not isinstance(config, dict):
            raise TypeError("set_config expects None, a dict, or a JSON path")
        for domain in ("kernel", "layout", "dataloader"):
            if domain in config:
                if not isinstance(config[domain], dict):
                    raise TypeError(f"autotune config[{domain!r}] must be "
                                    "a dict")
                _status[domain].update(config[domain])

    from ..nn.functional import attention as _attn

    _attn.pallas_flash_enabled = bool(_status["kernel"]["enable"])


def autotune_status() -> dict:
    """Snapshot of the current autotune configuration."""
    return json.loads(json.dumps(_status))
