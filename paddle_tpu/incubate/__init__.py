"""paddle_tpu.incubate — experimental APIs (reference: python/paddle/incubate/).

Populated: ``distributed.models.moe`` (MoELayer + gates + expert-parallel
all-to-all). Fused-layer and autograd subpackages land with their
subsystems.
"""
from . import distributed  # noqa: F401

__all__ = ["distributed"]
