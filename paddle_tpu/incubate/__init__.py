"""paddle_tpu.incubate — experimental APIs (reference: python/paddle/incubate/).

Populated: ``distributed.models.moe`` (MoELayer + gates + expert-parallel
all-to-all), ``autograd`` (functional vjp/jvp/Jacobian/Hessian + primapi
forward_grad/grad).
"""
from . import autograd  # noqa: F401
from . import distributed  # noqa: F401

__all__ = ["autograd", "distributed"]
