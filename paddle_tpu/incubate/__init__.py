"""paddle_tpu.incubate — experimental APIs (reference: python/paddle/incubate/).

Populated: ``distributed.models.moe`` (MoELayer + gates + expert-parallel
all-to-all), ``autograd`` (functional vjp/jvp/Jacobian/Hessian + primapi
forward_grad/grad), ``nn`` (fused transformer layers), ``asp`` (n:m
structured sparsity), ``optimizer`` (LookAhead / ModelAverage / LBFGS),
``autotune`` (kernel/layout/dataloader tuning config).
"""
from . import asp  # noqa: F401
from ._ops import (  # noqa: F401
    graph_khop_sampler, graph_reindex, graph_sample_neighbors,
    graph_send_recv, identity_loss, segment_max, segment_mean, segment_min,
    segment_sum, softmax_mask_fuse, softmax_mask_fuse_upper_triangle,
)
from . import autograd  # noqa: F401
from . import distributed  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from .autotune import autotune_status, set_config  # noqa: F401
from .optimizer import (  # noqa: F401
    LBFGS, DistributedFusedLamb, LookAhead, ModelAverage,
)

__all__ = ["autograd", "distributed", "asp", "nn", "optimizer",
           "LookAhead", "ModelAverage", "LBFGS", "DistributedFusedLamb",
           "set_config",
           "autotune_status", "softmax_mask_fuse",
           "softmax_mask_fuse_upper_triangle", "graph_send_recv",
           "graph_khop_sampler", "graph_sample_neighbors", "graph_reindex",
           "segment_sum", "segment_mean", "segment_max", "segment_min",
           "identity_loss"]
