"""paddle.incubate.asp — automatic structured (n:m) sparsity.

Reference parity: ``python/paddle/incubate/asp/`` (asp.py:216 ``decorate``,
:302 ``prune_model``; utils.py mask generators ``get_mask_1d`` /
``get_mask_2d_greedy`` / ``get_mask_2d_best`` and checkers). The TPU
redesign keeps the same workflow — prune supported weights to an n:m
pattern, then train with an optimizer wrapper that re-applies the masks
after every ``step`` so pruned entries stay zero — with masks held as
device arrays so the re-mask fuses into the compiled train step.
"""
from .asp import (  # noqa: F401
    ASPHelper,
    decorate,
    add_supported_layer,
    prune_model,
    reset_excluded_layers,
    set_excluded_layers,
)
from .utils import (  # noqa: F401
    calculate_density,
    check_mask_1d,
    check_mask_2d,
    check_sparsity,
    create_mask,
    get_mask_1d,
    get_mask_2d_best,
    get_mask_2d_greedy,
)

__all__ = [
    "calculate_density", "check_mask_1d", "get_mask_1d", "check_mask_2d",
    "get_mask_2d_greedy", "get_mask_2d_best", "create_mask",
    "check_sparsity", "decorate", "prune_model", "set_excluded_layers",
    "add_supported_layer",
    "reset_excluded_layers", "ASPHelper",
]
