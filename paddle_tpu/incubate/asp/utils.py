"""n:m sparsity mask generation and checking (numpy, host-side).

Reference parity: ``python/paddle/incubate/asp/utils.py`` (get_mask_1d
:179, get_mask_2d_greedy, get_mask_2d_best, check_mask_1d :135,
check_mask_2d :262, calculate_density :81). Masks are computed offline
on numpy weights; training applies them as device arrays.
"""
from __future__ import annotations

import itertools

import numpy as np

__all__ = [
    "calculate_density", "check_mask_1d", "get_mask_1d", "check_mask_2d",
    "get_mask_2d_greedy", "get_mask_2d_best", "create_mask", "check_sparsity",
]


def calculate_density(x) -> float:
    """Fraction of nonzero entries."""
    x = np.asarray(x)
    return float(np.count_nonzero(x)) / x.size


def _reshape_1d(mat: np.ndarray, m: int):
    """Pad the last dim to a multiple of m and view as rows of m."""
    mat = np.asarray(mat)
    if mat.ndim != 2:
        raise ValueError("expected a 2-D matrix")
    pad = (-mat.shape[1]) % m
    if pad:
        mat = np.concatenate(
            [mat, np.zeros((mat.shape[0], pad), mat.dtype)], axis=1)
    return mat.reshape(-1, m), pad


def check_mask_1d(mat, n: int, m: int) -> bool:
    """True when every group of m consecutive elements (row-major) has at
    most ``m - n`` nonzeros... i.e. at least ``m - n`` zeros? Reference
    semantics: each m-block keeps at most n nonzeros."""
    rows, _ = _reshape_1d(np.asarray(mat), m)
    return bool(np.all((rows != 0).sum(axis=1) <= n))


def get_mask_1d(mat, n: int, m: int) -> np.ndarray:
    """Keep the n largest-|w| entries of every m-block of each row."""
    mat = np.asarray(mat)
    rows, pad = _reshape_1d(mat, m)
    order = np.argsort(-np.abs(rows), axis=1, kind="stable")[:, :n]
    mask = np.zeros_like(rows, dtype=mat.dtype)
    np.put_along_axis(mask, order, 1, axis=1)
    mask = mask.reshape(mat.shape[0], -1)
    if pad:
        mask = mask[:, :mat.shape[1]]
    return mask


def _reshape_2d(mat: np.ndarray, m: int):
    """Pad both dims to multiples of m and emit m×m tiles."""
    mat = np.asarray(mat)
    pr = (-mat.shape[0]) % m
    pc = (-mat.shape[1]) % m
    if pr or pc:
        mat = np.pad(mat, ((0, pr), (0, pc)))
    r, c = mat.shape
    tiles = (mat.reshape(r // m, m, c // m, m).transpose(0, 2, 1, 3)
             .reshape(-1, m, m))
    return tiles, (pr, pc), (r, c)


def _tiles_to_mat(tiles: np.ndarray, padded_shape, orig_shape, m: int):
    r, c = padded_shape
    mat = (tiles.reshape(r // m, c // m, m, m).transpose(0, 2, 1, 3)
           .reshape(r, c))
    return mat[:orig_shape[0], :orig_shape[1]]


def check_mask_2d(mat, n: int, m: int) -> bool:
    """True when every m×m tile has at most n nonzeros per row AND per
    column (reference: check_mask_2d)."""
    tiles, _, _ = _reshape_2d(np.asarray(mat), m)
    nz = tiles != 0
    return bool(np.all(nz.sum(axis=2) <= n) and np.all(nz.sum(axis=1) <= n))


def get_mask_2d_greedy(mat, n: int, m: int) -> np.ndarray:
    """Greedy per-tile mask: walk entries in decreasing |w|, keep while the
    entry's row and column budgets (n each) allow."""
    mat = np.asarray(mat)
    tiles, _, padded = _reshape_2d(mat, m)
    masks = np.zeros_like(tiles)
    for t in range(tiles.shape[0]):
        tile = np.abs(tiles[t])
        order = np.dstack(np.unravel_index(
            np.argsort(-tile, axis=None, kind="stable"), (m, m)))[0]
        rows = np.zeros(m, np.int64)
        cols = np.zeros(m, np.int64)
        for i, j in order:
            if rows[i] < n and cols[j] < n:
                masks[t, i, j] = 1
                rows[i] += 1
                cols[j] += 1
    return _tiles_to_mat(masks, padded, mat.shape, m).astype(mat.dtype)


_PATTERNS_CACHE: dict = {}


def _valid_2d_patterns(n: int, m: int) -> np.ndarray:
    """All m×m 0/1 matrices with exactly n ones per row and per column
    (built as permutations of row patterns; reference caches these too)."""
    key = (n, m)
    if key in _PATTERNS_CACHE:
        return _PATTERNS_CACHE[key]
    row_patterns = [p for p in itertools.product([0, 1], repeat=m)
                    if sum(p) == n]
    out = []
    for combo in itertools.product(range(len(row_patterns)), repeat=m):
        mat = np.array([row_patterns[i] for i in combo], np.float64)
        if np.all(mat.sum(axis=0) == n):
            out.append(mat)
    pats = np.stack(out)
    _PATTERNS_CACHE[key] = pats
    return pats


def get_mask_2d_best(mat, n: int, m: int) -> np.ndarray:
    """Optimal per-tile mask: the valid n:m-per-row-and-column pattern with
    the largest retained |w| mass (exhaustive over valid patterns)."""
    mat = np.asarray(mat)
    pats = _valid_2d_patterns(n, m)  # [P, m, m]
    tiles, _, padded = _reshape_2d(mat, m)
    scores = np.einsum("pij,tij->tp", pats, np.abs(tiles).astype(np.float64))
    best = np.argmax(scores, axis=1)
    masks = pats[best]
    return _tiles_to_mat(masks, padded, mat.shape, m).astype(mat.dtype)


_MASK_FNS = {
    "mask_1d": get_mask_1d,
    "mask_2d_greedy": get_mask_2d_greedy,
    "mask_2d_best": get_mask_2d_best,
}
_CHECK_FNS = {
    "mask_1d": check_mask_1d,
    "mask_2d_greedy": check_mask_2d,
    "mask_2d_best": check_mask_2d,
}


def create_mask(tensor, func_name: str = "mask_1d", n: int = 2,
                m: int = 4) -> np.ndarray:
    """Dispatch over the mask algorithms, handling conv (4-D) weights by
    flattening to 2-D the way the reference does (OIHW → [O, I*H*W])."""
    t = np.asarray(tensor)
    shape = t.shape
    if t.ndim == 1:
        mat = t.reshape(1, -1)
    elif t.ndim == 2:
        mat = t
    elif t.ndim == 4:
        mat = t.reshape(shape[0], -1)
    else:
        raise ValueError(f"unsupported weight rank {t.ndim} for ASP")
    fn = _MASK_FNS.get(func_name)
    if fn is None:
        raise ValueError(f"unknown mask algorithm {func_name!r}; choose "
                         f"from {sorted(_MASK_FNS)}")
    return fn(mat, n, m).reshape(shape)


def check_sparsity(tensor, func_name: str = "mask_1d", n: int = 2,
                   m: int = 4) -> bool:
    t = np.asarray(tensor)
    mat = t.reshape(1, -1) if t.ndim == 1 else (
        t.reshape(t.shape[0], -1) if t.ndim != 2 else t)
    return _CHECK_FNS[func_name](mat, n, m)
