"""ASP workflow: prune supported layers, keep sparsity through training.

Reference parity: ``python/paddle/incubate/asp/asp.py`` (``decorate``
:216 wraps the optimizer so masks re-apply after each step — the
reference appends masking ops to the optimizer program; here the mask
multiply happens right after ``step()``, in jnp so it compiles into the
train step under jit; ``prune_model`` :302 computes masks with the
chosen algorithm; excluded-layer registry :40/:127).

Supported layers: Linear (2-D weights, pruned along the input dim) and
Conv2D (4-D OIHW weights flattened per output channel), matching the
reference's supported_layer_list.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from .utils import check_sparsity, create_mask

__all__ = ["decorate", "prune_model", "set_excluded_layers",
           "reset_excluded_layers", "ASPHelper"]


class ASPHelper:
    """Process-wide registry of masks and exclusions (reference keeps the
    same singletons keyed by program; eager mode needs just one)."""

    _excluded_param_names: set = set()
    # param uid -> (param, mask jnp array)
    _masks: Dict[int, tuple] = {}

    MASK_ALGO_MAP = {
        "mask_1d": "mask_1d",
        "mask_2d_greedy": "mask_2d_greedy",
        "mask_2d_best": "mask_2d_best",
    }

    @classmethod
    def _is_supported_param(cls, name: str, value) -> bool:
        if name in cls._excluded_param_names:
            return False
        # weights only (>=2-D); biases/norms stay dense
        return value.ndim in (2, 4)

    @classmethod
    def prune_model(cls, model, n: int = 2, m: int = 4,
                    mask_algo: str = "mask_1d",
                    with_mask: bool = True) -> Dict[str, np.ndarray]:
        if mask_algo not in cls.MASK_ALGO_MAP:
            raise ValueError(f"mask_algo must be one of "
                             f"{sorted(cls.MASK_ALGO_MAP)}, got {mask_algo!r}")
        masks: Dict[str, np.ndarray] = {}
        for name, p in model.named_parameters():
            v = np.asarray(p._value)
            if not cls._is_supported_param(name, v):
                continue
            # Prune along the reduction dim: Linear weights here are
            # [in, out] (y = x @ W), so mask groups run down the input
            # axis — transpose, mask rows, transpose back.
            if v.ndim == 2:
                mask = create_mask(v.T, cls.MASK_ALGO_MAP[mask_algo],
                                   n, m).T
            else:
                mask = create_mask(v, cls.MASK_ALGO_MAP[mask_algo], n, m)
            p._set_value(jnp.asarray(v * mask, p._value.dtype))
            masks[name] = mask
            if with_mask:
                cls._masks[p._uid] = (p, jnp.asarray(mask, p._value.dtype))
        return masks

    @classmethod
    def reapply_masks(cls) -> None:
        for p, mask in cls._masks.values():
            p._set_value(p._value * mask)

    @classmethod
    def check_model_sparsity(cls, model, n: int = 2, m: int = 4,
                             func_name: str = "mask_1d") -> bool:
        ok = True
        for name, p in model.named_parameters():
            if p._uid in cls._masks:
                v = np.asarray(p._value)
                ok &= check_sparsity(v.T if v.ndim == 2 else v,
                                     func_name, n, m)
        return bool(ok)


def set_excluded_layers(param_names: List[str], main_program=None) -> None:
    """Exclude parameters (by name) from pruning (reference: asp.py:40)."""
    ASPHelper._excluded_param_names.update(param_names)


def reset_excluded_layers(main_program=None) -> None:
    """Clear the exclusion list (reference: asp.py:127)."""
    ASPHelper._excluded_param_names.clear()


def prune_model(model, n: int = 2, m: int = 4, mask_algo: str = "mask_1d",
                with_mask: bool = True):
    """Prune ``model``'s supported weights to the n:m pattern.

    When ``with_mask`` is True the masks are retained so a decorated
    optimizer keeps the pattern through training (reference: asp.py:302).
    """
    return ASPHelper.prune_model(model, n, m, mask_algo, with_mask)


class OptimizerWithSparsityGuarantee:
    """Re-applies ASP masks after every ``step`` (reference: asp.py:548 —
    the decorated optimizer's masking ops)."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def step(self, *args, **kwargs):
        out = self._optimizer.step(*args, **kwargs)
        ASPHelper.reapply_masks()
        return out

    def minimize(self, loss, *args, **kwargs):
        out = self._optimizer.minimize(loss, *args, **kwargs)
        ASPHelper.reapply_masks()
        return out

    def __getattr__(self, item):
        return getattr(self._optimizer, item)


def decorate(optimizer) -> OptimizerWithSparsityGuarantee:
    """Wrap ``optimizer`` so pruned weights stay pruned (reference:
    asp.py:216)."""
    return OptimizerWithSparsityGuarantee(optimizer)
