"""ASP workflow: prune supported layers, keep sparsity through training.

Reference parity: ``python/paddle/incubate/asp/asp.py`` (``decorate``
:216 wraps the optimizer so masks re-apply after each step — the
reference appends masking ops to the optimizer program; here the mask
multiply happens right after ``step()``, in jnp so it compiles into the
train step under jit; ``prune_model`` :302 computes masks with the
chosen algorithm; excluded-layer registry :40/:127).

Supported layers: Linear (2-D weights, pruned along the input dim) and
Conv2D (4-D OIHW weights flattened per output channel), matching the
reference's supported_layer_list.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from .utils import check_sparsity, create_mask

__all__ = ["decorate", "prune_model", "set_excluded_layers",
           "reset_excluded_layers", "add_supported_layer", "ASPHelper"]


class ASPHelper:
    """Process-wide registry of masks and exclusions (reference keeps the
    same singletons keyed by program; eager mode needs just one)."""

    _excluded_param_names: set = set()
    # param uid -> (param, mask jnp array)
    _masks: Dict[int, tuple] = {}
    _custom_pruning: Dict[str, object] = {}

    MASK_ALGO_MAP = {
        "mask_1d": "mask_1d",
        "mask_2d_greedy": "mask_2d_greedy",
        "mask_2d_best": "mask_2d_best",
    }

    @classmethod
    def _is_supported_param(cls, name: str, value) -> bool:
        if name in cls._excluded_param_names:
            return False
        # weights only (>=2-D); biases/norms stay dense
        return value.ndim in (2, 4)

    @classmethod
    def prune_model(cls, model, n: int = 2, m: int = 4,
                    mask_algo: str = "mask_1d",
                    with_mask: bool = True) -> Dict[str, np.ndarray]:
        if mask_algo not in cls.MASK_ALGO_MAP:
            raise ValueError(f"mask_algo must be one of "
                             f"{sorted(cls.MASK_ALGO_MAP)}, got {mask_algo!r}")
        masks: Dict[str, np.ndarray] = {}
        for name, p in model.named_parameters():
            v = np.asarray(p._value)
            if name in cls._excluded_param_names:  # exclusion always wins
                continue
            # an add_supported_layer registration makes the param
            # prunable REGARDLESS of the default ndim filter (the
            # reference's registered layers bypass supported_layer_list
            # checks); match BEFORE the filter so custom shapes reach
            # their pruning function. False = not registered; None =
            # registered with the default pruning.
            registered = next(
                (fn for key, fn in cls._custom_pruning.items()
                 if key in name), False)
            if registered is False and not cls._is_supported_param(name, v):
                continue
            # Prune along the reduction dim: Linear weights here are
            # [in, out] (y = x @ W), so mask groups run down the input
            # axis — transpose, mask rows, transpose back.
            if callable(registered):
                # user pruning function (add_supported_layer): receives
                # (weight, m, n, func_name, param_name), returns
                # (pruned_weight, mask) like the reference's
                pruned, mask = registered(v, m, n, mask_algo, name)
                v = np.asarray(pruned)
                mask = np.asarray(mask)
            elif v.ndim == 2:
                mask = create_mask(v.T, cls.MASK_ALGO_MAP[mask_algo],
                                   n, m).T
            elif v.ndim >= 3:
                mask = create_mask(v, cls.MASK_ALGO_MAP[mask_algo], n, m)
            else:
                # registered-with-None 1-D param: default n:m over a
                # last-dim view (the reference's _default_pruning path)
                mask = create_mask(v.reshape(1, -1),
                                   cls.MASK_ALGO_MAP[mask_algo],
                                   n, m).reshape(v.shape)
            p._set_value(jnp.asarray(v * mask, p._value.dtype))
            masks[name] = mask
            if with_mask:
                cls._masks[p._uid] = (p, jnp.asarray(mask, p._value.dtype))
        return masks

    @classmethod
    def reapply_masks(cls) -> None:
        for p, mask in cls._masks.values():
            p._set_value(p._value * mask)

    @classmethod
    def check_model_sparsity(cls, model, n: int = 2, m: int = 4,
                             func_name: str = "mask_1d") -> bool:
        ok = True
        for name, p in model.named_parameters():
            if p._uid in cls._masks:
                v = np.asarray(p._value)
                ok &= check_sparsity(v.T if v.ndim == 2 else v,
                                     func_name, n, m)
        return bool(ok)


def set_excluded_layers(param_names: List[str], main_program=None) -> None:
    """Exclude parameters (by name) from pruning (reference: asp.py:40)."""
    ASPHelper._excluded_param_names.update(param_names)


def reset_excluded_layers(main_program=None) -> None:
    """Clear the exclusion list (reference: asp.py:127)."""
    ASPHelper._excluded_param_names.clear()


def add_supported_layer(layer, pruning_func=None) -> None:
    """Register a layer (by name or Layer subclass) as prunable, with an
    optional custom pruning function (reference:
    incubate/asp/supported_layer_list.py:85). ``pruning_func`` receives
    (weight, m, n, func_name, param_name) and returns
    (pruned_weight, mask); with None the default n:m mask applies to
    parameters whose name contains the registered name."""
    if isinstance(layer, str):
        name = layer
    elif isinstance(layer, type):
        name = layer.__name__.lower()
    else:
        name = type(layer).__name__.lower()
    ASPHelper._custom_pruning[name] = pruning_func


def prune_model(model, n: int = 2, m: int = 4, mask_algo: str = "mask_1d",
                with_mask: bool = True):
    """Prune ``model``'s supported weights to the n:m pattern.

    When ``with_mask`` is True the masks are retained so a decorated
    optimizer keeps the pattern through training (reference: asp.py:302).
    """
    return ASPHelper.prune_model(model, n, m, mask_algo, with_mask)


class OptimizerWithSparsityGuarantee:
    """Re-applies ASP masks after every ``step`` (reference: asp.py:548 —
    the decorated optimizer's masking ops)."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def step(self, *args, **kwargs):
        out = self._optimizer.step(*args, **kwargs)
        ASPHelper.reapply_masks()
        return out

    def minimize(self, loss, *args, **kwargs):
        out = self._optimizer.minimize(loss, *args, **kwargs)
        ASPHelper.reapply_masks()
        return out

    def __getattr__(self, item):
        return getattr(self._optimizer, item)


def decorate(optimizer) -> OptimizerWithSparsityGuarantee:
    """Wrap ``optimizer`` so pruned weights stay pruned (reference:
    asp.py:216)."""
    return OptimizerWithSparsityGuarantee(optimizer)
