"""L-BFGS full-batch optimizer with optional strong-Wolfe line search.

Reference parity: ``python/paddle/incubate/optimizer/lbfgs.py`` +
``line_search_dygraph.py`` (``step(closure)`` quasi-Newton loop with
``history_size`` curvature pairs and two-loop recursion). Host-driven:
the closure re-evaluates loss+grads eagerly; direction/line-search math
runs on flattened fp32 vectors.
"""
from __future__ import annotations

from typing import Callable, List, Optional

import jax.numpy as jnp
import numpy as np

from ...autograd import no_grad
from ...optimizer.optimizer import Optimizer

__all__ = ["LBFGS"]


class LBFGS(Optimizer):
    def __init__(self, learning_rate: float = 1.0, max_iter: int = 20,
                 max_eval: Optional[int] = None, tolerance_grad: float = 1e-7,
                 tolerance_change: float = 1e-9, history_size: int = 100,
                 line_search_fn: Optional[str] = None, parameters=None,
                 weight_decay=None, grad_clip=None, name: str = None):
        if max_eval is None:
            max_eval = max_iter * 5 // 4
        super().__init__(learning_rate=learning_rate, parameters=parameters,
                         weight_decay=weight_decay, grad_clip=grad_clip,
                         name=name)
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("line_search_fn must be None or 'strong_wolfe'")
        self.max_iter = max_iter
        self.max_eval = max_eval
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        self._s: List[np.ndarray] = []  # param deltas
        self._y: List[np.ndarray] = []  # grad deltas
        self._prev_flat_grad: Optional[np.ndarray] = None

    # -- flat view over the parameter list ----------------------------------
    def _params(self):
        return [p for p in (self._parameter_list or [])
                if not p.stop_gradient]

    def _gather_flat_grad(self) -> np.ndarray:
        out = []
        for p in self._params():
            g = p.grad
            gv = (np.zeros(p._value.size, np.float64) if g is None
                  else np.asarray(g._value, np.float64).ravel())
            out.append(gv)
        return np.concatenate(out)

    def _gather_flat_params(self) -> np.ndarray:
        return np.concatenate([np.asarray(p._value, np.float64).ravel()
                               for p in self._params()])

    def _set_flat_params(self, flat: np.ndarray) -> None:
        i = 0
        for p in self._params():
            n = int(np.prod(p._value.shape)) if p._value.shape else 1
            chunk = flat[i:i + n].reshape(p._value.shape)
            p._set_value(jnp.asarray(chunk, p._value.dtype))
            i += n

    def _directional_evaluate(self, closure, x: np.ndarray, t: float,
                              d: np.ndarray):
        self._set_flat_params(x + t * d)
        loss = float(closure().numpy())
        g = self._gather_flat_grad()
        return loss, g

    # -- two-loop recursion --------------------------------------------------
    def _direction(self, g: np.ndarray) -> np.ndarray:
        if not self._s:
            return -g
        q = g.copy()
        alphas = []
        rhos = [1.0 / max(float(y @ s), 1e-10)
                for s, y in zip(self._s, self._y)]
        for s, y, rho in zip(reversed(self._s), reversed(self._y),
                             reversed(rhos)):
            a = rho * (s @ q)
            alphas.append(a)
            q -= a * y
        y_last, s_last = self._y[-1], self._s[-1]
        gamma = float(s_last @ y_last) / max(float(y_last @ y_last), 1e-10)
        r = gamma * q
        for (s, y, rho), a in zip(zip(self._s, self._y, rhos),
                                  reversed(alphas)):
            b = rho * (y @ r)
            r += (a - b) * s
        return -r

    # -- strong-Wolfe line search (cubic interpolation, torch-style) --------
    def _strong_wolfe(self, closure, x, t, d, f, g, gtd,
                      c1=1e-4, c2=0.9, max_ls=25):
        d_norm = np.abs(d).max()
        g = g.copy()
        f_prev, g_prev, t_prev = f, g, 0.0
        done = False
        ls_iter = 0
        f_new, g_new = self._directional_evaluate(closure, x, t, d)
        gtd_new = float(g_new @ d)
        # bracket phase
        while ls_iter < max_ls:
            if f_new > (f + c1 * t * gtd) or (ls_iter > 1 and f_new >= f_prev):
                bracket = [t_prev, t]
                bracket_f = [f_prev, f_new]
                bracket_g = [g_prev, g_new.copy()]
                break
            if abs(gtd_new) <= -c2 * gtd:
                bracket = [t, t]
                bracket_f = [f_new, f_new]
                bracket_g = [g_new, g_new]
                done = True
                break
            if gtd_new >= 0:
                bracket = [t_prev, t]
                bracket_f = [f_prev, f_new]
                bracket_g = [g_prev, g_new.copy()]
                break
            min_step = t + 0.01 * (t - t_prev)
            max_step = t * 10
            tmp = t
            t = min(max(2 * t, min_step), max_step)
            t_prev = tmp
            f_prev, g_prev = f_new, g_new.copy()
            f_new, g_new = self._directional_evaluate(closure, x, t, d)
            gtd_new = float(g_new @ d)
            ls_iter += 1
        else:
            bracket = [0.0, t]
            bracket_f = [f, f_new]
            bracket_g = [g, g_new]

        # zoom phase: bisection (robust; cubic adds little on our scales)
        while not done and ls_iter < max_ls:
            lo, hi = (0, 1) if bracket_f[0] <= bracket_f[1] else (1, 0)
            if abs(bracket[1] - bracket[0]) * d_norm < self.tolerance_change:
                break
            t = 0.5 * (bracket[0] + bracket[1])
            f_new, g_new = self._directional_evaluate(closure, x, t, d)
            gtd_new = float(g_new @ d)
            ls_iter += 1
            if f_new > (f + c1 * t * gtd) or f_new >= bracket_f[lo]:
                bracket[hi] = t
                bracket_f[hi] = f_new
                bracket_g[hi] = g_new.copy()
            else:
                if abs(gtd_new) <= -c2 * gtd:
                    done = True
                elif gtd_new * (bracket[hi] - bracket[lo]) >= 0:
                    bracket[hi] = bracket[lo]
                    bracket_f[hi] = bracket_f[lo]
                    bracket_g[hi] = bracket_g[lo]
                bracket[lo] = t
                bracket_f[lo] = f_new
                bracket_g[lo] = g_new.copy()
        lo = 0 if bracket_f[0] <= bracket_f[1] else 1
        return bracket_f[lo], bracket_g[lo], bracket[lo]

    @no_grad()
    def step(self, closure: Callable):
        """One L-BFGS outer step. ``closure`` must zero grads, compute the
        loss, call backward, and return the loss Tensor."""
        with np.errstate(all="ignore"):
            return self._step_impl(closure)

    def _step_impl(self, closure):
        import paddle_tpu as _paddle  # lazy: avoid import cycle

        def eval_closure():
            self.clear_grad()
            with _paddle.autograd.enable_grad():
                loss = closure()
            return loss

        loss = eval_closure()
        orig_loss = loss
        f = float(loss.numpy())
        g = self._gather_flat_grad()
        if np.abs(g).max() <= self.tolerance_grad:
            return orig_loss
        n_eval = 1

        for _ in range(self.max_iter):
            d = self._direction(g)
            gtd = float(g @ d)
            if gtd > -self.tolerance_change:
                break
            t = (min(1.0, 1.0 / max(np.abs(g).sum(), 1e-10)) * self.get_lr()
                 if not self._s else self.get_lr())
            x = self._gather_flat_params()
            if self.line_search_fn == "strong_wolfe":
                f_new, g_new, t = self._strong_wolfe(
                    eval_closure, x, t, d, f, g, gtd)
                self._set_flat_params(x + t * d)
                n_eval += 1
            else:
                self._set_flat_params(x + t * d)
                loss_new = eval_closure()
                f_new = float(loss_new.numpy())
                g_new = self._gather_flat_grad()
                n_eval += 1
            s = (self._gather_flat_params() - x)
            y = g_new - g
            if float(y @ s) > 1e-10:
                self._s.append(s)
                self._y.append(y)
                if len(self._s) > self.history_size:
                    self._s.pop(0)
                    self._y.pop(0)
            delta_f = abs(f_new - f)
            f, g = f_new, g_new
            if np.abs(g).max() <= self.tolerance_grad:
                break
            if delta_f < self.tolerance_change:
                break
            if n_eval >= self.max_eval:
                break
        return orig_loss
