"""ModelAverage — evaluate with a sliding-window average of parameters.

Reference parity: ``python/paddle/incubate/optimizer/modelaverage.py:27``
(the ``average_accumulates`` op's window bookkeeping: cumulative sums
num_accumulates / old_num_accumulates and sum_1 / sum_2 / sum_3, window
restart when ``max_average_window`` is exceeded). ``step()`` accumulates
the current parameter values; ``apply()`` swaps in the window average
(a context manager that restores on exit unless ``need_restore=False``);
``restore()`` puts the trained weights back.
"""
from __future__ import annotations

from contextlib import contextmanager

import jax.numpy as jnp

from ...autograd import no_grad
from ...optimizer.optimizer import Optimizer

__all__ = ["ModelAverage"]


class ModelAverage(Optimizer):
    def __init__(self, average_window_rate, parameters=None,
                 min_average_window: int = 10000,
                 max_average_window: int = 10000, name: str = None):
        super().__init__(learning_rate=0.0, parameters=parameters, name=name)
        self.average_window = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        # per-param window state, mirroring average_accumulates:
        #  sum_1: sum inside the live window
        #  sum_2: sum of the previous (restarted) window
        #  sum_3: sum of windows before that
        self._state: dict = {}
        self._backup: dict = {}

    def _param_state(self, p):
        st = self._state.get(p._uid)
        if st is None:
            z = jnp.zeros_like(p._value)
            st = {"sum_1": z, "sum_2": z, "sum_3": z,
                  "num_accumulates": 0, "old_num_accumulates": 0,
                  "num_updates": 0}
            self._state[p._uid] = st
        return st

    @no_grad()
    def step(self):
        """Accumulate the current parameter values into the window."""
        for p in self._parameter_list or []:
            if p.stop_gradient:
                continue
            st = self._param_state(p)
            st["sum_1"] = st["sum_1"] + p._value
            st["num_accumulates"] += 1
            st["num_updates"] += 1
            window = max(
                self.min_average_window,
                min(self.max_average_window,
                    int(self.average_window * st["num_updates"])))
            if st["num_accumulates"] >= window:
                # restart the live window: demote sums one level
                st["sum_3"] = st["sum_2"]
                st["sum_2"] = st["sum_1"]
                st["sum_1"] = jnp.zeros_like(p._value)
                st["old_num_accumulates"] = (st["num_accumulates"]
                                             + st["old_num_accumulates"])
                st["num_accumulates"] = 0

    def _average_value(self, p):
        st = self._param_state(p)
        total = st["num_accumulates"] + st["old_num_accumulates"]
        if total == 0:
            return p._value
        s = st["sum_1"] + st["sum_2"] + st["sum_3"]
        return (s / total).astype(p._value.dtype)

    @contextmanager
    def apply(self, executor=None, need_restore: bool = True):
        """Swap the window-averaged weights in (and back out on exit)."""
        for p in self._parameter_list or []:
            if p.stop_gradient:
                continue
            self._backup[p._uid] = p._value
            p._set_value(self._average_value(p))
        try:
            yield
        finally:
            if need_restore:
                self.restore(executor)

    @no_grad()
    def restore(self, executor=None):
        """Restore the pre-``apply`` weights."""
        for p in self._parameter_list or []:
            if p._uid in self._backup:
                p._set_value(self._backup.pop(p._uid))

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self.step()
        return [], []
