"""paddle.incubate.optimizer.functional (reference:
python/paddle/incubate/optimizer/functional/__init__.py — __all__ =
['minimize_bfgs', 'minimize_lbfgs']).

TPU redesign: the reference implements BFGS/L-BFGS as static-graph
while_loop programs (functional/bfgs.py, lbfgs.py); here the solver is
jax.scipy.optimize's compiled BFGS/L-BFGS (the same strong-Wolfe line
search family) and the result is re-shaped to the reference's return
tuple: (is_converge, num_func_calls, position, objective_value,
objective_gradient).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....ops._apply import ensure_tensor
from ....tensor import Tensor

__all__ = ["minimize_bfgs", "minimize_lbfgs"]


def _run(method, objective_func, initial_position, max_iters, tolerance_grad,
         options=None):
    x0 = ensure_tensor(initial_position)._value.astype(jnp.float32)

    def f(x):
        out = objective_func(Tensor(x))
        return (out._value if isinstance(out, Tensor)
                else jnp.asarray(out)).reshape(())

    from jax.scipy.optimize import minimize as _minimize

    opts = {"maxiter": int(max_iters), "gtol": float(tolerance_grad)}
    opts.update(options or {})
    res = _minimize(f, x0, method=method, options=opts)
    grad = jax.grad(f)(res.x)
    # is_converge per the reference's contract: the gradient met the
    # tolerance (jax's res.success additionally requires line-search
    # bookkeeping that is over-strict at f32 precision)
    converged = jnp.logical_or(
        jnp.asarray(res.success),
        jnp.max(jnp.abs(grad)) <= jnp.asarray(tolerance_grad))
    return (Tensor(converged),
            Tensor(jnp.asarray(res.nfev, jnp.int32)),
            Tensor(res.x),
            Tensor(jnp.asarray(res.fun)),
            Tensor(grad))


def minimize_bfgs(objective_func, initial_position, max_iters=50,
                  tolerance_grad=1e-7, tolerance_change=1e-9,
                  initial_inverse_hessian_estimate=None,
                  line_search_fn="strong_wolfe", max_line_search_iters=50,
                  initial_step_length=1.0, dtype="float32", name=None):
    """reference: incubate/optimizer/functional/bfgs.py minimize_bfgs.
    Returns (is_converge, num_func_calls, position, objective_value,
    objective_gradient)."""
    if line_search_fn != "strong_wolfe":
        raise NotImplementedError("only strong_wolfe line search is "
                                  "supported")
    return _run("BFGS", objective_func, initial_position, max_iters,
                tolerance_grad)


def minimize_lbfgs(objective_func, initial_position, history_size=100,
                   max_iters=50, tolerance_grad=1e-8, tolerance_change=1e-8,
                   initial_inverse_hessian_estimate=None,
                   line_search_fn="strong_wolfe", max_line_search_iters=50,
                   initial_step_length=1.0, dtype="float32", name=None):
    """reference: incubate/optimizer/functional/lbfgs.py minimize_lbfgs.
    Same return tuple as minimize_bfgs; bounded-memory two-loop
    recursion with `history_size` curvature pairs."""
    if line_search_fn != "strong_wolfe":
        raise NotImplementedError("only strong_wolfe line search is "
                                  "supported")
    return _run("l-bfgs-experimental-do-not-rely-on-this", objective_func,
                initial_position, max_iters, tolerance_grad,
                options={"maxcor": int(history_size)})
