"""DistributedFusedLamb — large-batch LAMB for data-parallel training.

Reference parity: python/paddle/incubate/optimizer/distributed_fused_lamb.py
:95 — a CUDA multi-tensor LAMB whose knobs (alignment, hierarchical
allreduce, master-param norms) exist to hand-manage flat buffers and NCCL
stages.

TPU-native collapse: inside a jitted train step XLA already fuses the
per-parameter LAMB updates and GSPMD inserts the gradient allreduce, so the
math is exactly optimizer.Lamb plus the distributed conveniences the
reference adds: optional 1/world grad scaling and gradient accumulation.
The buffer-management knobs are accepted for signature parity and
documented as no-ops (XLA owns layout/fusion).
"""
from __future__ import annotations

import jax.numpy as jnp

from ...autograd import no_grad
from ...optimizer.optimizers import Lamb
from ...tensor import Tensor

__all__ = ["DistributedFusedLamb"]


class DistributedFusedLamb(Lamb):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 clip_after_allreduce=True, is_grad_scaled_by_nranks=True,
                 alignment=128, use_master_param_norm=True,
                 gradient_accumulation_steps=1, use_master_acc_grad=True,
                 nproc_per_node=None, use_hierarchical_allreduce=False,
                 name=None):
        super().__init__(learning_rate, lamb_weight_decay, beta1, beta2,
                         epsilon, parameters, grad_clip,
                         exclude_from_weight_decay_fn, name)
        # alignment / hierarchical-allreduce / master-param-norm knobs are
        # buffer-layout and NCCL staging controls with no TPU counterpart:
        # XLA lays out and fuses the flat update, GSPMD plans the collective
        self._is_grad_scaled_by_nranks = bool(is_grad_scaled_by_nranks)
        self._acc_steps = max(int(gradient_accumulation_steps), 1)
        self._acc_count = 0
        self._acc_grads: dict = {}  # param uid -> accumulated grad array

    def _world_size(self) -> int:
        from ...distributed import topology

        mesh = topology.get_mesh()
        return int(mesh.size) if mesh is not None else 1

    def step(self):
        """Gradient accumulation lives in INTERNAL buffers (reference:
        the fused kernel's acc stage) so the canonical
        ``backward(); step(); clear_grad()`` loop stays correct — the user's
        clear_grad cannot wipe pending microbatch grads, and the applied
        update uses the MEAN over acc_steps."""
        self._acc_count += 1
        if self._acc_steps > 1:
            with no_grad():
                for p in (self._parameter_list or []):
                    if p.grad is None:
                        continue
                    prev = self._acc_grads.get(p._uid)
                    g = p.grad._value
                    self._acc_grads[p._uid] = g if prev is None else prev + g
            if self._acc_count % self._acc_steps:
                return
            scale = jnp.float32(1.0 / self._acc_steps)
            with no_grad():
                for p in (self._parameter_list or []):
                    acc = self._acc_grads.get(p._uid)
                    if acc is not None:
                        p.grad = Tensor(acc * scale.astype(acc.dtype))
            self._acc_grads.clear()
        world = self._world_size()
        if not self._is_grad_scaled_by_nranks and world > 1:
            # reference contract: grads arrive SUMMED across ranks; scale
            # to the mean before the update
            with no_grad():
                for p in (self._parameter_list or []):
                    if p.grad is not None:
                        p.grad = Tensor(p.grad._value
                                        / jnp.asarray(world,
                                                      p.grad._value.dtype))
        super().step()
