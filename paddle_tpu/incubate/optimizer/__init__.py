"""paddle.incubate.optimizer — LookAhead, ModelAverage, LBFGS.

Reference parity: ``python/paddle/incubate/optimizer/`` (lookahead.py:25,
modelaverage.py:27, lbfgs.py + line_search_dygraph.py). All three are
host-driven wrappers over the eager tape; the per-step math is jnp, so
the slow/fast interpolation and window averaging stay on-device.
"""
from .lookahead import LookAhead  # noqa: F401
from .modelaverage import ModelAverage  # noqa: F401
from .lbfgs import LBFGS  # noqa: F401
from .fused_lamb import DistributedFusedLamb  # noqa: F401

__all__ = ["LookAhead", "ModelAverage", "LBFGS",
           "DistributedFusedLamb"]
