"""LookAhead optimizer ("k steps forward, 1 step back", Zhang et al. 2019).

Reference parity: ``python/paddle/incubate/optimizer/lookahead.py:25`` —
wraps an inner optimizer; every ``k`` inner steps the slow weights move
``alpha`` of the way toward the fast weights and the fast weights reset
to the slow ones.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...autograd import no_grad
from ...optimizer.optimizer import Optimizer

__all__ = ["LookAhead"]


class LookAhead(Optimizer):
    def __init__(self, inner_optimizer: Optimizer, alpha: float = 0.5,
                 k: int = 5, name: str = None):
        if inner_optimizer is None:
            raise ValueError("inner optimizer cannot be None")
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha must be within [0, 1]")
        if not (isinstance(k, int) and k > 0):
            raise ValueError("k must be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        super().__init__(
            learning_rate=self.alpha,
            parameters=inner_optimizer._parameter_list,
            name=name)
        self._slow: dict = {}  # param uid -> slow weights (jax array)
        self._k_step = 0

    @no_grad()
    def step(self):
        self.inner_optimizer.step()
        self._k_step += 1
        if self._k_step % self.k != 0:
            return
        for p in self._parameter_list or []:
            if p.stop_gradient:
                continue
            slow = self._slow.get(p._uid)
            if slow is None:
                # first sync point: slow weights start at the fast weights
                # as they were *before* this round began is unobservable
                # here, so reference-style: initialize from current value
                slow = p._value
            new_slow = slow + self.alpha * (p._value - slow)
            self._slow[p._uid] = new_slow
            p._set_value(jnp.asarray(new_slow, p._value.dtype))

    def clear_grad(self, set_to_zero: bool = False):
        self.inner_optimizer.clear_grad(set_to_zero)

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return [], []

    def state_dict(self) -> dict:
        sd = {"inner": self.inner_optimizer.state_dict(),
              "k_step": self._k_step,
              "slow": {uid: v for uid, v in self._slow.items()}}
        return sd

    def set_state_dict(self, state_dict: dict):
        self.inner_optimizer.set_state_dict(state_dict["inner"])
        self._k_step = int(state_dict.get("k_step", 0))
        self._slow = {uid: jnp.asarray(v)
                      for uid, v in state_dict.get("slow", {}).items()}
