"""MoE / expert parallelism (reference:
python/paddle/incubate/distributed/models/moe/)."""
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate  # noqa: F401
from .grad_clip import ClipGradForMOEByGlobalNorm  # noqa: F401
from .moe_layer import ExpertLayer, MoELayer  # noqa: F401
from .utils import _random_routing, count_by_gate, limit_by_capacity  # noqa: F401

__all__ = [
    "MoELayer", "ExpertLayer", "BaseGate", "NaiveGate", "GShardGate",
    "SwitchGate", "ClipGradForMOEByGlobalNorm", "limit_by_capacity",
    "count_by_gate",
]
