"""reference: incubate/distributed/models/moe/gate/base_gate.py."""
from __future__ import annotations

from ......nn.layer_base import Layer


class BaseGate(Layer):
    def __init__(self, num_expert: int, world_size: int):
        super().__init__()
        self.world_size = world_size
        self.num_expert = num_expert
        self.tot_expert = world_size * num_expert
        self.loss = None

    def forward(self, x):
        raise NotImplementedError("Base gate cannot be directly used for fwd")

    def set_loss(self, loss):
        self.loss = loss

    def get_loss(self, clear: bool = True):
        loss = self.loss
        if clear:
            self.loss = None
        return loss

    @property
    def has_loss(self) -> bool:
        return self.loss is not None
