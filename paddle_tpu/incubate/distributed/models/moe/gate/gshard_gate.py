"""reference: incubate/distributed/models/moe/gate/gshard_gate.py — top-2
gate with GShard load-balance aux loss, capacity limiting and random
proportional routing of the 2nd expert."""
from __future__ import annotations

import math

from ...... import ops as _ops
from ......nn import functional as F
from ......ops import math as _math
from ..utils import _random_routing, limit_by_capacity
from .naive_gate import NaiveGate


class GShardGate(NaiveGate):
    def __init__(self, d_model: int, num_expert: int, world_size: int,
                 topk: int = 2, capacity=(1.2, 2.4),
                 random_routing: bool = True, group=None):
        assert topk == 2, "topk should be 2 in gshard"
        super().__init__(d_model, num_expert, world_size)
        self.capacity = capacity
        self.random_routing = random_routing
        self.group = group

    def forward(self, x):
        topk_val, topk_idx, gate_score = super().forward(
            x, return_all_scores=True)
        s = gate_score.shape[0]
        # load-balance aux loss: fraction of tokens whose top-1 is expert e
        # (c_e) × mean gate prob of e (m_e); mean over experts × E²
        top1 = topk_idx[:, 0]
        c_e = _math.mean(
            F.one_hot(top1, self.tot_expert).astype("float32"), axis=0)
        m_e = _math.mean(F.softmax(gate_score, axis=1), axis=0)
        loss = _math.mean(_math.multiply(c_e, m_e)) * (self.num_expert ** 2)
        self.set_loss(loss)

        cap_rate = self.capacity[0 if self.training else 1]
        capacity = math.ceil(cap_rate * s)
        _, _, topk_idx = limit_by_capacity(
            topk_idx, self.num_expert, self.world_size, capacity,
            group=self.group)

        if self.random_routing and self.training:
            rand_prob = _ops.random.rand([s], dtype="float32")
            topk_idx = _random_routing(topk_idx, topk_val, rand_prob)
        return topk_val, topk_idx
