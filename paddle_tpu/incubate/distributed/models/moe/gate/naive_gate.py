"""reference: incubate/distributed/models/moe/gate/naive_gate.py — linear
gate + top-k selection (no capacity limit, no aux loss)."""
from __future__ import annotations

from ......nn.layer.common import Linear
from ......ops import manipulation as _manip
from .base_gate import BaseGate


class NaiveGate(BaseGate):
    def __init__(self, d_model: int, num_expert: int, world_size: int,
                 topk: int = 2):
        super().__init__(num_expert, world_size)
        self.gate = Linear(d_model, self.tot_expert)
        self.top_k = topk

    def forward(self, inp, return_all_scores: bool = False):
        gate = self.gate(inp)
        gate_top_k_val, gate_top_k_idx = _manip.topk(
            gate, k=self.top_k, axis=-1, largest=True, sorted=True)
        if return_all_scores:
            return gate_top_k_val, gate_top_k_idx, gate
        return gate_top_k_val, gate_top_k_idx
