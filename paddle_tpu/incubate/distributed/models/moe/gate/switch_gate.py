"""reference: incubate/distributed/models/moe/gate/switch_gate.py — top-1
switch-transformer gate: additive jitter noise (training), softmax scores,
capacity limiting, and the switch load-balance loss
Σ_e fraction_e · prob_e · E."""
from __future__ import annotations

import math

from ...... import ops as _ops
from ......nn import functional as F
from ......ops import math as _math
from ..utils import limit_by_capacity
from .naive_gate import NaiveGate


class SwitchGate(NaiveGate):
    def __init__(self, d_model: int, num_expert: int, world_size: int,
                 topk: int = 1, switch_eps: float = 0.1,
                 capacity=(1.2, 2.4), group=None):
        assert topk == 1, "topk should be 1 in switch"
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.switch_eps = switch_eps
        self.capacity = capacity
        self.group = group

    def forward(self, inp):
        score = self.gate(inp)
        if self.training:
            noise = _ops.random.rand(score.shape, dtype="float32")
            noise = noise * (2 * self.switch_eps) + (1.0 - self.switch_eps)
            score = score + noise
        score = F.softmax(score, axis=-1)
        top1_score, top1_idx = _ops.manipulation.topk(score, k=1, axis=-1)

        cap_rate = self.capacity[0 if self.training else 1]
        capacity = math.ceil(cap_rate * inp.shape[0])
        _, _, top1_idx = limit_by_capacity(
            top1_idx, self.num_expert, self.world_size, capacity,
            group=self.group)

        # switch load-balance loss over kept assignments
        kept = (top1_idx[:, 0] >= 0).astype("float32")
        n_kept = _math.clip(_math.sum(kept), min=1.0)
        frac = _math.sum(
            F.one_hot(_math.clip(top1_idx[:, 0], min=0), self.tot_expert)
            * kept[:, None], axis=0) / n_kept
        prob = _math.sum(score, axis=0) / n_kept
        loss = _math.sum(_math.multiply(frac, prob)) * self.tot_expert
        self.set_loss(loss)

        return top1_score, top1_idx
