"""MoE layer with expert parallelism, TPU-native.

Reference parity: ``MoELayer``
(python/paddle/incubate/distributed/models/moe/moe_layer.py:261) whose
dispatch/combine are CUDA global_scatter/global_gather collectives
(paddle/fluid/operators/collective/global_scatter_op.cu.cc) moving variable
-length token buffers between ranks.

TPU redesign: static-shape capacity dispatch. Each token's (expert, slot)
position is computed by a one-hot cumsum; tokens gather into a dense
[E, C, d] buffer (XLA gather — differentiable, sortless, SPMD-friendly) and
expert outputs gather back per (token, k). Expert parallelism runs the whole
dispatch inside ``shard_map`` over the moe mesh axis with
``jax.lax.all_to_all`` standing in for global_scatter/global_gather — the
collective rides ICI exactly like the reference's NCCL AllToAll rides
NVLink/IB. Dropped tokens (over capacity, or gshard random routing) simply
combine to a zero contribution, matching the reference's semantics.
"""
from __future__ import annotations

import math as _pymath
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .....nn import functional as F
from .....nn.layer.common import Linear
from .....nn.layer.container import LayerList
from .....nn.layer_base import Layer
from .....ops import manipulation as _manip
from .....ops._apply import apply_op, ensure_tensor
from .....tensor import Tensor
from .....distributed.topology import get_mesh
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate

__all__ = ["MoELayer", "ExpertLayer"]


class ExpertLayer(Layer):
    """Stackable two-Linear FFN expert (the reference docs' ExpertLayer
    shape). Homogeneous ExpertLayer banks take the fused expert-parallel
    path in MoELayer."""

    def __init__(self, d_model: int, d_hidden: int, activation: str = "gelu",
                 name=None, rank: int = 0, windex: int = 0,
                 num_expert: int = 1):
        super().__init__()
        self.htoh4 = Linear(d_model, d_hidden)
        self.h4toh = Linear(d_hidden, d_model)
        self._activation = activation

    def _act(self, x):
        if self._activation is None or self._activation == "identity":
            return x
        return getattr(F, self._activation)(x)

    def forward(self, x):
        return self.h4toh(self._act(self.htoh4(x)))


def _routing_plan(idx, tot_expert: int, capacity: int):
    """idx [T, k] int (−1 dropped) → static-shape routing arrays:
    gather_idx [E*C] (source token per slot), slot_valid [E*C],
    tok_slot [T*k] (each assignment's slot), tok_valid [T*k]."""
    T, k = idx.shape
    flat = idx.reshape(-1).astype(jnp.int32)
    valid = flat >= 0
    safe = jnp.clip(flat, 0, tot_expert - 1)
    oh = jnp.where(
        valid[:, None],
        (safe[:, None] == jnp.arange(tot_expert)[None, :]).astype(jnp.int32),
        0)
    pos = jnp.take_along_axis(jnp.cumsum(oh, axis=0), safe[:, None], 1)[:, 0] - 1
    valid = valid & (pos < capacity)
    n_slots = tot_expert * capacity
    slot = jnp.where(valid, safe * capacity + pos, n_slots)  # overflow bin
    token = jnp.arange(T * k, dtype=jnp.int32) // k
    tfs = jnp.zeros(n_slots + 1, jnp.int32).at[slot].add(token + 1)
    tfs = tfs[:n_slots]  # positions are unique per expert → no collisions
    slot_valid = tfs > 0
    gather_idx = jnp.maximum(tfs - 1, 0)
    tok_slot = jnp.minimum(slot, n_slots - 1)
    return gather_idx, slot_valid, tok_slot, valid


class MoELayer(Layer):
    """reference: moe_layer.py:261 — same constructor contract.

    ``moe_group`` selects the expert-parallel mesh axis: an axis-group handle
    (fleet ``get_data_parallel_group()``), an axis name string, or None
    (single-program local experts). ``capacity_factor`` is the TPU-native
    extra: per-expert capacity C = ceil(factor · T · k / E); None means
    C = T (exact, nothing ever drops in the layer itself — gates may still
    drop)."""

    def __init__(self, d_model: int, experts, gate=None, moe_group=None,
                 mp_group=None, recompute_interval: int = 0,
                 recompute_ctx=None, capacity_factor: Optional[float] = None):
        super().__init__()
        self.d_model = d_model
        self.recompute_interval = recompute_interval
        self.recompute_ctx = recompute_ctx
        self.capacity_factor = capacity_factor

        self._ep_axis = None
        self.world_size = 1
        mesh = get_mesh()
        axis = None
        if isinstance(moe_group, str):
            axis = moe_group
        elif moe_group is not None and hasattr(moe_group, "axis"):
            axis = moe_group.axis
        if axis and mesh is not None and axis in mesh.axis_names \
                and mesh.shape[axis] > 1:
            self._ep_axis = axis
            self.world_size = mesh.shape[axis]
        self._mesh_ref = mesh

        if not isinstance(experts, LayerList):
            experts = LayerList(experts)
        self.experts = experts
        self.num_expert = len(experts)
        self.tot_expert = self.num_expert  # single program sees all experts

        if gate is None:
            gate = {}
        if isinstance(gate, dict):
            self.top_k = gate.get("top_k", 2)
            kind = gate.get("type", "gshard")
            if kind == "naive" or kind is None:
                gate = NaiveGate(d_model, num_expert=self.num_expert,
                                 world_size=1, topk=self.top_k)
            elif kind == "gshard":
                gate = GShardGate(d_model, num_expert=self.num_expert,
                                  world_size=1, topk=self.top_k)
            elif kind == "switch":
                gate = SwitchGate(d_model, num_expert=self.num_expert,
                                  world_size=1, topk=self.top_k)
            else:
                raise AssertionError(
                    f"only naive/gshard/switch gates supported, got {kind}")
        elif isinstance(gate, BaseGate):
            self.top_k = gate.top_k
        else:
            raise TypeError("gate must be a dict or a moe.BaseGate instance")
        self.gate = gate

        self._stackable = all(isinstance(e, ExpertLayer) for e in experts) \
            and len({e._activation for e in experts
                     if isinstance(e, ExpertLayer)}) <= 1
        if self._ep_axis and not self._stackable:
            raise ValueError(
                "expert-parallel MoELayer needs a homogeneous ExpertLayer "
                "bank (stacked weights ride the mesh's expert axis); "
                "heterogeneous experts run with moe_group=None")

    # -------------------------------------------------------- local path
    def _capacity(self, T: int) -> int:
        if self.capacity_factor is None:
            return T
        return min(T, _pymath.ceil(
            self.capacity_factor * T * self.top_k / self.tot_expert))

    def _forward_local(self, x2d, value, idx, T):
        E, C = self.tot_expert, self._capacity(T)
        k = self.top_k

        def plan(iv):
            return _routing_plan(iv, E, C)

        gi, sv, ts, tv = apply_op(plan, [Tensor(idx._value, stop_gradient=True)],
                                  name="moe_routing_plan")
        gi_t = Tensor(gi._value, stop_gradient=True)
        ts_t = Tensor(ts._value, stop_gradient=True)

        def dispatch(xv, g, valid):
            return xv[g] * valid[:, None].astype(xv.dtype)

        expert_in = apply_op(dispatch, [x2d, gi_t, sv], name="moe_dispatch")
        expert_in = _manip.reshape(expert_in, [E, C, -1])

        outs = [self.experts[e](expert_in[e]) for e in range(E)]
        expert_out = _manip.stack(outs, axis=0)  # [E, C, d]

        def combine(eo, slots, valid, val):
            flat = eo.reshape(E * C, -1)
            y = flat[slots] * valid[:, None].astype(flat.dtype)  # [T*k, d]
            y = y.reshape(T, k, -1)
            return jnp.sum(y * val[..., None].astype(y.dtype), axis=1)

        return apply_op(combine, [expert_out, ts_t, tv, value],
                        name="moe_combine")

    # ------------------------------------------------- expert-parallel path
    def _forward_ep(self, x2d, value, idx, T):
        """Dispatch + all_to_all + stacked-expert FFN + all_to_all back,
        inside shard_map over the moe axis (tokens and experts both sharded
        on it). TPU-native global_scatter/global_gather."""
        mesh, axis = self._mesh_ref, self._ep_axis
        ep = self.world_size
        E, k = self.tot_expert, self.top_k
        if E % ep:
            raise ValueError(f"num_expert {E} not divisible by ep degree {ep}")
        if T % ep:
            raise ValueError(f"token count {T} not divisible by ep degree {ep}")
        T_l = T // ep
        C = self._capacity(T_l)
        E_l = E // ep
        act = _ACTS[self.experts[0]._activation or "identity"]

        params = []
        for e in self.experts:
            params += [e.htoh4.weight, e.htoh4.bias,
                       e.h4toh.weight, e.h4toh.bias]

        def fn(xv, val, iv, *flat_w):
            w1 = jnp.stack(flat_w[0::4])   # [E, d, h]
            b1 = jnp.stack(flat_w[1::4])   # [E, h]
            w2 = jnp.stack(flat_w[2::4])   # [E, h, d]
            b2 = jnp.stack(flat_w[3::4])   # [E, d]

            def kernel(x_l, val_l, idx_l, w1_l, b1_l, w2_l, b2_l):
                gi, sv, ts, tv = _routing_plan(idx_l, E, C)
                ein = x_l[gi] * sv[:, None].astype(x_l.dtype)  # [E*C, d]
                d = ein.shape[-1]
                # global_scatter: route each expert's buffer to its owner
                ein = ein.reshape(ep, E_l, C, d)
                ein = jax.lax.all_to_all(ein, axis, split_axis=0,
                                         concat_axis=0, tiled=False)
                # [ep_src, E_l, C, d] → experts see tokens from every rank
                ein = jnp.moveaxis(ein, 0, 1).reshape(E_l, ep * C, d)
                h = jnp.einsum("etd,edh->eth", ein, w1_l) + b1_l[:, None]
                h = act(h)
                eo = jnp.einsum("eth,ehd->etd", h, w2_l) + b2_l[:, None]
                # global_gather: route results back to token owners
                eo = jnp.moveaxis(eo.reshape(E_l, ep, C, d), 1, 0)
                eo = jax.lax.all_to_all(eo, axis, split_axis=0,
                                        concat_axis=0, tiled=False)
                flat = eo.reshape(E * C, d)
                y = flat[ts] * tv[:, None].astype(flat.dtype)
                y = y.reshape(T_l, k, d)
                return jnp.sum(y * val_l[..., None].astype(y.dtype), axis=1)

            return jax.shard_map(
                kernel, mesh=mesh,
                in_specs=(P(axis), P(axis), P(axis),
                          P(axis), P(axis), P(axis), P(axis)),
                out_specs=P(axis), check_vma=False,
            )(xv, val, iv, w1, b1, w2, b2)

        idx_in = Tensor(idx._value, stop_gradient=True)
        return apply_op(fn, [x2d, value, idx_in] + params, name="moe_ep")

    def forward(self, inp):
        inp = ensure_tensor(inp)
        if len(inp.shape) != 3:
            raise ValueError("MoELayer input must be [batch, seq, d_model]")
        B, S, d = inp.shape
        x2d = _manip.reshape(inp, [-1, d])
        T = B * S
        value, idx = self.gate(x2d)
        if self._ep_axis:
            out = self._forward_ep(x2d, value, idx, T)
        else:
            out = self._forward_local(x2d, value, idx, T)
        return _manip.reshape(out, [B, S, d])


_ACTS = {
    # matches nn.functional variants (gelu default approximate=False)
    "identity": lambda x: x,
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "relu": jax.nn.relu,
    "silu": jax.nn.silu,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
}
