"""MoE routing utilities, static-shape TPU redesign.

Reference parity: ``python/paddle/incubate/distributed/models/moe/utils.py``
(``limit_by_capacity`` :74) and
``python/paddle/distributed/models/moe/utils.py`` (``_random_routing`` :109).
The reference backs these with CUDA ops (number_count, limit_by_capacity,
random_routing); here they are static-shape XLA programs: capacity limiting
is a one-hot cumsum (position-in-expert) + mask, which jits and shards
cleanly (no dynamic shapes).
"""
from __future__ import annotations

import jax.numpy as jnp

from .....ops._apply import apply_op, ensure_tensor
from .....tensor import Tensor

__all__ = ["limit_by_capacity", "count_by_gate", "_random_routing"]


def _positions_in_expert(flat_idx, tot_expert):
    """flat_idx [N] int (−1 = dropped) → (pos [N], one_hot [N, E] int32).
    pos is each entry's 0-based arrival order within its expert."""
    valid = (flat_idx >= 0)
    safe = jnp.clip(flat_idx, 0, tot_expert - 1)
    oh = jnp.where(valid[:, None],
                   jnp.equal(safe[:, None],
                             jnp.arange(tot_expert)[None, :]).astype(jnp.int32),
                   0)
    cum = jnp.cumsum(oh, axis=0)
    pos = jnp.take_along_axis(cum, safe[:, None], axis=1)[:, 0] - 1
    pos = jnp.where(valid, pos, -1)
    return pos, oh


def limit_by_capacity(topk_idx, num_expert, world_size, capacity, group=None):
    """reference: moe/utils.py:74 — mark tokens routed beyond each expert's
    capacity with −1. Returns (local_expert_count, global_expert_count,
    new_topk_idx). Under single-controller SPMD the local/global counts
    coincide (the program sees global state; per-rank counts are a
    multi-process artifact of the NCCL design)."""
    t = ensure_tensor(topk_idx)
    tot = num_expert * world_size

    def fn(idx):
        shape = idx.shape
        flat = idx.reshape(-1).astype(jnp.int32)
        pos, oh = _positions_in_expert(flat, tot)
        keep = (flat >= 0) & (pos < capacity)
        new = jnp.where(keep, flat, -1)
        counts = jnp.sum(
            jnp.where(keep[:, None], oh, 0), axis=0).astype(jnp.int64)
        return counts, counts, new.reshape(shape)

    lec, gec, new_idx = apply_op(fn, [Tensor(t._value, stop_gradient=True)],
                                 name="limit_by_capacity")
    return lec, gec, new_idx


def count_by_gate(gate_idx, num_expert, world_size, require_pos=True, group=None):
    """reference: moe/utils.py count_by_gate — per-expert counts and each
    token's position within its expert."""
    t = ensure_tensor(gate_idx)
    tot = num_expert * world_size

    def fn(idx):
        flat = idx.reshape(-1).astype(jnp.int32)
        pos, oh = _positions_in_expert(flat, tot)
        counts = jnp.sum(oh, axis=0).astype(jnp.int64)
        return pos, counts, counts

    pos, lec, gec = apply_op(fn, [Tensor(t._value, stop_gradient=True)],
                             name="count_by_gate")
    return pos, lec, gec


def _random_routing(topk_idx, topk_value, prob, topk=2):
    """reference: distributed/models/moe/utils.py:109 — drop the 2nd expert
    where 2·value₂ < prob (random proportional routing)."""
    if topk != 2:
        raise RuntimeError("only topk=2 is supported now")
    it, vt, pt = ensure_tensor(topk_idx), ensure_tensor(topk_value), ensure_tensor(prob)

    def fn(idx, val, p):
        drop = (2.0 * val[:, 1]) < p
        second = jnp.where(drop, -1, idx[:, 1])
        return jnp.stack([idx[:, 0], second], axis=1)

    return apply_op(fn, [Tensor(it._value, stop_gradient=True),
                         Tensor(vt._value, stop_gradient=True),
                         Tensor(pt._value, stop_gradient=True)],
                    name="random_routing")
