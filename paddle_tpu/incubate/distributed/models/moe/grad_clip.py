"""MoE-aware global-norm gradient clipping.

Reference parity: ``ClipGradForMOEByGlobalNorm``
(python/paddle/incubate/distributed/models/moe/grad_clip.py:23). There,
expert parameters live only on their owning rank, so the expert-partition
norm must be allreduced over the moe group before combining with the
normal-parameter norm. Under single-controller GSPMD every parameter is a
global (possibly sharded) array and jnp reductions over sharded grads
already emit the psum — so both partitions reduce to one global-norm
computation; the class keeps the reference's constructor contract
(is_expert_param_func, moe_group) and the two-partition accounting for API
parity.
"""
from __future__ import annotations

import jax.numpy as jnp

from .....nn.clip import ClipGradBase
from .....tensor import Tensor

__all__ = ["ClipGradForMOEByGlobalNorm"]


class ClipGradForMOEByGlobalNorm(ClipGradBase):
    """reference: grad_clip.py:23."""

    def __init__(self, clip_norm: float, is_expert_param_func=None,
                 moe_group=None, group_name: str = "default_moe_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name
        self.moe_group = moe_group
        if moe_group is not None and getattr(moe_group, "nranks", 1) > 1:
            assert is_expert_param_func is not None, (
                "When moe group size > 1, a function for selecting expert "
                "params must be specified.")
        self.is_expert_param_func = is_expert_param_func

    def __str__(self):
        return f"Gradient Clip By GlobalNorm, global_norm={self.clip_norm}"

    def __call__(self, params_grads):
        split = (self.moe_group is not None
                 and getattr(self.moe_group, "nranks", 1) > 1)
        normal_sq = expert_sq = None
        clippable = set()
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                continue
            s = jnp.sum(g._value.astype(jnp.float32) ** 2)
            if split and self.is_expert_param_func(p):
                expert_sq = s if expert_sq is None else expert_sq + s
            else:
                normal_sq = s if normal_sq is None else normal_sq + s
            clippable.add(id(p))
        if not clippable:
            return params_grads
        # the expert-partition allreduce of the reference is implicit: sharded
        # grads psum inside jnp.sum under GSPMD
        total = sum(x for x in (normal_sq, expert_sq) if x is not None)
        global_norm = jnp.sqrt(total)
        factor = jnp.where(
            global_norm > self.clip_norm,
            self.clip_norm / jnp.maximum(global_norm, 1e-12), 1.0)
        out = []
        for p, g in params_grads:
            if g is None or id(p) not in clippable:
                out.append((p, g))
            else:
                out.append((p, Tensor(
                    (g._value * factor).astype(g._value.dtype))))
        return out
