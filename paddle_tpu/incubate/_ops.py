"""Incubate top-level ops (reference: python/paddle/incubate/__init__.py
__all__): fused softmax-mask, graph ops (aliases of paddle.geometric's
implementations — the reference later graduated them there too),
segment reductions, identity_loss.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd.engine import apply_op
from ..ops._apply import ensure_tensor

__all__ = [
    "softmax_mask_fuse", "softmax_mask_fuse_upper_triangle",
    "graph_send_recv", "graph_khop_sampler", "graph_sample_neighbors",
    "graph_reindex", "segment_sum", "segment_mean", "segment_max",
    "segment_min", "identity_loss",
]


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) in one region (reference: fused softmax_mask
    CUDA kernel; XLA fuses the add into the softmax)."""
    return apply_op(
        lambda v, m: jax.nn.softmax(
            v.astype(jnp.float32) + m.astype(jnp.float32),
            axis=-1).astype(v.dtype),
        [ensure_tensor(x), ensure_tensor(mask)], name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Causal-masked softmax (reference: softmax_mask_fuse_upper_triangle):
    positions above the diagonal are masked out."""

    def fn(v):
        s = v.shape[-1]
        causal = jnp.tril(jnp.ones((v.shape[-2], s), bool))
        logits = jnp.where(causal, v.astype(jnp.float32), -1e30)
        return jax.nn.softmax(logits, axis=-1).astype(v.dtype)

    return apply_op(fn, [ensure_tensor(x)],
                    name="softmax_mask_fuse_upper_triangle")


def identity_loss(x, reduction="none"):
    """Mark a tensor as a loss (reference: identity_loss op, IPU-oriented;
    semantically a reduction passthrough)."""
    t = ensure_tensor(x)
    if reduction in (0, "sum"):
        return t.sum()
    if reduction in (1, "mean"):
        return t.mean()
    if reduction in (2, "none"):
        return t
    raise ValueError(f"bad reduction {reduction!r}")


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    from ..geometric import send_u_recv

    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    from ..geometric import reindex_graph

    return reindex_graph(x, neighbors, count, value_buffer, index_buffer)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    from ..geometric import sample_neighbors

    return sample_neighbors(row, colptr, input_nodes,
                            sample_size=sample_size, eids=eids,
                            return_eids=return_eids)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop neighbor sampling (reference:
    incubate/operators/graph_khop_sampler.py:109 — returns
    ``(edge_src, edge_dst, sample_index, reindex_nodes)``: sampled edges
    reindexed to local ids, the unique original node ids, and the input
    nodes' local positions)."""
    import numpy as np

    import jax

    from ..geometric import sample_neighbors
    from ..tensor import Tensor

    if return_eids:
        raise NotImplementedError(
            "graph_khop_sampler(return_eids=True): edge-id tracking is not "
            "implemented; sample without eids")

    def host(x):
        return np.asarray(jax.device_get(
            x._value if isinstance(x, Tensor) else x))

    nodes = host(input_nodes).astype(np.int64)
    srcs, dsts = [], []
    frontier = nodes
    for size in sample_sizes:
        out = sample_neighbors(row, colptr, frontier, sample_size=size)
        neigh = host(out[0]).astype(np.int64)
        counts = host(out[1]).astype(np.int64)
        dst = np.repeat(frontier, counts)
        srcs.append(neigh)
        dsts.append(dst)
        # next hop expands from the NEW nodes only (reference behavior:
        # frontier grows without resampling already-expanded nodes)
        frontier = np.setdiff1d(np.unique(neigh),
                                np.concatenate([nodes, *srcs[:-1]])
                                if srcs[:-1] else nodes)
        if frontier.size == 0:
            break
    edge_src = np.concatenate(srcs) if srcs else np.zeros(0, np.int64)
    edge_dst = np.concatenate(dsts) if dsts else np.zeros(0, np.int64)
    # unique node table with input nodes first (their local ids = 0..n-1)
    rest = np.setdiff1d(np.unique(np.concatenate([edge_src, edge_dst]))
                        if edge_src.size else nodes, nodes)
    sample_index = np.concatenate([nodes, rest])
    lookup = {int(g): i for i, g in enumerate(sample_index)}
    remap = np.vectorize(lambda g: lookup[int(g)], otypes=[np.int64])
    edge_src_l = remap(edge_src) if edge_src.size else edge_src
    edge_dst_l = remap(edge_dst) if edge_dst.size else edge_dst
    reindex_nodes = np.arange(nodes.size, dtype=np.int64)
    import jax.numpy as jnp

    return (Tensor(jnp.asarray(edge_src_l)), Tensor(jnp.asarray(edge_dst_l)),
            Tensor(jnp.asarray(sample_index)),
            Tensor(jnp.asarray(reindex_nodes)))


def segment_sum(data, segment_ids, name=None):
    from ..geometric import segment_sum as _f

    return _f(data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    from ..geometric import segment_mean as _f

    return _f(data, segment_ids)


def segment_max(data, segment_ids, name=None):
    from ..geometric import segment_max as _f

    return _f(data, segment_ids)


def segment_min(data, segment_ids, name=None):
    from ..geometric import segment_min as _f

    return _f(data, segment_ids)
