"""paddle.incubate.autograd parity: functional + primitive AD.

Reference parity: python/paddle/incubate/autograd/ —
``functional.py`` (vjp :22, jvp :80, Jacobian :171, Hessian :260) and
``primapi.py`` (forward_grad :25, grad :108), plus the prim-state toggles
(enable_prim/disable_prim, primx orig2prim/prim2orig program rewrites).

TPU-native collapse: the reference's prim system exists to decompose big
grad ops into primitive ops so (a) higher-order AD works and (b) a compiler
(CINN) sees a small op set. On TPU both jobs belong to jax/XLA — jaxpr IS
the primitive decomposition and jax's vjp/jvp compose to any order — so
each API here is a thin functionalization of the user callable over the
eager tape into a pure jax function, then the corresponding jax transform.
``enable_prim`` is therefore a no-op switch kept for API compatibility.
"""
from __future__ import annotations

from typing import Callable, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...ops._apply import ensure_tensor
from ...tensor import Tensor

__all__ = ["vjp", "jvp", "Jacobian", "Hessian", "forward_grad", "grad",
           "enable_prim", "disable_prim", "prim_enabled"]

_prim_state = {"enabled": False}


def enable_prim():
    """reference: primapi — on TPU the primitive decomposition is jaxpr;
    the switch is kept for compatibility."""
    _prim_state["enabled"] = True


def disable_prim():
    _prim_state["enabled"] = False


def prim_enabled() -> bool:
    return _prim_state["enabled"]


def _tensorize(xs):
    if isinstance(xs, (list, tuple)):
        return [ensure_tensor(x) for x in xs]
    return [ensure_tensor(xs)]


def _functionalize(func: Callable, n: int):
    """Wrap a Tensor->Tensor callable as a pure jax function of n arrays."""

    def pure(*vals):
        from ...autograd import no_grad

        with no_grad():
            out = func(*[Tensor(v, stop_gradient=True) for v in vals])
        if isinstance(out, (list, tuple)):
            return tuple(o._value if isinstance(o, Tensor) else o
                         for o in out)
        return out._value if isinstance(out, Tensor) else out

    return pure


def vjp(func: Callable, xs, v=None):
    """reference: functional.py:22 — (outputs, vjp_result) of func at xs
    with cotangent v (defaults to ones)."""
    xs_list = _tensorize(xs)
    pure = _functionalize(func, len(xs_list))
    vals = [t._value for t in xs_list]
    outs, vjp_fn = jax.vjp(pure, *vals)
    if v is None:
        ct = jax.tree_util.tree_map(jnp.ones_like, outs)
    elif isinstance(v, (list, tuple)):
        ct = tuple(ensure_tensor(x)._value for x in v)
        if not isinstance(outs, tuple):
            ct = ct[0]
    else:
        ct = ensure_tensor(v)._value
    grads = vjp_fn(ct)
    outs_t = (tuple(Tensor(o) for o in outs) if isinstance(outs, tuple)
              else Tensor(outs))
    grads_t = [Tensor(g) for g in grads]
    return outs_t, (grads_t if len(grads_t) > 1 else grads_t[0])


def jvp(func: Callable, xs, v=None):
    """reference: functional.py:80 — forward-mode: (outputs, Jv)."""
    xs_list = _tensorize(xs)
    pure = _functionalize(func, len(xs_list))
    vals = [t._value for t in xs_list]
    if v is None:
        tangents = tuple(jnp.ones_like(x) for x in vals)
    elif isinstance(v, (list, tuple)):
        tangents = tuple(ensure_tensor(x)._value for x in v)
    else:
        tangents = (ensure_tensor(v)._value,)
    outs, jv = jax.jvp(pure, tuple(vals), tangents)
    outs_t = (tuple(Tensor(o) for o in outs) if isinstance(outs, tuple)
              else Tensor(outs))
    jv_t = (tuple(Tensor(o) for o in jv) if isinstance(jv, tuple)
            else Tensor(jv))
    return outs_t, jv_t


class Jacobian:
    """reference: functional.py:171 — lazy Jacobian with [] slicing.

    For func mapping [*, N] -> [*, M] (or flat vectors), ``J[:]``
    materializes the full matrix via jax.jacfwd/jacrev (picking the cheaper
    direction by shape).
    """

    def __init__(self, func: Callable, xs, is_batched: bool = False):
        self._xs = _tensorize(xs)
        self._pure = _functionalize(func, len(self._xs))
        self._vals = [t._value for t in self._xs]
        self._is_batched = is_batched
        self._mat = None

    @property
    def shape(self):
        return tuple(self._materialize().shape)

    def _materialize(self):
        if self._mat is None:
            if len(self._vals) != 1:
                raise ValueError("Jacobian expects a single xs tensor; "
                                 "concatenate inputs first (reference "
                                 "behavior)")
            x = self._vals[0]
            out_shape = jax.eval_shape(self._pure, x).shape
            in_sz = int(np.prod(x.shape[1:] if self._is_batched else x.shape))
            out_sz = int(np.prod(out_shape[1:] if self._is_batched
                                 else out_shape))
            jac_fn = jax.jacfwd if in_sz <= out_sz else jax.jacrev
            if self._is_batched:
                f = jax.vmap(jac_fn(self._pure))
                j = f(x)  # [B, out..., in...]
                B = x.shape[0]
                self._mat = jnp.reshape(j, (B, out_sz, in_sz))
            else:
                j = jac_fn(self._pure)(x)
                self._mat = jnp.reshape(j, (out_sz, in_sz))
        return self._mat

    def __getitem__(self, idx):
        return Tensor(self._materialize()[idx])

    def numpy(self):
        return np.asarray(self._materialize())


class Hessian:
    """reference: functional.py:260 — Hessian of a scalar-output func."""

    def __init__(self, func: Callable, xs, is_batched: bool = False):
        self._xs = _tensorize(xs)
        pure = _functionalize(func, len(self._xs))

        def scalar(x):
            out = pure(x)
            return jnp.reshape(out, ()) if not is_batched \
                else jnp.reshape(out, out.shape[:1])

        self._pure = scalar
        self._vals = [t._value for t in self._xs]
        self._is_batched = is_batched
        self._mat = None

    def _materialize(self):
        if self._mat is None:
            x = self._vals[0]
            if self._is_batched:
                h = jax.vmap(jax.hessian(
                    lambda xx: jnp.reshape(self._pure(xx[None]), ())))(x)
                B = x.shape[0]
                n = int(np.prod(x.shape[1:]))
                self._mat = jnp.reshape(h, (B, n, n))
            else:
                h = jax.hessian(self._pure)(x)
                n = int(np.prod(x.shape))
                self._mat = jnp.reshape(h, (n, n))
        return self._mat

    @property
    def shape(self):
        return tuple(self._materialize().shape)

    def __getitem__(self, idx):
        return Tensor(self._materialize()[idx])

    def numpy(self):
        return np.asarray(self._materialize())


def forward_grad(outputs, inputs, grad_inputs=None):
    """reference: primapi.py:25 — forward-mode grads of tape outputs w.r.t.
    tape inputs. The tape records (fn, in_vals) per node, so the computation
    from ``inputs`` to ``outputs`` is replayed as a pure function and pushed
    through jax.jvp."""
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    fn, in_vals = _replay_function(outputs, inputs)
    if grad_inputs is None:
        tangents = tuple(jnp.ones_like(v) for v in in_vals)
    else:
        gi = grad_inputs if isinstance(grad_inputs, (list, tuple)) \
            else [grad_inputs]
        tangents = tuple(ensure_tensor(g)._value for g in gi)
    _, jv = jax.jvp(fn, tuple(in_vals), tangents)
    if not isinstance(jv, tuple):
        return Tensor(jv)
    res = [Tensor(g) for g in jv]
    return res if len(res) > 1 else res[0]


def grad(outputs, inputs, grad_outputs=None):
    """reference: primapi.py:108 — reverse-mode via the same replay."""
    from ...autograd import engine

    outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    res = engine.grad(outs, ins, grad_outputs=grad_outputs,
                      retain_graph=True, allow_unused=True)
    return res if len(res) > 1 else res[0]


def _replay_function(outputs: Sequence[Tensor], inputs: Sequence[Tensor]):
    """Rebuild the pure function inputs->outputs from the tape (each GradNode
    recorded its forward fn + input values)."""
    def replay(*in_vals):
        env = {}  # uid -> value
        for t, v in zip(inputs, in_vals):
            env[t._uid] = v
        computed = set()

        def compute(node):
            if id(node) in computed:
                return
            computed.add(id(node))
            vals = []
            for t, uid, producer in node.edges:
                if uid not in env and producer is not None:
                    compute(producer)
                vals.append(env.get(uid, t._value))
            if node.fn is None:
                raise RuntimeError(
                    f"node {node.name} lacks a recorded forward fn")
            out = node.fn(*vals)
            outs = out if isinstance(out, tuple) else (out,)
            for uid, o in zip(node.out_uids, outs):
                env[uid] = o

        results = []
        for t in outputs:
            if t._grad_node is not None:
                compute(t._grad_node)
            results.append(env.get(t._uid, t._value))
        return tuple(results) if len(results) > 1 else results[0]

    return replay, [t._value for t in inputs]
