"""incubate.nn.functional — fused-op functional API.

Reference parity: ``python/paddle/incubate/nn/functional/`` (functional
spellings of the fused CUDA kernels: fused_multi_head_attention,
fused_feedforward, fused_multi_transformer, fused_matmul_bias /
fused_linear, fused_bias_dropout_residual_layer_norm, fused_dropout_add,
fused_ec_moe). On TPU "fused" means "one traced region XLA fuses" —
these functions express the same composite math; there is no separate
kernel to dispatch to, so the functional and layer forms share code.
"""
from __future__ import annotations

import jax.numpy as jnp

from ... import nn
from ...nn import functional as F
from ...ops._apply import ensure_tensor
from ...autograd.engine import apply_op

__all__ = [
    "fused_multi_head_attention", "fused_feedforward",
    "fused_multi_transformer", "fused_matmul_bias", "fused_linear",
    "fused_bias_dropout_residual_layer_norm", "fused_ec_moe",
    "fused_dropout_add",
]


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """gemm + bias epilogue (reference: fused_matmul_bias — cublasLt
    epilogue; XLA fuses the add into the dot)."""
    xt = ensure_tensor(x)
    yt = ensure_tensor(y)
    ins = [xt, yt]
    if bias is not None:
        ins.append(ensure_tensor(bias))

    def fn(a, b, *rest):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = a @ b
        if rest:
            out = out + rest[0]
        return out

    return apply_op(fn, ins, name="fused_matmul_bias")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias,
                             transpose_y=transpose_weight, name=name)


def fused_dropout_add(x, y, p=0.5, training=True,
                      mode="upscale_in_train", name=None):
    """dropout(x) + y in one region (reference: fused_dropout_add op)."""
    return F.dropout(x, p=p, training=training, mode=mode) + ensure_tensor(y)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True, name=None):
    """ln(residual + dropout(x + bias)) (reference: fused_transformer.py)."""
    h = ensure_tensor(x)
    if bias is not None:
        h = h + ensure_tensor(bias)
    h = F.dropout(h, p=dropout_rate, training=training)
    h = ensure_tensor(residual) + h
    dim = int(h.shape[-1])
    return F.layer_norm(h, [dim], weight=ln_scale, bias=ln_bias,
                        epsilon=ln_epsilon)


def fused_multi_head_attention(
        x, qkv_weight, linear_weight, pre_layer_norm=False,
        pre_ln_scale=None, pre_ln_bias=None, ln_scale=None, ln_bias=None,
        pre_ln_epsilon=1e-5, qkv_bias=None, linear_bias=None, cache_kv=None,
        attn_mask=None, dropout_rate=0.5, attn_dropout_rate=0.5,
        ln_epsilon=1e-5, training=True, mode="upscale_in_train",
        ring_id=-1, add_residual=True, num_heads=None, name=None):
    """Packed-QKV attention block with LN/residual epilogues (reference:
    incubate/nn/functional/fused_transformer.py fused_multi_head_attention;
    fused_attention_op.cu). qkv_weight: [3, H, D, E]."""
    xt = ensure_tensor(x)
    qkvw = ensure_tensor(qkv_weight)
    lw = ensure_tensor(linear_weight)
    residual = xt
    if pre_layer_norm:
        dim = int(xt.shape[-1])
        xt = F.layer_norm(xt, [dim], weight=pre_ln_scale, bias=pre_ln_bias,
                          epsilon=pre_ln_epsilon)
    ins = [xt, qkvw]
    has_qkv_bias = qkv_bias is not None
    if has_qkv_bias:
        ins.append(ensure_tensor(qkv_bias))

    def qkv_fn(v, w, *rest):
        # v [B,S,E] · w [3,H,D,E] → q,k,v [B,S,H,D]
        out = jnp.einsum("bse,thde->tbshd", v, w)
        if rest:
            out = out + rest[0][:, None, None]
        return out[0], out[1], out[2]

    q, k, v = apply_op(qkv_fn, ins, name="fused_qkv")
    cache_out = None
    if cache_kv is not None:
        ck = ensure_tensor(cache_kv)

        def extend(kk, vv, c):
            # c: [2, B, S_cache, H, D] in the same BSHD layout
            return (jnp.concatenate([c[0], kk], axis=1),
                    jnp.concatenate([c[1], vv], axis=1))

        k, v = apply_op(extend, [ensure_tensor(k), ensure_tensor(v), ck],
                        name="extend_cache")
        cache_out = apply_op(lambda kk, vv: jnp.stack([kk, vv]),
                             [ensure_tensor(k), ensure_tensor(v)],
                             name="stack_cache")
    ctx = F.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask,
        dropout_p=attn_dropout_rate if training else 0.0)
    merged = apply_op(lambda t: t.reshape(t.shape[0], t.shape[1], -1),
                      [ensure_tensor(ctx)], name="merge_heads")
    out = fused_matmul_bias(merged, lw, linear_bias)
    out = F.dropout(out, p=dropout_rate, training=training, mode=mode)
    if add_residual:
        out = ensure_tensor(residual) + out
    if not pre_layer_norm:
        dim = int(out.shape[-1])
        out = F.layer_norm(out, [dim], weight=ln_scale, bias=ln_bias,
                           epsilon=ln_epsilon)
    if cache_out is not None:
        return out, cache_out  # reference returns (out, cache_kv_out)
    return out


def fused_feedforward(
        x, linear1_weight, linear2_weight, linear1_bias=None,
        linear2_bias=None, ln1_scale=None, ln1_bias=None, ln2_scale=None,
        ln2_bias=None, dropout1_rate=0.5, dropout2_rate=0.5,
        activation="relu", ln1_epsilon=1e-5, ln2_epsilon=1e-5,
        pre_layer_norm=False, training=True, ring_id=-1, name=None):
    """FFN block with LN/residual epilogues (reference:
    fused_feedforward op)."""
    xt = ensure_tensor(x)
    residual = xt
    if pre_layer_norm:
        dim = int(xt.shape[-1])
        xt = F.layer_norm(xt, [dim], weight=ln1_scale, bias=ln1_bias,
                          epsilon=ln1_epsilon)
    h = fused_matmul_bias(xt, linear1_weight, linear1_bias)
    h = getattr(F, activation)(h)
    h = F.dropout(h, p=dropout1_rate, training=training)
    h = fused_matmul_bias(h, linear2_weight, linear2_bias)
    h = F.dropout(h, p=dropout2_rate, training=training)
    out = ensure_tensor(residual) + h
    if not pre_layer_norm:
        dim = int(out.shape[-1])
        out = F.layer_norm(out, [dim], weight=ln2_scale, bias=ln2_bias,
                           epsilon=ln2_epsilon)
    return out


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                            linear_weights, linear_biases, ffn_ln_scales,
                            ffn_ln_biases, ffn1_weights, ffn1_biases,
                            ffn2_weights, ffn2_biases, pre_layer_norm=True,
                            epsilon=1e-5, cache_kvs=None, attn_mask=None,
                            dropout_rate=0.0, activation="gelu",
                            training=False, mode="upscale_in_train",
                            ring_id=-1, name=None):
    """Stacked fused decoder layers (reference: fused_multi_transformer op).
    Functional form over per-layer weight lists."""
    out = ensure_tensor(x)
    n_layers = len(qkv_weights)
    new_caches = [] if cache_kvs is not None else None
    for i in range(n_layers):
        res = fused_multi_head_attention(
            out, qkv_weights[i], linear_weights[i],
            pre_layer_norm=pre_layer_norm, pre_ln_scale=ln_scales[i],
            pre_ln_bias=ln_biases[i], qkv_bias=qkv_biases[i],
            linear_bias=linear_biases[i], attn_mask=attn_mask,
            cache_kv=None if cache_kvs is None else cache_kvs[i],
            dropout_rate=dropout_rate, attn_dropout_rate=dropout_rate,
            training=training, mode=mode)
        if cache_kvs is not None:
            out, cache = res
            new_caches.append(cache)
        else:
            out = res
        out = fused_feedforward(
            out, ffn1_weights[i], ffn2_weights[i], linear1_bias=ffn1_biases[i],
            linear2_bias=ffn2_biases[i], ln1_scale=ffn_ln_scales[i],
            ln1_bias=ffn_ln_biases[i], dropout1_rate=dropout_rate,
            dropout2_rate=dropout_rate, activation=activation,
            pre_layer_norm=pre_layer_norm, training=training)
    if new_caches is not None:
        return out, new_caches  # reference returns (out, cache_kvs)
    return out


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias,
                 act_type="gelu", name=None):
    """Expert-choice MoE block (reference: incubate/nn/functional/
    fused_ec_moe.py:18 — note ``gate`` is the PRE-COMPUTED gate logits
    tensor [bsz, seq, num_experts], not a weight): softmax over experts,
    expert FFNs applied and gate-weighted — einsums XLA batches over
    the expert dim."""
    if act_type not in ("gelu", "relu"):
        raise ValueError("act_type must be 'gelu' or 'relu'")

    def fn(xv, gv, w1, b1, w2, b2):
        import jax

        gates = jax.nn.softmax(gv, axis=-1)              # [B,S,E]
        h = jnp.einsum("bsd,edf->bsef", xv, w1) + b1[None, None]
        h = jax.nn.gelu(h) if act_type == "gelu" else jax.nn.relu(h)
        o = jnp.einsum("bsef,efd->bsed", h, w2) + b2[None, None]
        return jnp.einsum("bsed,bse->bsd", o, gates)

    return apply_op(fn, [ensure_tensor(t) for t in
                         (x, gate, bmm0_weight, bmm0_bias, bmm1_weight,
                          bmm1_bias)], name="fused_ec_moe")
