"""Fused layer implementations (see package docstring for the design)."""
from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ... import ops
from ...nn import functional as F
from ...nn.layer_base import Layer
from ...ops._apply import ensure_tensor
from ...tensor import Parameter, Tensor

__all__ = [
    "FusedLinear", "FusedDropoutAdd", "FusedBiasDropoutResidualLayerNorm",
    "FusedMultiHeadAttention", "FusedFeedForward",
    "FusedTransformerEncoderLayer", "FusedMultiTransformer",
]


def _uniform_param(shape, fan_in):
    from ... import ops as O

    bound = 1.0 / math.sqrt(max(fan_in, 1))
    return Parameter(O.uniform(list(shape), min=-bound, max=bound)._value)


class FusedLinear(Layer):
    """reference: incubate/nn/layer/fused_linear.py:19 — gemm+bias epilogue;
    one dot under XLA."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self.transpose_weight = transpose_weight
        shape = ([out_features, in_features] if transpose_weight
                 else [in_features, out_features])
        self.weight = _uniform_param(shape, in_features)
        self.bias = None if bias_attr is False else Parameter(
            jnp.zeros((out_features,), "float32"))

    def forward(self, x):
        w = self.weight
        if self.transpose_weight:
            w = ops.t(w)
        return F.linear(x, w, self.bias)


class FusedDropoutAdd(Layer):
    """reference: fused_dropout_add.py:19 — dropout(x) + y."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return F.dropout(x, p=self.p, training=self.training,
                         mode=self.mode) + ensure_tensor(y)

    def extra_repr(self):
        return f"p={self.p}, mode={self.mode}"


class FusedBiasDropoutResidualLayerNorm(Layer):
    """reference: fused_transformer.py:82 — ln(residual + dropout(x + bias))."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.dropout_rate = dropout_rate
        self._epsilon = epsilon
        self.linear_bias = Parameter(jnp.zeros((embed_dim,), "float32"))
        self.ln_scale = Parameter(jnp.ones((embed_dim,), "float32"))
        self.ln_bias = Parameter(jnp.zeros((embed_dim,), "float32"))

    def forward(self, x, residual):
        h = ensure_tensor(x) + self.linear_bias
        h = F.dropout(h, p=self.dropout_rate, training=self.training)
        h = ensure_tensor(residual) + h
        return F.layer_norm(h, [self.embed_dim], weight=self.ln_scale,
                            bias=self.ln_bias, epsilon=self._epsilon)


class FusedMultiHeadAttention(Layer):
    """reference: fused_transformer.py:192 — packed-qkv attention block with
    pre/post LN, residual, and dropout epilogues (fused_attention_op.cu);
    here the core runs the Pallas flash kernel via
    F.scaled_dot_product_attention and XLA fuses the epilogues."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, transpose_qkv_wb=False, name=None):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError("embed_dim must be divisible by num_heads")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.normalize_before = normalize_before
        self.need_weights = need_weights
        self._epsilon = epsilon
        self.transpose_qkv_wb = transpose_qkv_wb
        if transpose_qkv_wb:
            qkv_w_shape = [embed_dim, 3 * embed_dim]
            qkv_b_shape = [3 * embed_dim]
        else:
            # reference layout: [3, num_heads, head_dim, embed_dim]
            qkv_w_shape = [3, num_heads, self.head_dim, embed_dim]
            qkv_b_shape = [3, num_heads, self.head_dim]
        self.qkv_weight = _uniform_param(qkv_w_shape, embed_dim)
        self.qkv_bias = Parameter(jnp.zeros(qkv_b_shape, "float32"))
        self.linear_weight = _uniform_param([embed_dim, embed_dim], embed_dim)
        self.linear_bias = Parameter(jnp.zeros((embed_dim,), "float32"))
        self.pre_ln_scale = Parameter(jnp.ones((embed_dim,), "float32"))
        self.pre_ln_bias = Parameter(jnp.zeros((embed_dim,), "float32"))
        self.ln_scale = Parameter(jnp.ones((embed_dim,), "float32"))
        self.ln_bias = Parameter(jnp.zeros((embed_dim,), "float32"))

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        x = ensure_tensor(query)
        residual = x
        if self.normalize_before:
            x = F.layer_norm(x, [self.embed_dim], weight=self.pre_ln_scale,
                             bias=self.pre_ln_bias, epsilon=self._epsilon)
        B, S = x.shape[0], x.shape[1]
        H, D = self.num_heads, self.head_dim
        if self.transpose_qkv_wb:
            qkv = ops.matmul(x, self.qkv_weight) + self.qkv_bias
            qkv = ops.reshape(qkv, [B, S, 3, H, D])
        else:
            # x [B,S,E] @ w [3,H,D,E] -> [B,S,3,H,D]
            w = ops.reshape(self.qkv_weight, [3 * H * D, self.embed_dim])
            qkv = ops.matmul(x, ops.t(w))
            qkv = ops.reshape(qkv, [B, S, 3, H, D]) \
                + ops.reshape(self.qkv_bias, [1, 1, 3, H, D])
        q = qkv[:, :, 0]  # [B, S, H, D]
        k = qkv[:, :, 1]
        v = qkv[:, :, 2]
        new_cache = None
        if cache is not None:
            # incremental decoding: cache = (k_past, v_past) [B, S_past, H, D]
            k_past, v_past = cache
            if k_past is not None and k_past.shape[1] > 0:
                k = ops.concat([ensure_tensor(k_past), k], axis=1)
                v = ops.concat([ensure_tensor(v_past), v], axis=1)
            new_cache = (k, v)
        ctx = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate if self.training else 0.0)
        ctx = ops.reshape(ctx, [B, S, self.embed_dim])
        out = ops.matmul(ctx, self.linear_weight) + self.linear_bias
        out = F.dropout(out, p=self.dropout_rate, training=self.training)
        out = residual + out
        if not self.normalize_before:
            out = F.layer_norm(out, [self.embed_dim], weight=self.ln_scale,
                               bias=self.ln_bias, epsilon=self._epsilon)
        if cache is not None:
            return out, new_cache
        return out

    def extra_repr(self):
        return (f"embed_dim={self.embed_dim}, num_heads={self.num_heads}, "
                f"dropout_rate={self.dropout_rate}, "
                f"normalize_before={self.normalize_before}")


class FusedFeedForward(Layer):
    """reference: fused_transformer.py:497 — ln/linear/act/dropout/linear/
    dropout/residual in one region (fused_feedforward_op.cc)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self._d_model = d_model
        self._dim_feedforward = dim_feedforward
        self._dropout_rate = dropout_rate
        self._act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                  else act_dropout_rate)
        self._act_method = activation
        self._normalize_before = normalize_before
        self._epsilon = epsilon
        self.linear1_weight = _uniform_param([d_model, dim_feedforward],
                                             d_model)
        self.linear1_bias = Parameter(jnp.zeros((dim_feedforward,),
                                                "float32"))
        self.linear2_weight = _uniform_param([dim_feedforward, d_model],
                                             dim_feedforward)
        self.linear2_bias = Parameter(jnp.zeros((d_model,), "float32"))
        self.ln1_scale = Parameter(jnp.ones((d_model,), "float32"))
        self.ln1_bias = Parameter(jnp.zeros((d_model,), "float32"))
        self.ln2_scale = Parameter(jnp.ones((d_model,), "float32"))
        self.ln2_bias = Parameter(jnp.zeros((d_model,), "float32"))

    def forward(self, src, cache=None):
        x = ensure_tensor(src)
        residual = x
        if self._normalize_before:
            x = F.layer_norm(x, [self._d_model], weight=self.ln1_scale,
                             bias=self.ln1_bias, epsilon=self._epsilon)
        h = F.linear(x, self.linear1_weight, self.linear1_bias)
        h = getattr(F, self._act_method)(h)
        h = F.dropout(h, p=self._act_dropout_rate, training=self.training)
        h = F.linear(h, self.linear2_weight, self.linear2_bias)
        h = F.dropout(h, p=self._dropout_rate, training=self.training)
        out = residual + h
        if not self._normalize_before:
            out = F.layer_norm(out, [self._d_model], weight=self.ln2_scale,
                               bias=self.ln2_bias, epsilon=self._epsilon)
        return out


class FusedTransformerEncoderLayer(Layer):
    """reference: fused_transformer.py:725 — FusedMultiHeadAttention +
    FusedFeedForward."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout_rate = (dropout_rate if attn_dropout_rate is None
                             else attn_dropout_rate)
        act_dropout_rate = (dropout_rate if act_dropout_rate is None
                            else act_dropout_rate)
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        if cache is not None:
            attn_out, new_cache = self.fused_attn(src, attn_mask=src_mask,
                                                  cache=cache)
            return self.ffn(attn_out), new_cache
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


class FusedMultiTransformer(Layer):
    """reference: fused_transformer.py:1021 — N pre-LN decoder blocks with
    packed per-layer weights and KV caches, the inference fast path
    (fused_multi_transformer_op.cu). Here each block is flash attention +
    fused epilogues; ``caches`` carry [B, H, S, D] K/V for incremental
    decoding."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 ln_scale_attrs=None, ln_bias_attrs=None, num_layers=-1,
                 nranks=1, trans_qkvw=True, ring_id=-1, name=None, **kwargs):
        super().__init__()
        if not normalize_before:
            raise NotImplementedError(
                "FusedMultiTransformer only supports normalize_before=True "
                "(reference contract)")
        if num_layers < 0:
            num_layers = 1
        self.num_layers = num_layers
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        blocks = []
        for i in range(num_layers):
            blocks.append(FusedTransformerEncoderLayer(
                embed_dim, num_heads, dim_feedforward,
                dropout_rate=dropout_rate, activation=activation,
                normalize_before=True))
        self.blocks = blocks
        for i, b in enumerate(blocks):
            self.add_sublayer(str(i), b)

    def forward(self, src, attn_mask=None, caches=None, **kwargs):
        x = ensure_tensor(src)
        if caches is None:
            for b in self.blocks:
                x = b(x, src_mask=attn_mask)
            return x
        if len(caches) != len(self.blocks):
            raise ValueError(
                f"caches must have one (k, v) pair per layer: got "
                f"{len(caches)} for {len(self.blocks)} layers")
        new_caches = []
        for b, c in zip(self.blocks, caches):
            x, nc = b(x, src_mask=attn_mask, cache=c)
            new_caches.append(nc)
        return x, new_caches


class FusedEcMoe(Layer):
    """Expert-choice MoE layer (reference: incubate/nn/layer/fused_ec_moe.py
    FusedEcMoe). Holds gate + per-expert FFN weights; forward delegates to
    the functional fused_ec_moe."""

    def __init__(self, hidden_size, inter_size, num_experts,
                 act_type="gelu", weight_attr=None, bias_attr=None):
        super().__init__()
        self.act_type = act_type
        self.bmm_weight0 = _uniform_param([num_experts, hidden_size,
                                           inter_size], hidden_size)
        self.bmm_bias0 = Parameter(jnp.zeros((num_experts, inter_size),
                                             "float32"))
        self.bmm_weight1 = _uniform_param([num_experts, inter_size,
                                           hidden_size], inter_size)
        self.bmm_bias1 = Parameter(jnp.zeros((num_experts, hidden_size),
                                             "float32"))

    def forward(self, x, gate):
        # reference contract (fused_ec_moe.py:92): the gate logits tensor
        # [bsz, seq, num_experts] comes from the caller's gate network
        from .functional import fused_ec_moe

        return fused_ec_moe(x, gate, self.bmm_weight0, self.bmm_bias0,
                            self.bmm_weight1, self.bmm_bias1,
                            act_type=self.act_type)
