"""paddle.incubate.nn — fused layers.

Reference parity: python/paddle/incubate/nn/layer/fused_transformer.py
(FusedMultiHeadAttention :192, FusedFeedForward :497,
FusedTransformerEncoderLayer :725, FusedMultiTransformer :1021,
FusedBiasDropoutResidualLayerNorm :82), fused_linear.py, fused_dropout_add.py.

TPU-native: the reference's CUDA megakernels (fused_attention_op.cu,
fused_feedforward_op.cc) exist to dodge kernel-launch overhead and HBM
round-trips; under XLA one traced forward IS one fused program, so these
layers express the same math (single packed qkv weight, pre/post-LN,
residual+dropout epilogues) through the flash-attention Pallas kernel +
plain ops and let the compiler fuse — same parameter surface, state_dict
keys, and numerics as the reference modules.
"""
from .layer import (  # noqa: F401
    FusedBiasDropoutResidualLayerNorm, FusedDropoutAdd, FusedFeedForward,
    FusedLinear, FusedMultiHeadAttention, FusedMultiTransformer,
    FusedTransformerEncoderLayer,
)

from . import functional  # noqa: F401
from .layer import FusedEcMoe  # noqa: F401

__all__ = [
    "FusedMultiHeadAttention", "FusedFeedForward",
    "FusedTransformerEncoderLayer", "FusedMultiTransformer", "FusedLinear",
    "FusedDropoutAdd", "FusedBiasDropoutResidualLayerNorm", "FusedEcMoe",
    "functional",
]
