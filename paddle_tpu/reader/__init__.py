"""paddle.reader — composable reader decorators (legacy data pipeline).

Reference parity: ``python/paddle/reader/decorator.py`` (cache,
map_readers, shuffle, chain, compose, buffered, firstn, xmap_readers,
multiprocess_reader). A "reader" is a zero-arg callable returning an
iterable of samples; decorators wrap readers into new readers. Kept for
scripts written against the legacy pipeline — paddle_tpu.io.DataLoader
is the first-class path.
"""
from __future__ import annotations

import itertools
import queue as _queue
import random as _random
import threading

__all__ = ["cache", "map_readers", "buffered", "compose", "chain",
           "shuffle", "firstn", "xmap_readers", "multiprocess_reader"]


class ComposeNotAligned(ValueError):
    pass


def cache(reader):
    """Cache the full pass in memory; later passes replay it."""
    all_data = []
    filled = []

    def reader_():
        if not filled:
            all_data.extend(reader())
            filled.append(True)
        return iter(all_data)

    return reader_


def map_readers(func, *readers):
    """Zip readers and map ``func`` over the sample tuples."""

    def reader_():
        its = [r() for r in readers]
        for items in zip(*its):
            yield func(*items)

    return reader_


def shuffle(reader, buf_size):
    """Reservoir-style windowed shuffle of ``buf_size`` samples."""

    def reader_():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return reader_


def chain(*readers):
    """Concatenate readers end to end."""

    def reader_():
        return itertools.chain(*[r() for r in readers])

    return reader_


def compose(*readers, **kwargs):
    """Zip readers into flat tuples; ``check_alignment=True`` (default)
    raises ComposeNotAligned when lengths differ."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader_():
        its = [r() for r in readers]
        if check_alignment:
            for items in itertools.zip_longest(*its):
                if any(i is None for i in items):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum((make_tuple(i) for i in items), ())
        else:
            for items in zip(*its):
                yield sum((make_tuple(i) for i in items), ())

    return reader_


def buffered(reader, size):
    """Decouple producer/consumer with a ``size``-bounded queue fed by a
    daemon thread (keeps IO ahead of compute)."""

    class _End:
        pass

    class _Error:
        def __init__(self, tb):
            self.tb = tb

    def reader_():
        q: _queue.Queue = _queue.Queue(maxsize=size)

        def fill():
            try:
                for item in reader():
                    q.put(item)
                q.put(_End)
            except Exception:
                import traceback

                q.put(_Error(traceback.format_exc()))

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is _End:
                break
            if isinstance(item, _Error):
                raise RuntimeError(f"buffered reader failed:\n{item.tb}")
            yield item

    return reader_


def firstn(reader, n):
    """First ``n`` samples only."""

    def reader_():
        return itertools.islice(reader(), n)

    return reader_


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over samples with ``process_num`` worker threads
    (reference uses threads here too; heavy decode belongs in
    io.DataLoader's process workers)."""

    def reader_():
        in_q: _queue.Queue = _queue.Queue(buffer_size)
        out_q: _queue.Queue = _queue.Queue(buffer_size)
        end = object()

        def feed():
            for i, item in enumerate(reader()):
                in_q.put((i, item))
            for _ in range(process_num):
                in_q.put(end)

        def work():
            while True:
                got = in_q.get()
                if got is end:
                    out_q.put(end)
                    return
                i, item = got
                out_q.put((i, mapper(item)))

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        finished = 0
        if order:
            stash = {}
            want = 0
            while finished < process_num:
                got = out_q.get()
                if got is end:
                    finished += 1
                    continue
                i, mapped = got
                stash[i] = mapped
                while want in stash:
                    yield stash.pop(want)
                    want += 1
            for i in sorted(stash):
                yield stash[i]
        else:
            while finished < process_num:
                got = out_q.get()
                if got is end:
                    finished += 1
                    continue
                yield got[1]

    return reader_


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave readers, each producing from its own process
    (reference: decorator.py:499). Samples must be picklable."""
    import multiprocessing as mp

    def reader_():
        ctx = mp.get_context("spawn")
        q = ctx.Queue(queue_size)
        sentinel = "__PADDLE_TPU_READER_END__"

        procs = [ctx.Process(target=_mp_feed, args=(r, q, sentinel),
                             daemon=True) for r in readers]
        for p in procs:
            p.start()
        ended = 0
        error = None
        while ended < len(readers):
            item = q.get()
            if isinstance(item, str) and item == sentinel:
                ended += 1
                continue
            if isinstance(item, tuple) and len(item) == 2 \
                    and item[0] == sentinel:
                ended += 1
                error = item[1]
                continue
            yield item
        for p in procs:
            p.join()
        if error is not None:
            raise RuntimeError(f"multiprocess reader failed:\n{error}")

    return reader_


def _mp_feed(reader, q, sentinel):
    try:
        for item in reader():
            q.put(item)
        q.put(sentinel)
    except Exception:
        import traceback

        # sentinel ALWAYS lands (a silent child death would hang the
        # consumer); the error rides along and re-raises parent-side
        q.put((sentinel, traceback.format_exc()))
