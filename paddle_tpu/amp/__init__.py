"""Placeholder — populated in a subsequent milestone."""
