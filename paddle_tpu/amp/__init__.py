"""paddle_tpu.amp — automatic mixed precision, bf16-first.

Reference parity: ``python/paddle/amp/`` — ``auto_cast``
(``amp/auto_cast.py:636``), ``decorate`` (:698), per-op allow/block lists
(``amp/amp_lists.py``; C++ intercept ``eager/eager_amp_auto_cast.h``), and
``GradScaler`` (``amp/grad_scaler.py:562``) dynamic loss scaling.

TPU-native: bf16 shares float32's exponent range, so the default recipe is
O1/O2 bf16 WITHOUT loss scaling (scaler enabled=False is a no-op passthrough
exactly like the reference when use_dynamic_loss_scaling=False). GradScaler
remains fully functional (and jit-traceable: its scale state registers via
``__jit_state__`` and the skip-step is a jnp.where) for float16 workflows.
"""
from __future__ import annotations

import contextlib
from typing import Optional, Sequence

import jax.numpy as jnp

from ..autograd import no_grad
from ..autograd.engine import amp_state
from ..ops._apply import ensure_tensor
from ..tensor import Tensor
from .. import dtypes

__all__ = ["auto_cast", "amp_guard", "decorate", "GradScaler", "white_list",
           "black_list"]

# reference: amp/amp_lists.py WHITE_LIST — MXU-bound ops where bf16 wins
WHITE_LIST = frozenset({
    "linear", "matmul", "mm", "bmm", "einsum", "dot",
    "conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose",
    "conv3d_transpose", "scaled_dot_product_attention", "flash_attention",
    "addmm", "matmul_v2",
    "vocab_parallel_embedding", "column_parallel_linear", "row_parallel_linear",
})

# reference: amp/amp_lists.py BLACK_LIST — numerically sensitive reductions
BLACK_LIST = frozenset({
    "exp", "log", "log2", "log10", "log1p", "pow", "square", "sqrt", "rsqrt",
    "softmax", "log_softmax", "logsumexp", "cross_entropy", "nll_loss",
    "softmax_with_cross_entropy", "parallel_cross_entropy",
    "mean", "sum", "prod", "cumsum", "norm", "p_norm",
    "batch_norm", "layer_norm", "instance_norm", "group_norm", "rms_norm",
    "sigmoid_cross_entropy_with_logits", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "kl_div", "smooth_l1_loss",
    "mse_loss", "l1_loss",
})


def white_list():
    return set(WHITE_LIST)


def black_list():
    return set(BLACK_LIST)


@contextlib.contextmanager
def auto_cast(enable: bool = True, custom_white_list: Optional[Sequence] = None,
              custom_black_list: Optional[Sequence] = None, level: str = "O1",
              dtype: str = "bfloat16", use_promote: bool = True):
    """reference: paddle.amp.auto_cast (amp/auto_cast.py:636).

    Examples:
        >>> layer = paddle.nn.Linear(4, 4)
        >>> x = paddle.to_tensor(np.ones((2, 4), "float32"))
        >>> with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
        ...     out = layer(x)
        >>> str(out.dtype)
        'bfloat16'

    O1: ops on the white list compute in ``dtype``; black list pinned fp32;
    everything else runs in its input dtype. O2: everything except the black
    list computes in ``dtype``.
    """
    if level not in ("O0", "O1", "O2"):
        raise ValueError(f"amp level must be O0/O1/O2, got {level}")
    target = dtypes.convert_dtype(dtype)
    if target not in (jnp.bfloat16, jnp.float16):
        raise ValueError(f"amp dtype must be bfloat16/float16, got {dtype}")
    white = set(WHITE_LIST) | set(custom_white_list or ())
    black = (set(BLACK_LIST) - set(custom_white_list or ())) | set(
        custom_black_list or ())
    white -= black
    prev = dict(amp_state)
    amp_state.update(
        enabled=bool(enable) and level != "O0", dtype=target, level=level,
        white=frozenset(white), black=frozenset(black),
    )
    try:
        yield
    finally:
        amp_state.update(prev)


amp_guard = auto_cast  # legacy alias (fluid.dygraph.amp_guard)


def decorate(models, optimizers=None, level: str = "O2", dtype: str = "bfloat16",
             master_weight: Optional[bool] = None, save_dtype: Optional[str] = None):
    """reference: paddle.amp.decorate (amp/auto_cast.py:698). O2 casts model
    floating params to ``dtype`` and turns on optimizer master weights
    (fp32 true-state accumulators) unless master_weight=False."""
    if level not in ("O1", "O2"):
        raise ValueError("decorate level must be O1 or O2")
    single_model = not isinstance(models, (list, tuple))
    single_opt = optimizers is not None and not isinstance(optimizers, (list, tuple))
    model_list = [models] if single_model else list(models)
    opt_list = ([optimizers] if single_opt else list(optimizers or []))
    if level == "O2":
        target = dtypes.convert_dtype(dtype)
        for m in model_list:
            for layer in m.sublayers(include_self=True):
                # norms keep fp32 params (reference keeps BN fp32 in O2)
                if type(layer).__name__.startswith(
                        ("BatchNorm", "LayerNorm", "SyncBatchNorm",
                         "InstanceNorm", "GroupNorm", "RMSNorm",
                         "LocalResponseNorm", "SpectralNorm")):
                    continue
                for p in layer._parameters.values():
                    if p is not None and jnp.issubdtype(p._value.dtype, jnp.floating):
                        p._set_value(p._value.astype(target))
        for opt in opt_list:
            if master_weight is not False:
                opt._multi_precision = True
    if optimizers is None:
        return models if single_model else model_list
    return (
        model_list[0] if single_model else model_list,
        opt_list[0] if single_opt else opt_list,
    )


class GradScaler:
    """reference: paddle.amp.GradScaler (amp/grad_scaler.py:562) — dynamic
    loss scaling. Fully traceable: scale/counter live in Tensor cells exposed
    to the jit tracer via ``__jit_state__``; the skip-on-inf is a jnp.where
    inside Optimizer.step (no host branch)."""

    def __init__(self, enable: bool = True, init_loss_scaling: float = 2.0 ** 15,
                 incr_ratio: float = 2.0, decr_ratio: float = 0.5,
                 incr_every_n_steps: int = 1000, decr_every_n_nan_or_inf: int = 2,
                 use_dynamic_loss_scaling: bool = True):
        self._enable = bool(enable)
        self._use_dynamic = bool(use_dynamic_loss_scaling) and self._enable
        self._scale = Tensor(jnp.float32(init_loss_scaling))
        self._good_steps = Tensor(jnp.int32(0))
        self._bad_steps = Tensor(jnp.int32(0))
        self._incr_ratio = float(incr_ratio)
        self._decr_ratio = float(decr_ratio)
        self._incr_every = int(incr_every_n_steps)
        self._decr_every = int(decr_every_n_nan_or_inf)
        self._unscaled: set = set()  # optimizer ids already unscaled this step

    def __jit_state__(self):
        return [self._scale, self._good_steps, self._bad_steps]

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._use_dynamic

    def get_loss_scaling(self):
        return Tensor(self._scale._value)

    def set_init_loss_scaling(self, v):
        self._scale._set_value(jnp.float32(v))

    def scale(self, loss):
        """reference: grad_scaler.py scale — multiply the loss."""
        if not self._enable:
            return ensure_tensor(loss)
        from ..ops import math as _math

        return _math.multiply(ensure_tensor(loss), Tensor(self._scale._value))

    @no_grad()
    def _unscale_and_check(self, optimizer):
        inv = 1.0 / self._scale._value
        found = jnp.bool_(False)
        for p in optimizer._parameter_list or []:
            if p.grad is None:
                continue
            g = p.grad._value * inv.astype(p.grad._value.dtype)
            found = found | ~jnp.all(jnp.isfinite(g.astype(jnp.float32)))
            p.grad = Tensor(g)
        return found

    def step(self, optimizer):
        """reference: grad_scaler.py step — unscale (at most once per step,
        so the unscale_-then-clip workflow doesn't divide twice), skip on
        inf/nan."""
        if not self._enable:
            optimizer.step()
            return
        if id(optimizer) in self._unscaled:
            found = optimizer._found_inf._value
        else:
            found = self._unscale_and_check(optimizer)
            optimizer._found_inf = Tensor(found)
        # tag the skip's origin so Optimizer.step books it under
        # paddle_tpu_amp_skipped_steps_total (the train sentinel reuses
        # the same _found_inf path but counts its skips separately)
        optimizer._found_inf_origin = "amp"
        try:
            optimizer.step()
        finally:
            optimizer._found_inf = None
            self._unscaled.discard(id(optimizer))
        self.update(found)

    def minimize(self, optimizer, scaled_loss):
        """reference: grad_scaler.py minimize — collects grads already
        produced by ``scaled_loss.backward()``; does NOT run backward itself
        (running it here would double-accumulate for users following the
        reference's documented scaled.backward() → scaler.minimize pattern)."""
        params = optimizer._parameter_list
        if params is not None and not any(p.grad is not None for p in params):
            raise RuntimeError(
                "GradScaler.minimize found no gradients: call "
                "scaled_loss.backward() before minimize()")
        self.step(optimizer)

    def unscale_(self, optimizer):
        if id(optimizer) in self._unscaled:
            return optimizer._found_inf._value
        found = self._unscale_and_check(optimizer)
        optimizer._found_inf = Tensor(found)
        optimizer._found_inf_origin = "amp"
        self._unscaled.add(id(optimizer))
        return found

    @no_grad()
    def update(self, found_inf=None):
        """reference: update_loss_scaling op semantics, traceable."""
        if not self._use_dynamic:
            return
        found = found_inf._value if isinstance(found_inf, Tensor) else found_inf
        if found is None:
            return
        scale, good, bad = (self._scale._value, self._good_steps._value,
                            self._bad_steps._value)
        new_bad = jnp.where(found, bad + 1, jnp.int32(0))
        new_good = jnp.where(found, jnp.int32(0), good + 1)
        shrink = new_bad >= self._decr_every
        grow = new_good >= self._incr_every
        new_scale = jnp.where(
            shrink, jnp.maximum(scale * self._decr_ratio, jnp.float32(1e-6)),
            jnp.where(grow, scale * self._incr_ratio, scale))
        new_bad = jnp.where(shrink, jnp.int32(0), new_bad)
        new_good = jnp.where(grow, jnp.int32(0), new_good)
        self._scale._set_value(new_scale)
        self._good_steps._set_value(new_good)
        self._bad_steps._set_value(new_bad)

    def state_dict(self):
        return {
            "scale": Tensor(self._scale._value),
            "incr_ratio": self._incr_ratio, "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every,
            "decr_every_n_nan_or_inf": self._decr_every,
            "incr_count": Tensor(self._good_steps._value),
            "decr_count": Tensor(self._bad_steps._value),
            "use_dynamic_loss_scaling": self._use_dynamic,
        }

    def load_state_dict(self, state):
        self._scale._set_value(
            state["scale"]._value if isinstance(state["scale"], Tensor)
            else jnp.float32(state["scale"]))
        if "incr_count" in state:
            v = state["incr_count"]
            self._good_steps._set_value(v._value if isinstance(v, Tensor) else jnp.int32(v))
        if "decr_count" in state:
            v = state["decr_count"]
            self._bad_steps._set_value(v._value if isinstance(v, Tensor) else jnp.int32(v))


def is_float16_supported(device=None) -> bool:
    """reference: amp/__init__ is_float16_supported. TPUs compute in
    bf16; fp16 storage works but the MXU fast path is bf16."""
    import jax

    return jax.default_backend() in ("tpu", "gpu")


def is_bfloat16_supported(device=None) -> bool:
    """bf16 is THE native TPU compute dtype; CPU XLA supports it too."""
    return True


__all__ += ["is_float16_supported", "is_bfloat16_supported"]
