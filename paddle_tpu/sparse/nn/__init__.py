"""paddle.sparse.nn parity: activations, norm, pooling, conv, attention.

Reference parity: python/paddle/sparse/nn/ — layer/{activation,norm,
pooling,conv}.py + functional/{activation,pooling,conv,transformer}.py.

TPU-native notes: structure-preserving ops (ReLU/LeakyReLU/Softmax/
BatchNorm) run directly on BCOO values/rows — the same computation the
reference's sparse kernels do. The 3D (submanifold) convolutions gather a
dense neighborhood per active site from a windowed dense view: on TPU the
dense conv is an MXU-native op, so the sparse conv computes
``conv(to_dense(x))`` and re-samples the output at the active sites
(SubmConv keeps the input's sparsity pattern, Conv3D takes the dense
output's nonzeros) — numerically identical to the reference's
gather-GEMM-scatter kernels (phi/kernels/sparse/gpu/conv_kernel.cu) for
the same geometry, trading HBM for MXU throughput. Genuinely
activity-bounded point-cloud workloads should bound the spatial extent.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ...nn.layer_base import Layer
from ...ops._apply import ensure_tensor
from ...tensor import Parameter, Tensor

__all__ = [
    "ReLU", "LeakyReLU", "ReLU6", "Softmax", "BatchNorm", "SyncBatchNorm",
    "MaxPool3D", "Conv3D", "SubmConv3D", "functional",
]


def _bcoo(x):
    from .. import SparseCooTensor, SparseCsrTensor

    if isinstance(x, SparseCsrTensor):
        x = x.to_sparse_coo()
    if not isinstance(x, SparseCooTensor):
        raise TypeError(f"expected sparse tensor, got {type(x).__name__}")
    return x


def _wrap(bcoo):
    from .. import SparseCooTensor

    return SparseCooTensor(bcoo)


class functional:
    """sparse/nn/functional surface."""

    @staticmethod
    def relu(x, name=None):
        s = _bcoo(x)
        return _wrap(jsparse.BCOO((jax.nn.relu(s._bcoo.data),
                                   s._bcoo.indices), shape=s._bcoo.shape))

    @staticmethod
    def leaky_relu(x, negative_slope=0.01, name=None):
        s = _bcoo(x)
        return _wrap(jsparse.BCOO(
            (jax.nn.leaky_relu(s._bcoo.data, negative_slope),
             s._bcoo.indices), shape=s._bcoo.shape))

    @staticmethod
    def relu6(x, name=None):
        s = _bcoo(x)
        return _wrap(jsparse.BCOO((jax.nn.relu6(s._bcoo.data),
                                   s._bcoo.indices), shape=s._bcoo.shape))

    @staticmethod
    def softmax(x, axis=-1, name=None):
        """Row-wise softmax over the SUPPORT only (reference:
        sparse/nn/functional/activation.py softmax — CSR row semantics)."""
        s = _bcoo(x).coalesce()
        if axis not in (-1, len(s.shape) - 1):
            raise ValueError("sparse softmax supports the last axis only")
        b = s._bcoo
        rows = b.indices[:, :-1]
        # segment-id per nnz from leading indices
        mults = np.cumprod([1] + list(reversed(s.shape[:-1])))[::-1][1:]
        seg = (b.indices[:, :-1]
               * jnp.asarray(mults, b.indices.dtype)).sum(-1)
        n_seg = int(np.prod(s.shape[:-1]))
        mx = jax.ops.segment_max(b.data, seg, num_segments=n_seg)
        e = jnp.exp(b.data - mx[seg])
        den = jax.ops.segment_sum(e, seg, num_segments=n_seg)
        return _wrap(jsparse.BCOO((e / den[seg], b.indices), shape=b.shape))

    @staticmethod
    def attention(query, key, value, sparse_mask, key_padding_mask=None,
                  attn_mask=None, name=None):
        """reference: sparse/nn/functional/transformer.py attention — scores
        restricted to sparse_mask's support (SDDMM + sparse softmax + spmm)."""
        from .. import masked_matmul, matmul as smatmul

        q = ensure_tensor(query)
        k = ensure_tensor(key)
        v = ensure_tensor(value)
        d = float(q.shape[-1])
        B, H = q.shape[0], q.shape[1]
        kp = None if key_padding_mask is None \
            else ensure_tensor(key_padding_mask)._value
        am = None if attn_mask is None else ensure_tensor(attn_mask)._value
        outs = []
        for b in range(B):
            for h in range(H):
                scores = masked_matmul(
                    q[b, h] / (d ** 0.5),
                    k[b, h].transpose([1, 0]), sparse_mask)
                sb = scores._bcoo
                data, idx = sb.data, sb.indices  # idx [nnz, 2] = (i, j)
                if am is not None:
                    data = data + am[idx[:, 0], idx[:, 1]]
                if kp is not None:
                    # True/nonzero = padded key -> excluded from softmax
                    data = jnp.where(kp[b][idx[:, 1]].astype(bool),
                                     jnp.asarray(-1e9, data.dtype), data)
                scores = _wrap(jsparse.BCOO((data, idx), shape=sb.shape))
                p = functional.softmax(scores)
                outs.append(smatmul(p, v[b, h]))
        stacked = jnp.stack(
            [o._value if isinstance(o, Tensor) else o._bcoo.todense()
             for o in outs]).reshape((B, H) + tuple(outs[0].shape))
        return Tensor(stacked)

    @staticmethod
    def max_pool3d(x, kernel_size, stride=None, padding=0, name=None):
        """reference: sparse/nn/functional/pooling.py — NDHWC sparse input."""
        s = _bcoo(x)
        dense = s._bcoo.todense()
        from ...nn import functional as F

        # NDHWC -> NCDHW for the dense pool, then back
        dn = jnp.moveaxis(dense, -1, 1)
        out = F.max_pool3d(Tensor(dn), kernel_size, stride=stride,
                           padding=padding)
        od = jnp.moveaxis(out._value, 1, -1)
        return _wrap(jsparse.BCOO.fromdense(od, n_dense=1))


class ReLU(Layer):
    """reference: sparse/nn/layer/activation.py ReLU."""

    def forward(self, x):
        return functional.relu(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return functional.leaky_relu(x, self.negative_slope)


class ReLU6(Layer):
    def forward(self, x):
        return functional.relu6(x)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return functional.softmax(x, self.axis)


class BatchNorm(Layer):
    """reference: sparse/nn/layer/norm.py BatchNorm — normalizes the VALUES
    over the channel (last) dim using running stats like dense BN, but only
    active sites contribute."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.weight = Parameter(jnp.ones((num_features,), "float32"))
        self.bias = Parameter(jnp.zeros((num_features,), "float32"))
        self.register_buffer("_mean", Tensor(
            jnp.zeros((num_features,), "float32"), stop_gradient=True))
        self.register_buffer("_variance", Tensor(
            jnp.ones((num_features,), "float32"), stop_gradient=True))

    def forward(self, x):
        s = _bcoo(x)
        vals = s._bcoo.data  # [nnz, C]
        if self.training:
            mean = vals.mean(0)
            var = vals.var(0)
            m = self.momentum
            self._mean._value = m * self._mean._value + (1 - m) * mean
            self._variance._value = (m * self._variance._value
                                     + (1 - m) * var)
        else:
            mean, var = self._mean._value, self._variance._value
        out = ((vals - mean) / jnp.sqrt(var + self.epsilon)
               * self.weight._value + self.bias._value)
        return _wrap(jsparse.BCOO((out, s._bcoo.indices),
                                  shape=s._bcoo.shape))


class SyncBatchNorm(BatchNorm):
    """reference: sparse/nn/layer/norm.py SyncBatchNorm — under GSPMD the
    batch stats are computed over the global (sharded) values, so plain
    BatchNorm is already sync."""


class MaxPool3D(Layer):
    """reference: sparse/nn/layer/pooling.py MaxPool3D."""

    def __init__(self, kernel_size, stride=None, padding=0, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding

    def forward(self, x):
        return functional.max_pool3d(x, self.kernel_size, self.stride,
                                     self.padding)


def _conv3d_impl(x, w, bias, stride, padding, dilation, subm):
    """Shared core of layer + functional sparse conv3d (NDHWC dense-conv
    resample — see module docstring for the TPU rationale)."""
    s = _bcoo(x)
    dense = s._bcoo.todense()  # [N, D, H, W, C]
    stride = stride if isinstance(stride, (tuple, list)) else (stride,) * 3
    pad = padding
    if isinstance(pad, int):
        pad = [(pad, pad)] * 3
    elif pad and isinstance(pad[0], int):
        pad = [(p, p) for p in pad]
    out = jax.lax.conv_general_dilated(
        dense, w, window_strides=tuple(stride), padding=pad,
        rhs_dilation=(dilation,) * 3
        if isinstance(dilation, int) else tuple(dilation),
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
    if bias is not None:
        out = out + bias
    if subm:
        # submanifold: output support == input support (spatial indices
        # carry over; channels are a trailing dense dim)
        spatial = s._bcoo.indices
        vals = out[tuple(spatial.T)]  # [nnz, Cout]
        return _wrap(jsparse.BCOO((vals, spatial), shape=tuple(out.shape)))
    return _wrap(jsparse.BCOO.fromdense(out, n_dense=1))


class _SparseConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__()
        from ...nn import initializer as I

        ks = kernel_size if isinstance(kernel_size, (tuple, list)) \
            else (kernel_size,) * 3
        self.ks = tuple(ks)
        self.stride = stride if isinstance(stride, (tuple, list)) \
            else (stride,) * 3
        self.padding = padding
        self.dilation = dilation
        self.subm = subm
        fan_in = in_channels * int(np.prod(ks))
        bound = 1.0 / np.sqrt(fan_in)
        from ... import ops as O

        self.weight = Parameter(O.uniform(
            list(self.ks) + [in_channels, out_channels],
            min=-bound, max=bound)._value)
        self.bias = Parameter(O.uniform(
            [out_channels], min=-bound, max=bound)._value) \
            if bias_attr is not False else None

    def forward(self, x):
        return _conv3d_impl(
            x, self.weight._value,
            self.bias._value if self.bias is not None else None,
            self.stride, self.padding, self.dilation, self.subm)


class Conv3D(_SparseConvNd):
    """reference: sparse/nn/layer/conv.py Conv3D (NDHWC sparse input)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=False,
                         bias_attr=bias_attr)


class SubmConv3D(_SparseConvNd):
    """reference: sparse/nn/layer/conv.py SubmConv3D — output sparsity
    pattern equals the input's (submanifold convolution)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=True,
                         bias_attr=bias_attr)


# `functional` as a REAL importable submodule (reference layout:
# sparse/nn/functional/) — imported last so functional.py can read the
# staticmethod holder and _conv3d_impl defined above; this rebinding
# replaces the class attribute with the module of the same surface
from . import functional  # noqa: E402,F401
