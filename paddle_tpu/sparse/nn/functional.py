"""paddle.sparse.nn.functional (reference:
python/paddle/sparse/nn/functional/__init__.py — conv3d, subm_conv3d,
max_pool3d, relu, relu6, leaky_relu, softmax, attention).

Importable as a real module (``import paddle.sparse.nn.functional``),
not just an attribute — loaded at the END of the parent package so the
implementations above it are fully defined.
"""
import sys

from ...ops._apply import ensure_tensor

_parent = sys.modules[__package__]
# the staticmethod holder defined in the parent (before this module
# rebinds the `functional` name to itself)
_impl = _parent.functional

relu = _impl.relu
relu6 = _impl.relu6
leaky_relu = _impl.leaky_relu
softmax = _impl.softmax
attention = _impl.attention
max_pool3d = _impl.max_pool3d


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NDHWC", name=None):
    """reference: sparse/nn/functional/conv.py conv3d — weight
    [kd, kh, kw, Cin, Cout], NDHWC sparse input."""
    w = ensure_tensor(weight)._value
    b = ensure_tensor(bias)._value if bias is not None else None
    return _parent._conv3d_impl(x, w, b, stride, padding, dilation,
                                subm=False)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """reference: sparse/nn/functional/conv.py subm_conv3d — output
    sparsity pattern equals the input's."""
    w = ensure_tensor(weight)._value
    b = ensure_tensor(bias)._value if bias is not None else None
    return _parent._conv3d_impl(x, w, b, stride, padding, dilation,
                                subm=True)


__all__ = ["conv3d", "subm_conv3d", "max_pool3d", "relu", "relu6",
           "leaky_relu", "softmax", "attention"]
