"""paddle.sparse parity package over jax.experimental.sparse.

Reference parity: python/paddle/sparse/ — creation (sparse_coo_tensor
:creation.py, sparse_csr_tensor), unary value-ops (unary.py), binary ops
(binary.py: mv/matmul/masked_matmul/add/subtract/multiply/divide),
addmm (multiary.py), and the nn layer/functional tier (sparse/nn).

TPU-native: the storage is BCOO/BCSR (jax.experimental.sparse) — XLA-
compilable batched-COO with gather/scatter lowering; ``matmul`` lowers to
``bcoo_dot_general`` and ``masked_matmul`` to the SDDMM primitive
``bcoo_dot_general_sampled`` (the reference's cuSPARSE SDDMM counterpart,
phi/kernels/sparse/gpu/masked_matmul). Structure-preserving unary ops map
over ``.values()`` exactly like the reference's sparse kernels.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..ops._apply import apply_op, ensure_tensor
from ..tensor import Tensor
from . import nn  # noqa: F401

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "is_same_shape",
    # unary
    "sin", "tan", "asin", "atan", "sinh", "tanh", "asinh", "atanh", "sqrt",
    "square", "log1p", "abs", "pow", "cast", "neg", "coalesce", "deg2rad",
    "rad2deg", "expm1", "transpose", "reshape", "isnan",
    # binary / multiary
    "mv", "matmul", "masked_matmul", "add", "subtract", "multiply", "divide",
    "addmm",
]


class SparseCooTensor:
    """COO sparse tensor over a BCOO payload (reference:
    phi/core/sparse_coo_tensor.h + python sparse_coo_tensor)."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo
        self.stop_gradient = True

    # -- paddle Tensor-ish surface ------------------------------------------
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return str(self._bcoo.data.dtype)

    @property
    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self) -> Tensor:
        return Tensor(self._bcoo.indices.T, stop_gradient=True)  # [ndim, nnz]

    def values(self) -> Tensor:
        return Tensor(self._bcoo.data, stop_gradient=self.stop_gradient)

    def to_dense(self) -> Tensor:
        return Tensor(self._bcoo.todense(),
                      stop_gradient=self.stop_gradient)

    def to_sparse_csr(self) -> "SparseCsrTensor":
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(
            self._bcoo.sum_duplicates(nse=self._bcoo.nse)))

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(self._bcoo.sum_duplicates(nse=self._bcoo.nse))

    def numpy(self):
        return np.asarray(self._bcoo.todense())

    def astype(self, dtype):
        from .. import dtypes

        dt = dtypes.convert_dtype(dtype)
        return SparseCooTensor(jsparse.BCOO(
            (self._bcoo.data.astype(dt), self._bcoo.indices),
            shape=self._bcoo.shape))

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR sparse tensor over BCSR (reference: sparse_csr_tensor.h)."""

    def __init__(self, bcsr: jsparse.BCSR):
        self._bcsr = bcsr
        self.stop_gradient = True

    @property
    def shape(self):
        return list(self._bcsr.shape)

    @property
    def dtype(self):
        return str(self._bcsr.data.dtype)

    @property
    def nnz(self):
        return int(self._bcsr.nse)

    def crows(self) -> Tensor:
        return Tensor(self._bcsr.indptr, stop_gradient=True)

    def cols(self) -> Tensor:
        return Tensor(self._bcsr.indices, stop_gradient=True)

    def values(self) -> Tensor:
        return Tensor(self._bcsr.data, stop_gradient=self.stop_gradient)

    def to_dense(self) -> Tensor:
        return Tensor(self._bcsr.todense(), stop_gradient=self.stop_gradient)

    def to_sparse_coo(self, sparse_dim=None) -> SparseCooTensor:
        return SparseCooTensor(self._bcsr.to_bcoo())

    def numpy(self):
        return np.asarray(self._bcsr.todense())

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


# ------------------------------------------------------------------- creation
def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True) -> SparseCooTensor:
    """reference: sparse/creation.py sparse_coo_tensor — indices [ndim, nnz],
    values [nnz, ...dense dims].

    Examples:
        >>> t = paddle.sparse.sparse_coo_tensor(
        ...     [[0, 1, 2], [1, 2, 0]], [1.0, 2.0, 3.0], shape=[3, 3])
        >>> t.shape
        [3, 3]
        >>> float(t.to_dense()[1][2])
        2.0
    """
    idx = np.asarray(indices.numpy() if isinstance(indices, Tensor)
                     else indices)
    vals = ensure_tensor(values)._value
    if dtype is not None:
        from .. import dtypes

        vals = vals.astype(dtypes.convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1)) \
            + tuple(vals.shape[1:])
    t = SparseCooTensor(jsparse.BCOO(
        (jnp.asarray(vals), jnp.asarray(idx.T)), shape=tuple(shape)))
    t.stop_gradient = stop_gradient
    return t


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True) -> SparseCsrTensor:
    """reference: sparse/creation.py sparse_csr_tensor."""
    crows = np.asarray(crows.numpy() if isinstance(crows, Tensor) else crows)
    cols = np.asarray(cols.numpy() if isinstance(cols, Tensor) else cols)
    vals = ensure_tensor(values)._value
    if dtype is not None:
        from .. import dtypes

        vals = vals.astype(dtypes.convert_dtype(dtype))
    t = SparseCsrTensor(jsparse.BCSR(
        (jnp.asarray(vals), jnp.asarray(cols), jnp.asarray(crows)),
        shape=tuple(shape)))
    t.stop_gradient = stop_gradient
    return t


def _coo(x) -> SparseCooTensor:
    if isinstance(x, SparseCooTensor):
        return x
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    raise TypeError(f"expected a sparse tensor, got {type(x).__name__}")


def is_same_shape(x, y) -> bool:
    """reference: sparse/binary.py is_same_shape."""
    return list(x.shape) == list(y.shape)


# ------------------------------------------------------- unary (value-mapped)
def _unary_factory(fn, name):
    def op(x, name_=None):
        s = _coo(x)
        out = jsparse.BCOO((fn(s._bcoo.data), s._bcoo.indices),
                           shape=s._bcoo.shape)
        r = SparseCooTensor(out)
        r.stop_gradient = s.stop_gradient
        return r

    op.__name__ = name
    op.__doc__ = f"reference: sparse/unary.py {name} — maps over values."
    return op


sin = _unary_factory(jnp.sin, "sin")
tan = _unary_factory(jnp.tan, "tan")
asin = _unary_factory(jnp.arcsin, "asin")
atan = _unary_factory(jnp.arctan, "atan")
sinh = _unary_factory(jnp.sinh, "sinh")
tanh = _unary_factory(jnp.tanh, "tanh")
asinh = _unary_factory(jnp.arcsinh, "asinh")
atanh = _unary_factory(jnp.arctanh, "atanh")
sqrt = _unary_factory(jnp.sqrt, "sqrt")
square = _unary_factory(jnp.square, "square")
log1p = _unary_factory(jnp.log1p, "log1p")
abs = _unary_factory(jnp.abs, "abs")
neg = _unary_factory(jnp.negative, "neg")
expm1 = _unary_factory(jnp.expm1, "expm1")
deg2rad = _unary_factory(jnp.deg2rad, "deg2rad")
rad2deg = _unary_factory(jnp.rad2deg, "rad2deg")
isnan = _unary_factory(jnp.isnan, "isnan")


def pow(x, factor, name=None):
    """reference: sparse/unary.py pow."""
    s = _coo(x)
    return SparseCooTensor(jsparse.BCOO(
        (jnp.power(s._bcoo.data, factor), s._bcoo.indices),
        shape=s._bcoo.shape))


def cast(x, index_dtype=None, value_dtype=None, name=None):
    """reference: sparse/unary.py cast."""
    from .. import dtypes

    s = _coo(x)
    data, idx = s._bcoo.data, s._bcoo.indices
    if value_dtype is not None:
        data = data.astype(dtypes.convert_dtype(value_dtype))
    if index_dtype is not None:
        idx = idx.astype(dtypes.convert_dtype(index_dtype))
    return SparseCooTensor(jsparse.BCOO((data, idx), shape=s._bcoo.shape))


def coalesce(x, name=None):
    """reference: sparse/unary.py coalesce — merge duplicate indices."""
    return _coo(x).coalesce()


def transpose(x, perm: Sequence[int], name=None):
    """reference: sparse/unary.py transpose."""
    s = _coo(x)
    return SparseCooTensor(s._bcoo.transpose(tuple(perm)))

def reshape(x, shape: Sequence[int], name=None):
    """reference: sparse/unary.py reshape."""
    s = _coo(x)
    return SparseCooTensor(s._bcoo.reshape(tuple(int(d) for d in shape)))


# ------------------------------------------------------------------- binary
def matmul(x, y, name=None):
    """reference: sparse/binary.py matmul — sparse @ dense (spmm) lowering
    to bcoo_dot_general; sparse @ sparse returns sparse."""
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)) \
            and isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        xs, ys = _coo(x)._bcoo, _coo(y)._bcoo
        out = jsparse.bcoo_dot_general(
            xs, ys, dimension_numbers=(((xs.ndim - 1,), (0,)), ((), ())))
        return SparseCooTensor(out)
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        xs = _coo(x)._bcoo
        yv = ensure_tensor(y)
        return apply_op(
            lambda yd: jsparse.bcoo_dot_general(
                xs, yd,
                dimension_numbers=(((xs.ndim - 1,), (0,)), ((), ()))),
            [yv], name="sparse_matmul")
    # dense @ sparse (2-D only: compute (y^T @ x^T)^T via spmm)
    ys = _coo(y)._bcoo
    xv = ensure_tensor(x)
    if ys.ndim != 2 or len(xv.shape) != 2:
        raise NotImplementedError(
            "dense @ sparse matmul supports 2-D operands; batched layouts "
            "need sparse @ dense (bcoo_dot_general) instead")
    return apply_op(
        lambda xd: jsparse.bcoo_dot_general(
            ys.transpose((1, 0)), xd.T,
            dimension_numbers=(((1,), (0,)), ((), ()))).T,
        [xv], name="dense_sparse_matmul")


def mv(x, vec, name=None):
    """reference: sparse/binary.py mv — sparse matrix × dense vector."""
    xs = _coo(x)._bcoo
    v = ensure_tensor(vec)
    return apply_op(
        lambda vd: jsparse.bcoo_dot_general(
            xs, vd, dimension_numbers=(((xs.ndim - 1,), (0,)), ((), ()))),
        [v], name="sparse_mv")


def masked_matmul(x, y, mask, name=None):
    """reference: sparse/binary.py masked_matmul — SDDMM: (x @ y) sampled at
    mask's sparsity (bcoo_dot_general_sampled; cuSPARSE SDDMM counterpart)."""
    m = _coo(mask)._bcoo
    xv, yv = ensure_tensor(x), ensure_tensor(y)

    def fn(xd, yd):
        out = jsparse.bcoo_dot_general_sampled(
            xd, yd, m.indices, dimension_numbers=(((1,), (0,)), ((), ())))
        return out

    vals = apply_op(fn, [xv, yv], name="masked_matmul")
    return SparseCooTensor(jsparse.BCOO(
        (vals._value, m.indices), shape=m.shape))


def _ewise(fn, x, y, name):
    xs, ys = _coo(x), _coo(y)
    if list(xs.shape) != list(ys.shape):
        raise ValueError(f"{name}: shapes {xs.shape} vs {ys.shape} differ")
    # union of patterns: concat indices, apply fn to aligned dense-free rep
    # via BCOO addition identities. add/sub are native; mul/div go through
    # the pattern union with zero-fill semantics.
    if fn in ("add", "sub"):
        data = ys._bcoo.data if fn == "add" else -ys._bcoo.data
        merged = jsparse.BCOO(
            (jnp.concatenate([xs._bcoo.data, data]),
             jnp.concatenate([xs._bcoo.indices, ys._bcoo.indices])),
            shape=xs._bcoo.shape)
        return SparseCooTensor(merged.sum_duplicates(nse=merged.nse))
    raise AssertionError(fn)


def add(x, y, name=None):
    """reference: sparse/binary.py add."""
    return _ewise("add", x, y, "add")


def subtract(x, y, name=None):
    """reference: sparse/binary.py subtract."""
    return _ewise("sub", x, y, "subtract")


def multiply(x, y, name=None):
    """reference: sparse/binary.py multiply — elementwise; result support is
    the intersection of patterns (zero elsewhere)."""
    xs, ys = _coo(x).coalesce(), _coo(y).coalesce()
    yd = ys._bcoo.todense()
    vals = xs._bcoo.data * yd[tuple(xs._bcoo.indices.T)]
    return SparseCooTensor(jsparse.BCOO(
        (vals, xs._bcoo.indices), shape=xs._bcoo.shape))


def divide(x, y, name=None):
    """reference: sparse/binary.py divide (y's zeros yield inf/nan like the
    reference's dense-math semantics)."""
    xs, ys = _coo(x).coalesce(), _coo(y).coalesce()
    yd = ys._bcoo.todense()
    vals = xs._bcoo.data / yd[tuple(xs._bcoo.indices.T)]
    return SparseCooTensor(jsparse.BCOO(
        (vals, xs._bcoo.indices), shape=xs._bcoo.shape))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """reference: sparse/multiary.py addmm — beta*input + alpha*(x@y)."""
    prod = matmul(x, y)
    if isinstance(prod, SparseCooTensor):
        prod = prod.to_dense()
    inp = input.to_dense() if isinstance(
        input, (SparseCooTensor, SparseCsrTensor)) else ensure_tensor(input)
    return beta * inp + alpha * prod
