"""Nested-structure helpers (reference: utils/layers_utils.py flatten /
map_structure / pack_sequence_as), backed by jax.tree_util."""
from __future__ import annotations

import jax

__all__ = ["flatten", "map_structure", "pack_sequence_as"]


def flatten(nest):
    leaves, _ = jax.tree_util.tree_flatten(nest)
    return leaves


def map_structure(func, *structures):
    return jax.tree_util.tree_map(func, *structures)


def pack_sequence_as(structure, flat_sequence):
    treedef = jax.tree_util.tree_structure(structure)
    return jax.tree_util.tree_unflatten(treedef, flat_sequence)
