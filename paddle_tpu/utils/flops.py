"""Model FLOPs counting (reference: utils/flops.py — per-op handler
table over the static program). TPU redesign: trace the layer with jax
and read XLA's own cost model (``lower().cost_analysis()``) — exact for
whatever the compiler will actually run, no per-op table to maintain.
"""
from __future__ import annotations

from typing import Sequence

import numpy as np

__all__ = ["flops"]


def flops(net, input_size: Sequence[int], custom_ops=None,
          print_detail: bool = False) -> int:
    """Analytic FLOPs of ``net`` on inputs of ``input_size`` (including
    batch dim). Returns total FLOPs for one forward pass."""
    import jax

    from ..tensor import Tensor

    def forward(x):
        from ..autograd import no_grad

        with no_grad():
            out = net(Tensor(x))
        return out._value if isinstance(out, Tensor) else out

    x = jax.ShapeDtypeStruct(tuple(int(s) for s in input_size), np.float32)
    lowered = jax.jit(forward).lower(x)
    cost = lowered.cost_analysis()
    if isinstance(cost, list):  # older jax returns [dict]
        cost = cost[0] if cost else {}
    total = int(cost.get("flops", 0))
    if print_detail:
        bytes_ = int(cost.get("bytes accessed", 0))
        print(f"FLOPs: {total:,}  bytes accessed: {bytes_:,} "
              f"(XLA cost analysis)")
    return total
