"""paddle.utils — dlpack interop, unique_name, deprecation, install check,
flops (reference: python/paddle/utils/)."""
from . import dlpack  # noqa: F401
from . import unique_name  # noqa: F401
from .deprecated import deprecated  # noqa: F401
from .download import get_weights_path_from_url  # noqa: F401
from .flops import flops  # noqa: F401
from .install_check import run_check  # noqa: F401
from .layers_utils import flatten, map_structure, pack_sequence_as  # noqa: F401

__all__ = ["dlpack", "unique_name", "deprecated", "flops", "run_check",
           "get_weights_path_from_url", "flatten", "map_structure",
           "pack_sequence_as"]
