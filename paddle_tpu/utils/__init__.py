"""paddle.utils — dlpack interop, unique_name, deprecation, install check,
flops (reference: python/paddle/utils/)."""
from . import dlpack  # noqa: F401
from . import unique_name  # noqa: F401
from .deprecated import deprecated  # noqa: F401
from .download import get_weights_path_from_url  # noqa: F401
from .flops import flops  # noqa: F401
from .install_check import run_check  # noqa: F401
from .layers_utils import flatten, map_structure, pack_sequence_as  # noqa: F401

__all__ = ["dlpack", "unique_name", "deprecated", "flops", "run_check",
           "get_weights_path_from_url", "flatten", "map_structure",
           "pack_sequence_as", "require_version", "try_import"]


def try_import(module_name, err_msg=None):
    """Import a module with an informative install hint on failure
    (reference: utils/lazy_import.py try_import)."""
    import importlib

    install_name = module_name.split(".")[0]
    if module_name == "cv2":
        install_name = "opencv-python"
    try:
        return importlib.import_module(module_name)
    except ImportError:
        if err_msg is None:
            err_msg = (f"Failed importing {module_name}. This likely means "
                       f"that some paddle modules require additional "
                       f"dependencies that have to be manually installed "
                       f"(usually with `pip install {install_name}`).")
        raise ImportError(err_msg)


def _parse_version(v, what):
    import re

    m = re.match(r"\d+(\.\d+){0,3}", v)
    if m is None or m.group() != v:
        raise ValueError(
            f"The value of '{what}' in require_version must be in format "
            f"'\\d+(\\.\\d+){{0,3}}', like '1.5.2.0', but received {v}")
    parts = [int(p) for p in v.split(".")]
    return parts + [0] * (4 - len(parts))


def require_version(min_version, max_version=None):
    """Raise unless the installed version is within [min_version,
    max_version] (reference: fluid/framework.py require_version)."""
    if not isinstance(min_version, str):
        raise TypeError("The type of 'min_version' in require_version must "
                        f"be str, but received {type(min_version)}.")
    if not isinstance(max_version, (str, type(None))):
        raise TypeError("The type of 'max_version' in require_version must "
                        f"be str or type(None), but received "
                        f"{type(max_version)}.")
    lo = _parse_version(min_version, "min_version")
    hi = _parse_version(max_version, "max_version") if max_version else None
    from ..version import major, minor, patch, rc

    cur = [int(major), int(minor), int(patch), int(rc)]
    if cur < lo or (hi is not None and cur > hi):
        bound = (f"in [{min_version}, {max_version}]" if max_version
                 else f">= {min_version}")
        raise Exception(
            f"VersionError: paddle-tpu version {'.'.join(map(str, cur))} "
            f"does not satisfy the requirement {bound}.")
