"""DLPack zero-copy tensor interop (reference: utils/dlpack.py:27,64).

``to_dlpack`` exports a paddle Tensor as a DLPack capsule; ``from_dlpack``
imports a capsule (or any object with ``__dlpack__``, e.g. a torch or
numpy tensor) as a paddle Tensor. On CPU the exchange is zero-copy;
device buffers go through jax's dlpack bridge.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    if not isinstance(x, Tensor):
        raise TypeError(f"to_dlpack expects a paddle Tensor, got {type(x)}")
    return x._value.__dlpack__()


class _CapsuleWrapper:
    """Adapts a raw DLPack capsule to the ``__dlpack__`` protocol newer
    jax consumes (capsules are single-use; wrap-and-import immediately)."""

    def __init__(self, capsule):
        self._capsule = capsule

    def __dlpack__(self, **kwargs):
        return self._capsule

    def __dlpack_device__(self):
        return (1, 0)  # kDLCPU; device capsules import via __dlpack__ objects


def from_dlpack(dlpack) -> Tensor:
    if hasattr(dlpack, "__dlpack__"):
        arr = jnp.from_dlpack(dlpack)
    else:  # raw capsule
        arr = jnp.from_dlpack(_CapsuleWrapper(dlpack))
    return Tensor(arr, stop_gradient=True)
