"""paddle.utils.cpp_extension (reference:
python/paddle/utils/cpp_extension/__init__.py — CppExtension,
CUDAExtension, load, setup, get_build_directory).

TPU-native redesign: the reference JIT-compiles custom C++/CUDA
*operators* against libpaddle and imports them as python ops. Here the
operator set is jax/XLA primitives (custom device code is Pallas — see
ops/pallas/), so the C++ extension story is the C-ABI one the rest of
the runtime uses (native/__init__.py): ``load`` compiles C++ sources
with the in-image toolchain into a shared object and returns a
``ctypes.CDLL``. ``setup``/``CppExtension`` delegate to setuptools for
wheel-time builds. ``CUDAExtension`` raises — this build has no CUDA.
"""
import os
import subprocess
import tempfile

__all__ = ["CppExtension", "CUDAExtension", "load", "setup",
           "get_build_directory"]


def get_build_directory(verbose=False):
    """Build dir for JIT-compiled extensions (reference:
    cpp_extension/extension_utils.py get_build_directory —
    PADDLE_EXTENSION_DIR wins, else a per-user cache dir)."""
    root = os.environ.get("PADDLE_EXTENSION_DIR")
    if not root:
        root = os.path.join(os.path.expanduser("~"), ".cache",
                            "paddle_tpu_extensions")
    os.makedirs(root, exist_ok=True)
    return root


def CppExtension(sources, *args, **kwargs):
    """setuptools.Extension preconfigured for C++ (reference:
    cpp_extension.py CppExtension). ``name`` is taken from kwargs or
    defaults like the reference's setup() contract."""
    from setuptools import Extension

    name = kwargs.pop("name", "paddle_tpu_ext")
    kwargs.setdefault("language", "c++")
    extra = kwargs.setdefault("extra_compile_args", [])
    if "-std=c++17" not in extra:
        extra.append("-std=c++17")
    return Extension(name, sources, *args, **kwargs)


def CUDAExtension(sources, *args, **kwargs):
    raise RuntimeError(
        "CUDAExtension is not available: this is the TPU-native build "
        "(no CUDA toolchain). Device code belongs in Pallas kernels "
        "(paddle_tpu/ops/pallas) — use CppExtension/load for host-side "
        "C++ components.")


def setup(**attrs):
    """setuptools.setup pass-through with the reference's ext_modules
    contract (reference: cpp_extension.py setup)."""
    from setuptools import setup as _setup

    return _setup(**attrs)


def load(name, sources, extra_cxx_cflags=None, extra_ldflags=None,
         extra_include_paths=None, build_directory=None, verbose=False,
         **unused):
    """JIT-compile C++ ``sources`` into ``lib<name>.so`` and dlopen it
    (reference: cpp_extension.py load). Returns a ``ctypes.CDLL`` of the
    C ABI — the TPU build's custom-op surface is jax-level, so there is
    no generated python-op module to import (see module docstring)."""
    import ctypes

    out_dir = build_directory or get_build_directory()
    os.makedirs(out_dir, exist_ok=True)
    out_path = os.path.join(out_dir, f"lib{name}.so")
    srcs = [sources] if isinstance(sources, str) else list(sources)
    stale = (not os.path.exists(out_path)
             or any(os.path.getmtime(s) > os.path.getmtime(out_path)
                    for s in srcs))
    if stale:
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=out_dir)
        os.close(fd)
        cmd = (["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread"]
               + [f"-I{p}" for p in (extra_include_paths or [])]
               + (extra_cxx_cflags or []) + srcs
               + ["-o", tmp] + (extra_ldflags or []))
        if verbose:
            print(" ".join(cmd))
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"cpp_extension build of {name} failed:\n"
                    f"{proc.stderr[-4000:]}")
            os.replace(tmp, out_path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    return ctypes.CDLL(out_path)
