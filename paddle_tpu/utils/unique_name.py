"""Unique name generator (reference: utils/unique_name.py — the
UniqueNameGenerator behind every auto-named parameter/op, with
``guard``/``switch`` for test isolation)."""
from __future__ import annotations

import contextlib
from collections import defaultdict

__all__ = ["generate", "switch", "guard"]


class UniqueNameGenerator:
    def __init__(self, prefix: str = ""):
        self.prefix = prefix
        self.ids: dict = defaultdict(int)

    def __call__(self, key: str) -> str:
        tmp = self.ids[key]
        self.ids[key] += 1
        return "_".join([self.prefix + key, str(tmp)])


_generator = UniqueNameGenerator()


def generate(key: str) -> str:
    return _generator(key)


def switch(new_generator: UniqueNameGenerator = None) -> UniqueNameGenerator:
    global _generator
    old = _generator
    _generator = new_generator or UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    if isinstance(new_generator, str):
        new_generator = UniqueNameGenerator(new_generator)
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
