"""Weights download helper (reference: utils/download.py).

This environment has zero egress, so remote fetches cannot happen; the
function honors an already-populated local cache (PADDLE_TPU_WEIGHTS_DIR
or ~/.cache/paddle_tpu/weights) and raises a clear error otherwise —
matching the vision models' documented offline-weights contract.
"""
from __future__ import annotations

import os

__all__ = ["get_weights_path_from_url"]


def _cache_dir() -> str:
    return os.environ.get(
        "PADDLE_TPU_WEIGHTS_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                     "weights"))


def get_weights_path_from_url(url: str, md5sum: str = None) -> str:
    fname = os.path.basename(url.split("?")[0])
    path = os.path.join(_cache_dir(), fname)
    if os.path.exists(path):
        return path
    raise FileNotFoundError(
        f"pretrained weights {fname!r} not in local cache {_cache_dir()!r} "
        "and this environment has no network egress; place the file there "
        "or set PADDLE_TPU_WEIGHTS_DIR")
