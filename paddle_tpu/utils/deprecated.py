"""@deprecated decorator (reference: utils/deprecated.py)."""
from __future__ import annotations

import functools
import warnings

__all__ = ["deprecated"]


def deprecated(update_to: str = "", since: str = "", reason: str = "",
               level: int = 1):
    """Mark an API deprecated: warns (level 1), or raises (level 2)."""

    def decorator(func):
        msg = f"API {func.__module__}.{func.__name__} is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f"; use {update_to} instead"
        if reason:
            msg += f" ({reason})"

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            if level == 2:
                raise RuntimeError(msg)
            if level >= 1:
                warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)

        wrapper.__deprecated_message__ = msg
        return wrapper

    return decorator
