"""Installation self-check (reference: utils/install_check.py run_check —
a tiny train step on one device, then on all visible devices)."""
from __future__ import annotations

__all__ = ["run_check"]


def run_check() -> None:
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn

    devs = jax.devices()
    print(f"Running verify PaddlePaddle(TPU) program... "
          f"({len(devs)} x {devs[0].platform})")

    paddle.seed(0)
    lin = nn.Linear(4, 4)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    loss = (lin(x) ** 2).mean()
    loss.backward()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
    opt.step()
    float(loss.numpy())  # force execution
    print("PaddlePaddle(TPU) works well on 1 device.")

    if len(devs) > 1:
        from paddle_tpu.distributed.sharding_api import shard_tensor
        from paddle_tpu.distributed.topology import create_mesh

        mesh = create_mesh({"dp": len(devs)})
        xt = paddle.to_tensor(np.ones((len(devs) * 2, 4), np.float32))
        xs = shard_tensor(xt, mesh, ["dp", None])
        ((lin(xs) ** 2).mean()).numpy()
        print(f"PaddlePaddle(TPU) works well on {len(devs)} devices.")
    print("PaddlePaddle(TPU) is installed successfully!")
