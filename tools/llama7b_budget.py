#!/usr/bin/env python
"""Llama-2 7B compile-only memory budget (BASELINE.md config 4).

Proves the 7B flagship FITS and COMPILES on a v5e-8-shaped mesh without
needing 8 real chips: builds the real ``LlamaForCausalLM`` at full 7B
shapes (zero-init — no RNG cost; values never matter because nothing
executes), applies the production recipe (ZeRO-3 ``p_g_os`` sharding +
per-layer recompute + fused chunked linear+CE + bf16 O2 master weights),
AOT-lowers the FULL train step through ``StaticFunction.lower()`` on an
8-virtual-device CPU mesh, and reads XLA's own buffer-assignment peak
(``compiled.memory_analysis().peak_memory_in_bytes`` — per device under
SPMD) plus a closed-form analytic table.

Reference counterpart: the reference proves 7B feasibility by running it
(Fleet 4D, BASELINE.md item 4); on TPU the compile-only route is exact
for the memory question because XLA's buffer assignment IS the runtime
allocator (no dynamic allocation at step time).

Usage (env is scrubbed + re-exec'd automatically):
    python tools/llama7b_budget.py              # full 7B, ~8 virtual chips
    python tools/llama7b_budget.py --smoke      # tiny shapes, CI-speed
Writes LLAMA7B_BUDGET.md + prints one JSON line; exits nonzero if the
per-chip peak exceeds --hbm-gb (default 16, v5e).
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

V5E_HBM_GB = 16.0


def _reexec_scrubbed(n_devices: int) -> None:
    from _budget_common import reexec_scrubbed
    reexec_scrubbed("_LLAMA7B_BUDGET_CHILD", n_devices)


def _zero_init_parameters() -> None:
    from _budget_common import zero_init_parameters
    zero_init_parameters()


def _analytic_rows(n_params: int, n_layers: int, hidden: int, batch: int,
                   seq: int, shards: int):
    """Closed-form per-chip budget for ZeRO-3 + bf16 O2 + recompute.
    Activation term: recompute stores only per-layer residual-stream
    boundaries (B*S*H bf16 each) + one layer's working set at backward."""
    rows = [
        ("params (fp32 master, ZeRO-3 sharded)", 4 * n_params / shards),
        ("params (bf16 compute copy, sharded)", 2 * n_params / shards),
        ("grads (bf16, reduce-scattered)", 2 * n_params / shards),
        ("adam m+v (fp32, sharded)", 8 * n_params / shards),
        ("residual boundaries (recompute)", 2 * batch * seq * hidden
         * n_layers),
        ("one-layer recompute working set (~6 B*S*H)",
         6 * 2 * batch * seq * hidden),
        ("all-gather buffer (largest layer, bf16)",
         2 * max(3 * hidden * 11008, 4 * hidden * hidden)),
    ]
    return rows


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes (CI validation of the flow)")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=4096)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--hbm-gb", type=float, default=V5E_HBM_GB)
    ap.add_argument("--no-write", action="store_true",
                    help="don't write LLAMA7B_BUDGET.md (smoke/CI)")
    args = ap.parse_args()
    _reexec_scrubbed(args.devices)

    import numpy as np

    _zero_init_parameters()

    import paddle_tpu as paddle
    from paddle_tpu import amp, jit
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.sharding import group_sharded_parallel
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 1, "mp_degree": 1, "pp_degree": 1, "sep_degree": 1,
        "sharding_degree": args.devices,
    }
    fleet.init(is_collective=True, strategy=strategy)

    if args.smoke:
        cfg = LlamaConfig(vocab_size=512, hidden_size=128, num_layers=2,
                          num_heads=4, num_key_value_heads=4,
                          max_position_embeddings=256,
                          recompute=True, fused_loss=True)
        batch, seq = 2, 128
    else:
        # Llama-2 7B (reference: llama-2-7b config.json — 32L/4096H/32H,
        # intermediate 11008, vocab 32000, ctx 4096)
        cfg = LlamaConfig(vocab_size=32000, hidden_size=4096, num_layers=32,
                          num_heads=32, num_key_value_heads=32,
                          intermediate_size=11008,
                          max_position_embeddings=args.seq,
                          recompute=True, fused_loss=True)
        batch, seq = args.batch, args.seq

    print(f"[budget] building model (zero-init, {args.devices}-dev mesh, "
          f"B{batch} S{seq})", flush=True)
    model = LlamaForCausalLM(cfg)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    print(f"[budget] params: {n_params/1e9:.3f} B", flush=True)

    opt = paddle.optimizer.AdamW(learning_rate=3e-4,
                                 parameters=model.parameters(),
                                 weight_decay=0.1)
    model, opt = amp.decorate(model, opt, level="O2", dtype="bfloat16")
    group_sharded_parallel(model, opt, "p_g_os")

    def train_fn(ids, labels):
        with amp.auto_cast(level="O2", dtype="bfloat16"):
            _, loss = model(ids, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = jit.StaticFunction(train_fn, observe=[model, opt], warmup=False)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (batch, seq)))
    labels = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (batch, seq)))

    print("[budget] AOT lowering full train step (no execution)...",
          flush=True)
    lowered = step.lower(ids, labels)
    print("[budget] compiling (XLA buffer assignment)...", flush=True)
    compiled = lowered.compile()
    ma = compiled.memory_analysis()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}

    peak = int(ma.peak_memory_in_bytes)
    gb = 1024 ** 3
    record = {
        "metric": "llama7b_per_chip_peak_hbm_gb" if not args.smoke
        else "llama_budget_smoke_peak_gb",
        "value": round(peak / gb, 2),
        "unit": "GiB/chip",
        "params_b": round(n_params / 1e9, 3),
        "config": f"zero3+recompute+fused_ce b{batch} s{seq} "
                  f"x{args.devices}dev",
        "argument_gb": round(ma.argument_size_in_bytes / gb, 2),
        "output_gb": round(ma.output_size_in_bytes / gb, 2),
        "temp_gb": round(ma.temp_size_in_bytes / gb, 2),
        "alias_gb": round(ma.alias_size_in_bytes / gb, 2),
        "flops_per_step": cost.get("flops"),
        "hbm_limit_gb": args.hbm_gb,
        "fits": peak / gb < args.hbm_gb,
    }
    print(json.dumps(record), flush=True)

    if not args.smoke and not args.no_write:
        rows = _analytic_rows(n_params, cfg.num_layers, cfg.hidden_size,
                              batch, seq, args.devices)
        lines = [
            "# Llama-2 7B per-chip memory budget (v5e-8, compile-only)",
            "",
            f"Recipe: ZeRO-3 (`p_g_os`) over sharding={args.devices}, "
            "per-layer recompute, fused chunked linear+CE (no [B*S,V] "
            f"logits), bf16 O2 master weights. B={batch}, S={seq}.",
            "",
            "## XLA buffer assignment (ground truth, per chip)",
            "",
            "| stat | GiB |",
            "|---|---|",
            f"| **peak** | **{peak/gb:.2f}** |",
            f"| arguments (params+opt state) | "
            f"{ma.argument_size_in_bytes/gb:.2f} |",
            f"| temps (activations, gathers) | "
            f"{ma.temp_size_in_bytes/gb:.2f} |",
            f"| outputs | {ma.output_size_in_bytes/gb:.2f} |",
            f"| aliased (donated state) | {ma.alias_size_in_bytes/gb:.2f} |",
            "",
            f"v5e HBM/chip: {args.hbm_gb:.0f} GiB -> "
            f"**{'FITS' if record['fits'] else 'DOES NOT FIT'}** "
            f"(headroom {args.hbm_gb - peak/gb:.1f} GiB).",
            "",
            "## Analytic cross-check (closed form)",
            "",
            "| component | GiB/chip |",
            "|---|---|",
        ]
        total = 0
        for name, b in rows:
            total += b
            lines.append(f"| {name} | {b/gb:.2f} |")
        lines += [
            f"| **sum** | **{total/gb:.2f}** |",
            "",
            "The analytic sum is the everything-live-at-once worst case; "
            "XLA's buffer liveness typically lands the true peak well "
            "below it (transient bf16 copies, grad buffers aliasing into "
            "the optimizer update). Temps total counts every temp "
            "allocation over the step, not the concurrent peak.",
            "",
            f"Params: {n_params/1e9:.3f} B. Generated by "
            "`tools/llama7b_budget.py` (StaticFunction.lower -> "
            "compiled.memory_analysis; per-device under SPMD).",
        ]
        out = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "LLAMA7B_BUDGET.md")
        with open(out, "w") as f:
            f.write("\n".join(lines) + "\n")
        print(f"[budget] wrote {out}", flush=True)

    return 0 if record["fits"] else 1


if __name__ == "__main__":
    sys.exit(main())
