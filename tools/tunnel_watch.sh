#!/usr/bin/env bash
# Probe the tunnel on a spaced cadence (killable subprocess probes, never
# stacked — the wedge discipline) and run the r4 rerun battery the moment
# a probe succeeds. One-shot: exits after the battery (or max probes).
set -uo pipefail
cd "$(dirname "$0")/.."

MAX_PROBES=${1:-40}
SLEEP_S=${2:-420}

for n in $(seq 1 "$MAX_PROBES"); do
  if timeout 140 python - <<'EOF'
import subprocess, sys
r = subprocess.run(
    [sys.executable, "-c", "import jax; d=jax.devices()[0]; "
     "assert d.platform in ('tpu','axon'); print('PROBE_OK')"],
    capture_output=True, text=True, timeout=120)
sys.exit(0 if (r.returncode == 0 and "PROBE_OK" in r.stdout) else 1)
EOF
  then
    echo "[watch] probe $n OK — running battery $(date -u +%H:%M:%S)"
    if bash tools/rerun_r04.sh 2>&1 | tail -80; then
      echo "[watch] battery done $(date -u +%H:%M:%S)"
      exit 0
    fi
    echo "[watch] battery FAILED $(date -u +%H:%M:%S)"
    exit 2
  fi
  echo "[watch] probe $n wedged $(date -u +%H:%M:%S); sleeping ${SLEEP_S}s"
  sleep "$SLEEP_S"
done
echo "[watch] gave up after $MAX_PROBES probes"
exit 1
