#!/usr/bin/env bash
# Probe the tunnel on a spaced cadence (killable subprocess probes, never
# stacked — the wedge discipline) and run the r5 battery the moment a
# probe succeeds. Battery exit 3 means "tunnel re-wedged mid-battery"
# (tools/rerun_r05.sh gate): resume probing — completed steps left
# done-markers, so the next window resumes where it stopped.
set -uo pipefail
cd "$(dirname "$0")/.."

MAX_PROBES=${1:-40}
SLEEP_S=${2:-420}

for n in $(seq 1 "$MAX_PROBES"); do
  if bash tools/probe_tunnel.sh; then
    echo "[watch] probe $n OK — running battery $(date -u +%H:%M:%S)"
    rc=0
    bash tools/rerun_r05.sh || rc=$?
    if [ "$rc" -eq 0 ]; then
      echo "[watch] battery done $(date -u +%H:%M:%S)"
      exit 0
    elif [ "$rc" -eq 3 ]; then
      echo "[watch] battery hit a re-wedge (rc=3) — resuming probes"
      sleep "$SLEEP_S"
      continue
    fi
    echo "[watch] battery FAILED rc=$rc $(date -u +%H:%M:%S)"
    exit 2
  fi
  echo "[watch] probe $n wedged $(date -u +%H:%M:%S); sleeping ${SLEEP_S}s"
  sleep "$SLEEP_S"
done
echo "[watch] gave up after $MAX_PROBES probes"
exit 1
