#!/usr/bin/env python
"""tpulint — static invariant linter for the paddle-tpu repo.

Enforces the invariants the runtime drills prove dynamically (rule
catalog: docs/ANALYSIS.md): TPL001 no-host-sync-in-compiled, TPL002
recompile hazards, TPL003/TPL004 metric & fault-point catalog parity
with the docs, TPL005 seeded determinism, TPL006 lock discipline,
TPL007 lock-order cycles, TPL008 check-then-act atomicity, TPL009
blocking-under-lock.

Usage:

  python tools/tpulint.py paddle_tpu tools examples
  python tools/tpulint.py --json paddle_tpu          # CI-diffable output
  python tools/tpulint.py --lock-graph paddle_tpu    # acquisition graph, DOT
  python tools/tpulint.py --write-baseline paddle_tpu tools examples

Exit codes: 0 clean (every finding baselined), 1 findings, 2 bad usage
or internal error. Inline suppression: ``# tpulint: disable=TPL001``
(comma list or ``all``) on the flagged line or a comment line above it.
The committed baseline (tools/tpulint_baseline.json) absorbs accepted
pre-existing findings; regenerate with --write-baseline and justify
every entry's ``note``.

The linter never imports paddle_tpu (or jax): the analysis package is
loaded standalone below, so tpulint still runs when the package import
is the thing that broke.
"""
from __future__ import annotations

import argparse
import importlib.util
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_DEFAULT_BASELINE = os.path.join(_REPO_ROOT, "tools",
                                 "tpulint_baseline.json")


def _load_analysis():
    """Import paddle_tpu.analysis WITHOUT executing paddle_tpu/__init__
    (which pulls jax): register the subpackage under a standalone name
    so its relative imports resolve against the synthetic package."""
    name = "_tpulint_analysis"
    if name in sys.modules:
        return sys.modules[name]
    pkg_dir = os.path.join(_REPO_ROOT, "paddle_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpulint", description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    default=["paddle_tpu", "tools", "examples"],
                    help="files or directories to lint (default: "
                         "paddle_tpu tools examples)")
    ap.add_argument("--root", default=_REPO_ROOT,
                    help="repo root (doc catalogs + relative paths)")
    ap.add_argument("--json", action="store_true",
                    help="stable JSON output (sorted, timestamp-free; "
                         "includes the lock acquisition graph)")
    ap.add_argument("--lock-graph", action="store_true",
                    help="print the declared-lock acquisition graph as "
                         "Graphviz DOT (cycle edges red) and exit; pipe "
                         "into `dot -Tsvg` to eyeball ordering")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default {_DEFAULT_BASELINE} "
                         f"when it exists)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept current findings into the baseline "
                         "file and exit 0 (then justify every note)")
    args = ap.parse_args(argv)

    try:
        analysis = _load_analysis()
    except Exception as e:     # pragma: no cover - loader failure path
        print(f"tpulint: cannot load paddle_tpu/analysis: {e}",
              file=sys.stderr)
        return 2

    config = analysis.LintConfig(root=args.root)
    try:
        result = analysis.lint_paths(args.paths, config)
    except (FileNotFoundError, ValueError) as e:
        print(f"tpulint: {e}", file=sys.stderr)
        return 2
    except Exception as e:   # the documented "internal error" exit —
        # a crash must stay distinguishable from "findings present"
        # for CI lanes branching on the code, and --json consumers
        # must never get a traceback where JSON was promised
        import traceback
        traceback.print_exc()
        print(f"tpulint: internal error: {e}", file=sys.stderr)
        return 2

    if args.lock_graph:
        graph = analysis.lock_graph_for(result.project)
        print(analysis.lock_graph_dot(graph), end="")
        # findings still gate the exit code: a red edge in the SVG and
        # a green CI lane must not disagree
        return 1 if any(f.rule == "TPL007" for f in result.findings) else 0

    baseline_path = args.baseline or (
        _DEFAULT_BASELINE if os.path.isfile(_DEFAULT_BASELINE) else None)
    if args.write_baseline:
        path = args.baseline or _DEFAULT_BASELINE
        analysis.write_baseline(path, result.findings)
        print(f"tpulint: wrote {len(result.findings)} finding(s) to "
              f"{os.path.relpath(path, args.root)} — justify every "
              f"entry's note")
        return 0

    entries = []
    if baseline_path and not args.no_baseline:
        try:
            entries = analysis.load_baseline(baseline_path)
        except (OSError, ValueError) as e:
            print(f"tpulint: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
    new, baselined = analysis.split_baseline(result.findings, entries)
    result.baselined = len(baselined)

    if args.json:
        print(analysis.to_json(result, new,
                               analysis.lock_graph_for(result.project)))
    else:
        print(analysis.to_text(result, new))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
