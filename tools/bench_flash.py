#!/usr/bin/env python
"""Flash-attention A/B: Pallas kernel (block-size sweep) vs XLA fused
attention, fwd and fwd+bwd, S ∈ {512, 1024, 2048, 4096} (VERDICT r2 #2).

Run ON the TPU (no env scrubbing). Appends one JSON line per (S, impl,
blocks, direction) to BENCH_NOTES_r05.json and prints a summary table to
stderr, plus a final recommendation line: the measured per-S dispatch
threshold for nn/functional/attention.py's pallas_flash_min_seq.

Usage: python tools/bench_flash.py [--quick]
"""
import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np

_NOTES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                      "BENCH_NOTES_r05.json")


def _log(m):
    print(m, file=sys.stderr, flush=True)


def _persist(rec):
    rec = dict(rec, ts=time.strftime("%Y-%m-%dT%H:%M:%S"))
    with open(_NOTES, "a") as f:
        f.write(json.dumps(rec) + "\n")


from _bench_timing import bench_chained  # noqa: E402  (shared clock — both
#   A/B harnesses must time identically; see _bench_timing.py)


def _bench(step, q, k, v, iters=32, reps=3):
    """Time `step` (a (q,k,v)->array-of-q's-shape fn); see _bench_timing."""
    t, _ = bench_chained(lambda qq, k, v: step(qq, k, v), q, (k, v),
                         iters=iters, reps=reps, log=_log)
    return t


def _load_banked(notes_path, D):
    """Banked flash_ab_summary entries for head dim D from the notes file:
    (banked_rec {str(S): entry}, banked_reps {int(S): reps}). Newest row
    wins per S — a --force re-measure deliberately supersedes older rows —
    and rows without a reps field gate at 0 (never satisfy a skip)."""
    from _bench_timing import iter_notes_rows

    banked_rec, banked_reps = {}, {}
    for row in iter_notes_rows(notes_path):
        if (row.get("metric") == "flash_ab_summary"
                and row.get("device") in ("tpu", "axon")
                and row.get("D", 64) == D):
            for s, entry in row.get("per_seq", {}).items():
                banked_rec[s] = entry
                banked_reps[int(s)] = row.get("reps", 0)
    return banked_rec, banked_reps


def _summarize_s(results, S):
    """Best-pallas-vs-xla summary entry for one S from the timing dict, or
    None when either side is missing (e.g. every pallas block failed)."""
    xla = results.get((S, "xla", None))
    if xla is None:
        return None
    pl_best = None
    for (s2, impl, blk), (tf, tb) in results.items():
        if s2 == S and impl == "pallas" and (
                pl_best is None or tb < pl_best[1][1]):
            pl_best = (blk, (tf, tb))
    if pl_best is None:
        return None
    win = pl_best[1][1] < xla[1]
    return {"xla_ms": round(xla[1] * 1e3, 2),
            "pallas_ms": round(pl_best[1][1] * 1e3, 2),
            "best_blocks": list(pl_best[0]), "pallas_wins": bool(win)}


def main():
    from _bench_timing import probe_or_exit

    # require_tpu: a CPU sweep would burn the battery's whole slot
    # producing numbers meaningless for dispatch thresholds
    probe_or_exit(240.0, log=_log)
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import flash_attention as fa

    quick = "--quick" in sys.argv
    argv = sys.argv
    # tie-break mode: --s 1024 --reps 9 restricts the sweep and raises
    # repetitions (the r4 sweeps' large-block S=1024 configs differed by
    # less than run-to-run noise at reps=3)
    only_s = (int(argv[argv.index("--s") + 1]) if "--s" in argv else None)
    reps = (int(argv[argv.index("--reps") + 1]) if "--reps" in argv else 3)
    # --d 128: the gpt13/llama head geometry (16 heads x 128) — block
    # timings at D=64 don't transfer (VMEM tile footprint doubles)
    only_d = (int(argv[argv.index("--d") + 1]) if "--d" in argv else None)
    dev = jax.devices()[0]
    on_tpu = dev.platform in ("tpu", "axon")
    _log(f"device: {dev.platform} (tpu={on_tpu})")
    if not on_tpu:  # backstop for the probe-passed-then-fell-back race
        _log("not on TPU — aborting (rc=2)")
        sys.exit(2)

    H, D = 16, 64  # flagship head geometry (GPT-355M: 16 heads x 64)
    if only_d is not None:
        D = only_d
    seqs = [1024] if quick else [512, 1024, 2048, 4096]
    if only_s is not None:
        seqs = [only_s]

    # resume: a re-run after a mid-sweep wedge must not re-measure (and
    # duplicate) already-banked S values — summary rows checkpoint PER S
    # as each completes; skip semantics live in _load_banked's docstring
    banked_rec, banked_reps = (
        _load_banked(_NOTES, D) if "--force" not in argv else ({}, {}))
    skip_s = {s for s, r in banked_reps.items() if r >= reps}
    if skip_s & set(seqs):
        _log(f"banked this round at reps>={reps} (skipping, --force to "
             f"re-measure): {sorted(skip_s & set(seqs))}")
    blocks = [(256, 512), (512, 512), (1024, 512), (512, 1024),
              (1024, 1024), (256, 1024)]
    causal, scale = True, 1.0 / np.sqrt(D)

    def xla_attn(q, k, v):
        # The PRODUCTION XLA path (attention._sdpa_ref): bf16 logits on the
        # MXU, f32 softmax. fa._ref_attention_bshd casts everything to f32 —
        # that is a numerics oracle, not a fair perf baseline (and its bwd
        # OOMs at S=2048: f32 [B,H,S,S] temps — measured r4).
        qh = jnp.swapaxes(q, 1, 2)
        kh = jnp.swapaxes(k, 1, 2)
        vh = jnp.swapaxes(v, 1, 2)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
        sq_, sk_ = logits.shape[-2], logits.shape[-1]
        cm = np.tril(np.ones((sq_, sk_), bool), sk_ - sq_)
        logits = jnp.where(jnp.asarray(cm), logits,
                           jnp.asarray(-1e30, logits.dtype))
        probs = jax.nn.softmax(logits.astype(jnp.float32), -1).astype(q.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
        return jnp.swapaxes(out, 1, 2)

    results = {}
    for S in seqs:
        if S in skip_s:
            continue
        B = max(1, 8 * 1024 // S)  # constant token budget ~8k
        rng = np.random.default_rng(0)
        mk = lambda: jnp.asarray(
            rng.standard_normal((B, S, H, D)), jnp.bfloat16)
        q, k, v = mk(), mk(), mk()

        def _chain_fwd(attn):
            def step(qq, k, v):
                o = attn(qq, k, v)
                return o / (jnp.max(jnp.abs(o.astype(jnp.float32)))
                            + 1e-6).astype(o.dtype)
            return step

        def _chain_bwd(attn):
            g = jax.grad(lambda qq, k, v: jnp.sum(
                attn(qq, k, v).astype(jnp.float32)), argnums=(0, 1, 2))

            def step(qq, k, v):
                # mix all three grads into the carry so none of the bwd
                # computation is dead code the compiler can strip
                dq, dk, dv = g(qq, k, v)
                mix = dq + 0.0625 * (dk + dv)
                return mix / (jnp.max(jnp.abs(mix.astype(jnp.float32)))
                              + 1e-6).astype(mix.dtype)
            return step

        # XLA reference, fwd and fwd+bwd
        t_fwd = _bench(_chain_fwd(xla_attn), q, k, v, reps=reps)
        t_bwd = _bench(_chain_bwd(xla_attn), q, k, v, reps=reps)
        results[(S, "xla", None)] = (t_fwd, t_bwd)
        _log(f"S={S} B={B} xla          fwd {t_fwd*1e3:7.2f}ms  "
             f"fwd+bwd {t_bwd*1e3:7.2f}ms")
        if on_tpu:
            _persist({"metric": "flash_ab", "impl": "xla", "S": S, "B": B, "H": H, "D": D,
                      "fwd_ms": round(t_fwd * 1e3, 2),
                      "fwdbwd_ms": round(t_bwd * 1e3, 2),
                      "device": dev.platform})

        for bq, bk in blocks:
            if bq > S or bk > S:
                continue

            def pallas_attn(q, k, v, _bq=bq, _bk=bk):
                return fa._flash_attention(q, k, v, jnp.float32(0), causal, scale, _bq, _bk)

            try:
                t_fwd = _bench(_chain_fwd(pallas_attn), q, k, v, reps=reps)
                t_bwd = _bench(_chain_bwd(pallas_attn), q, k, v, reps=reps)
            except Exception as e:
                _log(f"S={S} pallas bq{bq}/bk{bk} FAILED: "
                     f"{type(e).__name__}: {str(e)[:160]}")
                if on_tpu:
                    _persist({"metric": "flash_ab", "impl": "pallas",
                              "S": S, "bq": bq, "bk": bk,
                              "error": f"{type(e).__name__}: {str(e)[:300]}",
                              "device": dev.platform})
                continue
            results[(S, "pallas", (bq, bk))] = (t_fwd, t_bwd)
            _log(f"S={S} B={B} pallas {bq:4d}/{bk:<4d} fwd {t_fwd*1e3:7.2f}ms"
                 f"  fwd+bwd {t_bwd*1e3:7.2f}ms")
            if on_tpu:
                _persist({"metric": "flash_ab", "impl": "pallas", "S": S,
                          "B": B, "H": H, "D": D, "bq": bq, "bk": bk,
                          "fwd_ms": round(t_fwd * 1e3, 2),
                          "fwdbwd_ms": round(t_bwd * 1e3, 2),
                          "device": dev.platform})

        # checkpoint THIS S the moment it completes: a mid-sweep wedge
        # must not cost the next window the S values already measured
        entry = _summarize_s(results, S)
        if entry is not None and on_tpu:
            _persist({"metric": "flash_ab_summary", "per_seq": {S: entry},
                      "D": D, "reps": reps, "device": dev.platform})

    # recommendation: per S, best pallas config vs xla on fwd+bwd.
    # (The durable record is the per-S checkpoint rows persisted above —
    # nothing more is persisted here, so carried entries are never
    # re-dated and a partial run banks exactly what it measured.)
    _log("\n=== summary (fwd+bwd) ===")
    rec = {}
    for S in seqs:
        if S in skip_s:  # carry the banked row into this run's summary
            rec[S] = banked_rec[str(S)]
            _log(f"S={S}: (banked) xla {rec[S]['xla_ms']}ms vs pallas "
                 f"{rec[S]['pallas_ms']}ms @bq/bk={rec[S]['best_blocks']}")
            continue
        entry = _summarize_s(results, S)
        if entry is None:
            continue
        rec[S] = entry
        _log(f"S={S}: xla {entry['xla_ms']}ms vs pallas "
             f"{entry['pallas_ms']}ms @bq/bk={entry['best_blocks']} "
             f"-> {'PALLAS' if entry['pallas_wins'] else 'XLA'}")
    wins = sorted(s for s, r in rec.items() if r["pallas_wins"])
    threshold = wins[0] if wins else None
    _log(f"recommended pallas_flash_min_seq = {threshold}")
    print(json.dumps({"metric": "flash_ab_summary", "per_seq": rec,
                      "recommended_min_seq": threshold}))


if __name__ == "__main__":
    main()
