#!/usr/bin/env python
"""Digest BENCH_NOTES_r05.json into a human-readable summary: latest row
per metric, llama-bisect verdicts, flash A/B recommendations. The battery
runs it last so rerun_r05.log ends with the round's evidence at a glance.
"""
import json
import os
import sys

NOTES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "BENCH_NOTES_r05.json")


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else NOTES
    if not os.path.exists(path):
        print(f"no notes file at {path}")
        return 1
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                print(f"  (skipping malformed line: {line[:60]}...)")
    if not rows:
        print("notes file is empty")
        return 1

    print(f"=== digest of {os.path.basename(path)} ({len(rows)} rows) ===")

    # latest row per headline metric (TPU rows preferred)
    latest = {}
    for r in rows:
        m = r.get("metric")
        if m and m not in ("llama_bisect", "flash_ab", "flash_ab_summary"):
            latest[m] = r  # file is append-ordered: last wins
    for m in sorted(latest):
        r = latest[m]
        dev = r.get("device", "?")
        flag = " [CPU-FALLBACK]" if r.get("cpu_fallback") else ""
        mfu = r.get("mfu_vs_v5e_peak")
        mfu_s = f"  mfu={mfu:.2%}" if isinstance(mfu, (int, float)) else ""
        print(f"  {m}: {r.get('value')} {r.get('unit', '')} "
              f"({r.get('config', r.get('combo', ''))}, {dev}){mfu_s}{flag}")

    bisect = [r for r in rows if r.get("metric") == "llama_bisect"]
    if bisect:
        print(f"\n  llama_bisect: {len(bisect)} rows")
        for r in bisect:
            if r.get("probe") == "kernel_causality":
                print(f"    kernel D={r.get('D')}: err={r.get('err')} "
                      f"leak={r.get('leak')} "
                      f"{'OK' if r.get('ok') else 'FAIL'}")
            else:
                print(f"    traj[{r.get('tag')}]: first={r.get('first')} "
                      f"last={r.get('last')}")
    else:
        print("\n  llama_bisect: NO ROWS (quarantine unresolved)")

    summaries = [r for r in rows if r.get("metric") == "flash_ab_summary"]
    for r in summaries:
        print(f"\n  flash_ab_summary (D={r.get('D', 64)}): "
              f"min_seq={r.get('recommended_min_seq')} "
              f"per_seq={json.dumps(r.get('per_seq', {}))[:200]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
