#!/usr/bin/env python
"""Digest BENCH_NOTES_r05.json into a human-readable summary: latest row
per metric, llama-bisect verdicts, flash A/B recommendations. The battery
runs it last so rerun_r05.log ends with the round's evidence at a glance.
"""
import json
import os
import sys

NOTES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "BENCH_NOTES_r05.json")


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else NOTES
    if not os.path.exists(path):
        print(f"no notes file at {path}")
        return 1
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                print(f"  (skipping malformed line: {line[:60]}...)")
    if not rows:
        print("notes file is empty")
        return 1

    print(f"=== digest of {os.path.basename(path)} ({len(rows)} rows) ===")

    # BEST row per headline metric (ladders append every rung — the last
    # rung is rarely the best); a TPU row is never displaced by a CPU row
    # (local smokes/fallbacks append after real evidence)
    latest = {}
    for r in rows:
        m = r.get("metric")
        if m and m not in ("llama_bisect", "flash_ab", "flash_ab_summary"):
            prev = latest.get(m)
            r_tpu = r.get("device") in ("tpu", "axon")
            if prev is not None:
                p_tpu = prev.get("device") in ("tpu", "axon")
                if p_tpu and not r_tpu:
                    continue
                # best-by-value only for throughput metrics (ladders
                # append every rung); memory/size metrics (GiB — lower
                # is better, one row per combo) keep last-wins
                if (p_tpu == r_tpu
                        and r.get("unit") in ("tokens/s", "imgs/s")
                        and isinstance(prev.get("value"), (int, float))
                        and isinstance(r.get("value"), (int, float))
                        and r["value"] <= prev["value"]):
                    continue
            latest[m] = r
    for m in sorted(latest):
        r = latest[m]
        dev = r.get("device", "?")
        flag = " [CPU-FALLBACK]" if r.get("cpu_fallback") else ""
        mfu = r.get("mfu_vs_v5e_peak")
        mfu_s = f"  mfu={mfu:.2%}" if isinstance(mfu, (int, float)) else ""
        print(f"  {m}: {r.get('value')} {r.get('unit', '')} "
              f"({r.get('config', r.get('combo', ''))}, {dev}){mfu_s}{flag}")

    bisect = [r for r in rows if r.get("metric") == "llama_bisect"]
    if bisect:
        # a partial row is only news when no full trajectory row for the
        # same tag landed later (the partial is banked BEFORE the
        # discriminator evals; the full row supersedes it); multiple
        # bisect passes append duplicate rows — display the LAST per
        # (probe, tag/D) key so the digest shows one line per probe
        full_tags = {r.get("tag") for r in bisect
                     if r.get("probe") == "trajectory"}
        last_by_key = {}
        for r in bisect:
            last_by_key[(r.get("probe"), r.get("tag"), r.get("D"))] = r
        display = [r for r in bisect
                   if id(r) in set(map(id, last_by_key.values()))
                   and not (r.get("probe") == "trajectory_partial"
                            and r.get("tag") in full_tags)]
        print(f"\n  llama_bisect: {len(bisect)} rows "
              f"({len(display)} distinct probes shown)")
        for r in display:
            probe = r.get("probe")
            if probe == "kernel_causality":
                if r.get("error"):
                    print(f"    kernel: ERROR {r['error']}")
                else:
                    print(f"    kernel D={r.get('D')}: err={r.get('err')} "
                          f"leak={r.get('leak')} "
                          f"{'OK' if r.get('ok') else 'FAIL'}")
            elif probe == "verdict":
                status = "complete" if r.get("complete") else "INCOMPLETE"
                print(f"    VERDICT ({status}): {r.get('branch')}")
            elif probe == "trajectory_partial":
                print(f"    traj-partial[{r.get('tag')}]: "
                      f"first={r.get('first')} last={r.get('last')} "
                      f"(discriminator evals did not land)")
            elif r.get("error"):
                print(f"    traj[{r.get('tag')}]: ERROR {r['error']}")
            else:
                print(f"    traj[{r.get('tag')}]: first={r.get('first')} "
                      f"last={r.get('last')} "
                      f"fresh={r.get('loss_fresh_batch')} "
                      f"swap={r.get('loss_swapped_labels')} "
                      f"leak={r.get('input_leak')}")
        # a full trajectory row supersedes its partial twin — note overlap
    else:
        print("\n  llama_bisect: NO ROWS (quarantine unresolved)")

    # merge summary rows per D: bench_flash checkpoints per-S fragments
    # as each S completes (plus legacy whole-run rows) — display the union
    merged = {}
    for r in rows:
        if r.get("metric") != "flash_ab_summary":
            continue
        d = merged.setdefault(r.get("D", 64), {})
        for s, entry in r.get("per_seq", {}).items():
            d[int(s)] = entry
    for D in sorted(merged):
        per_seq = merged[D]
        wins = sorted(s for s, e in per_seq.items() if e.get("pallas_wins"))
        print(f"\n  flash_ab_summary (D={D}): "
              f"min_seq={wins[0] if wins else None} "
              f"per_seq={json.dumps({str(s): per_seq[s] for s in sorted(per_seq)})[:300]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
