#!/usr/bin/env python
"""Bisect the r4 llama-on-TPU loss anomaly (same-batch loss -> 0.0009).

r5 findings so far (BENCH_NOTES_r05.json `llama_bisect` rows):
  - kernel causality on REAL Mosaic: OK at D=64 and D=128 (leak 0.0)
  - plain-flash trajectory REPRODUCES the collapse (10.72 -> 0.038 in 10
    same-batch steps) — but the r4-era control probes OOM'd because all
    probes shared one process and the previous probe's ~10 GiB optimizer
    state was never freed.

This rewrite runs EVERY probe in its own subprocess (fresh chip memory),
and adds the two decisive leak discriminators to every trajectory probe:

  - fresh-batch eval after the 10 train steps: honest same-batch
    memorization leaves fresh-batch loss at the random floor (~ln 32000
    = 10.37); an architectural leak (forward pass reading the target)
    keeps it LOW, because the leak is input-wired, not weight-wired.
  - swapped-labels eval on the TRAINED batch: loss against arbitrary
    wrong labels. If loss tracks whatever labels are passed, the forward
    pass is reading the labels argument.

Probe axes (each isolates one suspect):
  plain-flash     Mosaic flash kernel        (reproduced the collapse)
  plain-noflash   XLA attention              (flash out of the loop)
  interp-flash    interpret-mode flash       (proven-causal kernel, same
                                              surrounding model code)
  fce-flash       fused chunked CE           (loss-path suspect)
  rc-fce-flash    + recompute                (the exact r4 bench config)
  nodonate-noflash  PADDLE_TPU_NO_DONATE=1   (donation is TPU-only;
                                              CPU ignores it)
  fp32-noflash    no amp O2                  (master-weight/cast path)
  sgd-flash       SGD instead of AdamW       (Adam-speed hypothesis: fast
                                              honest memorization)

Exit code 1 iff any probe ERRORS (cannot run). A collapsing trajectory
is an ANSWER, not a failure — the verdict row says which branch of the
ROUND5.md decision tree applies.
"""
import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np

_NOTES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                      "BENCH_NOTES_r05.json")

RANDOM_FLOOR = float(np.log(32000))  # ~10.37 nats
# a trajectory/eval loss this far below the random floor means the model is
# producing the target distribution (memorized or leaked), not exploring
COLLAPSE_T = RANDOM_FLOOR - 3.4  # ~7.0

# use_flash_attention=False alone is VACUOUS as a control: it routes to
# F.scaled_dot_product_attention, which itself dispatches to the Pallas
# flash kernel at S>=512 (nn/functional/attention.py:_use_pallas — the r4
# "flash is the production default" change). Caught r5 when the noflash
# trajectory matched flash to 4 decimals at every step. The env knob
# raises the dispatch threshold above S so sdpa stays on the XLA path.
_NO_FLASH_ENV = {"PADDLE_TPU_FLASH_MIN_SEQ": "99999"}

PROBES = {
    # tag -> (flash, rc, fce, env, optimizer)
    "plain-flash": dict(flash=True, rc=False, fce=False),
    "plain-noflash": dict(flash=False, rc=False, fce=False,
                          env=_NO_FLASH_ENV),
    "interp-flash": dict(flash=True, rc=False, fce=False,
                         env={"PADDLE_TPU_PALLAS_INTERPRET": "1"}),
    "fce-flash": dict(flash=True, rc=False, fce=True),
    "rc-fce-flash": dict(flash=True, rc=True, fce=True),
    "nodonate-noflash": dict(flash=False, rc=False, fce=False,
                             env={"PADDLE_TPU_NO_DONATE": "1",
                                  **_NO_FLASH_ENV}),
    "fp32-noflash": dict(flash=False, rc=False, fce=False, amp=False,
                         env=_NO_FLASH_ENV),
    "sgd-flash": dict(flash=True, rc=False, fce=False, opt="sgd"),
}


def _persist(rec):
    """Verdicts must survive pipe buffers and SIGKILL — append
    immediately (r4: a completed bisect's output was lost to a killed
    tail pipeline when the tunnel re-wedged)."""
    rec = dict(rec, metric="llama_bisect", ts=time.strftime("%H:%M:%S"))
    try:
        with open(_NOTES, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass


def probe_kernel_causality():
    """Child-mode only: importing jax claims the chip for this process's
    lifetime, so the parent must never call this in-process."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import flash_attention as fa

    bad = False
    rng = np.random.default_rng(0)
    for D in (64, 128):
        S = 1024
        q, k, v = (jnp.asarray(rng.standard_normal((2, S, 4, D)),
                               jnp.bfloat16) for _ in range(3))
        out = np.asarray(jax.device_get(
            fa.flash_attention_bshd(q, k, v, causal=True))).astype(np.float32)
        ref = np.asarray(jax.device_get(
            fa._ref_attention_bshd(q, k, v, True, 1.0 / np.sqrt(D)))
        ).astype(np.float32)
        err = float(np.max(np.abs(out - ref)))
        k2 = k.at[:, -1].add(100.0)
        out2 = np.asarray(jax.device_get(
            fa.flash_attention_bshd(q, k2, v, causal=True))).astype(np.float32)
        leak = float(np.max(np.abs((out2 - out)[:, :-1])))
        ok = err < 0.05 and leak < 1e-4
        bad = bad or not ok
        print(f"kernel D={D}: err_vs_ref={err:.4f} future_leak={leak:.6f} "
              f"{'OK' if ok else 'FAIL'}", flush=True)
        _persist({"probe": "kernel_causality", "D": D, "err": err,
                  "leak": leak, "ok": ok})
    return not bad


def llama_trajectory(tag, *, flash, rc, fce, amp_on=True, opt_name="adamw",
                     steps=10):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import amp, jit
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    device = jax.devices()[0].platform
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048, num_layers=12,
                      num_heads=16, num_key_value_heads=16,
                      max_position_embeddings=1024,
                      use_flash_attention=flash, recompute=rc,
                      fused_loss=fce)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if opt_name == "sgd":
        opt = paddle.optimizer.SGD(learning_rate=1e-4,
                                   parameters=model.parameters())
    else:
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())
    if amp_on:
        model, opt = amp.decorate(model, opt, level="O2", dtype="bfloat16")

    def _loss(ids, labels):
        if amp_on:
            with amp.auto_cast(level="O2", dtype="bfloat16"):
                _, loss = model(ids, labels=labels)
        else:
            _, loss = model(ids, labels=labels)
        return loss

    def train_fn(ids, labels):
        loss = _loss(ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = jit.StaticFunction(train_fn, observe=[model, opt], warmup=False)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, 32000, (2, 1024)))
    labels = paddle.to_tensor(np.roll(np.asarray(ids.numpy()), -1, axis=1))
    losses = []
    for _ in range(steps):
        l = step(ids, labels)
        losses.append(float(np.asarray(l.numpy(), dtype="float32")))

    # bank the expensive part (10 jitted chip steps) BEFORE the eager
    # discriminator evals — if those fail, the trajectory must survive;
    # the full row below supersedes this one (last-wins in _already_done)
    _persist({"probe": "trajectory_partial", "tag": tag, "device": device,
              "first": round(losses[0], 4), "last": round(losses[-1], 5),
              "traj": [round(x, 3) for x in losses]})

    # decisive discriminators: weights-vs-input leakage (eager eval, no
    # update). fresh = new batch; swap = trained inputs, arbitrary labels.
    with paddle.no_grad():
        fids = paddle.to_tensor(rng.integers(0, 32000, (2, 1024)))
        flabels = paddle.to_tensor(
            np.roll(np.asarray(fids.numpy()), -1, axis=1))
        loss_fresh = float(np.asarray(
            _loss(fids, flabels).numpy(), dtype="float32"))
        wrong = paddle.to_tensor(rng.integers(0, 32000, (2, 1024)))
        loss_swap = float(np.asarray(
            _loss(ids, wrong).numpy(), dtype="float32"))

    # routing ground truth, persisted so a stale row banked under the
    # WRONG routing (r5: the pre-fix vacuous noflash control) can never
    # satisfy _already_done for a tag that demands the other routing
    no_flash_routing = (not flash) and int(
        os.environ.get("PADDLE_TPU_FLASH_MIN_SEQ", "512")) > 1024

    collapsed = losses[-1] < COLLAPSE_T
    # weight-wired memorization: fresh stays at the random floor and
    # arbitrary labels score WORSE than floor (model confidently predicts
    # the trained continuation, not whatever labels are passed)
    leak_fresh = loss_fresh < COLLAPSE_T
    leak_swap = loss_swap < COLLAPSE_T
    print(f"llama[{tag}]: first={losses[0]:.3f} last={losses[-1]:.4f} "
          f"fresh={loss_fresh:.3f} swap={loss_swap:.3f} "
          f"traj={[round(x, 2) for x in losses]}", flush=True)
    _persist({"probe": "trajectory", "tag": tag, "device": device,
              "first": round(losses[0], 4), "last": round(losses[-1], 5),
              "loss_fresh_batch": round(loss_fresh, 4),
              "loss_swapped_labels": round(loss_swap, 4),
              "collapsed": collapsed, "input_leak": leak_fresh or leak_swap,
              "no_flash_routing": no_flash_routing,
              "traj": [round(x, 3) for x in losses]})
    return {"tag": tag, "last": losses[-1], "fresh": loss_fresh,
            "swap": loss_swap, "collapsed": collapsed,
            "input_leak": leak_fresh or leak_swap}


_consecutive_timeouts = 0


def _run_child(tag, timeout_s=1500):
    """One probe, one subprocess, one fresh chip claim. Tracks consecutive
    timeouts so a wedged tunnel (every chip claim hangs) aborts the probe
    sequence instead of burning timeout_s per remaining probe."""
    global _consecutive_timeouts
    spec = PROBES[tag]
    env = dict(os.environ)
    env.update(spec.get("env", {}))
    cmd = [sys.executable, os.path.abspath(__file__), "--probe", tag]
    print(f"--- probe {tag} (subprocess) ---", flush=True)
    try:
        r = subprocess.run(cmd, env=env, timeout=timeout_s)
        _consecutive_timeouts = 0
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        _consecutive_timeouts += 1
        print(f"llama[{tag}]: TIMEOUT {timeout_s}s", flush=True)
        _persist({"probe": "trajectory", "tag": tag, "error": "timeout"})
        return False


def _child_main(tag):
    spec = PROBES[tag]
    # direct --probe invocation must behave like the parent's dispatch:
    # the tag's distinguishing env (interpret mode, no-donate) applies
    # here too, not only via subprocess env inheritance
    os.environ.update(spec.get("env", {}))
    try:
        llama_trajectory(tag, flash=spec["flash"], rc=spec["rc"],
                         fce=spec["fce"], amp_on=spec.get("amp", True),
                         opt_name=spec.get("opt", "adamw"))
        return 0
    except Exception as e:  # noqa: BLE001 — a probe that cannot run must
        #                     still persist the reason before exiting
        msg = f"{type(e).__name__}: {str(e)[:200]}"
        print(f"llama[{tag}]: ERROR {msg}", flush=True)
        _persist({"probe": "trajectory", "tag": tag, "error": msg})
        return 1


def _already_done(tag):
    """The LAST banked probe row with the discriminator fields (append-only
    file: later rows supersede earlier ones, e.g. after a --force re-run)."""
    found = None
    try:
        with open(_NOTES) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if (rec.get("metric") == "llama_bisect"
                        and rec.get("probe") == "trajectory"
                        and rec.get("tag") == tag
                        and "loss_fresh_batch" in rec
                        # this bisects a TPU-only anomaly: rows banked by a
                        # CPU-fallback run (donation ignored, Mosaic never
                        # lowered) must not satisfy a TPU verdict
                        and rec.get("device") in ("tpu", "axon")
                        # a *-noflash tag demands a row proven to have run
                        # with flash dispatch OFF (and vice versa) — guards
                        # against rows banked under wrong/vacuous routing.
                        # Missing field (rows predating the check) defaults
                        # False: flash rows stay valid, unproven noflash
                        # rows are rejected and re-run.
                        and rec.get("no_flash_routing", False)
                        == ("noflash" in tag)):
                    found = rec
    except OSError:
        pass
    return found


def _norm(rec):
    """Uniform probe-result shape for verdict logic, from a banked row."""
    if not rec:
        return None
    return {"last": rec.get("last"), "fresh": rec.get("loss_fresh_batch"),
            "swap": rec.get("loss_swapped_labels"),
            "collapsed": rec.get("collapsed"),
            "input_leak": rec.get("input_leak")}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--probe", help="child mode: run one probe in-process")
    ap.add_argument("--force", action="store_true",
                    help="re-run probes that already have banked rows")
    args = ap.parse_args()
    if args.probe == "kernel":
        sys.exit(0 if probe_kernel_causality() else 1)
    if args.probe:
        sys.exit(_child_main(args.probe))

    # the parent NEVER imports jax — every probe (kernel included) runs in
    # its own subprocess so each gets a fresh, fully-released chip claim
    print("--- probe kernel (subprocess) ---", flush=True)
    try:
        ok = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--probe", "kernel"],
            timeout=600).returncode == 0
    except subprocess.TimeoutExpired:
        print("kernel probe: TIMEOUT", flush=True)
        _persist({"probe": "kernel_causality", "error": "timeout"})
        ok = False
    core = ["plain-flash", "plain-noflash", "interp-flash", "fce-flash",
            "rc-fce-flash"]
    results = {}

    def _run_fresh(tag):
        """Run the probe; only accept a row NEWER than what existed before
        (a forced/failed re-run must never fall back to the stale row)."""
        nonlocal ok
        prev = _already_done(tag)
        ok = _run_child(tag) and ok
        cur = _already_done(tag)
        return _norm(cur) if cur != prev else None

    for tag in core:
        done = None if args.force else _already_done(tag)
        if done:
            print(f"llama[{tag}]: already banked "
                  f"(last={done['last']} fresh={done['loss_fresh_batch']})",
                  flush=True)
            results[tag] = _norm(done)
            continue
        if _consecutive_timeouts >= 2:
            print(f"llama[{tag}]: SKIPPED — 2 consecutive probe timeouts "
                  "(wedged-tunnel signature); aborting the sequence",
                  flush=True)
            continue
        results[tag] = _run_fresh(tag)

    # conditional discriminators: only needed if the collapse survives
    # with flash out of the loop (model-level branch)
    def _run_conditional(tag):
        done = None if args.force else _already_done(tag)
        if done:
            return _norm(done)
        if _consecutive_timeouts >= 2:  # wedge abort covers these too
            print(f"llama[{tag}]: SKIPPED — wedged-tunnel abort", flush=True)
            return None
        return _run_fresh(tag)

    nf = results.get("plain-noflash") or {}
    if nf.get("collapsed"):
        for tag in ["nodonate-noflash", "fp32-noflash"]:
            results[tag] = _run_conditional(tag)
    pf = results.get("plain-flash") or {}
    if pf.get("collapsed") and not pf.get("input_leak"):
        # collapse without input leakage = honest memorization speed; the
        # sgd probe quantifies how much of that speed is Adam
        results["sgd-flash"] = _run_conditional("sgd-flash")

    # verdict: which branch of the ROUND5.md decision tree. A missing core
    # row (probe errored/timed out) means NO verdict — never un-quarantine
    # on partial evidence.
    complete = all(results.get(t) for t in core)
    any_input_leak = any((r or {}).get("input_leak") for r in results.values())
    flash_only = (pf.get("collapsed", False)
                  and not (nf.get("collapsed", True)))
    all_collapse = complete and all(results[t].get("collapsed")
                                    for t in core)
    if not complete:
        missing = [t for t in core if not results.get(t)]
        branch = f"INCOMPLETE: no verdict — probes missing rows: {missing}"
    elif any_input_leak:
        branch = "INPUT-LEAK: forward pass reads the target (real bug)"
    elif flash_only:
        branch = ("FLASH-ONLY collapse without input leak: Mosaic-lowering "
                  "numerics accelerate memorization; compare interp-flash")
    elif all_collapse:
        branch = ("ALL configs collapse, fresh-batch loss at floor: honest "
                  "same-batch memorization (h2048 + Adam is fast); the r4 "
                  "'anomaly' threshold was mis-calibrated — un-quarantine")
    else:
        branch = "MIXED: read the per-probe rows"
    print(f"VERDICT: {branch}", flush=True)
    _persist({"probe": "verdict", "branch": branch, "complete": complete,
              "probes": results})
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
