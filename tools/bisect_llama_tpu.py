#!/usr/bin/env python
"""Bisect the r4 llama-on-TPU loss anomaly (loss -> 0.0009 in 10 steps).

Interpret-mode flash is causal at D=128 (tests/test_flash_attention.py::
test_causality_no_future_leak), so the suspects are real-Mosaic behavior
or a model-level TPU-only interaction. Runs, in order, each in this one
process (run it under timeout; it claims the chip once):

  1. kernel causality probe on REAL hardware, D=64 and D=128
  2. tiny-step llama trajectories: plain vs rc vs fce vs rc+fce at B2
     (fits without remat), flash on vs off

Prints one verdict line per probe. Exit code 1 if any probe fails.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np

_NOTES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                      "BENCH_NOTES_r05.json")


def _persist(rec):
    """Verdicts must survive pipe buffers and SIGKILL — append
    immediately (r4: a completed bisect's output was lost to a killed
    tail pipeline when the tunnel re-wedged)."""
    rec = dict(rec, metric="llama_bisect", ts=time.strftime("%H:%M:%S"))
    try:
        with open(_NOTES, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass


def probe_kernel_causality():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import flash_attention as fa

    bad = False
    rng = np.random.default_rng(0)
    for D in (64, 128):
        S = 1024
        q, k, v = (jnp.asarray(rng.standard_normal((2, S, 4, D)),
                               jnp.bfloat16) for _ in range(3))
        out = np.asarray(jax.device_get(
            fa.flash_attention_bshd(q, k, v, causal=True))).astype(np.float32)
        ref = np.asarray(jax.device_get(
            fa._ref_attention_bshd(q, k, v, True, 1.0 / np.sqrt(D)))
        ).astype(np.float32)
        err = float(np.max(np.abs(out - ref)))
        k2 = k.at[:, -1].add(100.0)
        out2 = np.asarray(jax.device_get(
            fa.flash_attention_bshd(q, k2, v, causal=True))).astype(np.float32)
        leak = float(np.max(np.abs((out2 - out)[:, :-1])))
        ok = err < 0.05 and leak < 1e-4
        bad = bad or not ok
        print(f"kernel D={D}: err_vs_ref={err:.4f} future_leak={leak:.6f} "
              f"{'OK' if ok else 'FAIL'}", flush=True)
        _persist({"probe": "kernel_causality", "D": D, "err": err,
                  "leak": leak, "ok": ok})
    return not bad


def llama_trajectory(tag, *, flash, rc, fce, steps=10):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import amp, jit
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048, num_layers=12,
                      num_heads=16, num_key_value_heads=16,
                      max_position_embeddings=1024,
                      use_flash_attention=flash, recompute=rc,
                      fused_loss=fce)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    model, opt = amp.decorate(model, opt, level="O2", dtype="bfloat16")

    def train_fn(ids, labels):
        with amp.auto_cast(level="O2", dtype="bfloat16"):
            _, loss = model(ids, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = jit.StaticFunction(train_fn, observe=[model, opt], warmup=False)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, 32000, (2, 1024)))
    labels = paddle.to_tensor(np.roll(np.asarray(ids.numpy()), -1, axis=1))
    losses = []
    for _ in range(steps):
        l = step(ids, labels)
        losses.append(float(np.asarray(l.numpy(), dtype="float32")))
    print(f"llama[{tag}]: first={losses[0]:.3f} last={losses[-1]:.4f} "
          f"traj={[round(x, 2) for x in losses]}", flush=True)
    _persist({"probe": "trajectory", "tag": tag,
              "first": round(losses[0], 4), "last": round(losses[-1], 5),
              "traj": [round(x, 3) for x in losses]})
    # random-token CE floor is ~ln(32000)=10.37; losing >3 nats in 10
    # same-batch steps at lr 1e-4 means the model is reading the answer
    return losses[-1] > 7.0


def main():
    ok = probe_kernel_causality()
    for tag, kw in [
        ("plain-flash", dict(flash=True, rc=False, fce=False)),
        ("plain-noflash", dict(flash=False, rc=False, fce=False)),
        ("fce-flash", dict(flash=True, rc=False, fce=True)),
        ("rc-fce-flash", dict(flash=True, rc=True, fce=True)),
    ]:
        try:
            ok = llama_trajectory(tag, **kw) and ok
        except Exception as e:
            print(f"llama[{tag}]: ERROR {type(e).__name__}: {str(e)[:160]}",
                  flush=True)
            ok = False  # a probe that cannot run is a failed bisect, not
            #             a pass — exit code must say so
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
