#!/usr/bin/env python
"""Chaos drill for the serving stack: run the demo engine under a seeded
fault schedule and print a pass/fail resilience report.

The operational twin of tests/test_faults.py + tests/test_router.py
(docs/RESILIENCE.md): scenarios 1-6 arm ``paddle_tpu.faults`` injections
against a tiny llama engine — NaN quarantine, page-pool exhaustion,
compile-failure retry, deadline expiry + cancellation, queue
backpressure, watchdog trip + ``/healthz`` — and scenarios 7-10 drill the
ROUTER control plane: a NaN-poisoned + degraded engine fails its waiting
work over to a sibling exactly once (no duplicates, no drops), a rolling
``reload()`` across live traffic completes every request and lands every
engine on the new checkpoint's weights with the decode program still
compiled exactly once per engine, least-loaded dispatch beats blind
round-robin on p95 queue wait under skewed load, and a seeded
kill-engine-mid-decode drill (scenario 10): the busiest engine dies at a
scheduled step under sampled streaming traffic, ``router.step()``
contains the crash, and every in-flight request MIGRATES by token
journal — final streams bit-identical to an uninterrupted run, zero
duplicated or missing stream chunks. Scenario 11 re-runs the kill drill
under PREFIX-HEAVY traffic: migrated requests must re-prefill through the
adoptive sibling's radix prefix cache (``prefill_tokens_saved_total``
rises there), still bit-identical and exactly-once. Scenario 12 kills
the busiest engine BETWEEN PROMPT CHUNKS of a long request (ISSUE 11):
chunked-prefill progress is only a cache length, so the mid-prefill
request migrates with an empty journal, resumes from its chunk boundary
through the sibling's prefix cache, and streams bit-identically from
seq 0 — chunks exactly-once. Scenario 13 thread-fuzzes the control
plane under ``faults.LockSanitizer``: a driver thread (submit / step /
rolling reload), a /metrics+/healthz scraper and a health()/states()
prober race through 200 barrier-synced, seed-jittered iterations with
the router / registry / probe-cache / watchdog locks instrumented —
zero lock-order or reentrancy violations allowed, fleet must end
consistent. Scenario 14 re-runs the kill drill under SPECULATIVE
decoding (ISSUE 14): both replicas draft with spec_k=3 — one tenant
bursting at 100% acceptance, one fed always-rejected garbage — the
busiest engine dies between bursts, and every migrated journal must
carry only committed tokens (never an unaccepted draft), with final
streams bit-identical to a spec-off lone engine and chunks
exactly-once. Scenario 15 replays a seeded Poisson-burst loadgen trace
(ISSUE 15) against a 1-engine fleet with the queue-depth autoscaler
attached: the burst must scale the fleet up (new engines materialize
their pinned step shape from the persistent compile cache with ZERO
fresh compiles) and the post-burst cold signal must drain-then-remove
back to exactly 1 engine — every trace request completing or retiring
``"unavailable"`` exactly-once, no leaked pages or move-once marks.
Scenario 16 kills the busiest engine mid-stream under MULTI-LoRA +
CONSTRAINED traffic (ISSUE 16): every request decodes through a
hot-loaded adapter slot AND a grammar DFA mask, the migration journal
carries the per-request FSM state, and the adoptive sibling must resume
the grammar walk mid-structure — final streams bit-identical to an
uninterrupted lone-engine run, every output grammar-valid, chunks
exactly-once, grammar mask segments fully released afterward.
Scenario 17 re-runs the kill drill with the FLIGHT RECORDER under test
(ISSUE 17): the always-armed trace ring must auto-dump the last window
of fleet timeline from crash containment — the dumped file carries the
victim requests' full per-request timelines with the export → adopt
migration hop visible and every ``(req_id, seq)`` exactly-once across
the hop — while the streams stay bit-identical to an uninterrupted run.
Scenario 18 re-runs the kill drill with the HOST KV TIER armed
(ISSUE 18): int8 quantized pages on a page-starved pool, the victim
stream PARKED (its pages in host RAM) at the kill — containment must
drain the dead engine's HostPageStore, the adoptive (equally starved)
sibling must re-serve both migrants through its own park/unpark cycle,
and the streams stay bit-identical with chunks exactly-once.
Scenario 19 drills OVERLOAD as a first-class failure mode (ISSUE 19): a
16x tiered burst against a capacity-capped fleet under a step-latency
storm plus an engine kill, with the OverloadController armed — the
brownout ladder must climb to batch-slot preemption (journal + requeue,
the migration move turned inward), the deadline-aware gate must shed
doomed work at admission, and afterwards the ladder must return to
level 0 with every request accounted exactly-once, zero leaked pages,
and the one compiled step untouched.
Scenario 20 kills the PROCESS, not an engine (ISSUE 20): a WAL-armed
fleet serves a seeded loadgen trace in a CHILD python, the parent
SIGKILLs it mid-decode and restarts it with one engine fewer —
``Router.recover`` must replay the request WAL, re-admit every
unfinished stream through the journaled re-prefill path, resume
emission after the exact seq the client's chunk file proves delivered,
and complete every stream bit-identical to an uninterrupted reference
run with zero duplicate/missing seqs and ZERO fresh XLA compiles
during recovery (the shared disk compile cache).
Each scenario asserts both the behavior
AND the telemetry (every failure path must move its counter). Exit
code 0 iff every scenario passes.

Run: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python tools/chaos_serve.py
CI:  the whole ladder also runs as tests/test_chaos_serve.py (slow lane).
"""
import json
import os
import shutil
import sys
import tempfile
import urllib.error
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu import faults, metrics  # noqa: E402
from paddle_tpu.checkpoint import CheckpointManager  # noqa: E402
from paddle_tpu.models import LlamaForCausalLM, llama_tiny  # noqa: E402
from paddle_tpu.serving import (BackpressureError, GrammarFSM,  # noqa: E402
                                Router, ServingEngine, random_adapter,
                                toy_tokenizer)

SEED = int(os.environ.get("CHAOS_SEED", "0"))


def _model():
    paddle.seed(SEED)
    return LlamaForCausalLM(llama_tiny(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        num_key_value_heads=2, max_position_embeddings=64))


def _counter(name, **labels):
    fam = metrics.get_registry().get(name)
    if fam is None:
        return 0.0
    if labels and set(labels) != set(fam.label_names):
        # partial label set: aggregate the unnamed dimensions (e.g.
        # jit_compiles_total{fn=...} summed across its source split)
        return fam.sum_labels(**labels)
    return (fam.labels(**labels) if labels else fam).value


def _check(cond, what):
    if not cond:
        raise AssertionError(what)


_RNG = np.random.RandomState(7)
P5, P9, P3, P4 = (_RNG.randint(0, 128, (n,)) for n in (5, 9, 3, 4))


def scenario_nan_quarantine(model):
    """NaN in one sequence's KV: victim quarantined, mate token-identical
    to a fault-free run, pages recover, decode compiles once."""
    ref_eng = ServingEngine(model, page_size=4, max_batch_slots=2)
    rm = ref_eng.add_request(P5, max_new_tokens=8)
    ref_eng.add_request(P9, max_new_tokens=8)
    ref = ref_eng.run()

    before = _counter("paddle_tpu_serving_nan_quarantines_total")
    eng = ServingEngine(model, page_size=4, max_batch_slots=2)
    mate = eng.add_request(P5, max_new_tokens=8)
    victim = eng.add_request(P9, max_new_tokens=8)
    eng.step()
    with faults.inject("serving.decode_step",
                       call=lambda: eng.pool.poison_seq(victim),
                       times=1, seed=SEED):
        outs = eng.run()
    _check(outs[victim].finish_reason == "nan", "victim not quarantined")
    _check(list(outs[mate].token_ids) == list(ref[rm].token_ids),
           "batch-mate tokens diverged from fault-free run")
    _check(eng.pool.used_pages == 0, "pages leaked")
    _check(_counter("paddle_tpu_serving_nan_quarantines_total")
           == before + 1, "quarantine counter")
    counts = eng.compile_counts()
    _check(counts["step"] == counts["step_buckets"], "step recompiled")
    return (f"victim n_gen={outs[victim].n_gen} reason=nan; mate "
            f"token-identical ({outs[mate].n_gen} tokens)")


def scenario_pool_exhaustion(model):
    """One injected allocation failure mid-decode: victim errors out,
    everything else (including queued work) drains."""
    eng = ServingEngine(model, page_size=4, max_batch_slots=2)
    # the 4-token prompt exactly fills its prefill page, so ITS first
    # decode append draws the armed page — it is the victim
    victim = eng.add_request(P4, max_new_tokens=6)
    mate = eng.add_request(P3, max_new_tokens=6)
    queued = eng.add_request(P3, max_new_tokens=4)
    eng.step()
    with faults.inject("serving.kv_alloc",
                       raise_=faults.ResourceExhausted, times=1, seed=SEED):
        outs = eng.run()
    _check(outs[victim].finish_reason == "error", "victim not quarantined")
    _check(outs[mate].finish_reason == "length", "mate was disturbed")
    _check(outs[queued].finish_reason == "length", "queued work stranded")
    _check(eng.pool.used_pages == 0, "pages leaked")
    return "victim=error, mate+queued drained, 0 pages leaked"


def scenario_compile_retry(model):
    """A transient step-build failure is retried; buckets still compile
    exactly once each."""
    eng = ServingEngine(model, page_size=4, max_batch_slots=1)
    rid = eng.add_request(P4, max_new_tokens=3)
    before = _counter("paddle_tpu_faults_retries_total")
    with faults.inject("serving.compile_step",
                       raise_=RuntimeError("flaky build"), times=1,
                       seed=SEED):
        outs = eng.run()
    _check(outs[rid].finish_reason == "length", "request failed")
    _check(_counter("paddle_tpu_faults_retries_total") > before,
           "no retry recorded")
    counts = eng.compile_counts()
    _check(counts["step"] == counts["step_buckets"], "step recompiled")
    return "1 injected build failure, 1 retry, step compiled once/bucket"


def scenario_deadline_and_cancel(model):
    """Deadline expiry and cancel() retire with their own reasons and
    counters; pages free immediately. A deadline that lapses while still
    QUEUED retires ``"expired"`` (pages never allocated) — only admitted
    work can ``"timeout"`` (ISSUE 19)."""
    eng = ServingEngine(model, page_size=4, max_batch_slots=1)
    t_before = _counter("paddle_tpu_serving_request_timeouts_total")
    e_before = _counter("paddle_tpu_serving_expired_total")
    c_before = _counter("paddle_tpu_serving_cancellations_total")
    running = eng.add_request(P4, max_new_tokens=6)
    late = eng.add_request(P3, max_new_tokens=6, deadline_s=0.0)
    eng.step()
    cancelled = eng.add_request(P3, max_new_tokens=6)
    eng.cancel(cancelled)
    eng.slots[0].req.deadline = faults.Deadline(-1.0)  # force mid-decode
    outs = eng.run()
    _check(outs[late].finish_reason == "expired", "queued expiry")
    _check(outs[running].finish_reason == "timeout", "mid-decode timeout")
    _check(outs[cancelled].finish_reason == "cancelled", "cancel")
    _check(_counter("paddle_tpu_serving_request_timeouts_total")
           == t_before + 1, "timeout counter != exactly 1")
    _check(_counter("paddle_tpu_serving_expired_total")
           == e_before + 1, "expired counter != exactly 1")
    _check(_counter("paddle_tpu_serving_cancellations_total")
           == c_before + 1, "cancel counter != exactly 1")
    _check(eng.pool.used_pages == 0, "pages leaked")
    return "1 expiry + 1 timeout + 1 cancel, each counted exactly once"


def scenario_backpressure(model):
    """A bounded queue rejects with a retry_after_s hint, not OOM."""
    eng = ServingEngine(model, page_size=4, max_batch_slots=1, max_queue=1)
    eng.add_request(P3, max_new_tokens=2)
    try:
        eng.add_request(P3, max_new_tokens=2)
        raise AssertionError("full queue accepted a request")
    except BackpressureError as e:
        hint = e.retry_after_s
    _check(hint > 0, "no retry_after_s hint")
    eng.run()
    eng.add_request(P3, max_new_tokens=1)  # drained queue admits again
    eng.run()
    return f"rejected with retry_after_s={hint:.3f}s, recovered after drain"


def scenario_watchdog_healthz(model):
    """Latency injection trips the watchdog; /healthz goes 503 and
    recovers after healthy steps."""
    eng = ServingEngine(model, page_size=4, max_batch_slots=1,
                        watchdog_stall_s=0.005, watchdog_recovery_steps=2)
    with metrics.MetricsServer(health_cb=eng.health, port=0) as srv:
        with faults.inject("serving.step", delay_s=0.02, times=1,
                           seed=SEED):
            eng.step()
        try:
            urllib.request.urlopen(f"{srv.url}/healthz")
            raise AssertionError("/healthz stayed 200 while degraded")
        except urllib.error.HTTPError as e:
            _check(e.code == 503, f"expected 503, got {e.code}")
            _check(json.loads(e.read())["status"] == "degraded",
                   "degraded body")
        eng.step()
        eng.step()
        with urllib.request.urlopen(f"{srv.url}/healthz") as r:
            _check(r.status == 200, "no recovery")
    trips = eng.watchdog.trips
    _check(trips == 1, f"expected exactly 1 trip episode, got {trips}")
    return "tripped -> /healthz 503 -> recovered -> 200 (1 episode)"


def _trip_watchdog(engine):
    """Report one over-threshold step straight to the watchdog state
    machine — the deterministic stand-in for a stalled step (scenario 6
    drills the real latency-injection route; here the stall must hit ONE
    chosen engine of a fleet, and a sleep long enough to beat the 30 s
    default threshold has no place in a CI drill)."""
    engine.watchdog.end_step(engine.watchdog.stall_threshold_s + 1.0)


def scenario_router_failover(model):
    """Scenario 7: an engine is NaN-poisoned mid-stream AND degraded —
    the victim quarantines, every WAITING request completes on the
    sibling exactly once; with the whole fleet dark, waiting work retires
    "unavailable" instead of bouncing (no duplicates, no drops)."""
    r = Router()
    r.add_model("m", model, replicas=2, page_size=4, max_batch_slots=1,
                watchdog_recovery_steps=999)
    e0, e1 = r.engine("m/0"), r.engine("m/1")
    victim = e0.add_request(P9, max_new_tokens=8)
    e0.step()  # victim decoding in m/0's only slot
    queued = [e0.add_request(P3, max_new_tokens=3),
              e0.add_request(P4, max_new_tokens=3)]
    moved0 = _counter("paddle_tpu_router_requeued_total")
    un0 = _counter("paddle_tpu_router_unplaceable_total")
    e0.pool.poison_seq(victim)
    _trip_watchdog(e0)
    outs = r.run()
    _check(outs[victim].finish_reason == "nan", "victim not quarantined")
    _check([outs[q].finish_reason for q in queued] == ["length"] * 2,
           "requeued work did not complete on the sibling")
    _check(len(outs) == 3, "duplicate or dropped outputs")
    _check(_counter("paddle_tpu_router_requeued_total") == moved0 + 2,
           "requeue counter != exactly 2")
    _check(e0.pool.used_pages == 0 and e1.pool.used_pages == 0,
           "pages leaked")
    _check(r.states() == {"m/0": "degraded", "m/1": "healthy"},
           "gate states wrong")
    # both engines dark: a fresh waiting request has nowhere to go and
    # retires with the deterministic reason, exactly once
    b1 = e1.add_request(P9, max_new_tokens=12)
    e1.step()
    q2 = e1.add_request(P3, max_new_tokens=2)
    _trip_watchdog(e1)
    outs2 = r.run()
    _check(outs2[q2].finish_reason == "unavailable",
           "expected finish_reason=unavailable with no healthy engine")
    _check(outs2[b1].finish_reason == "length", "in-flight request lost")
    _check(_counter("paddle_tpu_router_unplaceable_total") == un0 + 1,
           "unplaceable counter != exactly 1")
    return ("victim=nan, 2 requeued once -> length on sibling; fleet dark "
            "-> unavailable exactly once")


def scenario_router_reload(model):
    """Scenario 8: rolling reload() across a live request stream — every
    request completes, every engine ends on the new checkpoint's weights,
    and decode stays compiled exactly once per engine per weight push."""
    tmp = tempfile.mkdtemp(prefix="chaos_ckpt_")
    try:
        paddle.seed(SEED + 1)
        donor = LlamaForCausalLM(llama_tiny(
            vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
            num_key_value_heads=2, max_position_embeddings=64))
        sd = donor.state_dict()
        CheckpointManager(tmp, max_to_keep=None).save(1, {"model": sd})
        # one model INSTANCE per replica (same seed, identical weights):
        # a shared instance would flip every replica at the first restore
        r = Router()
        r.add_model("m", [_model(), _model()], page_size=4,
                    max_batch_slots=1)
        live = [r.submit(p, model="m", max_new_tokens=6)
                for p in (P5, P9, P3, P4)]
        jit0 = _counter("paddle_tpu_jit_compiles_total",
                        fn="serving_step")
        ok0 = _counter("paddle_tpu_router_reloads_total", result="ok")
        summary = r.reload(tmp)
        outs = r.run()
        _check([e["result"] for e in summary["engines"]] == ["ok", "ok"],
               f"reload results: {summary}")
        _check(sorted(outs) == sorted(live),
               "live requests dropped or duplicated across reload")
        _check(all(outs[k].finish_reason == "length" for k in live),
               "a live request did not complete normally")
        k0 = next(iter(sd))
        fleet_compiles = 0
        for eng in r.engines("m"):
            _check(np.allclose(np.asarray(eng.model.state_dict()[k0]
                                          .numpy()),
                               np.asarray(sd[k0].numpy())),
                   f"engine {eng.engine_id} not on the new weights")
            counts = eng.compile_counts()
            _check(counts["step"] == counts["step_buckets"],
                   "step recompiled across the weight push")
            fleet_compiles += counts["step"]
        _check(_counter("paddle_tpu_jit_compiles_total",
                        fn="serving_step") == jit0 + fleet_compiles,
               "step compiles != one per bucket per engine")
        _check(_counter("paddle_tpu_router_reloads_total", result="ok")
               == ok0 + 2, "reload counter")
        _check(all(h.weights_step == 1 for h in r._model_handles("m")),
               "weights_step not recorded")
        return ("4 live requests completed across a 2-engine rolling "
                "push; weights=ckpt step 1 everywhere; step still "
                "1 compile/bucket/engine")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def scenario_router_least_loaded(model):
    """Scenario 9: skewed load — two long hogs pinned on engine 0. Blind
    round-robin parks half the short requests behind them; least-loaded
    dispatch steers every short to the idle sibling. Asserted on the
    queue-wait histogram (p95 AND mean) from the registry."""
    reg = metrics.get_registry()

    def drive(policy):
        r = Router()
        r.add_model("m", model, replicas=2, page_size=4,
                    max_batch_slots=1)
        # pre-warm both engines (compile prefill+decode) so the measured
        # waits are pure scheduling, not one-off XLA compile time
        for eid in ("m/0", "m/1"):
            r.engine(eid).add_request(P3, max_new_tokens=2)
            r.engine(eid).run()
        reg.reset()
        e0 = r.engine("m/0")
        for _ in range(2):  # the skew: 2 x 28-token hogs on engine 0
            e0.add_request(P3, max_new_tokens=28)
        for i in range(8):  # 8 short requests placed by `policy`
            if policy == "round-robin":
                r.engine(f"m/{i % 2}").add_request(P4, max_new_tokens=2)
            else:
                r.submit(P4, model="m", max_new_tokens=2)
        outs = r.run()
        _check(len(outs) == 10 and all(
            o.finish_reason == "length" for o in outs.values()),
            f"{policy}: workload did not drain cleanly")
        wait = reg.get("paddle_tpu_serving_queue_wait_seconds")
        return wait.quantile(0.95), wait.sum / wait.count

    p95_rr, mean_rr = drive("round-robin")
    p95_ll, mean_ll = drive("least-loaded")
    _check(p95_ll < p95_rr,
           f"least-loaded p95 {p95_ll:.4f}s !< round-robin {p95_rr:.4f}s")
    # the mean separates by ~40% structurally (half the shorts escape the
    # hogs); 0.9 keeps teeth against a regression to blind rotation while
    # tolerating CI wall-clock noise
    _check(mean_ll < 0.9 * mean_rr,
           f"least-loaded mean {mean_ll:.4f}s !< 0.9 x round-robin "
           f"{mean_rr:.4f}s")
    return (f"p95 queue-wait {p95_rr*1e3:.1f}ms (rr) -> "
            f"{p95_ll*1e3:.1f}ms (least-loaded), mean "
            f"{mean_rr*1e3:.1f}ms -> {mean_ll*1e3:.1f}ms")


def scenario_kill_engine_mid_decode(model):
    """Scenario 10 (ISSUE 7 acceptance): N sampled streaming requests;
    the busiest engine is killed at a scheduled step via the
    router.engine_step fault point. router.step() must contain the
    crash (mark down + migrate in-flight by token journal + requeue
    waiting), and every request must complete token-identical to an
    uninterrupted run with zero duplicated/missing stream chunks —
    deterministic decode makes engine death invisible to tenants."""
    specs = [(P5, 10, 0.9, 21), (P9, 9, 0.7, 22), (P3, 8, 1.1, 23)]
    # uninterrupted reference: a lone engine, same (prompt, seed, temp)
    # per request — per-request deterministic sampling makes this THE
    # oracle for the migrated run regardless of batch composition
    ref_eng = ServingEngine(model, page_size=4, max_batch_slots=2)
    ref_ids = [ref_eng.add_request(p, max_new_tokens=n, temperature=t,
                                   seed=s) for p, n, t, s in specs]
    ref_outs = ref_eng.run()
    refs = [list(ref_outs[r].token_ids) for r in ref_ids]
    _check(any(len(set(toks)) > 1 for toks in refs),
           "reference run is not actually sampling")

    r = Router()
    r.add_model("m", model, replicas=2, page_size=4, max_batch_slots=2)
    e0 = r.engine("m/0")  # the busiest engine: ALL traffic lands here
    chunks = {i: [] for i in range(len(specs))}

    def cb(i):
        return lambda rid, tok, fin, seq: chunks[i].append((seq, tok))

    rids = [e0.add_request(p, max_new_tokens=n, temperature=t, seed=s,
                           stream_cb=cb(i))
            for i, (p, n, t, s) in enumerate(specs)]
    crash0 = _counter("paddle_tpu_router_engine_crash_total",
                      engine_id="m/0", model_id="m")
    mig0 = _counter("paddle_tpu_router_migrated_total")
    req0 = _counter("paddle_tpu_router_requeued_total")
    for _ in range(3):
        r.step()  # 2 in-flight mid-decode, 1 waiting behind them
    with faults.inject("router.engine_step",
                       raise_=RuntimeError("engine killed mid-decode"),
                       times=1, seed=SEED):
        r.step()  # the scheduled kill — must NOT escape router.step()
    _check(r.states()["m/0"] == "down", "crashed engine not gated down")
    outs = r.run()
    _check(_counter("paddle_tpu_router_engine_crash_total",
                    engine_id="m/0", model_id="m") == crash0 + 1,
           "crash counter != exactly 1")
    _check(_counter("paddle_tpu_router_migrated_total") == mig0 + 2,
           "migrated counter != the 2 in-flight requests at the kill")
    _check(_counter("paddle_tpu_router_requeued_total") == req0 + 1,
           "requeue counter != the 1 waiting request at the kill")
    for i, (rid, ref) in enumerate(zip(rids, refs)):
        _check(outs[rid].finish_reason == "length",
               f"request {i} did not complete ({outs[rid].finish_reason})")
        _check(list(outs[rid].token_ids) == ref,
               f"request {i} diverged from the uninterrupted run")
        toks = [c for c in chunks[i] if c[1] is not None]
        _check([s for s, _ in toks] == list(range(len(ref))),
               f"request {i} stream chunks duplicated or missing")
        _check([t for _, t in toks] == ref,
               f"request {i} streamed tokens != final token_ids")
        _check(chunks[i][-1] == (len(ref), None),
               f"request {i} missing terminal chunk")
    _check(r._requeued == set(), "move-once marks leaked after the drill")
    _check(all(e.pool.used_pages == 0 for e in r.engines("m")),
           "pages leaked")
    return ("m/0 killed at step 4: 2 in-flight migrated + 1 waiting "
            "requeued; 3 sampled streams bit-identical to the "
            "uninterrupted run, chunks exactly-once")


def scenario_prefix_cache_failover(model):
    """Scenario 11 (ISSUE 8): prefix-heavy streaming traffic — every
    request shares a 24-token system prefix, both engines' radix caches
    hold it, and the busiest engine dies mid-decode. The migrated
    requests must re-prefill THROUGH the sibling's prefix cache
    (prefill_tokens_saved_total rises on the adoptive engine — failover
    of prefix-heavy traffic re-runs only the uncovered tail), with final
    streams bit-identical to an uninterrupted run and stream chunks
    exactly-once."""
    rng = np.random.RandomState(17)
    prefix = rng.randint(0, 128, (24,))
    suffixes = [rng.randint(0, 128, (k,)) for k in (3, 5, 2)]
    specs = [(np.concatenate([prefix, sfx]), n, t, s)
             for sfx, (n, t, s) in zip(suffixes, ((10, 0.9, 31),
                                                  (9, 0.7, 32),
                                                  (8, 1.1, 33)))]
    # uninterrupted oracle on a CACHE-LESS lone engine: deterministic
    # sampling makes it THE reference for cold, warm, and migrated runs
    ref_eng = ServingEngine(model, page_size=4, max_batch_slots=2,
                            prefix_cache=False)
    ref_ids = [ref_eng.add_request(p, max_new_tokens=n, temperature=t,
                                   seed=s) for p, n, t, s in specs]
    ref_outs = ref_eng.run()
    refs = [list(ref_outs[r].token_ids) for r in ref_ids]
    _check(any(len(set(toks)) > 1 for toks in refs),
           "reference run is not actually sampling")

    r = Router()
    r.add_model("m", model, replicas=2, page_size=4, max_batch_slots=2)
    # prefix-heavy fleet: the shared system prefix is warm on BOTH
    # engines (as it would be under routed traffic)
    for eid in ("m/0", "m/1"):
        e = r.engine(eid)
        e.add_request(np.concatenate([prefix, np.asarray([1])]),
                      max_new_tokens=1)
        e.run()
    e0, e1 = r.engine("m/0"), r.engine("m/1")
    chunks = {i: [] for i in range(len(specs))}

    def cb(i):
        return lambda rid, tok, fin, seq: chunks[i].append((seq, tok))

    rids = [e0.add_request(p, max_new_tokens=n, temperature=t, seed=s,
                           stream_cb=cb(i))
            for i, (p, n, t, s) in enumerate(specs)]
    saved1_0 = _counter("paddle_tpu_serving_prefill_tokens_saved_total",
                        engine_id="m/1", model_id="m")
    mig0 = _counter("paddle_tpu_router_migrated_total")
    for _ in range(3):
        r.step()  # 2 in-flight mid-decode, 1 waiting behind them
    with faults.inject("router.engine_step",
                       raise_=RuntimeError("engine killed mid-decode"),
                       times=1, seed=SEED):
        r.step()  # the scheduled kill
    _check(r.states()["m/0"] == "down", "crashed engine not gated down")
    outs = r.run()
    _check(_counter("paddle_tpu_router_migrated_total") == mig0 + 2,
           "migrated counter != the 2 in-flight requests at the kill")
    saved1 = _counter("paddle_tpu_serving_prefill_tokens_saved_total",
                      engine_id="m/1", model_id="m")
    # each adopted request matches the sibling's cached 24-token prefix
    # (6 full pages); the waiting one requeues and matches too
    _check(saved1 >= saved1_0 + 3 * 24,
           f"adoptive engine saved only {saved1 - saved1_0} prefill "
           f"tokens — migration did not ride the prefix cache")
    for i, (rid, ref) in enumerate(zip(rids, refs)):
        _check(outs[rid].finish_reason == "length",
               f"request {i} did not complete ({outs[rid].finish_reason})")
        _check(list(outs[rid].token_ids) == ref,
               f"request {i} diverged from the uninterrupted run")
        toks = [c for c in chunks[i] if c[1] is not None]
        _check([s for s, _ in toks] == list(range(len(ref))),
               f"request {i} stream chunks duplicated or missing")
        _check([t for _, t in toks] == ref,
               f"request {i} streamed tokens != final token_ids")
        _check(chunks[i][-1] == (len(ref), None),
               f"request {i} missing terminal chunk")
    _check(r._requeued == set(), "move-once marks leaked after the drill")
    _check(e1.pool.used_pages == 0, "pages leaked on the adoptive engine")
    return ("m/0 killed at step 4 under prefix-heavy traffic: 2 migrated "
            f"+ 1 requeued re-prefilled via m/1's cache "
            f"({int(saved1 - saved1_0)} prefill tokens saved); streams "
            "bit-identical, chunks exactly-once")


def scenario_kill_engine_mid_chunked_prefill(model):
    """Scenario 12 (ISSUE 11): the busiest engine is killed BETWEEN
    prompt chunks of a long request. Chunked-prefill progress is only a
    cache length, so the migrated request carries an EMPTY journal (no
    token had sampled yet), resumes on the sibling from its journaled
    chunk boundary — which the sibling's radix prefix cache re-covers
    (`prefill_tokens_saved_total` rises there) — and streams
    bit-identically from seq 0 with zero duplicated or missing chunks.
    A decoding tenant migrates alongside it, its stream also
    exactly-once across the hop."""
    rng = np.random.RandomState(23)
    prefix = rng.randint(0, 128, (24,))
    long_prompt = np.concatenate([prefix, rng.randint(0, 128, (20,))])
    specs = [(P5, 10, 0.9, 41), (long_prompt, 6, 0.8, 42)]
    ref_eng = ServingEngine(model, page_size=4, max_batch_slots=2,
                            prefix_cache=False)
    ref_ids = [ref_eng.add_request(p, max_new_tokens=n, temperature=t,
                                   seed=sd) for p, n, t, sd in specs]
    ref_outs = ref_eng.run()
    refs = [list(ref_outs[r].token_ids) for r in ref_ids]
    _check(any(len(set(toks)) > 1 for toks in refs),
           "reference run is not actually sampling")

    r = Router()
    # token_budget 8: the long prompt's 20 uncovered tokens need 3+
    # chunk steps, so there IS a chunk boundary to die between
    r.add_model("m", model, replicas=2, page_size=4, max_batch_slots=2,
                token_budget=8)
    for eid in ("m/0", "m/1"):  # shared prefix warm on BOTH caches
        e = r.engine(eid)
        e.add_request(np.concatenate([prefix, np.asarray([1])]),
                      max_new_tokens=1)
        e.run()
    e0, e1 = r.engine("m/0"), r.engine("m/1")
    chunks = {i: [] for i in range(len(specs))}

    def cb(i):
        return lambda rid, tok, fin, seq: chunks[i].append((seq, tok))

    dec = e0.add_request(P5, max_new_tokens=10, temperature=0.9, seed=41,
                         stream_cb=cb(0))
    r.step()
    r.step()  # the tenant is decoding
    lng = e0.add_request(long_prompt, max_new_tokens=6, temperature=0.8,
                         seed=42, stream_cb=cb(1))
    r.step()  # admit the long prompt + its first chunk
    st = next(s for s in e0.slots if s is not None
              and s.req.req_id == lng)
    _check(st.prefilling and st.pos > 24 and not st.gen,
           f"expected the long request mid-chunked-prefill at the kill "
           f"(pos={st.pos}, gen={st.gen})")
    boundary = st.pos
    saved1_0 = _counter("paddle_tpu_serving_prefill_tokens_saved_total",
                        engine_id="m/1", model_id="m")
    mig0 = _counter("paddle_tpu_router_migrated_total")
    with faults.inject("router.engine_step",
                       raise_=RuntimeError("engine killed between chunks"),
                       times=1, seed=SEED):
        r.step()  # the scheduled kill — between prompt chunks
    _check(r.states()["m/0"] == "down", "crashed engine not gated down")
    outs = r.run()
    _check(_counter("paddle_tpu_router_migrated_total") == mig0 + 2,
           "migrated counter != the decode tenant + the mid-prefill one")
    saved1 = _counter("paddle_tpu_serving_prefill_tokens_saved_total",
                      engine_id="m/1", model_id="m")
    _check(saved1 >= saved1_0 + 24,
           f"adoptive engine saved only {saved1 - saved1_0} prefill "
           f"tokens — resume did not ride the sibling's prefix cache")
    for i, (rid, ref) in enumerate(zip((dec, lng), refs)):
        _check(outs[rid].finish_reason == "length",
               f"request {i} did not complete ({outs[rid].finish_reason})")
        _check(list(outs[rid].token_ids) == ref,
               f"request {i} diverged from the uninterrupted run")
        toks = [c for c in chunks[i] if c[1] is not None]
        _check([sq for sq, _ in toks] == list(range(len(ref))),
               f"request {i} stream chunks duplicated or missing")
        _check([t for _, t in toks] == ref,
               f"request {i} streamed tokens != final token_ids")
        _check(chunks[i][-1] == (len(ref), None),
               f"request {i} missing terminal chunk")
    _check(r._requeued == set(), "move-once marks leaked after the drill")
    _check(e1.pool.used_pages == 0, "pages leaked on the adoptive engine")
    return (f"m/0 killed at chunk boundary pos={boundary} (prompt "
            f"{long_prompt.size}): mid-prefill request resumed via m/1's "
            f"cache ({int(saved1 - saved1_0)} tokens saved), both streams "
            "bit-identical, chunks exactly-once")


def scenario_thread_fuzz_control_plane(model):
    """Scenario 13: thread-fuzz the CONTROL PLANE under LockSanitizer —
    one driver thread runs submit/step/rolling-reload, a scraper hammers
    /metrics + /metrics.json + /healthz, a prober spins health()/states()
    (the any-thread half of the router's threading contract), all
    synchronized through a barrier each iteration with seeded per-thread
    jitter so the interleavings vary but reproduce. The sanitizer wraps
    the router, registry, probe-cache and watchdog locks; the drill
    passes iff ZERO lock-discipline violations were observed AND the
    fleet ends consistent (every request completed, no leaked pages)."""
    import threading
    import time

    iters = int(os.environ.get("CHAOS_FUZZ_ITERS", "200"))
    tmp = tempfile.mkdtemp(prefix="chaos_ckpt_")
    san = faults.LockSanitizer(
        order=("router", "engine", "scheduler", "pool"),
        leaves=("metrics.registry", "metrics.server.probe",
                "watchdog/0", "watchdog/1"))
    registry = metrics.get_registry()
    orig_reg_lock = None
    try:
        paddle.seed(SEED + 13)
        donor = LlamaForCausalLM(llama_tiny(
            vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
            num_key_value_heads=2, max_position_embeddings=64))
        CheckpointManager(tmp, max_to_keep=None).save(
            1, {"model": donor.state_dict()})
        r = Router()
        r.add_model("m", [_model(), _model()], page_size=4,
                    max_batch_slots=1)
        san.attach(r, "_lock", "router")
        # the registry lock is process-global: restore it in finally
        orig_reg_lock = san.attach(registry, "_lock", "metrics.registry")
        for i, eng in enumerate(r.engines("m")):
            san.attach(eng.watchdog, "_lock", f"watchdog/{i}")

        barrier = threading.Barrier(3)
        errors, live, prompts = [], [], (P5, P9, P3, P4)
        counts = {"drive": 0, "scrape": 0, "probe": 0}

        def drive(i, rng):
            if i % 5 == 0:
                live.append(r.submit(prompts[int(rng.randint(4))],
                                     model="m", max_new_tokens=2))
            r.step()
            if i % 67 == 66:  # rolling weight pushes mid-fuzz
                summary = r.reload(tmp)
                _check(all(e["result"] == "ok"
                           for e in summary["engines"]),
                       f"reload failed mid-fuzz: {summary}")

        def scrape(i, rng):
            path = ("/metrics", "/metrics.json",
                    "/healthz?engine=m/0")[i % 3]
            try:
                with urllib.request.urlopen(srv.url + path,
                                            timeout=10) as resp:
                    _check(resp.status == 200, f"{path}: {resp.status}")
            except urllib.error.HTTPError as e:
                # a scrape that lands mid-reload may read degraded: 503
                # on /healthz is consistent, a 5xx on /metrics is not
                _check(path.startswith("/healthz") and e.code == 503,
                       f"{path}: HTTP {e.code}")

        def probe(i, rng):
            h = r.health()
            _check(h.get("status") in ("ok", "degraded"),
                   f"health() shape: {h}")
            r.states()

        def worker(key, fn, idx):
            rng = np.random.RandomState(SEED * 997 + idx)
            try:
                for i in range(iters):
                    barrier.wait(timeout=60)
                    time.sleep(float(rng.uniform(0.0, 5e-4)))
                    fn(i, rng)
                    counts[key] += 1
            except threading.BrokenBarrierError:
                pass
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append((key, e))
                barrier.abort()

        with metrics.MetricsServer(health_cb=r.health, port=0) as srv:
            san.attach(srv, "_probe_lock", "metrics.server.probe")
            threads = [threading.Thread(target=worker, args=args,
                                        name=f"fuzz-{args[0]}")
                       for args in (("drive", drive, 1),
                                    ("scrape", scrape, 2),
                                    ("probe", probe, 3))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            _check(not any(t.is_alive() for t in threads),
                   "fuzz thread wedged")
        _check(not errors, f"fuzz thread failures: {errors}")
        _check(all(c == iters for c in counts.values()),
               f"threads did not complete all iterations: {counts}")
        outs = r.run()   # drain whatever the driver left in flight
        _check(sorted(outs) == sorted(live),
               "requests dropped or duplicated under fuzz")
        _check(all(outs[k].finish_reason == "length" for k in live),
               "a fuzzed request did not complete normally")
        _check(all(e.pool.used_pages == 0 for e in r.engines("m")),
               "pages leaked under fuzz")
        san.assert_clean()
        return (f"{iters} barrier-synced iterations x 3 threads "
                f"({len(live)} requests, {iters // 67} reloads, "
                f"{iters} scrapes): 0 sanitizer violations, fleet "
                "consistent")
    finally:
        if orig_reg_lock is not None:
            registry._lock = orig_reg_lock
        shutil.rmtree(tmp, ignore_errors=True)


class _SpecOracle:
    """Chaos drafter: proposes the known reference continuation for the
    prompts it was given (100% acceptance — every decode step is a full
    multi-token burst) and garbage for everyone else (0% acceptance —
    the KV rollback runs every step). Stateless, so one instance serves
    every replica, including post-migration re-drafting over
    prompt + journal."""

    def __init__(self, table):
        self.table = [(np.asarray(p).tolist(), [int(t) for t in ref])
                      for p, ref in table]

    def propose(self, ids, k=None):
        l = np.asarray(ids).tolist()
        for p, ref in self.table:
            done = len(l) - len(p)
            if 0 <= done and l[:len(p)] == p \
                    and l[len(p):] == ref[:done]:
                return np.asarray(ref[done:done + (k or 1)], np.int32)
        return np.full(k or 1, 127, np.int32)  # rejected every burst


def scenario_kill_engine_mid_spec_burst(model):
    """Scenario 14 (ISSUE 14): the kill-engine drill under SPECULATIVE
    decoding. Both replicas run spec_k=3 with a drafter that bursts
    4 tokens/step for two requests and feeds always-rejected garbage to
    the third, so at the kill the dying engine holds multi-token-burst
    progress AND a request whose every draft was rolled back. The
    migration journal is only ever committed tokens (accepted drafts
    commit inside the step; rejected ones truncate before landing), so
    every stream must end bit-identical to a lone SPEC-OFF engine —
    chunks exactly-once, drafts never leaking into a journal."""
    specs = [(P5, 10, 0.9, 21), (P9, 9, 0.7, 22), (P3, 8, 1.1, 23)]
    # the oracle is a SPEC-OFF lone engine: identical streams here prove
    # speculation + crash + migration changed no token anywhere
    ref_eng = ServingEngine(model, page_size=4, max_batch_slots=2)
    ref_ids = [ref_eng.add_request(p, max_new_tokens=n, temperature=t,
                                   seed=s) for p, n, t, s in specs]
    ref_outs = ref_eng.run()
    refs = [list(ref_outs[r].token_ids) for r in ref_ids]
    _check(any(len(set(toks)) > 1 for toks in refs),
           "reference run is not actually sampling")

    drafter = _SpecOracle([(specs[0][0], refs[0]), (specs[1][0], refs[1])])
    r = Router()
    r.add_model("m", model, replicas=2, page_size=4, max_batch_slots=2,
                spec_k=3, drafter=drafter)
    e0 = r.engine("m/0")  # the busiest engine: ALL traffic lands here
    chunks = {i: [] for i in range(len(specs))}

    def cb(i):
        return lambda rid, tok, fin, seq: chunks[i].append((seq, tok))

    rids = [e0.add_request(p, max_new_tokens=n, temperature=t, seed=s,
                           stream_cb=cb(i))
            for i, (p, n, t, s) in enumerate(specs)]
    crash0 = _counter("paddle_tpu_router_engine_crash_total",
                      engine_id="m/0", model_id="m")
    mig0 = _counter("paddle_tpu_router_migrated_total")
    req0 = _counter("paddle_tpu_router_requeued_total")
    drafted0 = _counter("paddle_tpu_serving_spec_drafted_tokens_total")
    accept0 = _counter("paddle_tpu_serving_spec_accepted_tokens_total")
    for _ in range(2):
        r.step()  # step 2 bursts both decoders to gen=5; req 2 waits
    drafted_pre = _counter(
        "paddle_tpu_serving_spec_drafted_tokens_total") - drafted0
    accept_pre = _counter(
        "paddle_tpu_serving_spec_accepted_tokens_total") - accept0
    _check(accept_pre > 0, "no accepted burst landed before the kill")
    with faults.inject("router.engine_step",
                       raise_=RuntimeError("engine killed mid-spec-burst"),
                       times=1, seed=SEED):
        r.step()  # the scheduled kill — must NOT escape router.step()
    _check(r.states()["m/0"] == "down", "crashed engine not gated down")
    # committed-tokens-only contract, visible at the kill: everything
    # streamed so far is a prefix of the spec-off oracle — an unaccepted
    # draft leaking into a journal/stream would diverge here
    for i, ref in enumerate(refs):
        got = [t for _, t in chunks[i] if t is not None]
        _check(got == ref[:len(got)],
               f"request {i} streamed a non-committed token by the kill")
    outs = r.run()
    _check(_counter("paddle_tpu_router_engine_crash_total",
                    engine_id="m/0", model_id="m") == crash0 + 1,
           "crash counter != exactly 1")
    _check(_counter("paddle_tpu_router_migrated_total") == mig0 + 2,
           "migrated counter != the 2 in-flight requests at the kill")
    _check(_counter("paddle_tpu_router_requeued_total") == req0 + 1,
           "requeue counter != the 1 waiting request at the kill")
    for i, (rid, ref) in enumerate(zip(rids, refs)):
        _check(outs[rid].finish_reason == "length",
               f"request {i} did not complete ({outs[rid].finish_reason})")
        _check(list(outs[rid].token_ids) == ref,
               f"request {i} diverged from the spec-off oracle")
        toks = [c for c in chunks[i] if c[1] is not None]
        _check([s for s, _ in toks] == list(range(len(ref))),
               f"request {i} stream chunks duplicated or missing")
        _check([t for _, t in toks] == ref,
               f"request {i} streamed tokens != final token_ids")
        _check(chunks[i][-1] == (len(ref), None),
               f"request {i} missing terminal chunk")
    drafted = _counter(
        "paddle_tpu_serving_spec_drafted_tokens_total") - drafted0
    accepted = _counter(
        "paddle_tpu_serving_spec_accepted_tokens_total") - accept0
    _check(drafted > accepted,
           "the garbage-drafted request never exercised rejection")
    _check(r._requeued == set(), "move-once marks leaked after the drill")
    _check(all(e.pool.used_pages == 0 for e in r.engines("m")),
           "pages leaked")
    return (f"m/0 killed mid-burst (drafted {int(drafted)}, accepted "
            f"{int(accepted)} incl. an always-rejected tenant): journals "
            "carried only committed tokens; 3 streams bit-identical to "
            "the spec-off run, chunks exactly-once")


def scenario_autoscale_under_burst(model):
    """Scenario 15 (ISSUE 15): the loadgen autoscaler drill. A seeded
    Poisson trace with an 8x burst window replays against a 1-engine
    fleet whose queue-depth autoscaler may grow to 3; the burst must
    scale the fleet up and the post-burst cold signal must drain it
    back to exactly 1 — strictly drain-then-remove, so every one of the
    trace's requests completes (or retires ``"unavailable"``)
    exactly-once, with zero duplicated outputs, zero leaked pages, zero
    leaked move-once marks, AND zero fresh jit compiles after the warm
    phase: every engine the scaler spawns materializes its pinned step
    shape from the shared persistent compile cache (ISSUE 14)."""
    from paddle_tpu import loadgen

    cache_dir = tempfile.mkdtemp(prefix="chaos15-compile-cache-")
    try:
        r = Router()
        r.add_model("m", model, replicas=1, page_size=4, num_pages=128,
                    max_batch_slots=4, max_model_len=64, token_budget=32,
                    min_step_tokens=32, max_queue=128,
                    compile_cache_dir=cache_dir)
        # warm phase: one request compiles THE pinned step shape
        # (min_step_tokens=token_budget -> a single grid bucket) and
        # persists it; from here on, scale-up must be compile-free
        r.submit(P5, max_new_tokens=2)
        r.run()
        cfg = loadgen.TraceConfig(
            seed=SEED + 15, num_requests=32, vocab_size=128,
            arrival_rate=8.0, burst_start=0.2, burst_duration=1.5,
            burst_factor=8.0, num_prompt_families=4, prefix_len=6,
            max_prompt_len=24, max_output_len=6,
            slow_consumer_fraction=0.05)
        trace = loadgen.generate_trace(cfg)
        scaler = loadgen.QueueDepthAutoscaler(
            r, config=loadgen.AutoscalerConfig(
                min_engines=1, max_engines=3, scale_up_depth=2.0,
                scale_down_depth=0.25, hot_steps=2, cold_steps=6,
                cooldown_steps=6))
        rep = loadgen.LoadDriver(r, trace, autoscaler=scaler).run()
        _check(rep.exactly_once,
               f"completion accounting violated: {rep.violations[:3]}")
        _check(rep.engines_peak >= 2, "the burst never scaled the fleet")
        _check(rep.engines_final == 1,
               f"fleet did not drain back to 1 ({rep.engines_final})")
        _check(rep.scale_ups >= 1 and rep.scale_downs >= 1,
               f"missing scale events (ups={rep.scale_ups}, "
               f"downs={rep.scale_downs})")
        _check(rep.scale_ups == rep.scale_downs,
               "unbalanced scale events for a fleet that returned home")
        bad = {k: v for k, v in rep.outcomes.items()
               if k not in ("stop", "length", "unavailable")}
        _check(not bad, f"requests neither completed nor retired "
               f"unavailable: {bad}")
        _check(sum(rep.outcomes.values()) == cfg.num_requests,
               "outcome count != trace size")
        _check(rep.fresh_compiles == 0,
               f"{rep.fresh_compiles} fresh compiles on scale-up "
               f"(persistent cache missed)")
        _check(all(e.pool.used_pages == 0 for e in r.engines("m")),
               "pages leaked")
        _check(r._requeued == set(), "move-once marks leaked")
        return (f"burst scaled 1->{rep.engines_peak}->1 "
                f"({rep.scale_ups} up, {rep.scale_downs} down), "
                f"{cfg.num_requests} requests exactly-once "
                f"({rep.outcomes}), 0 fresh compiles on scale-up, "
                f"goodput {rep.goodput_tok_s:.0f} tok/s")
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)


def scenario_kill_engine_mid_constrained_adapter_stream(model):
    """Scenario 16 (ISSUE 16): the kill drill under MULTI-LoRA +
    CONSTRAINED decoding. Every request samples through a hot-loaded
    adapter slot and a grammar DFA mask; the busiest engine dies after
    two decode steps, so every in-flight request is MID-STRUCTURE —
    its FSM state is a nonzero interior state that rides the migration
    journal (``resume_fsm_state``) to the sibling, which must resume
    the grammar walk where the dead engine left it. Streams must end
    bit-identical to an uninterrupted lone engine holding the same
    adapter weights, every output must validate against its grammar
    (including the FSM-driven ``"stop"``), chunks exactly-once, and the
    released mask segments must return every engine's grammar table to
    its identity row."""
    tok = toy_tokenizer(128)
    fsms = [GrammarFSM.compile(pat, tok)
            for pat in ("[ab]{1,4}", "[abc]{2,12}", "[ab]{1,6}")]
    specs = [(P5, fsms[0], 10, 0.9, 31), (P9, fsms[1], 8, 0.7, 32),
             (P3, fsms[2], 6, 1.1, 33)]
    # the oracle: a lone engine with the SAME adapter weights
    # (random_adapter is deterministic in (store shape, seed)) and the
    # same grammars, never killed — identical streams prove the crash +
    # FSM-journal migration changed no token anywhere
    ref_eng = ServingEngine(model, page_size=4, max_batch_slots=2)
    ref_eng.register_adapter("acme", random_adapter(ref_eng.adapters,
                                                    seed=16))
    ref_ids = [ref_eng.add_request(p, max_new_tokens=n, temperature=t,
                                   seed=s, adapter_id="acme", grammar=g)
               for p, g, n, t, s in specs]
    ref_outs = ref_eng.run()
    refs = [list(ref_outs[r].token_ids) for r in ref_ids]
    _check(all(g.validates(toks) for g, toks in zip(fsms, refs)),
           "oracle run produced a grammar-invalid stream")
    _check(ref_outs[ref_ids[0]].finish_reason == "stop",
           "request 0 never exercised the FSM-driven stop")

    r = Router()
    r.add_model("m", model, replicas=2, page_size=4, max_batch_slots=2)
    r.register_adapter("acme",
                       random_adapter(r.engine("m/0").adapters, seed=16),
                       model="m")
    e0 = r.engine("m/0")  # the busiest engine: ALL traffic lands here
    chunks = {i: [] for i in range(len(specs))}

    def cb(i):
        return lambda rid, tk, fin, seq: chunks[i].append((seq, tk))

    rids = [e0.add_request(p, max_new_tokens=n, temperature=t, seed=s,
                           adapter_id="acme", grammar=g, stream_cb=cb(i))
            for i, (p, g, n, t, s) in enumerate(specs)]
    crash0 = _counter("paddle_tpu_router_engine_crash_total",
                      engine_id="m/0", model_id="m")
    mig0 = _counter("paddle_tpu_router_migrated_total")
    req0 = _counter("paddle_tpu_router_requeued_total")
    gtok0 = _counter("paddle_tpu_serving_grammar_tokens_total")
    valid0 = _counter("paddle_tpu_serving_grammar_completions_total",
                      result="valid")
    invalid0 = _counter("paddle_tpu_serving_grammar_completions_total",
                        result="invalid")
    for _ in range(2):
        r.step()  # both decoders reach gen=2: mid-structure; req 2 waits
    _check(_counter("paddle_tpu_serving_grammar_tokens_total") - gtok0
           >= 4, "no grammar-masked tokens landed before the kill")
    with faults.inject(
            "router.engine_step",
            raise_=RuntimeError("engine killed mid-constrained-stream"),
            times=1, seed=SEED):
        r.step()  # the scheduled kill — must NOT escape router.step()
    _check(r.states()["m/0"] == "down", "crashed engine not gated down")
    # everything streamed so far must be a prefix of the oracle — a
    # grammar-divergent sample or a stale FSM state would diverge here
    for i, ref in enumerate(refs):
        got = [t for _, t in chunks[i] if t is not None]
        _check(got == ref[:len(got)],
               f"request {i} streamed a grammar-divergent token")
        if i < 2:  # the two decoding slots; request 2 is still queued
            _check(got and len(got) < len(ref),
                   f"request {i} not mid-structure at the kill")
    outs = r.run()
    _check(_counter("paddle_tpu_router_engine_crash_total",
                    engine_id="m/0", model_id="m") == crash0 + 1,
           "crash counter != exactly 1")
    _check(_counter("paddle_tpu_router_migrated_total") == mig0 + 2,
           "migrated counter != the 2 in-flight requests at the kill")
    _check(_counter("paddle_tpu_router_requeued_total") == req0 + 1,
           "requeue counter != the 1 waiting request at the kill")
    for i, (rid, ref, fsm) in enumerate(zip(rids, refs, fsms)):
        _check(outs[rid].finish_reason == ref_outs[ref_ids[i]]
               .finish_reason,
               f"request {i} finish_reason diverged from the oracle")
        _check(list(outs[rid].token_ids) == ref,
               f"request {i} diverged from the uninterrupted oracle")
        _check(fsm.validates(outs[rid].token_ids),
               f"request {i} completed grammar-invalid after migration")
        toks = [c for c in chunks[i] if c[1] is not None]
        _check([s for s, _ in toks] == list(range(len(ref))),
               f"request {i} stream chunks duplicated or missing")
        _check([t for _, t in toks] == ref,
               f"request {i} streamed tokens != final token_ids")
        _check(chunks[i][-1] == (len(ref), None),
               f"request {i} missing terminal chunk")
    valid = _counter("paddle_tpu_serving_grammar_completions_total",
                     result="valid") - valid0
    _check(valid == len(specs),
           f"grammar-valid completions counter moved {valid}, "
           f"want {len(specs)}")
    _check(_counter("paddle_tpu_serving_grammar_completions_total",
                    result="invalid") == invalid0,
           "a completion retired grammar-invalid")
    _check(all(len(e._grammar_segments) == 0 for e in r.engines("m")),
           "grammar mask segments leaked after the drill")
    _check(r._requeued == set(), "move-once marks leaked after the drill")
    _check(all(e.pool.used_pages == 0 for e in r.engines("m")),
           "pages leaked")
    return (f"m/0 killed mid-structure: FSM journals resumed on the "
            f"sibling, {len(specs)} adapter+grammar streams "
            "bit-identical to the uninterrupted run, every output "
            "grammar-valid, chunks exactly-once, mask segments released")


def scenario_flight_recorder_on_crash(model):
    """Scenario 17 (ISSUE 17): the kill drill with the FLIGHT RECORDER
    under test. A fresh tracer (tiny window, scenario-owned flight dir)
    is installed BEFORE the fleet is built, all sampled streaming
    traffic lands on m/0, and the busiest engine dies mid-decode. Crash
    containment must auto-dump the last window of fleet timeline: the
    dumped JSON carries each victim request's timeline with the
    export -> adopt migration hop visible and every ``(req_id, seq)``
    exactly-once ACROSS the hop (one fleet-global seq stream per
    request), the dumps counter moves with reason="crash", and the
    streams still end bit-identical to an uninterrupted run — the
    recorder observes the crash, never perturbs it."""
    from paddle_tpu.serving import tracing

    specs = [(P5, 10, 0.9, 21), (P9, 9, 0.7, 22), (P3, 8, 1.1, 23)]
    ref_eng = ServingEngine(model, page_size=4, max_batch_slots=2)
    ref_ids = [ref_eng.add_request(p, max_new_tokens=n, temperature=t,
                                   seed=s) for p, n, t, s in specs]
    ref_outs = ref_eng.run()
    refs = [list(ref_outs[r].token_ids) for r in ref_ids]

    flight_dir = tempfile.mkdtemp(prefix="chaos17_flight_")
    old = None
    try:
        # install BEFORE building the fleet: engines and router capture
        # the process tracer at construction
        old = tracing.set_tracer(tracing.RequestTracer(
            capacity=8192, flight_dir=flight_dir, window_s=120.0))
        tracer = tracing.get_tracer()
        dumps0 = _counter("paddle_tpu_trace_recorder_dumps_total",
                          reason="crash")
        r = Router()
        r.add_model("m", model, replicas=2, page_size=4,
                    max_batch_slots=2)
        e0 = r.engine("m/0")  # the busiest engine: ALL traffic here
        rids = [e0.add_request(p, max_new_tokens=n, temperature=t,
                               seed=s) for p, n, t, s in specs]
        for _ in range(3):
            r.step()  # 2 in-flight mid-decode, 1 waiting behind them
        with faults.inject("router.engine_step",
                           raise_=RuntimeError("engine killed"),
                           times=1, seed=SEED):
            r.step()  # the kill — containment must dump the recorder
        _check(r.states()["m/0"] == "down", "crashed engine not gated")
        files = sorted(os.listdir(flight_dir))
        _check(len(files) == 1,
               f"expected exactly 1 auto-dump, found {files}")
        _check("crash" in files[0], f"dump not tagged crash: {files[0]}")
        with open(os.path.join(flight_dir, files[0])) as f:
            dump = json.load(f)
        _check(dump["reason"] == "crash", "dump reason")
        _check(_counter("paddle_tpu_trace_recorder_dumps_total",
                        reason="crash") == dumps0 + 1,
               "dumps counter != exactly 1 crash dump")
        # every victim request's timeline is in the dump, with the
        # migration hop visible: exported off m/0, adopted (or
        # requeued) onto m/1, seqs contiguous ACROSS the hop
        for i, rid in enumerate(rids):
            tl = dump["requests"].get(str(rid))
            _check(tl, f"request {i} missing from the dump")
            names = [e["name"] for e in tl]
            _check("req.enqueue" in names,
                   f"request {i} dump lost its admission history")
            hop = {"req.adopt", "req.requeue"} & set(names)
            _check(hop, f"request {i} dump shows no migration hop "
                   f"({names})")
            _check(tracing.validate_events(tl) == [],
                   f"request {i} seqs not exactly-once across the hop: "
                   f"{tracing.validate_events(tl)}")
            hopper = next(e for e in tl if e["name"] in hop)
            _check(hopper["label"] == "m/1",
                   f"request {i} hop landed on {hopper['label']!r}")
        outs = r.run()
        for i, (rid, ref) in enumerate(zip(rids, refs)):
            _check(list(outs[rid].token_ids) == ref,
                   f"request {i} diverged from the uninterrupted run")
        # the full live journal (not just the dump window) stays
        # exactly-once after the drill drains
        _check(tracing.validate_events(tracer.events()) == [],
               "live journal lost exactly-once after the drill")
        _check(tracer.dropped == 0, "ring wrapped mid-drill (sizing)")
        retired = [e for e in tracer.events()
                   if e["name"] == "req.retire"
                   and e["req_id"] in set(rids)]
        _check(len(retired) == len(rids),
               f"{len(retired)} retire events for {len(rids)} requests")
        _check(r._requeued == set(), "move-once marks leaked")
        _check(all(e.pool.used_pages == 0 for e in r.engines("m")),
               "pages leaked")
        n_ev = len(dump["events"])
        return (f"m/0 killed at step 4: containment auto-dumped "
                f"{n_ev} events; all {len(rids)} victim timelines in "
                f"the file with the m/0->m/1 hop visible, seqs "
                f"exactly-once across the hop, streams bit-identical")
    finally:
        tracing.set_tracer(old)  # old None = back to lazy env default
        shutil.rmtree(flight_dir, ignore_errors=True)


def scenario_kill_engine_with_offloaded_pages(model):
    """Scenario 18 (ISSUE 18): the kill drill with the HOST KV TIER
    armed. Both replicas run int8 KV pages + host_offload on a
    page-starved pool, all traffic lands on m/0, and admission pressure
    PARKS the low-priority stream — its quantized pages live in host RAM
    — before the engine is killed. Containment must evacuate a parked
    slot exactly like a resident one (its resume state is only the token
    journal; host pages are abandoned KV that re-prefills on the
    sibling), the dead engine's HostPageStore must drain (no leaked host
    RAM), and the adoptive sibling — just as page-starved — must repeat
    the park/unpark dance to serve both migrants, with final streams
    bit-identical to an uncontended lone-engine run and chunks
    exactly-once."""
    specs = [(P9, 10, 0.9, 51, 5), (np.concatenate([P5, P3]), 4, 0.7,
                                    52, 0)]
    # uncontended oracle: a lone int8 engine with ample pages — park,
    # migration and re-prefill must all be invisible to the streams
    ref_eng = ServingEngine(model, page_size=4, max_batch_slots=2,
                            kv_dtype="int8")
    ref_ids = [ref_eng.add_request(p, max_new_tokens=n, temperature=t,
                                   seed=s) for p, n, t, s, _ in specs]
    ref_outs = ref_eng.run()
    refs = [list(ref_outs[r].token_ids) for r in ref_ids]
    _check(any(len(set(toks)) > 1 for toks in refs),
           "reference run is not actually sampling")

    r = Router()
    # 7 usable pages vs 5+3 worst-case pages: the two requests can
    # never be resident together — parking is the only way both serve
    r.add_model("m", model, replicas=2, page_size=4, num_pages=8,
                max_batch_slots=3, kv_dtype="int8", host_offload=True)
    e0, e1 = r.engine("m/0"), r.engine("m/1")
    chunks = {i: [] for i in range(len(specs))}

    def cb(i):
        return lambda rid, tk, fin, seq: chunks[i].append((seq, tk))

    off0 = _counter("paddle_tpu_serving_kv_offload_pages_total",
                    engine_id="m/0", model_id="m")
    mig0 = _counter("paddle_tpu_router_migrated_total")
    p0, n0, t0, s0, pr0 = specs[0]
    lo = e0.add_request(p0, max_new_tokens=n0, temperature=t0, seed=s0,
                        priority=pr0, stream_cb=cb(0))
    r.step()
    r.step()  # lo is decoding and holds the pool's worst-case pages
    p1, n1, t1, s1, pr1 = specs[1]
    hi = e0.add_request(p1, max_new_tokens=n1, temperature=t1, seed=s1,
                        priority=pr1, stream_cb=cb(1))
    r.step()  # pressure parks lo; hi admits against its pages
    _check(e0.pool.offloaded_pages(lo) > 0,
           "pressure never parked the low-priority stream")
    _check(_counter("paddle_tpu_serving_kv_offload_pages_total",
                    engine_id="m/0", model_id="m") > off0,
           "offload counter never moved")
    with faults.inject("router.engine_step",
                       raise_=RuntimeError("engine killed while parked"),
                       times=1, seed=SEED):
        r.step()  # the kill — a parked slot is among the victims
    _check(r.states()["m/0"] == "down", "crashed engine not gated down")
    # the dead engine's host tier must drain with the evacuation: host
    # RAM holding abandoned quantized pages is a leak, not a tier
    _check(e0.pool.offloaded_pages() == 0,
           "dead engine's HostPageStore leaked offloaded pages")
    _check(e0.pool.used_pages == 0, "dead engine leaked HBM pages")
    outs = r.run()
    _check(_counter("paddle_tpu_router_migrated_total") == mig0 + 2,
           "migrated counter != the 2 in-flight requests at the kill")
    for i, (rid, ref) in enumerate(zip((lo, hi), refs)):
        _check(outs[rid].finish_reason == "length",
               f"request {i} did not complete ({outs[rid].finish_reason})")
        _check(list(outs[rid].token_ids) == ref,
               f"request {i} diverged from the uncontended run")
        toks = [c for c in chunks[i] if c[1] is not None]
        _check([sq for sq, _ in toks] == list(range(len(ref))),
               f"request {i} stream chunks duplicated or missing")
        _check([t for _, t in toks] == ref,
               f"request {i} streamed tokens != final token_ids")
        _check(chunks[i][-1] == (len(ref), None),
               f"request {i} missing terminal chunk")
    _check(e1.pool.used_pages == 0 and e1.pool.offloaded_pages() == 0,
           "adoptive engine leaked pages across its own park/unpark")
    _check(_counter("paddle_tpu_serving_kv_prefetch_late_total",
                    engine_id="m/1", model_id="m") == 0,
           "a prefetch landed late inside the step path on the sibling")
    _check(r._requeued == set(), "move-once marks leaked after the drill")
    counts = e1.compile_counts()
    _check(counts["step"] == counts["step_buckets"],
           "quantized step recompiled on the adoptive engine")
    return ("m/0 killed with a PARKED int8 stream: host store drained, "
            "both migrants re-served through m/1's own park/unpark, "
            "streams bit-identical, chunks exactly-once")


def scenario_brownout_under_burst(model):
    """Scenario 19 (ISSUE 19): overload survived by POLICY, not
    capacity. A 16x-burst tiered trace replays against a capacity-CAPPED
    2-engine fleet (no autoscaler) under a pinned fault schedule — a
    step-latency storm covering the burst plus an engine kill with timed
    revival — with the OverloadController armed. The brownout ladder
    must CLIMB to slot preemption (level >= 3: batch-tier decodes are
    journaled and requeued, the migration move turned inward), the
    deadline-aware gate must shed doomed standard work at admission with
    honest retry hints, and after the storm the ladder must walk fully
    BACK DOWN: final level 0, every preempted stream re-served, zero
    leaked pages, zero move-once marks, the one compiled step never
    recompiled, and every one of the trace's requests accounted
    exactly-once across admitted/shed/expired outcomes."""
    from paddle_tpu import loadgen
    from paddle_tpu.serving import (OverloadConfig, OverloadController,
                                    RetryBudget, tracing)

    r = Router(retry_budget=RetryBudget(capacity=16.0,
                                        refill_per_step=1.0))
    r.add_model("m", model, replicas=2, page_size=4, num_pages=128,
                max_batch_slots=8, max_model_len=64, token_budget=32,
                min_step_tokens=32, max_queue=128)
    for h in r.handles("m"):
        h.engine.add_request(P4, max_new_tokens=2)
        h.engine.run()
    tiers = (
        loadgen.TierSpec("interactive", priority=0, weight=0.15,
                         ttft_slo_s=1.5, itl_slo_s=0.5),
        loadgen.TierSpec("standard", priority=1, weight=0.5185,
                         deadline_s=6.0, ttft_slo_s=2.0, itl_slo_s=1.0),
        loadgen.TierSpec("batch", priority=2, weight=0.3315,
                         ttft_slo_s=10.0, itl_slo_s=5.0),
    )
    cfg = loadgen.TraceConfig(
        seed=SEED, num_requests=64, vocab_size=128,
        arrival_rate=8.0, burst_start=0.3, burst_duration=1.5,
        burst_factor=16.0, num_prompt_families=6, prefix_len=8,
        max_prompt_len=28, output_len_mean=24.0, output_len_sigma=0.5,
        max_output_len=32, slow_consumer_fraction=0.05, tiers=tiers)
    trace = loadgen.generate_trace(cfg)
    schedule = loadgen.FaultSchedule([
        loadgen.FaultEvent(t_s=0.1, kind="latency", delay_s=0.07,
                           steps=300),
        loadgen.FaultEvent(t_s=0.6, kind="kill", engine_index=0,
                           down_s=0.6),
    ])
    ctl = OverloadController(r, config=OverloadConfig(
        hot_backlog_s=0.12, cold_backlog_s=0.08, hot_steps=1,
        cold_steps=6, cooldown_steps=3, batch_chunk_cap=4))
    rep = loadgen.LoadDriver(r, trace, overload=ctl,
                             fault_schedule=schedule, step_dt=0.02).run()
    _check(rep.exactly_once,
           f"completion accounting violated: {rep.violations[:3]}")
    peak = max([lv for _, lv in ctl.events], default=0)
    _check(peak >= 3, f"ladder never reached preemption (peak={peak})")
    _check(ctl.level == 0,
           f"ladder did not walk back down (final={ctl.level})")
    _check(rep.outcomes.get("shed", 0) > 0,
           "the admission gate never shed doomed work")
    _check(_counter("paddle_tpu_serving_requests_total",
                    event="preempted") > 0,
           "no batch-tier slot was ever preempted")
    evs = {e["name"] for e in tracing.get_tracer().events()}
    _check({"req.shed", "req.preempt", "brownout.level"} <= evs,
           f"overload trace events missing: {evs}")
    bad = {k: v for k, v in rep.outcomes.items()
           if k not in ("stop", "length", "shed", "expired", "timeout",
                        "unavailable")}
    _check(not bad, f"unknown outcomes: {bad}")
    _check(sum(rep.outcomes.values()) == cfg.num_requests,
           "outcome count != trace size")
    inter = rep.tiers["interactive"].ttft_attainment
    _check(inter is not None and inter >= 0.75,
           f"interactive tier missed its TTFT SLO in the storm "
           f"({inter}) — the ladder exists to prevent exactly this")
    _check(all(e.pool.used_pages == 0 for e in r.engines("m")),
           "pages leaked")
    _check(r._requeued == set(), "move-once marks leaked")
    for e in r.engines("m"):
        counts = e.compile_counts()
        _check(counts["step"] == counts["step_buckets"],
               "brownout action recompiled the step")
    return (f"ladder 0->{peak}->0 ({len(ctl.events)} transitions), "
            f"outcomes {dict(sorted(rep.outcomes.items()))}, "
            f"interactive TTFT attainment {inter:.2f}, "
            f"0 leaked pages, step compiled once")


# ── 20. durable serving: SIGKILL the serving PROCESS mid-decode ──────────


def scenario_kill_serving_process(model):
    """ISSUE 20 acceptance: the request WAL survives PROCESS death.

    A child python serves a seeded trace behind ``Router(wal_dir=...)``,
    journaling admissions + every committed token batch (one fsync per
    step) and appending each DELIVERED chunk to a file — the file is the
    client. The parent SIGKILLs it mid-decode, then restarts the fleet
    with ONE ENGINE FEWER; ``Router.recover`` replays the WAL and
    resumes every stream after the cursor the chunk file proves
    delivered. Every completed stream must be bit-identical to an
    uninterrupted reference run, seqs exactly-once (no dup, no gap),
    with at least one stream genuinely resumed mid-decode and ZERO
    fresh XLA compiles paid during recovery (shared disk compile
    cache)."""
    from paddle_tpu.loadgen import restart

    workdir = tempfile.mkdtemp(prefix="chaos-wal-")
    try:
        res = restart.run_restart_drill(
            workdir, replicas_before=2, replicas_after=1,
            num_requests=6, kill_after_chunks=8)
        ref = restart.streams_by_index(res["ref_chunks"])
        full = restart.streams_by_index(
            res["pre_chunks"] + res["post_chunks"])
        _check(res["killed_after"] < len(res["ref_chunks"]),
               "SIGKILL landed after the workload drained — not "
               "mid-decode")
        _check(set(full) == set(ref), "stream set diverged across the "
               f"restart: {sorted(full)} vs {sorted(ref)}")
        for idx, chunks in sorted(ref.items()):
            _check(full[idx] == chunks,
                   f"stream {idx} not bit-identical across process "
                   f"death: {full[idx]} vs {chunks}")
            seqs = [s for _, _, s in full[idx]]
            _check(seqs == list(range(len(seqs))),
                   f"stream {idx} seqs not exactly-once: {seqs}")
        timing = res["timing"]
        resumed = timing.get("outcomes", {}).get("resumed", 0)
        _check(resumed >= 1,
               f"no stream resumed mid-decode (outcomes "
               f"{timing.get('outcomes')}) — the drill proved nothing")
        _check(timing["fresh_compiles"] == 0,
               f"recovery paid {timing['fresh_compiles']} fresh XLA "
               "compiles — the disk compile cache was cold")
        _check(res["rto_s"] is not None, "no recovered token observed")
        return (f"{len(ref)} streams bit-identical across SIGKILL "
                f"(killed at chunk {res['killed_after']}, {resumed} "
                f"resumed on a 2->1 engine fleet), 0 fresh compiles, "
                f"RTO {res['rto_s']:.2f}s")
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


SCENARIOS = [
    ("nan-quarantine-no-poison", scenario_nan_quarantine),
    ("page-pool-exhaustion-drain", scenario_pool_exhaustion),
    ("compile-failure-retry", scenario_compile_retry),
    ("deadline-and-cancel", scenario_deadline_and_cancel),
    ("queue-backpressure", scenario_backpressure),
    ("watchdog-healthz", scenario_watchdog_healthz),
    ("router-failover-requeue-once", scenario_router_failover),
    ("router-rolling-reload", scenario_router_reload),
    ("router-least-loaded-dispatch", scenario_router_least_loaded),
    ("kill-engine-mid-decode", scenario_kill_engine_mid_decode),
    ("prefix-cache-failover-migration", scenario_prefix_cache_failover),
    ("kill-engine-mid-chunked-prefill",
     scenario_kill_engine_mid_chunked_prefill),
    ("thread-fuzz-control-plane", scenario_thread_fuzz_control_plane),
    ("kill-engine-mid-spec-burst", scenario_kill_engine_mid_spec_burst),
    ("autoscale-under-burst", scenario_autoscale_under_burst),
    ("kill-engine-mid-constrained-adapter-stream",
     scenario_kill_engine_mid_constrained_adapter_stream),
    ("flight-recorder-on-crash", scenario_flight_recorder_on_crash),
    ("kill-engine-with-offloaded-pages",
     scenario_kill_engine_with_offloaded_pages),
    ("brownout-under-burst", scenario_brownout_under_burst),
    ("kill-serving-process-mid-decode", scenario_kill_serving_process),
]


def main() -> int:
    model = _model()
    print(f"chaos_serve: seed={SEED}, {len(SCENARIOS)} scenarios\n")
    failures = 0
    for name, fn in SCENARIOS:
        faults.reset()
        try:
            detail = fn(model)
            print(f"  PASS  {name:<28} {detail}")
        except Exception as e:  # noqa: BLE001 — report, don't crash
            failures += 1
            print(f"  FAIL  {name:<28} {e!r}")
    faults.reset()
    injected = _counter("paddle_tpu_faults_injected_total",
                        point="serving.decode_step")
    print(f"\nfault points armed this run: "
          f"{sorted(faults.known_points())}")
    print(f"injected (decode_step alone): {int(injected)}; full telemetry: "
          f"python tools/metrics_dump.py --demo")
    verdict = "RESILIENT" if failures == 0 else f"{failures} FAILURE(S)"
    print(f"verdict: {verdict}")
    return 0 if failures == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
