#!/usr/bin/env python
"""Chaos drill for the serving stack: run the demo engine under a seeded
fault schedule and print a pass/fail resilience report.

The operational twin of tests/test_faults.py (docs/RESILIENCE.md): six
scenarios arm ``paddle_tpu.faults`` injections against a tiny llama
engine — NaN quarantine, page-pool exhaustion, compile-failure retry,
deadline expiry + cancellation, queue backpressure, watchdog trip +
``/healthz`` — and each asserts both the behavior AND the telemetry
(every failure path must move its counter). Exit code 0 iff every
scenario passes.

Run: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python tools/chaos_serve.py
"""
import json
import os
import sys
import urllib.error
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu import faults, metrics  # noqa: E402
from paddle_tpu.models import LlamaForCausalLM, llama_tiny  # noqa: E402
from paddle_tpu.serving import BackpressureError, ServingEngine  # noqa: E402

SEED = int(os.environ.get("CHAOS_SEED", "0"))


def _model():
    paddle.seed(SEED)
    return LlamaForCausalLM(llama_tiny(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        num_key_value_heads=2, max_position_embeddings=64))


def _counter(name, **labels):
    fam = metrics.get_registry().get(name)
    if fam is None:
        return 0.0
    return (fam.labels(**labels) if labels else fam).value


def _check(cond, what):
    if not cond:
        raise AssertionError(what)


_RNG = np.random.RandomState(7)
P5, P9, P3, P4 = (_RNG.randint(0, 128, (n,)) for n in (5, 9, 3, 4))


def scenario_nan_quarantine(model):
    """NaN in one sequence's KV: victim quarantined, mate token-identical
    to a fault-free run, pages recover, decode compiles once."""
    ref_eng = ServingEngine(model, page_size=4, max_batch_slots=2)
    rm = ref_eng.add_request(P5, max_new_tokens=8)
    ref_eng.add_request(P9, max_new_tokens=8)
    ref = ref_eng.run()

    before = _counter("paddle_tpu_serving_nan_quarantines_total")
    eng = ServingEngine(model, page_size=4, max_batch_slots=2)
    mate = eng.add_request(P5, max_new_tokens=8)
    victim = eng.add_request(P9, max_new_tokens=8)
    eng.step()
    with faults.inject("serving.decode_step",
                       call=lambda: eng.pool.poison_seq(victim),
                       times=1, seed=SEED):
        outs = eng.run()
    _check(outs[victim].finish_reason == "nan", "victim not quarantined")
    _check(list(outs[mate].token_ids) == list(ref[rm].token_ids),
           "batch-mate tokens diverged from fault-free run")
    _check(eng.pool.used_pages == 0, "pages leaked")
    _check(_counter("paddle_tpu_serving_nan_quarantines_total")
           == before + 1, "quarantine counter")
    _check(eng.compile_counts()["decode"] == 1, "decode recompiled")
    return (f"victim n_gen={outs[victim].n_gen} reason=nan; mate "
            f"token-identical ({outs[mate].n_gen} tokens)")


def scenario_pool_exhaustion(model):
    """One injected allocation failure mid-decode: victim errors out,
    everything else (including queued work) drains."""
    eng = ServingEngine(model, page_size=4, max_batch_slots=2)
    victim = eng.add_request(P3, max_new_tokens=6)
    mate = eng.add_request(P4, max_new_tokens=6)
    queued = eng.add_request(P3, max_new_tokens=4)
    eng.step()
    with faults.inject("serving.kv_alloc",
                       raise_=faults.ResourceExhausted, times=1, seed=SEED):
        outs = eng.run()
    _check(outs[victim].finish_reason == "error", "victim not quarantined")
    _check(outs[mate].finish_reason == "length", "mate was disturbed")
    _check(outs[queued].finish_reason == "length", "queued work stranded")
    _check(eng.pool.used_pages == 0, "pages leaked")
    return "victim=error, mate+queued drained, 0 pages leaked"


def scenario_compile_retry(model):
    """A transient decode-build failure is retried; still one compile."""
    eng = ServingEngine(model, page_size=4, max_batch_slots=1)
    rid = eng.add_request(P4, max_new_tokens=3)
    before = _counter("paddle_tpu_faults_retries_total")
    with faults.inject("serving.compile_decode",
                       raise_=RuntimeError("flaky build"), times=1,
                       seed=SEED):
        outs = eng.run()
    _check(outs[rid].finish_reason == "length", "request failed")
    _check(_counter("paddle_tpu_faults_retries_total") > before,
           "no retry recorded")
    _check(eng.compile_counts()["decode"] == 1, "decode recompiled")
    return "1 injected build failure, 1 retry, decode compiled once"


def scenario_deadline_and_cancel(model):
    """Deadline expiry and cancel() retire with their own reasons and
    counters; pages free immediately."""
    eng = ServingEngine(model, page_size=4, max_batch_slots=1)
    t_before = _counter("paddle_tpu_serving_request_timeouts_total")
    c_before = _counter("paddle_tpu_serving_cancellations_total")
    running = eng.add_request(P4, max_new_tokens=6)
    late = eng.add_request(P3, max_new_tokens=6, deadline_s=0.0)
    eng.step()
    cancelled = eng.add_request(P3, max_new_tokens=6)
    eng.cancel(cancelled)
    eng.slots[0].req.deadline = faults.Deadline(-1.0)  # force mid-decode
    outs = eng.run()
    _check(outs[late].finish_reason == "timeout", "queued timeout")
    _check(outs[running].finish_reason == "timeout", "mid-decode timeout")
    _check(outs[cancelled].finish_reason == "cancelled", "cancel")
    _check(_counter("paddle_tpu_serving_request_timeouts_total")
           == t_before + 2, "timeout counter != exactly 2")
    _check(_counter("paddle_tpu_serving_cancellations_total")
           == c_before + 1, "cancel counter != exactly 1")
    _check(eng.pool.used_pages == 0, "pages leaked")
    return "2 timeouts + 1 cancel, each counted exactly once"


def scenario_backpressure(model):
    """A bounded queue rejects with a retry_after_s hint, not OOM."""
    eng = ServingEngine(model, page_size=4, max_batch_slots=1, max_queue=1)
    eng.add_request(P3, max_new_tokens=2)
    try:
        eng.add_request(P3, max_new_tokens=2)
        raise AssertionError("full queue accepted a request")
    except BackpressureError as e:
        hint = e.retry_after_s
    _check(hint > 0, "no retry_after_s hint")
    eng.run()
    eng.add_request(P3, max_new_tokens=1)  # drained queue admits again
    eng.run()
    return f"rejected with retry_after_s={hint:.3f}s, recovered after drain"


def scenario_watchdog_healthz(model):
    """Latency injection trips the watchdog; /healthz goes 503 and
    recovers after healthy steps."""
    eng = ServingEngine(model, page_size=4, max_batch_slots=1,
                        watchdog_stall_s=0.005, watchdog_recovery_steps=2)
    with metrics.MetricsServer(health_cb=eng.health, port=0) as srv:
        with faults.inject("serving.step", delay_s=0.02, times=1,
                           seed=SEED):
            eng.step()
        try:
            urllib.request.urlopen(f"{srv.url}/healthz")
            raise AssertionError("/healthz stayed 200 while degraded")
        except urllib.error.HTTPError as e:
            _check(e.code == 503, f"expected 503, got {e.code}")
            _check(json.loads(e.read())["status"] == "degraded",
                   "degraded body")
        eng.step()
        eng.step()
        with urllib.request.urlopen(f"{srv.url}/healthz") as r:
            _check(r.status == 200, "no recovery")
    trips = eng.watchdog.trips
    _check(trips == 1, f"expected exactly 1 trip episode, got {trips}")
    return "tripped -> /healthz 503 -> recovered -> 200 (1 episode)"


SCENARIOS = [
    ("nan-quarantine-no-poison", scenario_nan_quarantine),
    ("page-pool-exhaustion-drain", scenario_pool_exhaustion),
    ("compile-failure-retry", scenario_compile_retry),
    ("deadline-and-cancel", scenario_deadline_and_cancel),
    ("queue-backpressure", scenario_backpressure),
    ("watchdog-healthz", scenario_watchdog_healthz),
]


def main() -> int:
    model = _model()
    print(f"chaos_serve: seed={SEED}, {len(SCENARIOS)} scenarios\n")
    failures = 0
    for name, fn in SCENARIOS:
        faults.reset()
        try:
            detail = fn(model)
            print(f"  PASS  {name:<28} {detail}")
        except Exception as e:  # noqa: BLE001 — report, don't crash
            failures += 1
            print(f"  FAIL  {name:<28} {e!r}")
    faults.reset()
    injected = _counter("paddle_tpu_faults_injected_total",
                        point="serving.decode_step")
    print(f"\nfault points armed this run: "
          f"{sorted(faults.known_points())}")
    print(f"injected (decode_step alone): {int(injected)}; full telemetry: "
          f"python tools/metrics_dump.py --demo")
    verdict = "RESILIENT" if failures == 0 else f"{failures} FAILURE(S)"
    print(f"verdict: {verdict}")
    return 0 if failures == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
