#!/usr/bin/env python
"""Eager-mode per-op dispatch overhead vs jit (SURVEY §7 hard part #4).

The reference benchmarks its eager dispatch in
test/cpp/eager/performance_tests/benchmark_utils.cc (scale-sum loops through
the C++ ad_func path). Here every eager op is a Python apply_op -> jax.vjp
dispatch; under jit the same chain traces away. This measures both:

  1. eager small-op loop: y = x*2 + 1 over a (8,) tensor, N times
     (tape on: the realistic training-debug path)
  2. eager with no_grad (tape off: pure dispatch cost)
  3. the same loop inside ONE StaticFunction (compiled; the deploy path)
  4. raw jax eager for reference (what the dispatch layer adds on top)

Appends a JSON line to BENCH_NOTES_r05.json. Run with no args.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np


def _bench(fn, n, warmup=20):
    for _ in range(warmup):
        fn()
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - t0) / n


def main():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import jit

    dev = jax.devices()[0]
    N = int(os.environ.get("BENCH_EAGER_ITERS", 300))
    OPS_PER_ITER = 2  # mul + add

    x = paddle.to_tensor(np.ones(8, np.float32))
    x.stop_gradient = False

    def eager_tape():
        return (x * 2.0 + 1.0).value.block_until_ready()

    def eager_nograd():
        with paddle.no_grad():
            return (x * 2.0 + 1.0).value.block_until_ready()

    xj = jnp.ones(8, jnp.float32)

    def raw_jax():
        return ((xj * 2.0) + 1.0).block_until_ready()

    def chain(v):
        for _ in range(OPS_PER_ITER * 50):  # 100 small ops in one program
            v = v * 2.0 + 1.0
        return v

    compiled = jit.StaticFunction(chain, warmup=False)
    y = compiled(paddle.to_tensor(np.ones(8, np.float32)))  # compile
    y.value.block_until_ready()

    def jit_chain():
        return compiled(x).value.block_until_ready()

    t_tape = _bench(eager_tape, N) / OPS_PER_ITER
    t_nograd = _bench(eager_nograd, N) / OPS_PER_ITER
    t_raw = _bench(raw_jax, N) / OPS_PER_ITER
    t_jit = _bench(jit_chain, max(20, N // 10)) / (OPS_PER_ITER * 50 * 2)

    rec = {
        "metric": "eager_dispatch_overhead",
        "unit": "us/op",
        "device": str(dev.platform),
        "eager_tape_us": round(t_tape * 1e6, 1),
        "eager_nograd_us": round(t_nograd * 1e6, 1),
        "raw_jax_us": round(t_raw * 1e6, 1),
        "jit_us_per_op": round(t_jit * 1e6, 2),
        "tape_overhead_us": round((t_tape - t_raw) * 1e6, 1),
        "jit_speedup_x": round(t_tape / max(t_jit, 1e-12), 1),
    }
    print(json.dumps(rec), flush=True)
    notes = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                         "BENCH_NOTES_r05.json")
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(notes, "a") as f:
        f.write(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()
