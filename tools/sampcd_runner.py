#!/usr/bin/env python
"""Docstring code-sample runner — the repo's equivalent of the reference's
``tools/sampcd_processor.py`` (which extracts ``>>> `` example blocks from
API docstrings and executes them as CI; see reference
tools/sampcd_processor.py:1 "Sample code check").

TPU-first redesign: samples run on CPU (PALLAS_AXON_POOL_IPS must be unset
by the harness so the axon plugin never claims the chip for doc snippets),
each docstring's block executes in a fresh namespace with ``paddle``
pre-imported, and failures report module:qualname so the sample is
findable. Output matching is NOT enforced (array reprs are
device/precision-dependent); a sample passes iff it executes without
raising — the same contract the reference applies to non-deterministic
samples via its SKIP directives.

Usage:
  python tools/sampcd_runner.py            # whole package
  python tools/sampcd_runner.py nn jit     # only these subpackage prefixes
"""
import doctest
import os
import sys
import traceback

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

PKG = "paddle_tpu"


def iter_sample_blocks(prefixes=()):
    """Yield (location, sample_source) for every ``>>>`` block in package
    docstrings, found by scanning source files (import-free discovery —
    importing every module to inspect it would execute heavyweight module
    bodies twice and hide import-order bugs)."""
    parser = doctest.DocTestParser()
    pkg_root = os.path.join(REPO, PKG)
    for dirpath, _dirnames, filenames in os.walk(pkg_root):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, REPO)
            mod_rel = os.path.relpath(path, pkg_root)
            if prefixes and not any(
                    mod_rel.startswith(p) for p in prefixes):
                continue
            try:
                src = open(path, encoding="utf-8").read()
            except OSError:
                continue
            if ">>> " not in src:
                continue
            import ast as _ast
            try:
                tree = _ast.parse(src)
            except SyntaxError:
                continue
            for node in _ast.walk(tree):
                if not isinstance(node, (_ast.Module, _ast.ClassDef,
                                         _ast.FunctionDef,
                                         _ast.AsyncFunctionDef)):
                    continue
                doc = _ast.get_docstring(node, clean=True)
                if not doc or ">>>" not in doc:
                    continue
                name = getattr(node, "name", "<module>")
                try:
                    examples = parser.get_examples(doc)
                except ValueError as e:
                    # malformed sample (inconsistent indentation etc.):
                    # report it as a failing block with attribution
                    # instead of killing the whole discovery walk
                    yield (f"{rel}:{name}",
                           f"raise ValueError({str(e)[:120]!r})")
                    continue
                if not examples:
                    continue
                block = "".join(e.source for e in examples)
                yield f"{rel}:{name}", block


def run_block(location, source):
    ns = {}
    preamble = ("import numpy as np\n"
                "import paddle_tpu as paddle\n")
    try:
        exec(preamble + source, ns)  # noqa: S102 — that IS the check
        return None
    except Exception:
        return traceback.format_exc(limit=3)


def main():
    # self-scrub: doc snippets must NEVER claim the TPU tunnel. Re-exec
    # into the repo's standard CPU-only env (the same scrub bench.py's
    # CPU fallback performs) unless already scrubbed.
    if (os.environ.get("PALLAS_AXON_POOL_IPS")
            or os.environ.get("JAX_PLATFORMS") != "cpu"):
        import subprocess
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("PJRT_LIBRARY_PATH", None)
        env["JAX_PLATFORMS"] = "cpu"
        return subprocess.call([sys.executable] + sys.argv, env=env)
    prefixes = tuple(sys.argv[1:])
    blocks = list(iter_sample_blocks(prefixes))
    if not blocks:
        print("no docstring samples found", file=sys.stderr)
        return 1
    failures = []
    for loc, src in blocks:
        err = run_block(loc, src)
        status = "ok" if err is None else "FAIL"
        print(f"  [{status}] {loc} ({len(src.splitlines())} lines)")
        if err:
            failures.append((loc, err))
    print(f"{len(blocks) - len(failures)}/{len(blocks)} sample blocks pass")
    for loc, err in failures:
        print(f"--- {loc} ---\n{err}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
