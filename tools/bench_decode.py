#!/usr/bin/env python
"""Autoregressive decode throughput (KV-cache, device-side while_loop).

Greedy decode on one chip: B8, prompt 128, 128 new tokens — the whole
decode is ONE compiled program (models/generation.py device loop), so
the measurement is real device time, not 63ms-per-token tunnel round
trips. Covers GPT-355M and Llama-0.76B (set BENCH_DECODE_MODELS to a
comma list to narrow). Appends each row to BENCH_NOTES_r05.json.

``--paged``: continuous-batching engine sweep (paddle_tpu.serving) —
engine tokens/s vs this dense loop at batch {1, 8, 32}, one JSON row per
(mode, batch) in the same record shape as the dense rows
(``*_paged_decode_tokens_per_sec_per_chip`` vs
``*_decode_tokens_per_sec_per_chip``).

``--shared-prefix``: prefix-cache scenario (ISSUE 8) — N requests
(BENCH_SHARED_N, default 100) sharing a BENCH_SHARED_PREFIX-token
(default 1024) common prefix with unique 16-token suffixes. Reports
``prefill_tokens_saved_total`` (expect ~(N-1) x prefix), cold-vs-warm
prefill wall time, TTFT p50/p95, a bit-identity check of a warm stream
against a cache-off cold run, and the unified-step compile count (one
per token-grid bucket).

``--mixed``: long-prompt-admission scenario (ISSUE 11) — N decoding
tenants (BENCH_MIXED_TENANTS, default 3) while one
BENCH_MIXED_PROMPT-token (default 10000) prompt admits and
chunk-prefills under BENCH_MIXED_BUDGET tokens/step through the unified
ragged step with a pinned grid. Reports tenants' p50/p95/p99 ITL before
vs during admission (asserts p95 within 15%), the long prompt's TTFT, a
zero-recompile assert over the admission, and a bit-identity check of
every stream against admission-free runs — BENCH_MIXED row.

``--spec``: speculative decoding (ISSUE 14) — BENCH_SPEC_BATCH greedy
decoders with period-3 repeating prompts run spec-off then spec-on
(NGramDrafter, k=BENCH_SPEC_K). Drafts ride the unified step as extra
grid rows (data, not programs) and verification reuses the
per-position sampling keys, so the row asserts every stream
bit-identical spec on vs off and reports tokens/s for both modes plus
the drafted/accepted acceptance rate. Also emits a cold-vs-warm
engine start-up row: a first engine compiles fresh into a persistent
compile-cache dir, a second identical engine (in-process memory layer
dropped) must materialize every program from disk and start faster.

``--host-tier``: KV-memory-economics sweep (ISSUE 18) — bf16 vs int8
KV pages at the SAME fixed HBM budget (BENCH_KV_HBM_KIB, head_dim 128
so the int8 page-byte ratio is (2*hd)/(hd+4) = 1.94x). Per dtype the
sweep sizes the pool with ``pages_for_hbm_budget``, actually serves
that many concurrent users, and measures p95 ITL both at capacity and
at a MATCHED batch (the apples-to-apples 1.15x guard), plus the spec
acceptance rate per dtype (the quantized-attention tolerance guard),
an int8+host-offload park/prefetch phase whose parked stream must be
bit-identical to an uncontended run, and a full-arm compile pin
(int8 + host tier + spec + grammar on one engine, step ==
step_buckets, zero steady-state recompiles). Emits ONE ``BENCH_KV``
row; ``--kv-out BENCH_KV.json`` commits it (the artifact comes from
the CPU smoke, like BENCH_LOAD.json — tests/test_bench_tools.py pins
its SCHEMA, never host-dependent values).

``--kv-dtype {bf16,int8}``: page dtype for the ``--paged`` engine rows
(config tag gains ``-kv<dtype>``) — ``--paged --kv-dtype int8`` is the
acceptance-criterion spelling for the users/chip claim on silicon.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np

_NOTES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                      "BENCH_NOTES_r05.json")

# BENCH_KV schema (ISSUE 18) — tests/test_bench_tools.py pins these key
# sets against the committed BENCH_KV.json exactly like BENCH_LOAD:
# values are host-dependent, keys (and the determinism-contract booleans)
# are the contract
KV_ROW_KEYS = ("metric", "value", "unit", "vs_baseline", "config",
               "device", "report")
KV_REPORT_KEYS = ("hbm_budget_kib", "page_size", "head_dim", "n_kv_heads",
                  "num_layers", "prompt_tokens", "new_tokens",
                  "users_ratio", "itl_p95_ratio", "spec_acceptance_delta",
                  "tiers", "host_tier", "full_arm")
KV_TIER_KEYS = ("kv_dtype", "page_bytes", "num_pages", "users_per_chip",
                "tokens_per_sec", "itl_ms", "itl_matched_p95_ms",
                "spec_acceptance_rate", "peak_pages", "step_compiles",
                "step_buckets")
KV_HOST_KEYS = ("offload_pages", "prefetch_pages", "prefetch_late",
                "parked_seen", "round_trip_bit_exact")
KV_ARM_KEYS = ("features", "step_compiles", "step_buckets",
               "extra_jit_compiles")


def build_kv_row(report: dict, config_label: str, device: str) -> dict:
    """The one BENCH_KV row, schema-pinned: headline value is the
    users/chip ratio int8 vs bf16 at the same HBM budget; the per-dtype
    evidence rides under ``report`` trimmed to the schema-stable keys."""
    rep = {k: report[k] for k in KV_REPORT_KEYS}
    rep["tiers"] = {name: {k: tier[k] for k in KV_TIER_KEYS}
                    for name, tier in report["tiers"].items()}
    rep["host_tier"] = {k: report["host_tier"][k] for k in KV_HOST_KEYS}
    rep["full_arm"] = {k: report["full_arm"][k] for k in KV_ARM_KEYS}
    return {
        "metric": "BENCH_KV",
        "value": round(float(report["users_ratio"]), 3),
        "unit": "ratio",
        "vs_baseline": 1.0,
        "config": config_label,
        "device": device,
        "report": rep,
    }


def _build(model_name, prompt, new, small):
    import paddle_tpu as paddle

    if model_name == "gpt":
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        cfg = GPTConfig(vocab_size=128 if small else 50304,
                        hidden_size=64 if small else 1024,
                        num_layers=2 if small else 24,
                        num_heads=4 if small else 16,
                        max_position_embeddings=prompt + new,
                        hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
        paddle.seed(0)
        return GPTForCausalLM(cfg), cfg.vocab_size, "gpt-355m"
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=128 if small else 32000,
                      hidden_size=64 if small else 2048,
                      num_layers=2 if small else 12,
                      num_heads=4 if small else 16,
                      num_key_value_heads=4 if small else 16,
                      max_position_embeddings=prompt + new)
    paddle.seed(0)
    return LlamaForCausalLM(cfg), cfg.vocab_size, "llama-0.76b"


def _already_banked(metric, B, prompt, new, tag=""):
    """Resume safety: a partial failure exits 1, the battery re-runs the
    whole tool, and append-only notes would duplicate the model that
    succeeded — skip rows already banked on silicon this round. Keyed by
    the (B, prompt, new) geometry too: decode is memory-bound, so batch
    probes (battery step 8b, B=32) are distinct measurements, not
    re-runs of the b8 row. ``tag`` is an extra config discriminator
    (the paged rows' ``-kv<dtype>`` — an int8 row must not skip on a
    banked bf16 row at the same geometry)."""
    from _bench_timing import iter_notes_rows
    suffix = tag + _geometry(B, prompt, new)
    return any(rec.get("metric") == metric
               and rec.get("device") in ("tpu", "axon")
               and str(rec.get("config", "")).endswith(suffix)
               for rec in iter_notes_rows(_NOTES))


def _geometry(B, prompt, new):
    """One source of truth for the config-label geometry suffix — the
    banked-row skip matches on exactly this string, so the two sites
    cannot drift."""
    return f"-decode-b{B}-p{prompt}-n{new}-greedy"


def _bench_one(model_name, rt, B, prompt, new, dev, small):
    import paddle_tpu as paddle

    metric = f"{model_name}_decode_tokens_per_sec_per_chip"
    if not small and _already_banked(metric, B, prompt, new):
        print(f"decode[{model_name}]: b{B}-p{prompt}-n{new} already banked "
              "this round — skipping", file=sys.stderr)
        return
    model, vocab, label = _build(model_name, prompt, new, small)
    model.eval()
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, vocab, (B, prompt)))

    t0 = time.time()
    model.generate(ids, max_new_tokens=new, temperature=0.0,
                   device_loop=True)
    compile_s = time.time() - t0
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        model.generate(ids, max_new_tokens=new, temperature=0.0,
                       device_loop=True)
        best = min(best, time.perf_counter() - t0 - rt)
    # generate() fetches the result (host concat) — already synced
    tok_s = B * new / best
    rec = {
        "metric": metric,
        "value": round(tok_s, 1), "unit": "tokens/s", "vs_baseline": 1.0,
        "config": label + _geometry(B, prompt, new),
        "total_s": round(best, 3), "compile_s": round(compile_s, 1),
        "per_token_ms": round(1e3 * best / new, 2),
        "device": str(dev.platform),
    }
    print(json.dumps(rec))
    if small:
        return  # CPU smoke: never pollute the round's evidence file
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(_NOTES, "a") as f:
        f.write(json.dumps(rec) + "\n")


def _latency_percentiles():
    """TTFT / inter-token-latency p50/p95/p99 (ms) from the serving
    histograms — the latency half of the paged row (ISSUE 2): BENCH
    rows carry SLO percentiles next to the throughput number."""
    from paddle_tpu import metrics

    reg = metrics.get_registry()
    out = {}
    for key, name in (("ttft_ms", "paddle_tpu_serving_ttft_seconds"),
                      ("itl_ms",
                       "paddle_tpu_serving_inter_token_seconds")):
        h = reg.get(name)
        if h is None or h.count == 0:
            continue
        out[key] = {f"p{int(q * 100)}": round(h.quantile(q) * 1e3, 3)
                    for q in (0.5, 0.95, 0.99)}
    return out


def _bench_paged_one(model_name, rt, B, prompt, new, dev, small,
                     kv_dtype=None):
    """Engine (paged, continuous-batching) throughput at batch B — same
    record shape as _bench_one so BENCH digests treat both alike.
    ``kv_dtype`` (``--kv-dtype``) selects the KV page dtype; the config
    tag carries it so bf16/int8 rows bank separately."""
    import paddle_tpu as paddle  # noqa: F401  (model seed side effect)
    from paddle_tpu import metrics
    from paddle_tpu.serving import ServingEngine

    kvtag = f"-kv{kv_dtype}" if kv_dtype else ""
    metric = f"{model_name}_paged_decode_tokens_per_sec_per_chip"
    if not small and _already_banked(metric, B, prompt, new, tag=kvtag):
        print(f"paged[{model_name}]: {kvtag}b{B}-p{prompt}-n{new} already "
              "banked this round — skipping", file=sys.stderr)
        return
    model, vocab, label = _build(model_name, prompt, new, small)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, vocab, (prompt,)) for _ in range(B)]
    engine = ServingEngine(
        model, page_size=16, max_batch_slots=B,
        token_budget=max(B * prompt, 1024),
        kv_dtype=kv_dtype or "f32")

    def run_once():
        for p in prompts:
            engine.add_request(p, max_new_tokens=new, temperature=0.0)
        engine.run()

    t0 = time.time()
    run_once()  # compile prefill bucket + the single decode program
    compile_s = time.time() - t0
    # isolate the measured runs' latency histograms from the compile
    # pass: a compile-inflated TTFT p99 would be nonsense
    metrics.get_registry().reset()
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        run_once()
        best = min(best, time.perf_counter() - t0 - rt)
    tok_s = B * new / best
    rec = {
        "metric": metric,
        "value": round(tok_s, 1), "unit": "tokens/s", "vs_baseline": 1.0,
        "config": label + "-paged" + kvtag + _geometry(B, prompt, new),
        "total_s": round(best, 3), "compile_s": round(compile_s, 1),
        "per_token_ms": round(1e3 * best / new, 2),
        "step_compiles": engine.compile_counts()["step"],
        "peak_pages": engine.pool.peak_used,
        "device": str(dev.platform),
    }
    rec.update(_latency_percentiles())
    print(json.dumps(rec))
    if small:
        return  # CPU smoke: never pollute the round's evidence file
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(_NOTES, "a") as f:
        f.write(json.dumps(rec) + "\n")


def _bench_shared_prefix(model_name, rt, prefix_len, new, dev, small):
    """Prefix-cache proof: N requests over one shared prefix. The first
    request prefills the whole prompt (cold, and seeds the radix cache);
    every later one matches the cached prefix pages and prefills only
    its 16-token unique suffix — the saved-tokens counter and the
    cold/warm wall-clock ratio are the row's payload."""
    import paddle_tpu as paddle  # noqa: F401  (model seed side effect)
    from paddle_tpu import metrics
    from paddle_tpu.serving import ServingEngine

    n_req = int(os.environ.get("BENCH_SHARED_N", "6" if small else "100"))
    if small:
        prefix_len = min(prefix_len, 48)
    suffix = 16
    metric = f"{model_name}_shared_prefix_prefill_tokens_saved"
    cfg_tag = f"-shared-prefix-b{n_req}-p{prefix_len}-n{new}-greedy"
    if not small:
        from _bench_timing import iter_notes_rows
        if any(rec.get("metric") == metric
               and rec.get("device") in ("tpu", "axon")
               and str(rec.get("config", "")).endswith(cfg_tag)
               for rec in iter_notes_rows(_NOTES)):
            print(f"shared-prefix[{model_name}]: b{n_req}-p{prefix_len}-"
                  f"n{new} already banked this round — skipping",
                  file=sys.stderr)
            return
    model, vocab, label = _build(model_name, prefix_len + suffix, new,
                                 small)
    model.eval()
    rng = np.random.default_rng(0)
    prefix = rng.integers(0, vocab, (prefix_len,))
    prompts = [np.concatenate([prefix, rng.integers(0, vocab, (suffix,))])
               for _ in range(n_req)]

    # bit-identity oracle: one prompt end-to-end on a CACHE-OFF engine
    off = ServingEngine(model, page_size=16, max_batch_slots=2,
                        token_budget=prefix_len + suffix,
                        prefix_cache=False)
    ref_id = off.add_request(prompts[1], max_new_tokens=new,
                             temperature=0.8, seed=11)
    ref = list(off.run()[ref_id].token_ids)

    engine = ServingEngine(model, page_size=16,
                           max_batch_slots=min(n_req, 8),
                           token_budget=prefix_len + suffix)
    # compile pass: one cold + one warm request builds the full-prefill
    # AND suffix-prefill programs plus the single decode program, so the
    # measured section below times serving, not XLA
    wid = engine.add_request(prompts[0], max_new_tokens=1)
    engine.run()
    engine.add_request(prompts[1], max_new_tokens=1)
    engine.run()
    del wid

    reg = metrics.get_registry()

    def saved():
        fam = reg.get("paddle_tpu_serving_prefill_tokens_saved_total")
        return 0.0 if fam is None else fam.value

    # cold measurement on the SAME engine via the per-request opt-out
    # (programs already compiled; prefix_cache=False forces the full
    # prefill a pre-cache engine would run) — apples-to-apples against
    # the warm sweep below
    t0 = time.perf_counter()
    engine.add_request(prompts[0], max_new_tokens=new,
                       prefix_cache=False)
    engine.run()
    cold_s = time.perf_counter() - t0 - rt

    # isolate the measured warm section: reset zeroes every series
    # (families and label children stay registered), THEN snapshot the
    # compile counter so extra_jit_compiles counts only warm-sweep builds
    metrics.get_registry().reset()
    jit0 = _counter_value("paddle_tpu_jit_compiles_total",
                          fn="serving_step")
    s0 = saved()
    warm_tokens = {}
    t0 = time.perf_counter()
    for i, p in enumerate(prompts):
        warm_tokens[engine.add_request(
            p, max_new_tokens=new, temperature=0.8, seed=11 if i == 1
            else i)] = i
    outs = engine.run()
    warm_s = time.perf_counter() - t0 - rt
    tokens_saved = saved() - s0
    warm_ref_id = next(r for r, i in warm_tokens.items() if i == 1)
    warm_equals_cold = list(outs[warm_ref_id].token_ids) == ref

    h = reg.get("paddle_tpu_serving_ttft_seconds")
    ttft = ({f"p{int(q * 100)}": round(h.quantile(q) * 1e3, 3)
             for q in (0.5, 0.95)} if h is not None and h.count else {})
    rec = {
        "metric": metric,
        "value": round(tokens_saved, 1), "unit": "tokens",
        "vs_baseline": 1.0,
        "config": label + cfg_tag,
        "requests": n_req, "prefix_len": prefix_len,
        "expected_saved": (n_req - 1) * (prefix_len // 16) * 16,
        "cold_run_s": round(cold_s, 3),
        "warm_total_s": round(warm_s, 3),
        "warm_per_req_s": round(warm_s / max(n_req, 1), 4),
        "warm_equals_cold": bool(warm_equals_cold),
        "step_compiles": engine.compile_counts()["step"],
        "extra_jit_compiles": _counter_value(
            "paddle_tpu_jit_compiles_total", fn="serving_step") - jit0,
        "ttft_ms": ttft,
        "device": str(dev.platform),
    }
    print(json.dumps(rec))
    if not warm_equals_cold:
        raise AssertionError(
            "warm-cache stream diverged from the cache-off cold run")
    if rec["extra_jit_compiles"]:
        raise AssertionError("step recompiled during the warm sweep")
    if small:
        return  # CPU smoke: never pollute the round's evidence file
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(_NOTES, "a") as f:
        f.write(json.dumps(rec) + "\n")


def _bench_mixed(model_name, rt, dev, small):
    """Long-prompt-admission scenario (ISSUE 11): N decoding tenants +
    one 10k-token prompt through the unified ragged step. The engine is
    pinned to ONE step shape (``min_step_tokens=token_budget``), so a
    prompt chunk rides grid rows a decode-only step already pays for —
    the measured claim is that the decoding tenants' p95/p99 ITL stays
    flat (within 15%) while the long prompt admits and chunk-prefills,
    with ZERO recompiles during admission and every stream bit-identical
    to an admission-free run (the determinism contract: chunking and
    batch composition never change a token)."""
    import paddle_tpu as paddle  # noqa: F401  (model seed side effect)
    from paddle_tpu import metrics
    from paddle_tpu.serving import ServingEngine

    prompt_len = int(os.environ.get("BENCH_MIXED_PROMPT", "10000"))
    tenants = int(os.environ.get("BENCH_MIXED_TENANTS", "3"))
    budget = int(os.environ.get("BENCH_MIXED_BUDGET",
                                "64" if small else "256"))
    new = int(os.environ.get("BENCH_MIXED_NEW", "64"))
    long_new = 4
    metric = f"{model_name}_mixed_admission_itl_p95_ratio"
    cfg_tag = (f"-mixed-t{tenants}-p{prompt_len}-budget{budget}-n{new}"
               f"-sampled")
    if not small:
        from _bench_timing import iter_notes_rows
        if any(rec.get("metric") == metric
               and rec.get("device") in ("tpu", "axon")
               and str(rec.get("config", "")).endswith(cfg_tag)
               for rec in iter_notes_rows(_NOTES)):
            print(f"mixed[{model_name}]: {cfg_tag} already banked this "
                  "round — skipping", file=sys.stderr)
            return
    if small:
        # CPU smoke: a 1-layer trunk keeps the 10k-token page-gather
        # tractable while exercising the full scheduler/step machinery
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig(vocab_size=128, hidden_size=32, num_layers=1,
                          num_heads=2, num_key_value_heads=1,
                          max_position_embeddings=prompt_len + new + 8)
        paddle.seed(0)
        model, vocab, label = LlamaForCausalLM(cfg), 128, "llama-smoke"
    else:
        model, vocab, label = _build(model_name, prompt_len, new, small)
    model.eval()
    rng = np.random.default_rng(0)
    tenant_prompts = [rng.integers(0, vocab, (16,)) for _ in range(tenants)]
    long_prompt = rng.integers(0, vocab, (prompt_len,))
    spec = dict(max_new_tokens=new, temperature=0.9)

    def build_engine():
        # min_step_tokens == token_budget pins the compiled grid: every
        # step (decode-only or mixed) is ONE shape, so ITL flatness is
        # the design's to lose, not the bucket set's
        return ServingEngine(model, page_size=64,
                             max_batch_slots=tenants + 1,
                             max_model_len=prompt_len + new + 8,
                             token_budget=budget,
                             min_step_tokens=budget)

    def drive(eng, admit_long):
        """Run N tenants; optionally admit the long prompt after two
        steps. Returns (per-tenant token (timestamp, id) lists, long
        prompt (ttft_s, token_ids))."""
        stamps = {i: [] for i in range(tenants)}

        def cb(i):
            return (lambda r, tok, fin, seq:
                    stamps[i].append((time.perf_counter(), tok))
                    if tok is not None else None)

        for i, p in enumerate(tenant_prompts):
            eng.add_request(p, stream_cb=cb(i), seed=100 + i, **spec)
        eng.step()
        eng.step()
        long_info = {}
        if admit_long:
            # the zero-recompile window is THE ADMISSION: the engine
            # compiled its one pinned grid bucket while the tenants
            # started decoding above; from here to drain, the long
            # prompt's chunks must add nothing
            jit0 = _counter_value("paddle_tpu_jit_compiles_total",
                                  fn="serving_step")
            t0 = time.perf_counter()
            long_first = []

            def long_cb(r, tok, fin, seq):
                if tok is not None and not long_first:
                    long_first.append(time.perf_counter() - t0)

            rid = eng.add_request(long_prompt, max_new_tokens=long_new,
                                  temperature=0.9, seed=7,
                                  stream_cb=long_cb)
            outs = eng.run()
            long_info = {"ttft_s": long_first[0],
                         "tokens": list(outs[rid].token_ids),
                         "extra_compiles": _counter_value(
                             "paddle_tpu_jit_compiles_total",
                             fn="serving_step") - jit0}
        else:
            eng.run()
        return stamps, long_info

    def itl_ms(stamps):
        gaps = sorted(g for s in stamps.values()
                      for g in np.diff([t for t, _ in s]))
        if not gaps:
            return {}
        q = lambda f: round(1e3 * gaps[min(int(f * len(gaps)),
                                           len(gaps) - 1)], 3)
        return {"p50": q(0.50), "p95": q(0.95), "p99": q(0.99)}

    # no separate compile pass: the compiled program cache is
    # per-engine, so each phase's engine warms its one pinned grid
    # bucket during its own first tenant steps — BEFORE any measured
    # quantity (ITL gaps are between tokens, which all land after the
    # first step's compile; the long prompt's TTFT clock starts at its
    # enqueue, two steps after the grid compiled)

    # phase A — no-admission baseline
    base_stamps, _ = drive(build_engine(), admit_long=False)
    base = itl_ms(base_stamps)
    # long-prompt oracle: the same config, ALONE — batch composition
    # must not change a single token of anyone's stream
    _, long_alone = drive(build_engine(), admit_long=True)

    # phase B — the measured admission run, zero-recompile asserted
    eng = build_engine()
    mixed_stamps, long_info = drive(eng, admit_long=True)
    extra_compiles = long_info["extra_compiles"]
    during = itl_ms(mixed_stamps)

    streams_identical = (
        long_info["tokens"] == long_alone["tokens"]
        and all([t for _, t in mixed_stamps[i]]
                == [t for _, t in base_stamps[i]]
                for i in range(tenants)))
    ratio = (during["p95"] / base["p95"]) if base.get("p95") else 0.0
    rec = {
        "metric": metric,
        "value": round(ratio, 3), "unit": "ratio", "vs_baseline": 1.0,
        "config": label + cfg_tag,
        "tenants": tenants, "long_prompt_tokens": prompt_len,
        "token_budget": budget,
        "itl_before_ms": base, "itl_during_ms": during,
        "ttft_long_ms": round(1e3 * long_info["ttft_s"], 1),
        "extra_jit_compiles": extra_compiles,
        "streams_identical": bool(streams_identical),
        "step_compiles": eng.compile_counts()["step"],
        "device": str(dev.platform),
    }
    print(json.dumps(rec))
    if extra_compiles:
        raise AssertionError(
            "the unified step recompiled during long-prompt admission")
    if not streams_identical:
        raise AssertionError(
            "a stream diverged under admission — chunking/batch "
            "composition leaked into sampling")
    if ratio > 1.15:
        raise AssertionError(
            f"decoding tenants' p95 ITL degraded {ratio:.2f}x during "
            f"admission (budget {budget}) — exceeds the 15% bound")
    if small:
        return  # CPU smoke: never pollute the round's evidence file
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(_NOTES, "a") as f:
        f.write(json.dumps(rec) + "\n")


def _bench_spec(model_name, rt, dev, small):
    """Speculative-decoding scenario (ISSUE 14): B greedy decoders with
    period-3 repeating prompts — an n-gram drafter's best case — run
    spec-off then spec-on through the unified ragged step. Drafts enter
    as extra grid rows of programs the engine already compiled, and
    acceptance compares drafts against the per-position sampled targets,
    so every stream must be bit-identical to the spec-off run; the row
    reports tokens/s for both modes and the drafted/accepted counters'
    acceptance rate. Both phases share one persistent compile-cache dir
    so each timed engine materializes its programs from cache, keeping
    XLA out of the throughput window."""
    import tempfile

    import paddle_tpu as paddle  # noqa: F401  (model seed side effect)
    from paddle_tpu.serving import ServingEngine

    B = int(os.environ.get("BENCH_SPEC_BATCH", "4"))
    new = int(os.environ.get("BENCH_SPEC_NEW", "32" if small else "128"))
    spec_k = int(os.environ.get("BENCH_SPEC_K", "4"))
    metric = f"{model_name}_spec_decode_speedup_ratio"
    cfg_tag = f"-spec-b{B}-k{spec_k}-n{new}-greedy"
    if not small:
        from _bench_timing import iter_notes_rows
        if any(rec.get("metric") == metric
               and rec.get("device") in ("tpu", "axon")
               and str(rec.get("config", "")).endswith(cfg_tag)
               for rec in iter_notes_rows(_NOTES)):
            print(f"spec[{model_name}]: {cfg_tag} already banked this "
                  "round — skipping", file=sys.stderr)
            return
    model, vocab, label = _build(model_name, 64, new + spec_k + 2, small)
    model.eval()
    # period-3 prompts: the suffix always recurs earlier, so the n-gram
    # drafter proposes from step one — and greedy decode tends to lock
    # into the cycle, giving real (not vacuous) acceptance
    prompts = [np.tile((np.arange(3) + 5 * i) % vocab, 8).astype(np.int64)
               for i in range(B)]
    cache_dir = tempfile.mkdtemp(prefix="bench_spec_jitcache_")

    def run(spec_on):
        eng = ServingEngine(
            model, page_size=16, max_batch_slots=B,
            max_model_len=int(prompts[0].size) + new + spec_k + 2,
            spec_k=spec_k if spec_on else 0,
            compile_cache_dir=cache_dir)
        stamps = []

        def cb(r, tok, fin, seq):
            if tok is not None:
                stamps.append(time.perf_counter())

        for i, p in enumerate(prompts):
            eng.add_request(p, max_new_tokens=new, temperature=0.0,
                            seed=11 + i, stream_cb=cb)
        eng.step()  # prefill (and its compile) outside the timed window
        eng.step()  # first decode step: materialize the decode bucket
        t0 = time.perf_counter()
        outs = eng.run()
        dt = time.perf_counter() - t0
        toks = [list(outs[r].token_ids) for r in sorted(outs)]
        tps = sum(1 for t in stamps if t >= t0) / dt if dt else 0.0
        return eng, toks, tps

    # warmup pass per mode seeds the persistent cache; the timed pass's
    # engine then materializes from memory/disk instead of compiling
    run(False)
    _, toks_off, tps_off = run(False)
    run(True)
    d0 = _counter_value("paddle_tpu_serving_spec_drafted_tokens_total")
    a0 = _counter_value("paddle_tpu_serving_spec_accepted_tokens_total")
    eng_on, toks_on, tps_on = run(True)
    drafted = _counter_value(
        "paddle_tpu_serving_spec_drafted_tokens_total") - d0
    accepted = _counter_value(
        "paddle_tpu_serving_spec_accepted_tokens_total") - a0
    streams_identical = toks_on == toks_off
    ratio = tps_on / tps_off if tps_off else 0.0
    rec = {
        "metric": metric,
        "value": round(ratio, 3), "unit": "ratio", "vs_baseline": 1.0,
        "config": label + cfg_tag,
        "batch": B, "spec_k": spec_k, "new_tokens": new,
        "tokens_per_sec_spec_off": round(tps_off, 1),
        "tokens_per_sec_spec_on": round(tps_on, 1),
        "drafted_tokens": int(drafted), "accepted_tokens": int(accepted),
        "acceptance_rate": (round(accepted / drafted, 3)
                            if drafted else 0.0),
        "streams_identical": bool(streams_identical),
        "step_compiles": eng_on.compile_counts()["step"],
        "device": str(dev.platform),
    }
    print(json.dumps(rec))
    if not streams_identical:
        raise AssertionError(
            "a stream diverged with speculation on — drafting leaked "
            "into sampling")
    if not drafted:
        raise AssertionError(
            "drafter proposed nothing on period-3 prompts — the suffix "
            "match is broken")
    if not small and ratio <= 1.0:
        raise AssertionError(
            f"speculation did not improve decode throughput "
            f"({ratio:.2f}x at k={spec_k}, "
            f"acceptance {rec['acceptance_rate']})")
    if small:
        return  # CPU smoke: never pollute the round's evidence file
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(_NOTES, "a") as f:
        f.write(json.dumps(rec) + "\n")


def _bench_cache_startup(model_name, rt, dev, small):
    """Cold-vs-warm engine start-up (ISSUE 14): the first engine in a
    fresh compile-cache dir compiles from XLA (source="fresh") and
    serializes each executable; a second identical engine — with the
    in-process memory layer dropped — must materialize every step
    program from disk (source="disk", zero fresh) and produce
    bit-identical tokens. The row reports both wall times and the
    per-source jit_compiles_total deltas."""
    import tempfile

    import paddle_tpu as paddle  # noqa: F401  (model seed side effect)
    from paddle_tpu import jit
    from paddle_tpu.serving import ServingEngine

    new = 8
    metric = f"{model_name}_engine_startup_warm_vs_cold_ratio"
    cfg_tag = f"-cachestart-n{new}"
    if not small:
        from _bench_timing import iter_notes_rows
        if any(rec.get("metric") == metric
               and rec.get("device") in ("tpu", "axon")
               and str(rec.get("config", "")).endswith(cfg_tag)
               for rec in iter_notes_rows(_NOTES)):
            print(f"cache-startup[{model_name}]: {cfg_tag} already "
                  "banked this round — skipping", file=sys.stderr)
            return
    model, vocab, label = _build(model_name, 32, new + 2, small)
    model.eval()
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, vocab, (16,))
    cache_dir = tempfile.mkdtemp(prefix="bench_jitcache_")
    sources = ("fresh", "disk", "memory")

    def serve():
        src0 = {s: _counter_value("paddle_tpu_jit_compiles_total",
                                  source=s) for s in sources}
        t0 = time.perf_counter()
        eng = ServingEngine(model, page_size=16, max_batch_slots=1,
                            max_model_len=int(prompt.size) + new + 2,
                            compile_cache_dir=cache_dir)
        rid = eng.add_request(prompt, max_new_tokens=new, temperature=0.0,
                              seed=5)
        toks = list(eng.run()[rid].token_ids)
        dt = time.perf_counter() - t0
        srcs = {s: int(_counter_value("paddle_tpu_jit_compiles_total",
                                      source=s) - src0[s])
                for s in sources}
        return dt, srcs, toks

    cold_dt, cold_src, cold_toks = serve()
    jit.clear_compile_cache(memory=True)  # force the disk layer
    warm_dt, warm_src, warm_toks = serve()
    ratio = warm_dt / cold_dt if cold_dt else 0.0
    rec = {
        "metric": metric,
        "value": round(ratio, 3), "unit": "ratio", "vs_baseline": 1.0,
        "config": label + cfg_tag,
        "cold_start_s": round(cold_dt, 3), "warm_start_s": round(warm_dt, 3),
        "cold_sources": cold_src, "warm_sources": warm_src,
        "streams_identical": bool(warm_toks == cold_toks),
        "device": str(dev.platform),
    }
    print(json.dumps(rec))
    if warm_toks != cold_toks:
        raise AssertionError(
            "warm (disk-cached) engine's stream diverged from the cold "
            "compile's — serialization changed the program")
    if not cold_src["fresh"]:
        raise AssertionError("cold start compiled nothing fresh — the "
                             "cache dir was not cold")
    if warm_src["fresh"] or not warm_src["disk"]:
        raise AssertionError(
            f"warm start did not come from disk: {warm_src}")
    if small:
        return  # CPU smoke: never pollute the round's evidence file
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(_NOTES, "a") as f:
        f.write(json.dumps(rec) + "\n")


def _bench_kv_tiers(rt, dev, small, out_path):
    """KV-memory-economics sweep (ISSUE 18): bf16 vs int8 KV pages at
    ONE fixed HBM budget. head_dim is 128 so the int8 page-byte ratio is
    (2*128)/(128+4) = 1.94x — the users/chip claim is sizing math that
    the sweep then PROVES by serving that many concurrent users per
    dtype. The ITL guard compares p95 at a MATCHED batch (bf16's
    capacity) so int8's extra users don't masquerade as per-token cost;
    the quantization-quality guard is the spec acceptance rate (a
    toleranced contract — quantized attention is NOT bit-checked); the
    host-tier phase parks a low-priority int8 stream under page
    pressure and requires its tokens bit-identical to an uncontended
    run; the full-arm phase pins the compile surface with quantization
    + host tier + spec + grammar armed at once."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import (GrammarFSM, ServingEngine,
                                    page_bytes, pages_for_hbm_budget,
                                    toy_tokenizer)

    budget_kib = int(os.environ.get("BENCH_KV_HBM_KIB", "256"))
    page, prompt_t, new = 16, 16, 16
    n_layers, n_kv, hd = 2, 1, 128
    metric = "BENCH_KV"
    cfg_tag = (f"-kvtiers-hbm{budget_kib}kib-hd{hd}-p{prompt_t}-n{new}"
               f"-greedy")
    if not small:
        from _bench_timing import iter_notes_rows
        if any(rec.get("metric") == metric
               and rec.get("device") in ("tpu", "axon")
               and str(rec.get("config", "")).endswith(cfg_tag)
               for rec in iter_notes_rows(_NOTES)):
            print(f"kv-tiers: {cfg_tag} already banked this round — "
                  "skipping", file=sys.stderr)
            return
    cfg = LlamaConfig(vocab_size=128, hidden_size=256,
                      num_layers=n_layers, num_heads=2,
                      num_key_value_heads=n_kv,
                      max_position_embeddings=64)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    vocab = cfg.vocab_size
    label = "llama-kv128" + cfg_tag
    need = -(-(prompt_t + new) // page)  # pages one user reserves

    sizing = {kv: pages_for_hbm_budget(budget_kib * 1024, page, n_kv, hd,
                                       n_layers, kv_dtype=kv)
              for kv in ("bf16", "int8")}
    users = {kv: max((p - 1) // need, 1) for kv, p in sizing.items()}
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, vocab, (prompt_t,))
               for _ in range(max(users.values()))]

    def drive(eng, n_users):
        """Serve n_users greedy decoders to drain; returns (wall_s,
        sorted inter-token gaps across all streams)."""
        stamps = [[] for _ in range(n_users)]

        def cb(i):
            return (lambda r, tok, fin, seq:
                    stamps[i].append(time.perf_counter())
                    if tok is not None else None)

        t0 = time.perf_counter()
        for i in range(n_users):
            eng.add_request(prompts[i], max_new_tokens=new,
                            temperature=0.0, seed=i, stream_cb=cb(i))
        eng.run()
        dt = time.perf_counter() - t0 - rt
        return dt, sorted(g for s in stamps for g in np.diff(s))

    def pq(gaps, q):
        return gaps[min(int(q * len(gaps)), len(gaps) - 1)] if gaps else 0.0

    # per-dtype capacity phase: the pool is sized by the budget and the
    # engine must actually hold that many users resident at once
    # (prefix_cache off — shared pages would flatter the capacity claim)
    tiers, engines = {}, {}
    for kv in ("bf16", "int8"):
        eng = ServingEngine(model, page_size=page, num_pages=sizing[kv],
                            max_batch_slots=users[kv],
                            max_model_len=prompt_t + new,
                            token_budget=max(users[kv] * prompt_t, 64),
                            prefix_cache=False, kv_dtype=kv)
        drive(eng, users[kv])            # compile pass
        dt, gaps = drive(eng, users[kv])
        cc = eng.compile_counts()
        tiers[kv] = {
            "kv_dtype": kv,
            "page_bytes": page_bytes(page, n_kv, hd, n_layers,
                                     kv_dtype=kv),
            "num_pages": sizing[kv], "users_per_chip": users[kv],
            "tokens_per_sec": round(users[kv] * new / dt, 1),
            "itl_ms": {f"p{int(q * 100)}": round(1e3 * pq(gaps, q), 3)
                       for q in (0.5, 0.95)},
            "peak_pages": eng.pool.peak_used,
            "step_compiles": cc["step"], "step_buckets": cc["step_buckets"],
        }
        engines[kv] = eng

    # matched-batch ITL: both dtypes at bf16's capacity AND bf16's slot
    # count, best-of-3 p95 — the capacity engines differ in
    # max_batch_slots (the compiled step's row grid), so the bf16 one is
    # reused while int8 gets a fresh equal-slot engine; the 1.15x guard
    # must compare equal work, not 15 padded rows against 7
    for kv in ("bf16", "int8"):
        eng = engines[kv]
        if users[kv] != users["bf16"]:
            eng = ServingEngine(model, page_size=page,
                                num_pages=sizing[kv],
                                max_batch_slots=users["bf16"],
                                max_model_len=prompt_t + new,
                                token_budget=max(
                                    users["bf16"] * prompt_t, 64),
                                prefix_cache=False, kv_dtype=kv)
            drive(eng, users["bf16"])    # compile pass
        best = float("inf")
        for _ in range(3):
            _, gaps = drive(eng, users["bf16"])
            best = min(best, pq(gaps, 0.95))
        tiers[kv]["itl_matched_p95_ms"] = round(1e3 * best, 3)

    # spec-acceptance guard: period-3 prompts, greedy, k=3 — acceptance
    # on quantized pages may not fall more than the documented 0.25
    # tolerance below bf16 (docs/SERVING.md "KV page tiers")
    for kv in ("bf16", "int8"):
        eng = ServingEngine(model, page_size=page, num_pages=64,
                            max_batch_slots=4,
                            max_model_len=24 + 24 + 5,
                            spec_k=3, kv_dtype=kv)
        d0 = _counter_value("paddle_tpu_serving_spec_drafted_tokens_total")
        a0 = _counter_value("paddle_tpu_serving_spec_accepted_tokens_total")
        for i in range(4):
            eng.add_request(np.tile((np.arange(3) + 5 * i) % vocab, 8),
                            max_new_tokens=24, temperature=0.0, seed=11 + i)
        eng.run()
        drafted = _counter_value(
            "paddle_tpu_serving_spec_drafted_tokens_total") - d0
        accepted = _counter_value(
            "paddle_tpu_serving_spec_accepted_tokens_total") - a0
        tiers[kv]["spec_acceptance_rate"] = (
            round(accepted / drafted, 3) if drafted else 0.0)

    # host-tier phase: int8 + host_offload under real page pressure — a
    # priority-5 stream is parked for a priority-0 arrival, round-trips
    # through the HostPageStore, and must finish bit-identical to an
    # uncontended solo run (the offload tier's warm_equals_cold contract)
    lo_p, hi_p = np.arange(1, 9), np.arange(2, 10)
    solo = ServingEngine(model, page_size=4, num_pages=64,
                         max_batch_slots=2, max_model_len=18,
                         kv_dtype="int8")
    r_ref = solo.add_request(lo_p, max_new_tokens=10, temperature=0.0,
                             seed=5)
    ref = list(solo.run()[r_ref].token_ids)
    eng = ServingEngine(model, page_size=4, num_pages=8,
                        max_batch_slots=3, max_model_len=18,
                        kv_dtype="int8", host_offload=True)
    c0 = {n: _counter_value(f"paddle_tpu_serving_kv_{n}")
          for n in ("offload_pages_total", "prefetch_pages_total",
                    "prefetch_late_total")}
    lo = eng.add_request(lo_p, max_new_tokens=10, temperature=0.0,
                         seed=5, priority=5)
    eng.step()
    eng.step()  # lo decoding and holding worst-case pages
    hi = eng.add_request(hi_p, max_new_tokens=4, temperature=0.0,
                         seed=6, priority=0)
    outs = eng.run()
    dc = {n: int(_counter_value(f"paddle_tpu_serving_kv_{n}") - v)
          for n, v in c0.items()}
    host = {
        "offload_pages": dc["offload_pages_total"],
        "prefetch_pages": dc["prefetch_pages_total"],
        "prefetch_late": dc["prefetch_late_total"],
        "parked_seen": dc["offload_pages_total"] > 0,
        "round_trip_bit_exact": (list(outs[lo].token_ids) == ref
                                 and len(outs[hi].token_ids) == 4),
    }

    # full-arm compile pin: quantization + host tier + spec + grammar on
    # ONE engine; a second identical traffic pass must compile nothing
    eng = ServingEngine(model, page_size=4, num_pages=64,
                        max_batch_slots=4, max_model_len=40,
                        kv_dtype="int8", host_offload=True, spec_k=3)
    fsm = GrammarFSM.compile("[ab]{1,6}", toy_tokenizer(vocab))

    def arm_traffic(seed0):
        eng.add_request(np.tile(np.arange(3) + 1, 6), max_new_tokens=8,
                        temperature=0.0, seed=seed0)
        eng.add_request(prompts[0], max_new_tokens=6, temperature=0.9,
                        seed=seed0 + 1, grammar=fsm)
        eng.add_request(prompts[1], max_new_tokens=8, temperature=0.7,
                        seed=seed0 + 2)
        eng.run()

    arm_traffic(0)  # compile pass
    jit0 = _counter_value("paddle_tpu_jit_compiles_total",
                          fn="serving_step")
    arm_traffic(10)
    cc = eng.compile_counts()
    arm = {
        "features": ["int8", "host_offload", "spec", "grammar"],
        "step_compiles": cc["step"], "step_buckets": cc["step_buckets"],
        "extra_jit_compiles": int(_counter_value(
            "paddle_tpu_jit_compiles_total", fn="serving_step") - jit0),
    }

    report = {
        "hbm_budget_kib": budget_kib, "page_size": page, "head_dim": hd,
        "n_kv_heads": n_kv, "num_layers": n_layers,
        "prompt_tokens": prompt_t, "new_tokens": new,
        "users_ratio": round(users["int8"] / users["bf16"], 3),
        "itl_p95_ratio": round(
            tiers["int8"]["itl_matched_p95_ms"]
            / max(tiers["bf16"]["itl_matched_p95_ms"], 1e-9), 3),
        "spec_acceptance_delta": round(
            tiers["int8"]["spec_acceptance_rate"]
            - tiers["bf16"]["spec_acceptance_rate"], 3),
        "tiers": tiers, "host_tier": host, "full_arm": arm,
    }
    rec = build_kv_row(report, label, str(dev.platform))
    print(json.dumps(rec))
    if report["users_ratio"] < 1.9:
        raise AssertionError(
            f"int8 sustains only {report['users_ratio']:.2f}x users/chip "
            f"vs bf16 at {budget_kib} KiB — below the 1.9x bar")
    for kv in ("bf16", "int8"):
        if tiers[kv]["peak_pages"] < users[kv]:
            raise AssertionError(
                f"{kv} never held its {users[kv]} users resident at once "
                f"(peak_pages {tiers[kv]['peak_pages']})")
        if tiers[kv]["step_compiles"] != tiers[kv]["step_buckets"]:
            raise AssertionError(f"{kv} compile surface unpinned: "
                                 f"{tiers[kv]}")
    # the latency bound is a silicon claim (decode is memory-bound on
    # TPU, where int8's halved page traffic pays for the dequant; a CPU
    # smoke measures interpreter overhead) — same gating as _bench_spec's
    # speedup assert
    if not small and report["itl_p95_ratio"] > 1.15:
        raise AssertionError(
            f"int8 p95 ITL is {report['itl_p95_ratio']:.2f}x bf16 at the "
            f"matched batch — exceeds the 15% bound")
    if (tiers["int8"]["spec_acceptance_rate"]
            < tiers["bf16"]["spec_acceptance_rate"] - 0.25):
        raise AssertionError(
            f"quantized spec acceptance fell past the 0.25 tolerance: "
            f"{report['spec_acceptance_delta']}")
    if not (host["parked_seen"] and host["round_trip_bit_exact"]):
        raise AssertionError(f"host-tier phase failed: {host}")
    if host["prefetch_late"]:
        raise AssertionError(
            f"{host['prefetch_late']} late prefetches — the scheduler "
            "let a step block on a host→HBM copy")
    if arm["extra_jit_compiles"] or arm["step_compiles"] != arm[
            "step_buckets"]:
        raise AssertionError(f"full-arm compile surface unpinned: {arm}")
    if out_path:
        # the committed artifact (BENCH_KV.json): overwrite-whole like
        # BENCH_LOAD.json — written even from the CPU smoke, because the
        # schema test pins keys and determinism booleans, never timings
        with open(out_path, "w") as f:
            json.dump(rec, f, indent=2, sort_keys=True)
            f.write("\n")
    if small:
        return  # CPU smoke: never pollute the round's evidence file
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(_NOTES, "a") as f:
        f.write(json.dumps(rec) + "\n")


def _counter_value(name, **labels):
    from paddle_tpu import metrics

    fam = metrics.get_registry().get(name)
    if fam is None:
        return 0.0
    if labels and set(labels) != set(fam.label_names):
        # partial label set: aggregate the unnamed dimensions (e.g.
        # jit_compiles_total{fn=...} summed across its source split)
        return fam.sum_labels(**labels)
    return (fam.labels(**labels) if labels else fam).value


def _parse_args(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        prog="bench_decode",
        description="Decode benchmarks: dense while_loop decode by "
                    "default; flags select engine scenarios (combinable "
                    "— each selected scenario emits its own BENCH rows).",
        epilog="Geometry via env: BENCH_BATCH, BENCH_PROMPT, "
               "BENCH_NEW_TOKENS, BENCH_DECODE_MODELS (comma list of "
               "gpt,llama), BENCH_DECODE_SMALL=1 for a CPU smoke that "
               "never writes the notes file. Per-scenario knobs: "
               "BENCH_PAGED_BATCHES, BENCH_SHARED_N/BENCH_SHARED_PREFIX, "
               "BENCH_MIXED_*, BENCH_SPEC_BATCH/BENCH_SPEC_K/"
               "BENCH_SPEC_NEW.")
    ap.add_argument("--paged", action="store_true",
                    help="continuous-batching engine sweep vs the dense "
                         "loop at BENCH_PAGED_BATCHES")
    ap.add_argument("--shared-prefix", action="store_true",
                    dest="shared_prefix",
                    help="prefix-cache scenario (ISSUE 8): N requests "
                         "sharing one common prefix")
    ap.add_argument("--mixed", action="store_true",
                    help="long-prompt-admission scenario (ISSUE 11): "
                         "tenant ITL flatness under chunked prefill")
    ap.add_argument("--spec", action="store_true",
                    help="speculative decoding (ISSUE 14): spec on/off "
                         "tokens/s + acceptance rate, plus a cold-vs-"
                         "warm compile-cache start-up row")
    ap.add_argument("--host-tier", action="store_true", dest="host_tier",
                    help="KV-memory-economics sweep (ISSUE 18): bf16 vs "
                         "int8 users/chip at one HBM budget "
                         "(BENCH_KV_HBM_KIB) + host-offload round-trip "
                         "+ full-arm compile pin — one BENCH_KV row")
    ap.add_argument("--kv-dtype", choices=("bf16", "int8"), default=None,
                    help="KV page dtype for the --paged engine rows "
                         "(rows tag their config with -kv<dtype>)")
    ap.add_argument("--kv-out", default=None,
                    help="write the BENCH_KV row to this file (e.g. "
                         "BENCH_KV.json); stdout always gets it")
    return ap.parse_args(argv)


def main():
    args = _parse_args()
    from _bench_timing import probe_or_exit, roundtrip_baseline

    small = os.environ.get("BENCH_DECODE_SMALL") == "1"
    if not small:
        # require_tpu: decode numbers are tunnel-specific (the in-tool
        # check below stays as a backstop for direct non-battery runs)
        probe_or_exit(240.0, log=lambda m: print(m, file=sys.stderr))
    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform in ("tpu", "axon")
    if not on_tpu and not small:
        print("not on TPU — aborting (decode numbers are tunnel-specific; "
              "BENCH_DECODE_SMALL=1 for a CPU smoke)", file=sys.stderr)
        sys.exit(2)

    B = int(os.environ.get("BENCH_BATCH", 8))
    prompt = int(os.environ.get("BENCH_PROMPT", 128))
    new = int(os.environ.get("BENCH_NEW_TOKENS", 128))
    models = [m.strip() for m in
              os.environ.get("BENCH_DECODE_MODELS", "gpt,llama").split(",")
              if m.strip()]
    known = {"gpt", "llama"}
    if not models or not set(models) <= known:
        print(f"BENCH_DECODE_MODELS must name models from {sorted(known)}; "
              f"got {models!r}", file=sys.stderr)
        sys.exit(2)
    rt = roundtrip_baseline(lambda m: print(m, file=sys.stderr))
    failures = 0

    def attempt(tag, fn, *fargs):
        # one scenario's OOM/regression must not lose the others' rows
        nonlocal failures
        try:
            fn(*fargs)
        except Exception as e:
            failures += 1
            print(f"{tag}: {type(e).__name__}: {str(e)[:160]}",
                  file=sys.stderr)

    if args.host_tier:
        attempt("kv-tiers", _bench_kv_tiers, rt, dev, small, args.kv_out)
    if args.spec:
        for name in models:
            attempt(f"spec[{name}]", _bench_spec, name, rt, dev, small)
            attempt(f"cache-startup[{name}]", _bench_cache_startup,
                    name, rt, dev, small)
    if args.mixed:
        for name in models:
            attempt(f"mixed[{name}]", _bench_mixed, name, rt, dev, small)
    if args.shared_prefix:
        shared_prefix = int(os.environ.get("BENCH_SHARED_PREFIX", "1024"))
        for name in models:
            attempt(f"shared-prefix[{name}]", _bench_shared_prefix,
                    name, rt, shared_prefix, new, dev, small)
    if args.paged:
        # engine-vs-dense sweep: one dense and one paged row per batch
        batches = [int(b) for b in os.environ.get(
            "BENCH_PAGED_BATCHES", "1,8,32").split(",") if b.strip()]
        for name in models:
            for b in batches:
                attempt(f"decode[{name}] b{b}", _bench_one,
                        name, rt, b, prompt, new, dev, small)
                attempt(f"paged[{name}] b{b}", _bench_paged_one,
                        name, rt, b, prompt, new, dev, small,
                        args.kv_dtype)
    if not (args.spec or args.mixed or args.shared_prefix or args.paged
            or args.host_tier):
        for name in models:
            attempt(f"decode[{name}]", _bench_one,
                    name, rt, B, prompt, new, dev, small)
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
