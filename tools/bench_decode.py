#!/usr/bin/env python
"""Autoregressive decode throughput (KV-cache, device-side while_loop).

GPT-355M greedy decode on one chip: B8, prompt 128, 128 new tokens — the
whole decode is ONE compiled program (models/generation.py device loop),
so the measurement is real device time, not 63ms-per-token tunnel round
trips. Appends the result to BENCH_NOTES_r05.json.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np

_NOTES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                      "BENCH_NOTES_r05.json")


def main():
    import jax

    from _bench_timing import roundtrip_baseline

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    dev = jax.devices()[0]
    on_tpu = dev.platform in ("tpu", "axon")
    if not on_tpu:
        print("not on TPU — aborting (decode numbers are tunnel-specific)",
              file=sys.stderr)
        sys.exit(2)

    B = int(os.environ.get("BENCH_BATCH", 8))
    prompt = int(os.environ.get("BENCH_PROMPT", 128))
    new = int(os.environ.get("BENCH_NEW_TOKENS", 128))
    cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                    num_heads=16, max_position_embeddings=prompt + new,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (B, prompt)))

    t0 = time.time()
    out = model.generate(ids, max_new_tokens=new, temperature=0.0,
                         device_loop=True)
    compile_s = time.time() - t0
    rt = roundtrip_baseline(lambda m: print(m, file=sys.stderr))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = model.generate(ids, max_new_tokens=new, temperature=0.0,
                             device_loop=True)
        best = min(best, time.perf_counter() - t0 - rt)
    # generate() fetches the result (host concat) — already synced
    tok_s = B * new / best
    rec = {
        "metric": "gpt_decode_tokens_per_sec_per_chip",
        "value": round(tok_s, 1), "unit": "tokens/s", "vs_baseline": 1.0,
        "config": f"gpt-355m-decode-b{B}-p{prompt}-n{new}-greedy",
        "total_s": round(best, 3), "compile_s": round(compile_s, 1),
        "per_token_ms": round(1e3 * best / new, 2),
        "device": str(dev.platform),
    }
    print(json.dumps(rec))
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(_NOTES, "a") as f:
        f.write(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()
