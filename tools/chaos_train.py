#!/usr/bin/env python
"""Chaos drill for the training checkpoint stack: kill saves at every
phase of the commit protocol and prove no work is ever lost.

The operational twin of tests/test_checkpoint_manager.py (docs/
RESILIENCE.md "Checkpoint commit protocol"): five scenarios arm
``paddle_tpu.faults`` injections against a real train loop + a
``checkpoint.CheckpointManager`` —

1. crash matrix   — a seeded fault at EVERY save phase (shard write,
                    fsync, manifest, COMMIT marker, publish rename;
                    sync AND async flush) must leave the previous
                    committed step the loadable latest, bit-exact;
2. corruption     — bit-rot in the newest step is caught by CRC32,
                    quarantined, and restore falls back one step;
3. preemption     — SIGTERM mid-run checkpoints via save_on_signal();
                    a fresh process-equivalent resumes sample-exact and
                    matches an uninterrupted run token-for-token for
                    10 steps (params AND optimizer moments bitwise);
4. retention      — GC keeps exactly max_to_keep committed steps;
5. telemetry      — every failure path moved its counter
                    (saves_total{failed}, corrupt_total, fallback,
                    last_committed_step gauge).

Exit code 0 iff every scenario passes.

Run: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python tools/chaos_train.py
"""
import os
import signal
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import tempfile  # noqa: E402

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn as nn  # noqa: E402
from paddle_tpu import checkpoint as ck  # noqa: E402
from paddle_tpu import faults, metrics  # noqa: E402
from paddle_tpu.io import DataLoader  # noqa: E402
from paddle_tpu.io.dataset import Dataset  # noqa: E402

SEED = int(os.environ.get("CHAOS_SEED", "0"))


class RegressionDS(Dataset):
    def __len__(self):
        return 32

    def __getitem__(self, i):
        x = np.float32([i / 32.0, 1.0 - i / 32.0, (i % 5) / 5.0])
        return x, np.float32([x @ np.float32([0.5, -0.25, 1.0])])


def build(seed=None):
    paddle.seed(SEED if seed is None else seed)
    net = nn.Linear(3, 1)
    opt = paddle.optimizer.AdamW(learning_rate=0.05,
                                 parameters=net.parameters())
    return net, opt, nn.MSELoss()


def train_steps(net, opt, loss, loader, n, it=None):
    for _ in range(n):
        if it is None:
            it = iter(loader)
        try:
            x, y = next(it)
        except StopIteration:  # epoch rolled; loader epoch counter advanced
            it = iter(loader)
            x, y = next(it)
        l = loss(net(x), y)
        l.backward()
        opt.step()
        opt.clear_grad()
    return it


def params_of(net, opt):
    out = {f"net.{k}": np.asarray(v.numpy())
           for k, v in net.state_dict().items()}
    for k, v in opt.state_dict().items():
        if hasattr(v, "numpy"):
            out[f"opt.{k}"] = np.asarray(v.numpy())
    return out


def _check(cond, what):
    if not cond:
        raise AssertionError(what)


def _counter(name, **labels):
    fam = metrics.get_registry().get(name)
    if fam is None:
        return 0.0
    return (fam.labels(**labels) if labels else fam).value


def state_of(net, opt, loader, step):
    return ck.capture_train_state(model=net, optimizer=opt,
                                  dataloader=loader, step=step)


PHASES = [
    ("shard write", "ckpt.write", {"times": 1}),
    ("fsync", "ckpt.fsync", {"times": 1}),
    ("manifest write", "ckpt.manifest", {"times": 1}),
    ("COMMIT marker", "ckpt.commit", {"times": 1}),
    ("commit rename", "ckpt.commit", {"times": 1, "after": 1}),
]


def scenario_crash_matrix(root):
    """Fault at every phase × {sync, async flush}: the previous committed
    step must stay the latest and load bit-exact."""
    d = os.path.join(root, "matrix")
    mgr = ck.CheckpointManager(d)
    net, opt, loss = build()
    loader = DataLoader(RegressionDS(), batch_size=4, shuffle=True)
    train_steps(net, opt, loss, loader, 3)
    golden = params_of(net, opt)
    mgr.save(0, state_of(net, opt, loader, 0))
    step = 1
    for mode in ("sync", "async"):
        for label, point, sched in PHASES:
            with faults.inject(point, raise_=faults.FaultInjected,
                               seed=SEED, **sched) as spec:
                try:
                    if mode == "async":
                        mgr.save(step, state_of(net, opt, loader, step),
                                 async_save=True).wait()
                    else:
                        mgr.save(step, state_of(net, opt, loader, step))
                    _check(False, f"{mode}/{label}: save survived the fault")
                except faults.FaultInjected:
                    pass
                _check(spec.fired == 1, f"{mode}/{label}: fault never fired")
            _check(mgr.latest_step() == 0,
                   f"{mode}/{label}: latest_step "
                   f"{mgr.latest_step()} != 0 after killed save")
            res = mgr.restore_or_init()
            _check(res.restored and res.step == 0,
                   f"{mode}/{label}: restore_or_init missed step 0")
            n2, o2, _ = build(seed=SEED + 1)
            ck.restore_train_state(res.state, model=n2, optimizer=o2)
            got = params_of(n2, o2)
            for k, v in golden.items():
                _check(np.array_equal(got[k], v),
                       f"{mode}/{label}: restored leaf {k} not bit-exact")
    print(f"  [ok] crash matrix: {len(PHASES)} phases x sync+async, "
          f"step 0 never lost")


def scenario_corruption(root):
    d = os.path.join(root, "bitrot")
    mgr = ck.CheckpointManager(d)
    net, opt, loss = build()
    loader = DataLoader(RegressionDS(), batch_size=4, shuffle=True)
    mgr.save(0, state_of(net, opt, loader, 0))
    golden = params_of(net, opt)
    train_steps(net, opt, loss, loader, 2)
    mgr.save(1, state_of(net, opt, loader, 1))
    # flip one byte in a newest-step shard: size unchanged, CRC must catch
    step_dir = mgr.step_path(1)
    victim = next(os.path.join(step_dir, f) for f in os.listdir(step_dir)
                  if f.endswith(".npy"))
    with open(victim, "r+b") as fh:
        fh.seek(-1, os.SEEK_END)
        last = fh.read(1)
        fh.seek(-1, os.SEEK_END)
        fh.write(bytes([last[0] ^ 0xFF]))
    c0 = _counter("paddle_tpu_ckpt_corrupt_total")
    f0 = _counter("paddle_tpu_ckpt_restore_fallback_total")
    res = mgr.restore_or_init()
    _check(res.step == 0, f"fallback step {res.step} != 0")
    n2, o2, _ = build(seed=SEED + 1)
    ck.restore_train_state(res.state, model=n2, optimizer=o2)
    got = params_of(n2, o2)
    _check(all(np.array_equal(got[k], v) for k, v in golden.items()),
           "fallback state not bit-exact")
    _check(mgr.latest_step() == 0, "corrupt step still visible")
    _check(_counter("paddle_tpu_ckpt_corrupt_total") == c0 + 1,
           "corrupt_total did not move")
    _check(_counter("paddle_tpu_ckpt_restore_fallback_total") == f0 + 1,
           "fallback counter did not move")
    print("  [ok] corruption: CRC caught bit-rot, quarantined, fell back "
          "bit-exact")


def scenario_preemption(root):
    """SIGTERM -> save_on_signal checkpoint -> fresh resume == 10
    uninterrupted steps, token for token."""
    # uninterrupted reference
    net, opt, loss = build()
    loader = DataLoader(RegressionDS(), batch_size=4, shuffle=True)
    it = train_steps(net, opt, loss, loader, 10)
    golden = params_of(net, opt)

    # preempted run: 5 steps, SIGTERM, handler checkpoints
    d = os.path.join(root, "preempt")
    mgr = ck.CheckpointManager(d)
    net1, opt1, loss1 = build()
    loader1 = DataLoader(RegressionDS(), batch_size=4, shuffle=True)
    it1 = train_steps(net1, opt1, loss1, loader1, 5)
    scope = mgr.save_on_signal(
        lambda: (5, state_of(net1, opt1, loader1, 5)), exit_on_save=False)
    try:
        os.kill(os.getpid(), signal.SIGTERM)
    finally:
        scope.uninstall()
    _check(mgr.preempted, "preemption flag not set")
    _check(mgr.latest_step() == 5, "signal handler did not commit step 5")

    # "new process": fresh objects, wrong seed — restore must win
    net2, opt2, loss2 = build(seed=SEED + 77)
    loader2 = DataLoader(RegressionDS(), batch_size=4, shuffle=True)
    res = mgr.restore_or_init()
    _check(res.restored and res.step == 5, "resume missed step 5")
    ck.restore_train_state(res.state, model=net2, optimizer=opt2,
                           dataloader=loader2)
    train_steps(net2, opt2, loss2, loader2, 5)
    got = params_of(net2, opt2)
    bad = [k for k, v in golden.items() if not np.array_equal(got[k], v)]
    _check(not bad, f"resumed run diverged from uninterrupted: {bad}")
    print("  [ok] preemption: SIGTERM checkpointed; resume matched "
          "uninterrupted 10-step run bitwise (params + moments)")


def scenario_retention(root):
    d = os.path.join(root, "gc")
    mgr = ck.CheckpointManager(d, max_to_keep=3)
    net, opt, loss = build()
    loader = DataLoader(RegressionDS(), batch_size=4, shuffle=True)
    for s in range(7):
        train_steps(net, opt, loss, loader, 1)
        mgr.save(s, state_of(net, opt, loader, s))
    _check(mgr.all_steps() == [4, 5, 6],
           f"retention kept {mgr.all_steps()}, wanted [4, 5, 6]")
    print("  [ok] retention: GC kept last 3 of 7 committed steps")


def scenario_telemetry(root):
    d = os.path.join(root, "telemetry")
    mgr = ck.CheckpointManager(d)
    net, opt, loss = build()
    loader = DataLoader(RegressionDS(), batch_size=4, shuffle=True)
    ok0 = _counter("paddle_tpu_ckpt_saves_total", result="committed")
    fail0 = _counter("paddle_tpu_ckpt_saves_total", result="failed")
    mgr.save(0, state_of(net, opt, loader, 0))
    with faults.inject("ckpt.write", raise_=faults.FaultInjected, times=1):
        try:
            mgr.save(1, state_of(net, opt, loader, 1))
        except faults.FaultInjected:
            pass
    _check(_counter("paddle_tpu_ckpt_saves_total",
                    result="committed") == ok0 + 1, "committed did not move")
    _check(_counter("paddle_tpu_ckpt_saves_total",
                    result="failed") == fail0 + 1, "failed did not move")
    gauge = metrics.get_registry().get("paddle_tpu_ckpt_last_committed_step")
    _check(gauge is not None and gauge.value == 0,
           "last_committed_step gauge wrong")
    hist = metrics.get_registry().get("paddle_tpu_ckpt_save_seconds")
    _check(hist is not None and hist.labels(mode="sync").count >= 1,
           "save histogram empty")
    print("  [ok] telemetry: saves_total{committed,failed}, gauge, "
          "histogram all moved")


def main():
    scenarios = [scenario_crash_matrix, scenario_corruption,
                 scenario_preemption, scenario_retention,
                 scenario_telemetry]
    failures = 0
    with tempfile.TemporaryDirectory() as root:
        for fn in scenarios:
            name = fn.__name__.replace("scenario_", "")
            print(f"[chaos_train] {name} (seed={SEED})")
            try:
                fn(os.path.join(root, name))
            except Exception as exc:  # noqa: BLE001 - drill report
                failures += 1
                print(f"  [FAIL] {name}: {exc}")
    print(f"[chaos_train] {len(scenarios) - failures}/{len(scenarios)} "
          f"scenarios passed")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
