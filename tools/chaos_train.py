#!/usr/bin/env python
"""Chaos drill for the training resilience stack: kill saves at every
phase of the commit protocol, poison gradients on a schedule, and prove
no work is ever lost and no anomaly survives.

The operational twin of tests/test_checkpoint_manager.py and
tests/test_sentinel.py (docs/RESILIENCE.md "Checkpoint commit protocol" +
"Self-healing training"): eight scenarios arm ``paddle_tpu.faults``
injections against a real train loop —

1. crash matrix   — a seeded fault at EVERY save phase (shard write,
                    fsync, manifest, COMMIT marker, publish rename;
                    sync AND async flush) must leave the previous
                    committed step the loadable latest, bit-exact;
2. corruption     — bit-rot in the newest step is caught by CRC32,
                    quarantined, and restore falls back one step;
3. preemption     — SIGTERM mid-run checkpoints via save_on_signal();
                    a fresh process-equivalent resumes sample-exact and
                    matches an uninterrupted run token-for-token for
                    10 steps (params AND optimizer moments bitwise);
4. retention      — GC keeps exactly max_to_keep committed steps;
5. telemetry      — every failure path moved its counter
                    (saves_total{failed}, corrupt_total, fallback,
                    last_committed_step gauge);
6. sentinel skip  — seeded NaN gradients at a scheduled step: the
                    TrainSentinel suppresses exactly that update; final
                    params + moments bit-identical to a clean run that
                    never applied the poisoned batch;
7. sentinel rollback — a persistent NaN region: skip-batch escalates to
                    rollback to the last-known-good COMMITTED mark
                    (CheckpointManager.restore, checksum-verified) +
                    deterministic skip-forward past the quarantined
                    window; final params + moments bit-identical to a
                    clean run trained only on the healthy batches, with
                    ZERO extra XLA compiles (jit counter pinned);
8. sentinel abort — anomalies that persist through every rollback walk
                    the full escalation ladder (skip → rollback → LR
                    re-ramp + widened skip → abort) with exact counters.

Exit code 0 iff every scenario passes.

Run: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python tools/chaos_train.py

CI: tests/test_chaos_train.py runs every scenario as a slow-marked test
(``SCENARIOS`` below is the single source of truth).
"""
import os
import signal
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import tempfile  # noqa: E402

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn as nn  # noqa: E402
from paddle_tpu import checkpoint as ck  # noqa: E402
from paddle_tpu import faults, metrics  # noqa: E402
from paddle_tpu.io import DataLoader  # noqa: E402
from paddle_tpu.io.dataset import Dataset  # noqa: E402

SEED = int(os.environ.get("CHAOS_SEED", "0"))


class RegressionDS(Dataset):
    def __len__(self):
        return 32

    def __getitem__(self, i):
        x = np.float32([i / 32.0, 1.0 - i / 32.0, (i % 5) / 5.0])
        return x, np.float32([x @ np.float32([0.5, -0.25, 1.0])])


def build(seed=None):
    paddle.seed(SEED if seed is None else seed)
    net = nn.Linear(3, 1)
    opt = paddle.optimizer.AdamW(learning_rate=0.05,
                                 parameters=net.parameters())
    return net, opt, nn.MSELoss()


def train_steps(net, opt, loss, loader, n, it=None):
    for _ in range(n):
        if it is None:
            it = iter(loader)
        try:
            x, y = next(it)
        except StopIteration:  # epoch rolled; loader epoch counter advanced
            it = iter(loader)
            x, y = next(it)
        l = loss(net(x), y)
        l.backward()
        opt.step()
        opt.clear_grad()
    return it


def params_of(net, opt):
    out = {f"net.{k}": np.asarray(v.numpy())
           for k, v in net.state_dict().items()}
    for k, v in opt.state_dict().items():
        if hasattr(v, "numpy"):
            out[f"opt.{k}"] = np.asarray(v.numpy())
    return out


def _check(cond, what):
    if not cond:
        raise AssertionError(what)


def _counter(name, **labels):
    fam = metrics.get_registry().get(name)
    if fam is None:
        return 0.0
    return (fam.labels(**labels) if labels else fam).value


def state_of(net, opt, loader, step):
    return ck.capture_train_state(model=net, optimizer=opt,
                                  dataloader=loader, step=step)


PHASES = [
    ("shard write", "ckpt.write", {"times": 1}),
    ("fsync", "ckpt.fsync", {"times": 1}),
    ("manifest write", "ckpt.manifest", {"times": 1}),
    ("COMMIT marker", "ckpt.commit", {"times": 1}),
    ("commit rename", "ckpt.commit", {"times": 1, "after": 1}),
]


def scenario_crash_matrix(root):
    """Fault at every phase × {sync, async flush}: the previous committed
    step must stay the latest and load bit-exact."""
    d = os.path.join(root, "matrix")
    mgr = ck.CheckpointManager(d)
    net, opt, loss = build()
    loader = DataLoader(RegressionDS(), batch_size=4, shuffle=True)
    train_steps(net, opt, loss, loader, 3)
    golden = params_of(net, opt)
    mgr.save(0, state_of(net, opt, loader, 0))
    step = 1
    for mode in ("sync", "async"):
        for label, point, sched in PHASES:
            with faults.inject(point, raise_=faults.FaultInjected,
                               seed=SEED, **sched) as spec:
                try:
                    if mode == "async":
                        mgr.save(step, state_of(net, opt, loader, step),
                                 async_save=True).wait()
                    else:
                        mgr.save(step, state_of(net, opt, loader, step))
                    _check(False, f"{mode}/{label}: save survived the fault")
                except faults.FaultInjected:
                    pass
                _check(spec.fired == 1, f"{mode}/{label}: fault never fired")
            _check(mgr.latest_step() == 0,
                   f"{mode}/{label}: latest_step "
                   f"{mgr.latest_step()} != 0 after killed save")
            res = mgr.restore_or_init()
            _check(res.restored and res.step == 0,
                   f"{mode}/{label}: restore_or_init missed step 0")
            n2, o2, _ = build(seed=SEED + 1)
            ck.restore_train_state(res.state, model=n2, optimizer=o2)
            got = params_of(n2, o2)
            for k, v in golden.items():
                _check(np.array_equal(got[k], v),
                       f"{mode}/{label}: restored leaf {k} not bit-exact")
    print(f"  [ok] crash matrix: {len(PHASES)} phases x sync+async, "
          f"step 0 never lost")


def scenario_corruption(root):
    d = os.path.join(root, "bitrot")
    mgr = ck.CheckpointManager(d)
    net, opt, loss = build()
    loader = DataLoader(RegressionDS(), batch_size=4, shuffle=True)
    mgr.save(0, state_of(net, opt, loader, 0))
    golden = params_of(net, opt)
    train_steps(net, opt, loss, loader, 2)
    mgr.save(1, state_of(net, opt, loader, 1))
    # flip one byte in a newest-step shard: size unchanged, CRC must catch
    step_dir = mgr.step_path(1)
    victim = next(os.path.join(step_dir, f) for f in os.listdir(step_dir)
                  if f.endswith(".npy"))
    with open(victim, "r+b") as fh:
        fh.seek(-1, os.SEEK_END)
        last = fh.read(1)
        fh.seek(-1, os.SEEK_END)
        fh.write(bytes([last[0] ^ 0xFF]))
    c0 = _counter("paddle_tpu_ckpt_corrupt_total")
    f0 = _counter("paddle_tpu_ckpt_restore_fallback_total")
    res = mgr.restore_or_init()
    _check(res.step == 0, f"fallback step {res.step} != 0")
    n2, o2, _ = build(seed=SEED + 1)
    ck.restore_train_state(res.state, model=n2, optimizer=o2)
    got = params_of(n2, o2)
    _check(all(np.array_equal(got[k], v) for k, v in golden.items()),
           "fallback state not bit-exact")
    _check(mgr.latest_step() == 0, "corrupt step still visible")
    _check(_counter("paddle_tpu_ckpt_corrupt_total") == c0 + 1,
           "corrupt_total did not move")
    _check(_counter("paddle_tpu_ckpt_restore_fallback_total") == f0 + 1,
           "fallback counter did not move")
    print("  [ok] corruption: CRC caught bit-rot, quarantined, fell back "
          "bit-exact")


def scenario_preemption(root):
    """SIGTERM -> save_on_signal checkpoint -> fresh resume == 10
    uninterrupted steps, token for token."""
    # uninterrupted reference
    net, opt, loss = build()
    loader = DataLoader(RegressionDS(), batch_size=4, shuffle=True)
    it = train_steps(net, opt, loss, loader, 10)
    golden = params_of(net, opt)

    # preempted run: 5 steps, SIGTERM, handler checkpoints
    d = os.path.join(root, "preempt")
    mgr = ck.CheckpointManager(d)
    net1, opt1, loss1 = build()
    loader1 = DataLoader(RegressionDS(), batch_size=4, shuffle=True)
    it1 = train_steps(net1, opt1, loss1, loader1, 5)
    scope = mgr.save_on_signal(
        lambda: (5, state_of(net1, opt1, loader1, 5)), exit_on_save=False)
    try:
        os.kill(os.getpid(), signal.SIGTERM)
    finally:
        scope.uninstall()
    _check(mgr.preempted, "preemption flag not set")
    _check(mgr.latest_step() == 5, "signal handler did not commit step 5")

    # "new process": fresh objects, wrong seed — restore must win
    net2, opt2, loss2 = build(seed=SEED + 77)
    loader2 = DataLoader(RegressionDS(), batch_size=4, shuffle=True)
    res = mgr.restore_or_init()
    _check(res.restored and res.step == 5, "resume missed step 5")
    ck.restore_train_state(res.state, model=net2, optimizer=opt2,
                           dataloader=loader2)
    train_steps(net2, opt2, loss2, loader2, 5)
    got = params_of(net2, opt2)
    bad = [k for k, v in golden.items() if not np.array_equal(got[k], v)]
    _check(not bad, f"resumed run diverged from uninterrupted: {bad}")
    print("  [ok] preemption: SIGTERM checkpointed; resume matched "
          "uninterrupted 10-step run bitwise (params + moments)")


def scenario_retention(root):
    d = os.path.join(root, "gc")
    mgr = ck.CheckpointManager(d, max_to_keep=3)
    net, opt, loss = build()
    loader = DataLoader(RegressionDS(), batch_size=4, shuffle=True)
    for s in range(7):
        train_steps(net, opt, loss, loader, 1)
        mgr.save(s, state_of(net, opt, loader, s))
    _check(mgr.all_steps() == [4, 5, 6],
           f"retention kept {mgr.all_steps()}, wanted [4, 5, 6]")
    print("  [ok] retention: GC kept last 3 of 7 committed steps")


def scenario_telemetry(root):
    d = os.path.join(root, "telemetry")
    mgr = ck.CheckpointManager(d)
    net, opt, loss = build()
    loader = DataLoader(RegressionDS(), batch_size=4, shuffle=True)
    ok0 = _counter("paddle_tpu_ckpt_saves_total", result="committed")
    fail0 = _counter("paddle_tpu_ckpt_saves_total", result="failed")
    mgr.save(0, state_of(net, opt, loader, 0))
    with faults.inject("ckpt.write", raise_=faults.FaultInjected, times=1):
        try:
            mgr.save(1, state_of(net, opt, loader, 1))
        except faults.FaultInjected:
            pass
    _check(_counter("paddle_tpu_ckpt_saves_total",
                    result="committed") == ok0 + 1, "committed did not move")
    _check(_counter("paddle_tpu_ckpt_saves_total",
                    result="failed") == fail0 + 1, "failed did not move")
    gauge = metrics.get_registry().get("paddle_tpu_ckpt_last_committed_step")
    _check(gauge is not None and gauge.value == 0,
           "last_committed_step gauge wrong")
    hist = metrics.get_registry().get("paddle_tpu_ckpt_save_seconds")
    _check(hist is not None and hist.labels(mode="sync").count >= 1,
           "save histogram empty")
    print("  [ok] telemetry: saves_total{committed,failed}, gauge, "
          "histogram all moved")


# ----------------------------------------------------------------------
# sentinel scenarios (6-8): self-healing training, ISSUE 9
# ----------------------------------------------------------------------
def _nan_grads(net):
    """Fault-point callback: poison the live gradients with NaN (the
    seeded schedule on the ``train.grads`` point decides WHEN)."""
    import jax.numpy as jnp

    from paddle_tpu.tensor import Tensor

    def poison():
        w = net.weight
        if w.grad is not None:
            w.grad = Tensor(jnp.full_like(w.grad._value, jnp.nan))
    return poison


def _guarded_run(sentinel, net, opt, loss, steps):
    """Drive a guard()-wrapped custom loop for ``steps`` guarded calls;
    returns the loader so callers can read the final stream position."""
    loader = DataLoader(RegressionDS(), batch_size=4)
    sentinel.bind(model=net, optimizer=opt, dataloader=loader)
    sentinel.note_epoch(0)
    guarded = sentinel.guard(lambda x, y: loss(net(x), y), optimizer=opt)
    it, done = iter(loader), 0
    while done < steps:
        try:
            x, y = next(it)
        except StopIteration:
            it = iter(loader)
            continue
        rep = guarded(x, y)
        if rep.rolled_back:
            it = iter(loader)  # restored position + quarantine skip
        done += 1
    return loader


def _clean_replay(loss_cls, excluded, final):
    """Reference run: same stream, to the same final position, updating
    only on batches outside ``excluded`` {(epoch, batch), ...}."""
    net, opt, loss = build()
    loader = DataLoader(RegressionDS(), batch_size=4)
    it, ep, b = iter(loader), 0, 0
    while (ep, b) != (final["epoch"], final["batch"]):
        try:
            x, y = next(it)
        except StopIteration:
            it, ep, b = iter(loader), ep + 1, 0
            continue
        cur, b = (ep, b), b + 1
        if cur in excluded:
            continue
        l = loss(net(x), y)
        l.backward()
        opt.step()
        opt.clear_grad()
    return net, opt


def _excluded_from_journal(journal):
    excluded = set()
    for e in journal:
        if e["event"] == "rollback":
            d = e["data"]
            excluded.update((d["epoch"], i) for i in
                            range(d["batch"], d["batch"] + e["skipped"]))
        elif e.get("action") == "skip":
            excluded.add((e["data"]["epoch"], e["data"]["batch"] - 1))
    return excluded


def scenario_sentinel_skip(root):
    """Seeded NaN injection at one scheduled step -> skip-batch, exact
    counters, and bit-identity to a clean run without that batch."""
    from paddle_tpu.faults import TrainSentinel

    net, opt, loss = build()
    sent = TrainSentinel(skip_limit=2, healthy_window=2, min_history=4)
    a0 = _counter("paddle_tpu_train_anomalies_total", kind="nonfinite_grad")
    s0 = _counter("paddle_tpu_train_skipped_batches_total")
    with faults.inject("train.grads", call=_nan_grads(net), seed=SEED,
                       after=5, times=1) as spec:
        loader = _guarded_run(sent, net, opt, loss, steps=14)
    _check(spec.fired == 1, "NaN fault never fired")
    _check(sent.skipped_batches == 1 and sent.rollbacks == 0,
           f"wanted exactly 1 skip, 0 rollbacks; got "
           f"{sent.skipped_batches}/{sent.rollbacks}")
    _check(_counter("paddle_tpu_train_anomalies_total",
                    kind="nonfinite_grad") == a0 + 1,
           "anomalies_total{nonfinite_grad} did not move exactly once")
    _check(_counter("paddle_tpu_train_skipped_batches_total") == s0 + 1,
           "skipped_batches_total did not move exactly once")
    excluded = _excluded_from_journal(sent.journal())
    _check(len(excluded) == 1, f"journal window wrong: {excluded}")
    n2, o2 = _clean_replay(loss, excluded, loader.state_dict())
    got, want = params_of(net, opt), params_of(n2, o2)
    bad = [k for k, v in want.items() if not np.array_equal(got[k], v)]
    _check(not bad, f"guarded run diverged from clean run: {bad}")
    print("  [ok] sentinel skip: 1 NaN batch suppressed, counters exact, "
          "params + moments bit-identical to clean run")


def scenario_sentinel_rollback(root):
    """Persistent NaN region -> rollback to the last committed mark +
    deterministic skip-forward; bit-identity to a clean run on the
    healthy batches; zero extra XLA compiles."""
    from paddle_tpu.faults import TrainSentinel

    compiles0 = _counter("paddle_tpu_jit_compiles_total")
    net, opt, loss = build()
    mgr = ck.CheckpointManager(os.path.join(root, "marks"))
    sent = TrainSentinel(skip_limit=1, healthy_window=2, mark_every=2,
                         min_history=4)
    sent.bind(manager=mgr)
    r0 = _counter("paddle_tpu_train_rollbacks_total")
    with faults.inject("train.grads", call=_nan_grads(net), seed=SEED,
                       after=5, times=3) as spec:
        loader = _guarded_run(sent, net, opt, loss, steps=18)
    _check(spec.fired == 3, f"region fault fired {spec.fired} != 3")
    _check(sent.rollbacks == 1,
           f"wanted exactly 1 rollback, got {sent.rollbacks}")
    _check(_counter("paddle_tpu_train_rollbacks_total") == r0 + 1,
           "rollbacks_total did not move exactly once")
    _check(sent.last_good_step is not None
           and sent.last_good_step in mgr.all_steps() + [sent.global_step],
           "last-known-good mark not committed")
    _check(_counter("paddle_tpu_jit_compiles_total") == compiles0,
           "guarding cost an extra XLA compile")
    excluded = _excluded_from_journal(sent.journal())
    _check(excluded, "journal recorded no quarantine window")
    n2, o2 = _clean_replay(loss, excluded, loader.state_dict())
    got, want = params_of(net, opt), params_of(n2, o2)
    bad = [k for k, v in want.items() if not np.array_equal(got[k], v)]
    _check(not bad, f"rolled-back run diverged from clean run: {bad}")
    print("  [ok] sentinel rollback: restored committed mark, skipped "
          f"{sorted(excluded)} deterministically, bit-identical to clean "
          "run, 0 extra compiles")


def scenario_sentinel_abort(root):
    """Anomalies that survive every rollback exhaust the ladder: skip ->
    rollback -> LR re-ramp + widened skip -> abort, counters exact."""
    from paddle_tpu.faults import SentinelAbort, TrainSentinel

    net, opt, loss = build()
    mgr = ck.CheckpointManager(os.path.join(root, "marks"))
    sent = TrainSentinel(skip_limit=0, lr_reramp_after=2,
                         abort_after_rollbacks=2, healthy_window=2)
    a0 = _counter("paddle_tpu_train_anomalies_total", kind="nonfinite_grad")
    r0 = _counter("paddle_tpu_train_rollbacks_total")
    rr0 = _counter("paddle_tpu_train_lr_reramps_total")
    ab0 = _counter("paddle_tpu_train_aborts_total", reason="rollback_limit")
    aborted = False
    try:
        with faults.inject("train.grads", call=_nan_grads(net), seed=SEED,
                           after=3):
            _guarded_run(sent, net, opt, loss, steps=30)
    except SentinelAbort as exc:
        aborted = True
        _check(exc.reason == "rollback_limit",
               f"abort reason {exc.reason!r} != 'rollback_limit'")
        _check(exc.journal and exc.journal[-1]["event"] == "abort",
               "abort journal missing its terminal entry")
    _check(aborted, "escalation never reached abort")
    _check(sent.rollbacks == 2, f"rollbacks {sent.rollbacks} != 2")
    _check(_counter("paddle_tpu_train_anomalies_total",
                    kind="nonfinite_grad") == a0 + 3,
           "anomaly counter not exactly 3 (rollback, rollback, abort)")
    _check(_counter("paddle_tpu_train_rollbacks_total") == r0 + 2,
           "rollbacks_total not exactly 2")
    _check(_counter("paddle_tpu_train_lr_reramps_total") == rr0 + 1,
           "lr_reramps_total not exactly 1")
    _check(_counter("paddle_tpu_train_aborts_total",
                    reason="rollback_limit") == ab0 + 1,
           "aborts_total{rollback_limit} not exactly 1")
    _check(opt.get_lr() < 0.05, "LR re-ramp never reduced the LR")
    print("  [ok] sentinel abort: 2 rollbacks + re-ramp + widened skip, "
          "then SentinelAbort with exact counters and journal")


SCENARIOS = [
    ("crash-matrix", scenario_crash_matrix),
    ("corruption", scenario_corruption),
    ("preemption", scenario_preemption),
    ("retention", scenario_retention),
    ("telemetry", scenario_telemetry),
    ("sentinel-skip", scenario_sentinel_skip),
    ("sentinel-rollback", scenario_sentinel_rollback),
    ("sentinel-abort", scenario_sentinel_abort),
]


def main():
    failures = 0
    with tempfile.TemporaryDirectory() as root:
        for name, fn in SCENARIOS:
            print(f"[chaos_train] {name} (seed={SEED})")
            faults.reset()
            try:
                fn(os.path.join(root, name))
            except Exception as exc:  # noqa: BLE001 - drill report
                failures += 1
                print(f"  [FAIL] {name}: {exc}")
            finally:
                faults.reset()
    print(f"[chaos_train] {len(SCENARIOS) - failures}/{len(SCENARIOS)} "
          f"scenarios passed")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
