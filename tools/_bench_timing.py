"""Shared timing methodology for the on-chip A/B harnesses.

Under the axon tunnel `block_until_ready` does not reliably wait for remote
execution (r4: measured 0.02ms "runs" of a kernel with a 0.2ms analytic
floor), so per-call wall timing is garbage. Every harness therefore times
ITERS chained data-dependent calls inside ONE jit, fetches a scalar derived
from the result (the device_get cannot return before every iteration ran),
and subtracts the measured scalar round-trip. Both A/B sides of every
decision (flash dispatch threshold, fused-adamw retirement) must use this
same clock — keep it here, not copy-pasted per tool.
"""
from __future__ import annotations

import subprocess
import sys
import time

_RT_BASELINE = None


def probe_backend(timeout_s: float = 240.0, log=None):
    """Probe backend init in a KILLABLE subprocess before any in-process
    jax import. The axon plugin can hang (not error) inside client init —
    r5 session 3 lost 16 min of a 30-min battery slot to exactly that in
    bench_decode, which touched jax.devices() directly.

    Returns the probed platform string ('tpu'/'axon'/'cpu'/...) on
    success, or None on hang/error. Callers map None to a TRANSIENT abort
    (rc=3: the watcher retries) and 'cpu' to their permanent
    wrong-environment path (rc=2). The probe runs in its own process
    GROUP and the whole group is killed on timeout — subprocess.run's
    kill reaches only the direct child, and an orphaned probe grandchild
    parked in axon client init is exactly the stacked hung chip-claim
    that wedges the tunnel."""
    import os
    import signal

    code = ("import jax, jax.numpy as jnp;"
            "d=jax.devices();"
            "jnp.zeros((8,8)).block_until_ready();"
            "print('PROBE_OK', d[0].platform, len(d))")
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True, start_new_session=True)
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        proc.wait()
        if log:
            log(f"probe HUNG past {timeout_s:.0f}s (process group killed)")
        return None
    ok = proc.returncode == 0 and "PROBE_OK" in out
    platform = None
    if ok:
        platform = [ln for ln in out.splitlines()
                    if "PROBE_OK" in ln][-1].split()[1]
    if log:
        tail = (out + err).strip().splitlines()[-2:]
        log(f"probe rc={proc.returncode} platform={platform}: "
            f"{' | '.join(tail)}")
    return platform


def probe_or_exit(timeout_s: float = 240.0, require_tpu: bool = True,
                  log=None) -> str:
    """probe_backend + the battery rc contract in one place: exit 3
    (transient — the watcher retries) on hang/error, exit 2 (permanent
    wrong-environment) on a CPU-only host when require_tpu. Returns the
    platform, already validated, so callers never pay a second in-process
    jax init just to re-discover it.

    Deliberately NOT skipped when a parent (battery gate / bonus battery)
    probed seconds earlier: each probe is a FRESH chip claim, and a fresh
    claim is exactly what can wedge — r5 session 3's decode hang happened
    in the window right after a successful gate probe. The ~20-40 s
    healthy-path cost buys a 240 s bound on what was a full-step-budget
    burn."""
    _log = log or (lambda m: print(m, file=sys.stderr))
    plat = probe_backend(timeout_s, log=_log)
    if plat is None:
        _log("backend probe hung/failed — aborting fast (rc=3) so the "
             "battery slot survives; the watcher owns the retry cadence")
        sys.exit(3)
    if require_tpu and plat == "cpu":
        _log("not on TPU — aborting (rc=2): permanent wrong-environment, "
             "not a condition the watcher can retry away")
        sys.exit(2)
    return plat


def sync_fetch(x):
    """Force REAL completion of jax array `x`: fetch a tiny host slice
    derived from it (block_until_ready alone is not trustworthy here)."""
    import jax
    import jax.numpy as jnp

    float(jax.device_get(jnp.sum(jnp.ravel(x)[:8].astype(jnp.float32))))


def roundtrip_baseline(log=None):
    """Measured cost of one scalar fetch through the tunnel (min of 5)."""
    global _RT_BASELINE
    if _RT_BASELINE is None:
        import jax
        import jax.numpy as jnp

        x = jnp.zeros((), jnp.float32)
        float(jax.device_get(x))
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            float(jax.device_get(x + 0.0))
            ts.append(time.perf_counter() - t0)
        _RT_BASELINE = min(ts)
        if log:
            log(f"scalar round-trip baseline: {_RT_BASELINE*1e3:.2f}ms")
    return _RT_BASELINE


def iter_notes_rows(path):
    """Yield parsed rows from a BENCH_NOTES jsonl file, skipping unreadable
    lines — the one shared parser for every tool's banked-row resume logic
    (bench_decode._already_banked, bench_flash resume)."""
    import json
    try:
        with open(path) as f:
            for ln in f:
                try:
                    yield json.loads(ln)
                except ValueError:
                    continue
    except OSError:
        return


def bench_chained(step, carry, consts, iters=32, reps=3, log=None,
                  donate=False):
    """Time `step(carry, *consts) -> carry` chained ITERS times in one jit.

    `carry` may be any pytree; returns (seconds_per_iter, final_carry) —
    final_carry matters when the caller donates buffers into the chain
    (donate=True aliases the carry in-place; required when the carry is a
    multi-GB state that would otherwise double in HBM).
    """
    import functools

    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def many(carry, *consts):
        def body(_, c):
            return step(c, *consts)
        return jax.lax.fori_loop(0, iters, body, carry)

    def _sync(out):
        sync_fetch(jax.tree_util.tree_leaves(out)[0])

    out = many(carry, *consts)
    _sync(out)  # compile + settle
    rt = roundtrip_baseline(log)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = many(out, *consts)
        _sync(out)
        best = min(best, time.perf_counter() - t0)
    return max(best - rt, 1e-9) / iters, out
