"""Shared timing methodology for the on-chip A/B harnesses.

Under the axon tunnel `block_until_ready` does not reliably wait for remote
execution (r4: measured 0.02ms "runs" of a kernel with a 0.2ms analytic
floor), so per-call wall timing is garbage. Every harness therefore times
ITERS chained data-dependent calls inside ONE jit, fetches a scalar derived
from the result (the device_get cannot return before every iteration ran),
and subtracts the measured scalar round-trip. Both A/B sides of every
decision (flash dispatch threshold, fused-adamw retirement) must use this
same clock — keep it here, not copy-pasted per tool.
"""
from __future__ import annotations

import time

_RT_BASELINE = None


def sync_fetch(x):
    """Force REAL completion of jax array `x`: fetch a tiny host slice
    derived from it (block_until_ready alone is not trustworthy here)."""
    import jax
    import jax.numpy as jnp

    float(jax.device_get(jnp.sum(jnp.ravel(x)[:8].astype(jnp.float32))))


def roundtrip_baseline(log=None):
    """Measured cost of one scalar fetch through the tunnel (min of 5)."""
    global _RT_BASELINE
    if _RT_BASELINE is None:
        import jax
        import jax.numpy as jnp

        x = jnp.zeros((), jnp.float32)
        float(jax.device_get(x))
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            float(jax.device_get(x + 0.0))
            ts.append(time.perf_counter() - t0)
        _RT_BASELINE = min(ts)
        if log:
            log(f"scalar round-trip baseline: {_RT_BASELINE*1e3:.2f}ms")
    return _RT_BASELINE


def bench_chained(step, carry, consts, iters=32, reps=3, log=None,
                  donate=False):
    """Time `step(carry, *consts) -> carry` chained ITERS times in one jit.

    `carry` may be any pytree; returns (seconds_per_iter, final_carry) —
    final_carry matters when the caller donates buffers into the chain
    (donate=True aliases the carry in-place; required when the carry is a
    multi-GB state that would otherwise double in HBM).
    """
    import functools

    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def many(carry, *consts):
        def body(_, c):
            return step(c, *consts)
        return jax.lax.fori_loop(0, iters, body, carry)

    def _sync(out):
        sync_fetch(jax.tree_util.tree_leaves(out)[0])

    out = many(carry, *consts)
    _sync(out)  # compile + settle
    rt = roundtrip_baseline(log)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = many(out, *consts)
        _sync(out)
        best = min(best, time.perf_counter() - t0)
    return max(best - rt, 1e-9) / iters, out
