#!/usr/bin/env python
"""A/B: fused AdamW Pallas kernel vs XLA elementwise update (VERDICT r2 #6).

Run ON the TPU. 355M-param-scale flat buffers (the bench model's size).
Appends the result to BENCH_NOTES_r04.json.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np

_NOTES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                      "BENCH_NOTES_r04.json")


def _bench(fn, args, iters=30):
    import jax
    jax.block_until_ready(fn(*args))
    for _ in range(3):
        jax.block_until_ready(fn(*args))
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        ts.append(time.perf_counter() - t0)
    ts.sort()
    kept = ts[: max(1, len(ts) - len(ts) // 5)]
    return sum(kept) / len(kept)


def main():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.fused_adamw import (fused_adamw_flat,
                                                   xla_adamw_flat)

    dev = jax.devices()[0]
    on_tpu = dev.platform in ("tpu", "axon")
    n = int(os.environ.get("BENCH_ADAMW_N", 355_000_000 if on_tpu
                           else 1_000_000))
    print(f"device={dev.platform} n={n}", file=sys.stderr)
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal(n), jnp.float32)
    m = jnp.zeros(n, jnp.float32)
    v = jnp.zeros(n, jnp.float32)
    g = jnp.asarray(rng.standard_normal(n), jnp.float32) * 1e-3
    lr = jnp.float32(1e-4)
    t = jnp.float32(10.0)

    f_pl = jax.jit(fused_adamw_flat)
    f_x = jax.jit(xla_adamw_flat)

    # correctness first
    o_pl = f_pl(w, m, v, g, lr, t)
    o_x = f_x(w, m, v, g, lr, t)
    for a, b in zip(o_pl, o_x):
        np.testing.assert_allclose(np.asarray(a[:4096]), np.asarray(b[:4096]),
                                   rtol=1e-6, atol=1e-7)
    print("numerics match", file=sys.stderr)

    t_pl = _bench(f_pl, (w, m, v, g, lr, t))
    t_x = _bench(f_x, (w, m, v, g, lr, t))
    gb = n * 4 * 7 / 1e9  # r: w,m,v,g  w: w,m,v
    rec = {
        "metric": "fused_adamw_ab", "n_params": n,
        "pallas_ms": round(t_pl * 1e3, 3), "xla_ms": round(t_x * 1e3, 3),
        "pallas_gbps": round(gb / t_pl, 1), "xla_gbps": round(gb / t_x, 1),
        "pallas_wins": bool(t_pl < t_x), "device": str(dev.platform),
    }
    print(json.dumps(rec))
    if on_tpu:
        rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
        with open(_NOTES, "a") as f:
            f.write(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()
