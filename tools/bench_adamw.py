#!/usr/bin/env python
"""A/B: fused AdamW Pallas kernel vs XLA elementwise update (VERDICT r2 #6).

Run ON the TPU. 355M-param-scale flat buffers (the bench model's size).
Appends the result to BENCH_NOTES_r05.json.

Timing: chained data-dependent iterations inside one jit + terminal scalar
fetch, minus the measured scalar round-trip — under the axon tunnel
`block_until_ready` does not reliably wait for remote execution (r4), so
per-call wall timing is garbage. Correctness is checked at small N first;
the timed run holds only one (w, m, v) chain to stay inside HBM
(355M x 4 states x f32 in+out with both impls' outputs live OOMed r4).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np

_NOTES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                      "BENCH_NOTES_r05.json")


from _bench_timing import bench_chained  # noqa: E402  (shared clock — both
#   A/B harnesses must time identically; see _bench_timing.py)


def _bench(update, w, m, v, g, lr, t, iters=20, reps=3):
    # donate=True: the loop carry aliases the (w, m, v) state — without it,
    # inputs + carry + outputs tripled the 4.3GB state and OOMed a 16GB
    # chip (measured r4). The final carry is handed back so the next impl
    # can be benchmarked on the same buffers.
    def step(c, g):
        return update(c[0], c[1], c[2], g, lr, t)

    return bench_chained(step, (w, m, v), (g,), iters=iters, reps=reps,
                         log=lambda m_: print(m_, file=sys.stderr),
                         donate=True)


def main():
    from _bench_timing import probe_or_exit

    # require_tpu: the pallas A/B side has no CPU-interpret path — a CPU
    # "run" only ever produced a mid-sweep crash
    probe_or_exit(240.0)
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.fused_adamw import (fused_adamw_flat,
                                                   xla_adamw_flat)

    dev = jax.devices()[0]
    on_tpu = dev.platform in ("tpu", "axon")
    # (no CPU sizing: probe_or_exit above guarantees an accelerator here)
    n = int(os.environ.get("BENCH_ADAMW_N", 355_000_000))
    # align to the LARGEST swept blocking (256*1024): the kernel's pad
    # path would otherwise copy all four flat buffers every loop
    # iteration, and a rows count not divisible by block_rows makes
    # fused_adamw_flat halve its block (8192-alignment benched a crippled
    # 16x1024 blocking in r4 — every sweep point must run at its stated
    # blocking)
    n -= n % (256 * 1024)
    print(f"device={dev.platform} n={n}", file=sys.stderr)
    rng = np.random.default_rng(0)
    lr = jnp.float32(1e-4)
    t = jnp.float32(10.0)

    # correctness first, at a size where both impls' outputs fit comfortably
    ns = min(n, 2_000_000)
    ws = jnp.asarray(rng.standard_normal(ns), jnp.float32)
    ms = jnp.zeros(ns, jnp.float32)
    vs = jnp.zeros(ns, jnp.float32)
    gs = jnp.asarray(rng.standard_normal(ns), jnp.float32) * 1e-3
    o_pl = jax.jit(fused_adamw_flat)(ws, ms, vs, gs, lr, t)
    o_x = jax.jit(xla_adamw_flat)(ws, ms, vs, gs, lr, t)
    for a, b in zip(o_pl, o_x):
        np.testing.assert_allclose(np.asarray(a[:4096]), np.asarray(b[:4096]),
                                   rtol=1e-6, atol=1e-7)
    del o_pl, o_x, ws, ms, vs, gs
    print("numerics match", file=sys.stderr)

    w = jnp.asarray(rng.standard_normal(n), jnp.float32)
    m = jnp.zeros(n, jnp.float32)
    v = jnp.zeros(n, jnp.float32)
    g = jnp.asarray(rng.standard_normal(n), jnp.float32) * 1e-3

    # blocking sweep: 128 is the largest block that fits v5e's 16MB scoped
    # VMEM (measured r5: the original 256 design point needs 16.79M and
    # fails to compile); 256 stays in the sweep to document exactly that,
    # and in case future hardware fits it
    import functools

    pallas_rows = {}
    for br in (128, 256):
        try:
            t_br, (w, m, v) = _bench(
                functools.partial(fused_adamw_flat, block_rows=br),
                w, m, v, g, lr, t)
            pallas_rows[br] = round(t_br * 1e3, 3)
        except Exception as e:
            # a compile/runtime resource failure is DATA (the 256-row
            # point is expected to exceed v5e's scoped VMEM); anything
            # else is a bug in the harness/kernel and must surface
            msg = f"{type(e).__name__}: {e}"
            # match on resource-exhaustion STATUS text, not wrapper type
            # names — jaxlib wraps every runtime error in XlaRuntimeError
            # and swallowing those would bank wrong verdicts
            if not any(s in msg for s in
                       ("RESOURCE_EXHAUSTED", "ResourceExhausted",
                        "vmem", "VMEM")):
                raise
            pallas_rows[br] = f"compile-fail: {msg[:80]}"
            print(f"pallas block_rows={br}: {pallas_rows[br]}",
                  file=sys.stderr)
            # only a runtime failure lands after the carry was donated;
            # a compile-time failure leaves the buffers alive — skip the
            # ~4.3GB rebuild then
            if w.is_deleted():
                w = jnp.asarray(rng.standard_normal(n), jnp.float32)
                m = jnp.zeros(n, jnp.float32)
                v = jnp.zeros(n, jnp.float32)
    timed = [v_ for v_ in pallas_rows.values() if isinstance(v_, float)]
    if not timed:
        print("no pallas blocking compiled; XLA wins by default",
              file=sys.stderr)
    t_pl = min(timed) / 1e3 if timed else float("inf")
    t_x, _ = _bench(xla_adamw_flat, w, m, v, g, lr, t)
    gb = n * 4 * 7 / 1e9  # r: w,m,v,g  w: w,m,v
    rec = {
        "metric": "fused_adamw_ab", "n_params": n,
        "pallas_ms": round(t_pl * 1e3, 3) if timed else None,
        "pallas_ms_by_block_rows": pallas_rows,
        "xla_ms": round(t_x * 1e3, 3),
        "pallas_gbps": round(gb / t_pl, 1) if timed else None,
        "xla_gbps": round(gb / t_x, 1),
        "pallas_wins": bool(t_pl < t_x), "device": str(dev.platform),
    }
    print(json.dumps(rec))
    if on_tpu:
        rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
        with open(_NOTES, "a") as f:
            f.write(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()
