#!/usr/bin/env bash
# THE tunnel health probe — single source of truth for watcher + battery.
# Killable subprocess probe (never stacked; the wedge discipline): exit 0
# iff jax sees a real accelerator within the budget.
timeout 140 python - <<'EOF'
import subprocess, sys
r = subprocess.run(
    [sys.executable, "-c", "import jax; d=jax.devices()[0]; "
     "assert d.platform in ('tpu','axon'); print('PROBE_OK')"],
    capture_output=True, text=True, timeout=120)
sys.exit(0 if (r.returncode == 0 and "PROBE_OK" in r.stdout) else 1)
EOF
