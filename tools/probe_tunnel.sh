#!/usr/bin/env bash
# THE tunnel health probe — single source of truth for watcher + battery.
# Killable subprocess probe (never stacked; the wedge discipline): exit 0
# iff jax sees a real accelerator within the budget. Delegates to
# _bench_timing.probe_backend — the ONE probe implementation, which kills
# the probe's whole process GROUP on timeout (a direct-child-only kill
# orphans an axon grandchild parked in client init: a stacked hung chip
# claim, the exact wedge this probe exists to detect).
here="$(cd "$(dirname "$0")" && pwd)"
timeout 150 python -c "
import sys
sys.path.insert(0, '$here')
from _bench_timing import probe_backend
plat = probe_backend(120.0, log=lambda m: print(m, file=sys.stderr))
sys.exit(0 if plat not in (None, 'cpu') else 1)
"
