#!/usr/bin/env bash
# Tunnel-return battery, most-valuable-first so a re-wedge costs least.
# Each step runs under its own timeout; a hang kills only that step.
set -uo pipefail
cd "$(dirname "$0")/.."
# everything also lands in a line-buffered log — pipe buffers lose
# output when a re-wedge gets steps SIGKILLed (happened r4)
exec > >(stdbuf -oL tee -a rerun_r04.log) 2>&1
echo "=== battery start $(date -u +%H:%M:%S) ==="

echo "=== 1. llama anomaly bisect (answers the quarantine) ==="
timeout 1800 python tools/bisect_llama_tpu.py
echo "bisect rc=$?"

echo "=== 2. resnet50 re-measure (old row is suspect-high) ==="
BENCH_SMALL=0 timeout 900 python bench.py --model resnet50

echo "=== 3. fused AdamW re-verdict at designed 256x1024 blocking ==="
timeout 900 python tools/bench_adamw.py

echo "=== 4. flash S=1024 block tie-break (reps=9) ==="
timeout 1200 python tools/bench_flash.py --s 1024 --reps 9

echo "=== 5. bert re-measure with chained clock ==="
timeout 900 python bench.py --model bert

echo "=== 6. decode throughput (device-side while_loop) ==="
timeout 1800 python tools/bench_decode.py

echo "=== 7. bert B64 batch probe ==="
BENCH_BATCH=64 timeout 900 python bench.py --model bert

echo "done — see BENCH_NOTES_r04.json"
