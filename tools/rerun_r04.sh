#!/usr/bin/env bash
# Shim: the long-running r4 tunnel watcher invokes this path by name.
# Round 5 replaced the battery — forward to it.
exec bash "$(dirname "$0")/rerun_r05.sh" "$@"
