#!/usr/bin/env bash
# API-parity gate (reference: tools/print_signatures.py + paddle/fluid/API.spec
# CI gate — the reference diffs live signatures against a checked-in spec;
# here the spec IS the reference tree's own __all__ lists, and the gate tests
# compare this package against them name by name).
#
# Usage: tools/check_parity.sh [extra pytest args]
# Runs every parity-gate test on the 8-virtual-device CPU mesh.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export PALLAS_AXON_POOL_IPS=
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"

exec python -m pytest -q \
  tests/test_api_tail.py \
  tests/test_namespace_tail.py \
  tests/test_legacy_tail.py \
  tests/test_nn_tail.py \
  tests/test_static_nn.py::test_static_nn_parity_gate \
  tests/test_api_fingerprint.py \
  "$@"
