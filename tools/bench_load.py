#!/usr/bin/env python
"""Fleet-level load benchmark: replay a seeded paddle_tpu.loadgen trace
against a Router fleet with the queue-depth autoscaler attached and
emit ONE ``BENCH_LOAD`` row — goodput tok/s, per-tier SLO attainment,
unavailable rate, scale trajectory — the first bench artifact that
measures the fleet, not a lone engine (ISSUE 15).

The committed ``BENCH_LOAD.json`` comes from the CPU smoke::

    JAX_PLATFORMS=cpu python tools/bench_load.py --out BENCH_LOAD.json

Fixed seed + fixed fleet: the REQUEST STREAM and the completion
accounting are reproducible (same trace bytes, same outcome counts,
exactly-once always); latencies and goodput are whatever the host does
that day, which is why ``tests/test_bench_tools.py`` asserts the
artifact's SCHEMA, never its values. Knobs ride argv/env:
``--requests/--seed/--max-engines`` (or BENCH_LOAD_REQUESTS etc.) size
the drill; the defaults finish in seconds on CPU.

The row shape follows tools/bench_decode.py (metric/value/unit/
vs_baseline/config/device) so BENCH digests treat fleet rows like
engine rows; the fleet-only evidence lands under ``"report"``.

``--chaos`` emits a BENCH_CHAOS row instead (ISSUE 19: brownout armed
vs off under the same burst + fault schedule); ``--restart`` emits a
BENCH_RECOVERY row (ISSUE 20: SIGKILL a WAL-armed child fleet
mid-decode, restart 2->1 engines, score the RTO, assert zero fresh
compiles during recovery, and price the WAL's steady-state p95 ITL
overhead against a WAL-off control — committed as
``BENCH_RECOVERY.json``, schema-pinned like the others).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

# every key a BENCH_LOAD row must carry — tests/test_bench_tools.py
# pins this schema against the committed BENCH_LOAD.json
ROW_KEYS = ("metric", "value", "unit", "vs_baseline", "config", "device",
            "report")
REPORT_KEYS = ("seed", "num_requests", "goodput_tok_s", "outcomes",
               "tiers", "unavailable_rate", "timeout_rate",
               "prefix_hit_ratio", "engines_peak", "engines_final",
               "scale_ups", "scale_downs", "adapter_goodput",
               "constrained_validity", "exactly_once", "violations")
TIER_KEYS = ("requests", "ttft_slo_s", "itl_slo_s", "ttft_attainment",
             "itl_attainment", "ttft_breakdown")
# the attribution buckets a tier's ttft_breakdown carries (ISSUE 17) —
# mirrors serving.tracing.TTFT_BUCKETS, literal here so the schema is
# readable without importing the stack
BREAKDOWN_KEYS = ("queue", "compile", "cold_prefill", "warm_prefill",
                  "decode", "migration", "host_overhead")

# --chaos artifact schema (ISSUE 19): one BENCH_CHAOS row holding TWO
# runs of the SAME seed-0 burst trace + fault schedule against the same
# capacity-capped fleet — brownout armed vs brownout-off control — so
# the attainment delta is the overload controller's measured value.
# tests/test_bench_tools.py pins these against the committed
# BENCH_CHAOS.json.
CHAOS_KEYS = ("metric", "value", "unit", "vs_baseline", "config",
              "device", "seed", "num_requests", "faults", "armed",
              "control")
CHAOS_RUN_KEYS = ("goodput_tok_s", "outcomes", "shed_rate",
                  "expired_rate", "interactive_ttft_attainment",
                  "brownout_peak_level", "brownout_final_level",
                  "brownout_transitions", "retry_budget_exhausted",
                  "compile_counts_stable", "leaked_pages",
                  "exactly_once", "violations")

# --restart artifact schema (ISSUE 20): one BENCH_RECOVERY row from the
# cross-process kill-and-recover drill (paddle_tpu.loadgen.restart) —
# headline value is the RTO (SIGKILL instant to first recovered token
# landing at the client), vs_baseline is the WAL's steady-state cost
# (WAL-on p95 inter-token latency over WAL-off, same in-process
# workload). tests/test_bench_tools.py pins these against the
# committed BENCH_RECOVERY.json.
RECOVERY_KEYS = ("metric", "value", "unit", "vs_baseline", "config",
                 "device", "seed", "num_requests", "drill", "overhead")
RECOVERY_DRILL_KEYS = ("replicas_before", "replicas_after", "streams",
                       "killed_after_chunks", "bit_identical",
                       "seqs_exactly_once", "outcomes",
                       "fresh_compiles_recovery", "recover_s", "rto_s")
RECOVERY_OVERHEAD_KEYS = ("wal_on_p95_itl_s", "wal_off_p95_itl_s",
                          "itl_overhead_ratio", "requests",
                          "fsyncs_per_step")


def build_row(report_dict: dict, config_label: str, device: str) -> dict:
    """The one BENCH_LOAD row, schema-pinned: headline value is goodput
    tok/s; the LoadReport evidence (already a plain dict) rides along
    trimmed to the schema-stable keys."""
    rep = {k: report_dict[k] for k in REPORT_KEYS}
    rep["tiers"] = {
        name: {k: tier[k] for k in TIER_KEYS}
        for name, tier in report_dict["tiers"].items()}
    return {
        "metric": "BENCH_LOAD",
        "value": round(float(report_dict["goodput_tok_s"]), 1),
        "unit": "tokens/s",
        "vs_baseline": 1.0,
        "config": config_label,
        "device": device,
        "report": rep,
    }


def run_drill(seed: int, requests: int, max_engines: int):
    """Seeded heavy-tail drill: Zipf sharing + Poisson burst + slow
    consumers + mixed tiers against a 1-engine fleet the autoscaler may
    grow to ``max_engines``. Returns (LoadReport, config_label,
    device_platform)."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import loadgen
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.serving import Router, random_adapter

    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        num_key_value_heads=2, max_position_embeddings=64))
    router = Router()
    router.add_model("bench", model, replicas=1, page_size=4,
                     num_pages=128, max_batch_slots=4, max_model_len=64,
                     token_budget=32, min_step_tokens=32, max_queue=128)
    # two LoRA tenants, hot-loaded fleet-wide before traffic; the spec
    # propagates so autoscaler-spawned replicas hold them too
    store = router.engine("bench/0").adapters
    router.register_adapter("acme", random_adapter(store, seed=11),
                            model="bench")
    router.register_adapter("zen", random_adapter(store, seed=12),
                            model="bench")
    cfg = loadgen.TraceConfig(
        seed=seed, num_requests=requests, vocab_size=128,
        arrival_rate=8.0, burst_start=0.3, burst_duration=1.5,
        burst_factor=6.0, num_prompt_families=6, prefix_len=8,
        max_prompt_len=28, max_output_len=8,
        slow_consumer_fraction=0.05,
        # tenancy mixes (ISSUE 16): 50% base model, two adapter tenants;
        # a third of requests constrained to short letter runs — the
        # {1,6} lower bound keeps even a 1-token truncation grammar-valid
        adapter_mix=((None, 0.5), ("acme", 0.3), ("zen", 0.2)),
        schema_mix=((None, 0.67), ("[ab]{1,6}", 0.33)))
    trace = loadgen.generate_trace(cfg)
    scaler = loadgen.QueueDepthAutoscaler(
        router, config=loadgen.AutoscalerConfig(
            min_engines=1, max_engines=max_engines, scale_up_depth=2.0,
            scale_down_depth=0.25, hot_steps=2, cold_steps=6,
            cooldown_steps=6))
    report = loadgen.LoadDriver(router, trace, autoscaler=scaler).run()
    label = (f"llama-tiny fleet 1..{max_engines} seed={seed} "
             f"n={requests} burst=6x zipf=1.2 slow=5% "
             f"adapters=2@50% constrained=33%")
    return report, label, str(jax.devices()[0].platform)


def _chaos_tiers():
    """Deadline-bearing tier mix for the chaos drill. The interactive
    slice is deliberately SMALL (0.15): brownout protects the premium
    tier by sacrificing the rest, which is only a coherent policy when
    the premium tier alone fits the fleet's degraded capacity — if
    interactive work by itself overwhelms the storm-slowed engines, no
    admission policy can save it. The standard tier carries an
    engine-enforced deadline, so the expiry sweep and the
    deadline-aware gate both see real work.

    The interactive TTFT SLO (1.5 s) sits between what a preempting
    ladder delivers under the storm (max observed ~1.3 s: one chunked
    prefill behind at most one 70 ms-slowed step) and what a jammed
    fleet delivers (2 s+: a full long-decode residual) — below the
    physical floor no policy looks good, above the jam every policy
    does."""
    from paddle_tpu import loadgen

    return (
        loadgen.TierSpec("interactive", priority=0, weight=0.15,
                         ttft_slo_s=1.5, itl_slo_s=0.5),
        loadgen.TierSpec("standard", priority=1, weight=0.5185,
                         deadline_s=6.0, ttft_slo_s=2.0, itl_slo_s=1.0),
        loadgen.TierSpec("batch", priority=2, weight=0.3315,
                         ttft_slo_s=10.0, itl_slo_s=5.0),
    )


def run_chaos_drill(seed: int, requests: int, armed: bool) -> dict:
    """One chaos run: the seed-0 6x burst trace against a CAPACITY-
    CAPPED 2-engine fleet (no autoscaler — overload must be survived,
    not scaled away), with a seeded FaultSchedule (one engine kill with
    timed revival + one injected step-latency burst) riding the replay.
    ``armed`` attaches the OverloadController; the control run faces
    the identical trace and faults without it. Resets the metrics
    registry and tracer first so the two runs score in isolation."""
    import paddle_tpu as paddle
    from paddle_tpu import faults, loadgen, metrics
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.serving import (OverloadConfig, OverloadController,
                                    RetryBudget, Router, tracing)

    metrics.get_registry().reset()
    tracing.get_tracer().reset()
    faults.reset()
    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        num_key_value_heads=2, max_position_embeddings=64))
    router = Router(retry_budget=RetryBudget(capacity=16.0,
                                             refill_per_step=1.0))
    # host_offload stays OFF: this drill is slots-scarce, not
    # pages-scarce (128 pages x 4 tokens covers every stream), and
    # brownout-parking a batch decode would FREEZE its slot for the
    # storm — the page-pressure tier is proven in tests/chaos instead
    router.add_model("chaos", model, replicas=2, page_size=4,
                     num_pages=128, max_batch_slots=8, max_model_len=64,
                     token_budget=32, min_step_tokens=32, max_queue=128)
    # warm the one compiled step per engine BEFORE traffic (a
    # production fleet restores executables from the PR 14 disk cache):
    # without this, the first interactive arrivals pay the cold compile
    # and both runs miss the same SLOs for reasons no overload policy
    # can touch
    import numpy as np
    for h in router.handles("chaos"):
        h.engine.add_request(np.arange(4, dtype=np.int32),
                             max_new_tokens=2)
        h.engine.run()
    cfg = loadgen.TraceConfig(
        seed=seed, num_requests=requests, vocab_size=128,
        arrival_rate=8.0, burst_start=0.3, burst_duration=1.5,
        burst_factor=16.0, num_prompt_families=6, prefix_len=8,
        # LONG decodes (mean 24 vs BENCH_LOAD's 8): with a queue-jumping
        # priority tier, interactive TTFT in a jam is the RESIDUAL of
        # the earliest-finishing in-service stream — queue depth is
        # irrelevant, hold duration is everything. Long holds are what
        # the preempting ladder relieves and what buries the control.
        max_prompt_len=28, output_len_mean=24.0, output_len_sigma=0.5,
        max_output_len=32,
        slow_consumer_fraction=0.05, tiers=_chaos_tiers())
    trace = loadgen.generate_trace(cfg)
    # the incident, pinned (not drawn) so the artifact is legible: a
    # step-latency storm covering the whole arrival burst — every
    # engine step pays +70 ms, so a long decode holds its slot for
    # ~2 s of wall time and slot contention becomes the fight — plus
    # one engine kill mid-burst with timed revival (its migrated
    # streams land on the survivor mid-storm)
    schedule = loadgen.FaultSchedule([
        loadgen.FaultEvent(t_s=0.1, kind="latency", delay_s=0.07,
                           steps=300),
        loadgen.FaultEvent(t_s=0.6, kind="kill", engine_index=0,
                           down_s=0.6),
    ])
    ctl = None
    if armed:
        # asymmetric hysteresis — climb fast, descend slow: at
        # interactive-only the shed itself empties the queue, and a
        # symmetric controller would read that as recovery, de-escalate
        # mid-storm, re-admit the flood, and flap
        ctl = OverloadController(router, config=OverloadConfig(
            hot_backlog_s=0.12, cold_backlog_s=0.08, hot_steps=1,
            cold_steps=6, cooldown_steps=3, batch_chunk_cap=4))
    # step_dt MUST be fine-grained here: the default (2/arrival_rate =
    # 0.25 s/sweep) collapses the whole burst into ~6 sweeps, which
    # both dumps ~10 arrivals per sweep and gives the ladder (one
    # observe per sweep) no time to climb before the storm has passed
    report = loadgen.LoadDriver(router, trace, overload=ctl,
                                fault_schedule=schedule,
                                step_dt=0.02).run()
    cc = [h.engine.compile_counts() for h in router.handles("chaos")]
    leaked = sum(h.engine.pool.used_pages for h in router.handles("chaos"))
    reg = metrics.get_registry()
    fam = reg.get("paddle_tpu_router_retry_budget_exhausted_total")
    exhausted = int(fam.value) if fam is not None else 0
    inter = report.tiers["interactive"].ttft_attainment
    return {
        "goodput_tok_s": round(report.goodput_tok_s, 1),
        "outcomes": report.outcomes,
        "shed_rate": round(report.shed_rate, 4),
        "expired_rate": round(report.expired_rate, 4),
        "interactive_ttft_attainment": (None if inter is None
                                        else round(inter, 4)),
        "brownout_peak_level": (0 if ctl is None else
                                max([lv for _, lv in ctl.events],
                                    default=0)),
        "brownout_final_level": 0 if ctl is None else ctl.level,
        "brownout_transitions": 0 if ctl is None else len(ctl.events),
        "retry_budget_exhausted": exhausted,
        "compile_counts_stable": all(c["step"] == c["step_buckets"]
                                     for c in cc),
        "leaked_pages": int(leaked),
        "exactly_once": report.exactly_once,
        "violations": report.violations,
        "_schedule": schedule,   # stripped by build_chaos_row
    }


def build_chaos_row(seed: int, requests: int, armed: dict, control: dict,
                    device: str) -> dict:
    """The one BENCH_CHAOS row, schema-pinned: headline value is the
    ARMED run's interactive TTFT attainment; ``vs_baseline`` is the
    multiple over the brownout-off control on the identical trace and
    fault schedule."""
    schedule = armed.pop("_schedule")
    control.pop("_schedule", None)
    a = armed["interactive_ttft_attainment"] or 0.0
    c = control["interactive_ttft_attainment"] or 0.0
    return {
        "metric": "BENCH_CHAOS",
        "value": round(a, 4),
        "unit": "interactive_ttft_attainment",
        "vs_baseline": round(a / c, 2) if c else None,
        "config": (f"llama-tiny fleet=2 (capped) seed={seed} "
                   f"n={requests} burst=16x kills=1 latency=1 "
                   f"brownout-on vs brownout-off"),
        "device": device,
        "seed": seed,
        "num_requests": requests,
        "faults": [{"t_s": round(e.t_s, 3), "kind": e.kind,
                    "down_s": e.down_s, "delay_s": e.delay_s,
                    "steps": e.steps} for e in schedule.events],
        "armed": armed,
        "control": control,
    }


def _measure_itl(wal_dir, requests: int, cache_dir=None) -> dict:
    """One in-process run of the restart-drill workload on a 1-engine
    fleet, timing every stream chunk delivery: returns the p95
    inter-token gap plus the WAL's fsync-per-step evidence (group
    commit = ONE fsync per ``router.step()`` no matter how many
    requests landed tokens). ``wal_dir=None`` is the WAL-off control.
    ``cache_dir`` shares one disk compile cache across runs — without
    it every run pays its own fresh XLA compiles mid-step (the
    in-process memory cache does not span routers) and seconds of
    compile noise drown the microseconds of fsync under measurement."""
    import time as _time

    import numpy as np

    from paddle_tpu import metrics
    from paddle_tpu.loadgen import restart
    from paddle_tpu.loadgen.trace import TraceConfig, generate_trace

    router = restart.build_router(wal_dir, replicas=1,
                                  compile_cache_dir=cache_dir)
    arrivals: dict = {}

    def _cb(idx):
        def cb(rid, tok, fin, seq):
            if tok is not None:
                arrivals.setdefault(idx, []).append(_time.perf_counter())
        return cb

    trace = generate_trace(TraceConfig(
        num_requests=requests, **restart._TRACE_KW))
    for tr in trace.requests:
        router.submit(np.asarray(tr.prompt, np.int32),
                      model=restart.MODEL_ID,
                      max_new_tokens=tr.max_new_tokens,
                      temperature=tr.temperature, seed=tr.seed,
                      priority=tr.priority, stream_cb=_cb(tr.index))
    fam = metrics.get_registry().get("paddle_tpu_wal_fsync_seconds")
    fsync0 = fam.count if fam is not None else 0
    steps = 0
    while router.has_work:
        router.step()
        steps += 1
    router.shutdown()
    fam = metrics.get_registry().get("paddle_tpu_wal_fsync_seconds")
    fsyncs = (fam.count if fam is not None else 0) - fsync0
    gaps = [b - a for times in arrivals.values()
            for a, b in zip(times, times[1:])]
    return {"p95_itl_s": float(np.percentile(gaps, 95)) if gaps else 0.0,
            "steps": steps, "fsyncs": int(fsyncs)}


def run_recovery_drill(seed: int, requests: int) -> dict:
    """The ISSUE 20 acceptance drill, measured: (1) the cross-process
    kill-and-recover (child fleet SIGKILLed mid-decode, restarted 2->1
    engines over a shared disk compile cache) scoring RTO, recovery
    fresh-compiles, and bit-identical/exactly-once stream checks; (2)
    the WAL's steady-state overhead — the same in-process workload with
    the WAL armed vs off, comparing p95 inter-token latency (group
    commit amortizes ONE fsync per step across the whole batch)."""
    import shutil
    import tempfile

    from paddle_tpu.loadgen import restart

    workdir = tempfile.mkdtemp(prefix="bench-recovery-")
    try:
        res = restart.run_restart_drill(
            workdir, replicas_before=2, replicas_after=1,
            num_requests=requests, kill_after_chunks=8)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    ref = restart.streams_by_index(res["ref_chunks"])
    full = restart.streams_by_index(
        res["pre_chunks"] + res["post_chunks"])
    bit_identical = full == ref
    seqs_ok = all(
        [s for _, _, s in chunks] == list(range(len(chunks)))
        for chunks in full.values())
    timing = res["timing"]
    drill = {
        "replicas_before": 2, "replicas_after": 1,
        "streams": len(ref),
        "killed_after_chunks": res["killed_after"],
        "bit_identical": bit_identical,
        "seqs_exactly_once": seqs_ok,
        "outcomes": timing.get("outcomes", {}),
        "fresh_compiles_recovery": timing["fresh_compiles"],
        "recover_s": round(timing["recover_s"], 4),
        "rto_s": (None if res["rto_s"] is None
                  else round(res["rto_s"], 4)),
    }
    # overhead: one warmup run populates a shared disk compile cache,
    # then WAL-off and WAL-on measure identical warm workloads — any
    # residual delta is the WAL's append+fsync, not compile noise
    scratch = tempfile.mkdtemp(prefix="bench-recovery-itl-")
    try:
        cache = os.path.join(scratch, "xla-cache")
        _measure_itl(None, requests, cache_dir=cache)
        off = _measure_itl(None, requests, cache_dir=cache)
        on = _measure_itl(os.path.join(scratch, "wal"), requests,
                          cache_dir=cache)
    finally:
        shutil.rmtree(scratch, ignore_errors=True)
    ratio = (on["p95_itl_s"] / off["p95_itl_s"]
             if off["p95_itl_s"] > 0 else None)
    overhead = {
        "wal_on_p95_itl_s": round(on["p95_itl_s"], 6),
        "wal_off_p95_itl_s": round(off["p95_itl_s"], 6),
        "itl_overhead_ratio": (None if ratio is None
                               else round(ratio, 4)),
        "requests": requests,
        "fsyncs_per_step": (round(on["fsyncs"] / on["steps"], 4)
                            if on["steps"] else None),
    }
    return {"drill": drill, "overhead": overhead}


def build_recovery_row(seed: int, requests: int, measured: dict,
                       device: str) -> dict:
    """The one BENCH_RECOVERY row, schema-pinned: headline value is the
    RTO in seconds (SIGKILL to first recovered token at the client);
    ``vs_baseline`` is the WAL-on/WAL-off p95 ITL ratio — the price of
    durability in steady state."""
    return {
        "metric": "BENCH_RECOVERY",
        "value": measured["drill"]["rto_s"],
        "unit": "seconds_rto",
        "vs_baseline": measured["overhead"]["itl_overhead_ratio"],
        "config": (f"llama-tiny wal fleet=2->1 seed={seed} "
                   f"n={requests} sigkill-mid-decode shared-xla-cache"),
        "device": device,
        "seed": seed,
        "num_requests": requests,
        "drill": measured["drill"],
        "overhead": measured["overhead"],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int,
                    default=int(os.environ.get("BENCH_LOAD_SEED", "0")))
    ap.add_argument("--requests", type=int,
                    default=int(os.environ.get("BENCH_LOAD_REQUESTS",
                                               "0")) or None,
                    help="trace length (default: 32, or 64 for "
                         "--chaos)")
    ap.add_argument("--max-engines", type=int,
                    default=int(os.environ.get("BENCH_LOAD_MAX_ENGINES",
                                               "3")))
    ap.add_argument("--chaos", action="store_true",
                    help="run the ISSUE 19 chaos drill instead: the "
                         "same seed-0 burst trace + seeded fault "
                         "schedule twice (brownout armed vs off) "
                         "against a capacity-capped fleet, emitting a "
                         "BENCH_CHAOS row")
    ap.add_argument("--restart", action="store_true",
                    help="run the ISSUE 20 recovery drill instead: "
                         "SIGKILL a WAL-armed child fleet mid-decode, "
                         "restart it 2->1 engines, score RTO / zero "
                         "fresh recovery compiles / bit-identical "
                         "streams plus the WAL-on vs WAL-off p95 ITL "
                         "overhead, emitting a BENCH_RECOVERY row")
    ap.add_argument("--out", default=None,
                    help="write the row to this file (e.g. "
                         "BENCH_LOAD.json); stdout always gets it")
    args = ap.parse_args(argv)
    requests = args.requests or (64 if args.chaos else
                                 6 if args.restart else 32)

    if args.restart:
        import jax
        measured = run_recovery_drill(args.seed, requests)
        row = build_recovery_row(args.seed, requests, measured,
                                 str(jax.devices()[0].platform))
        print(json.dumps(row, indent=2, sort_keys=True))
        d, o = row["drill"], row["overhead"]
        ok = (d["bit_identical"] and d["seqs_exactly_once"]
              and d["fresh_compiles_recovery"] == 0
              and d["rto_s"] is not None)
        if not ok:
            print(f"RECOVERY DRILL FAILED: {d}", file=sys.stderr)
            return 1
        if (o["itl_overhead_ratio"] is not None
                and o["itl_overhead_ratio"] > 1.05):
            print(f"WAL ITL OVERHEAD {o['itl_overhead_ratio']}x > "
                  f"1.05x gate", file=sys.stderr)
            return 1
        if args.out:
            with open(args.out, "w") as f:
                json.dump(row, f, indent=2, sort_keys=True)
                f.write("\n")
        return 0

    if args.chaos:
        import jax
        armed = run_chaos_drill(args.seed, requests, armed=True)
        control = run_chaos_drill(args.seed, requests, armed=False)
        row = build_chaos_row(args.seed, requests, armed, control,
                              str(jax.devices()[0].platform))
        print(json.dumps(row, indent=2, sort_keys=True))
        ok = (row["armed"]["exactly_once"]
              and row["control"]["exactly_once"])
        if not ok:
            print(f"ACCOUNTING VIOLATIONS: "
                  f"{row['armed']['violations']} / "
                  f"{row['control']['violations']}", file=sys.stderr)
            return 1
        if args.out:
            with open(args.out, "w") as f:
                json.dump(row, f, indent=2, sort_keys=True)
                f.write("\n")
        return 0

    report, label, device = run_drill(args.seed, requests,
                                      args.max_engines)
    row = build_row(report.to_dict(), label, device)
    print(json.dumps(row, indent=2, sort_keys=True))
    if not report.exactly_once:
        print(f"ACCOUNTING VIOLATIONS: {report.violations}",
              file=sys.stderr)
        return 1
    if args.out:
        with open(args.out, "w") as f:
            json.dump(row, f, indent=2, sort_keys=True)
            f.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
