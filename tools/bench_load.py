#!/usr/bin/env python
"""Fleet-level load benchmark: replay a seeded paddle_tpu.loadgen trace
against a Router fleet with the queue-depth autoscaler attached and
emit ONE ``BENCH_LOAD`` row — goodput tok/s, per-tier SLO attainment,
unavailable rate, scale trajectory — the first bench artifact that
measures the fleet, not a lone engine (ISSUE 15).

The committed ``BENCH_LOAD.json`` comes from the CPU smoke::

    JAX_PLATFORMS=cpu python tools/bench_load.py --out BENCH_LOAD.json

Fixed seed + fixed fleet: the REQUEST STREAM and the completion
accounting are reproducible (same trace bytes, same outcome counts,
exactly-once always); latencies and goodput are whatever the host does
that day, which is why ``tests/test_bench_tools.py`` asserts the
artifact's SCHEMA, never its values. Knobs ride argv/env:
``--requests/--seed/--max-engines`` (or BENCH_LOAD_REQUESTS etc.) size
the drill; the defaults finish in seconds on CPU.

The row shape follows tools/bench_decode.py (metric/value/unit/
vs_baseline/config/device) so BENCH digests treat fleet rows like
engine rows; the fleet-only evidence lands under ``"report"``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

# every key a BENCH_LOAD row must carry — tests/test_bench_tools.py
# pins this schema against the committed BENCH_LOAD.json
ROW_KEYS = ("metric", "value", "unit", "vs_baseline", "config", "device",
            "report")
REPORT_KEYS = ("seed", "num_requests", "goodput_tok_s", "outcomes",
               "tiers", "unavailable_rate", "timeout_rate",
               "prefix_hit_ratio", "engines_peak", "engines_final",
               "scale_ups", "scale_downs", "adapter_goodput",
               "constrained_validity", "exactly_once", "violations")
TIER_KEYS = ("requests", "ttft_slo_s", "itl_slo_s", "ttft_attainment",
             "itl_attainment", "ttft_breakdown")
# the attribution buckets a tier's ttft_breakdown carries (ISSUE 17) —
# mirrors serving.tracing.TTFT_BUCKETS, literal here so the schema is
# readable without importing the stack
BREAKDOWN_KEYS = ("queue", "compile", "cold_prefill", "warm_prefill",
                  "decode", "migration", "host_overhead")


def build_row(report_dict: dict, config_label: str, device: str) -> dict:
    """The one BENCH_LOAD row, schema-pinned: headline value is goodput
    tok/s; the LoadReport evidence (already a plain dict) rides along
    trimmed to the schema-stable keys."""
    rep = {k: report_dict[k] for k in REPORT_KEYS}
    rep["tiers"] = {
        name: {k: tier[k] for k in TIER_KEYS}
        for name, tier in report_dict["tiers"].items()}
    return {
        "metric": "BENCH_LOAD",
        "value": round(float(report_dict["goodput_tok_s"]), 1),
        "unit": "tokens/s",
        "vs_baseline": 1.0,
        "config": config_label,
        "device": device,
        "report": rep,
    }


def run_drill(seed: int, requests: int, max_engines: int):
    """Seeded heavy-tail drill: Zipf sharing + Poisson burst + slow
    consumers + mixed tiers against a 1-engine fleet the autoscaler may
    grow to ``max_engines``. Returns (LoadReport, config_label,
    device_platform)."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import loadgen
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.serving import Router, random_adapter

    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        num_key_value_heads=2, max_position_embeddings=64))
    router = Router()
    router.add_model("bench", model, replicas=1, page_size=4,
                     num_pages=128, max_batch_slots=4, max_model_len=64,
                     token_budget=32, min_step_tokens=32, max_queue=128)
    # two LoRA tenants, hot-loaded fleet-wide before traffic; the spec
    # propagates so autoscaler-spawned replicas hold them too
    store = router.engine("bench/0").adapters
    router.register_adapter("acme", random_adapter(store, seed=11),
                            model="bench")
    router.register_adapter("zen", random_adapter(store, seed=12),
                            model="bench")
    cfg = loadgen.TraceConfig(
        seed=seed, num_requests=requests, vocab_size=128,
        arrival_rate=8.0, burst_start=0.3, burst_duration=1.5,
        burst_factor=6.0, num_prompt_families=6, prefix_len=8,
        max_prompt_len=28, max_output_len=8,
        slow_consumer_fraction=0.05,
        # tenancy mixes (ISSUE 16): 50% base model, two adapter tenants;
        # a third of requests constrained to short letter runs — the
        # {1,6} lower bound keeps even a 1-token truncation grammar-valid
        adapter_mix=((None, 0.5), ("acme", 0.3), ("zen", 0.2)),
        schema_mix=((None, 0.67), ("[ab]{1,6}", 0.33)))
    trace = loadgen.generate_trace(cfg)
    scaler = loadgen.QueueDepthAutoscaler(
        router, config=loadgen.AutoscalerConfig(
            min_engines=1, max_engines=max_engines, scale_up_depth=2.0,
            scale_down_depth=0.25, hot_steps=2, cold_steps=6,
            cooldown_steps=6))
    report = loadgen.LoadDriver(router, trace, autoscaler=scaler).run()
    label = (f"llama-tiny fleet 1..{max_engines} seed={seed} "
             f"n={requests} burst=6x zipf=1.2 slow=5% "
             f"adapters=2@50% constrained=33%")
    return report, label, str(jax.devices()[0].platform)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int,
                    default=int(os.environ.get("BENCH_LOAD_SEED", "0")))
    ap.add_argument("--requests", type=int,
                    default=int(os.environ.get("BENCH_LOAD_REQUESTS",
                                               "32")))
    ap.add_argument("--max-engines", type=int,
                    default=int(os.environ.get("BENCH_LOAD_MAX_ENGINES",
                                               "3")))
    ap.add_argument("--out", default=None,
                    help="write the row to this file (e.g. "
                         "BENCH_LOAD.json); stdout always gets it")
    args = ap.parse_args(argv)

    report, label, device = run_drill(args.seed, args.requests,
                                      args.max_engines)
    row = build_row(report.to_dict(), label, device)
    print(json.dumps(row, indent=2, sort_keys=True))
    if not report.exactly_once:
        print(f"ACCOUNTING VIOLATIONS: {report.violations}",
              file=sys.stderr)
        return 1
    if args.out:
        with open(args.out, "w") as f:
            json.dump(row, f, indent=2, sort_keys=True)
            f.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
