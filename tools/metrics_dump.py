#!/usr/bin/env python
"""Dump a paddle_tpu metrics snapshot as JSON (or Prometheus text).

Three sources, in order of usefulness:

  --url http://host:port   scrape a running MetricsServer (fetches
                           /metrics.json; with --prometheus, /metrics)
  --demo                   run a tiny CPU serving workload in-process
                           and dump the registry it populated (smoke /
                           docs walkthrough; also what the tests drive)
  --demo --router          same, but through a 2-replica Router fleet:
                           least-loaded dispatch, a degrade + failover,
                           and a rolling weight reload, so the router
                           series (paddle_tpu_router_dispatch_total
                           {engine_id,model_id}, _requeued_total,
                           _reloads_total{result}, _engine_state and the
                           per-engine serving labels) are all live
  (neither)                dump THIS process's default registry — only
                           meaningful when imported and called after a
                           workload, so the CLI warns on an empty one

Output goes to stdout, or --out FILE. Examples:

  python tools/metrics_dump.py --demo | jq '.paddle_tpu_serving_ttft_seconds'
  python tools/metrics_dump.py --demo --router --prometheus | grep router_
  python tools/metrics_dump.py --url http://127.0.0.1:9100 --out snap.json

--check-docs diffs the LIVE registry against the docs/OBSERVABILITY.md
catalog through the same parser tpulint's TPL003 rule uses — the
runtime cross-check of the static rule. A live family missing from the
docs exits 1; documented families the workload didn't light up are
listed informationally (a --demo run can't touch every subsystem).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _demo_registry():
    """Tiny CPU-fallback engine run (tests/test_serving.py scale): a few
    requests through prefill+decode so every serving instrument is live —
    including the prefix-cache series (two requests share an 8-token
    prefix, so hits/misses/saved and the cached-pages gauge all move)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import metrics
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.serving import ServingEngine

    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny(
        vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
        num_key_value_heads=2, max_position_embeddings=32))
    engine = ServingEngine(model, page_size=4, max_batch_slots=2)
    rng = np.random.default_rng(0)
    for n, new in ((5, 4), (3, 6), (7, 3)):
        engine.add_request(rng.integers(1, 64, (n,)), max_new_tokens=new)
    engine.run()
    # prefix-cache traffic: a shared 8-token system prefix — the second
    # request is a warm hit (paddle_tpu_serving_prefix_hits_total,
    # _prefill_tokens_saved_total, _prefix_cached_pages go live)
    shared = rng.integers(1, 64, (8,))
    for tail in (1, 2):
        engine.add_request(np.concatenate([shared, [tail]]),
                           max_new_tokens=3)
        engine.run()
    _demo_train_sentinel()
    _demo_loadgen()
    _demo_overload()
    _demo_adapters_grammar()
    _demo_tracing()
    _demo_wal_recovery()
    return metrics.get_registry()


def _demo_wal_recovery():
    """Kill-and-recover drill (ISSUE 20): serve a couple of requests
    through a WAL-armed router, ABANDON it mid-decode (no seal — the
    same registry state a crash leaves), then recover into a second
    router over the same wal_dir and drain — so the whole durability
    family set (paddle_tpu_wal_{append,fsync,replay}_seconds,
    paddle_tpu_wal_records_total{kind}, _corrupt_records_total,
    paddle_tpu_wal_recovered_requests_total{outcome}) is live in the
    --demo snapshot."""
    import shutil
    import tempfile

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.serving import Router

    def _model():
        paddle.seed(0)
        return LlamaForCausalLM(llama_tiny(
            vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
            num_key_value_heads=2, max_position_embeddings=32))

    tmp = tempfile.mkdtemp(prefix="metrics_demo_wal_")
    try:
        crashed = Router(wal_dir=tmp)
        crashed.add_model("wal-demo", _model(), replicas=1, page_size=4,
                          max_batch_slots=2)
        rng = np.random.default_rng(2)
        for n in (5, 4):
            crashed.submit(rng.integers(1, 64, (n,)), model="wal-demo",
                           max_new_tokens=6)
        for _ in range(3):
            crashed.step()      # mid-decode: journaled, unfinished
        # the "crash": the router is simply abandoned, WAL unsealed
        survivor = Router(wal_dir=tmp)
        survivor.add_model("wal-demo", _model(), replicas=1,
                           page_size=4, max_batch_slots=2)
        survivor.recover()
        survivor.shutdown()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _demo_adapters_grammar():
    """Multi-LoRA + constrained-decoding drill (ISSUE 16): hot-load an
    adapter through the Router (canary warm-up included), then decode
    one adapter-routed constrained request and one base-model
    constrained request through a garbage drafter whose every proposal
    the grammar pre-filter drops — so the whole
    paddle_tpu_serving_adapter_* / _grammar_* family set plus
    paddle_tpu_serving_adapter_loads_total is live in the snapshot."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.serving import (GrammarFSM, Router, random_adapter,
                                    toy_tokenizer)

    class _Garbage:
        def propose(self, ids, k=None):
            # token 0 decodes to ' ' — never inside [AB]{1,6}, so every
            # draft against the grammar is host-filtered before the step
            return np.zeros(k or 1, np.int32)

    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny(
        vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
        num_key_value_heads=2, max_position_embeddings=32))
    router = Router()
    router.add_model("tenancy-demo", model, replicas=1, page_size=4,
                     max_batch_slots=2, spec_k=2, drafter=_Garbage())
    store = router.engine("tenancy-demo/0").adapters
    router.register_adapter("acme", random_adapter(store, seed=1),
                            model="tenancy-demo")
    fsm = GrammarFSM.compile("[AB]{1,6}", toy_tokenizer(64))
    rng = np.random.default_rng(1)
    router.submit(rng.integers(1, 64, (5,)), model="tenancy-demo",
                  max_new_tokens=6, adapter_id="acme", grammar=fsm)
    router.submit(rng.integers(1, 64, (4,)), model="tenancy-demo",
                  max_new_tokens=4, grammar=fsm)
    router.run()


def _demo_tracing():
    """Trace-journal drill (ISSUE 17): overflow a deliberately tiny
    private ring and dump one flight record into a scratch dir, so the
    tracing series (paddle_tpu_trace_dropped_events_total,
    paddle_tpu_trace_recorder_dumps_total{reason}) are live in the
    snapshot — the loadgen drill above already lights the attribution
    histogram paddle_tpu_loadgen_ttft_breakdown_seconds{tier,bucket}
    through the driver's scoring pass."""
    import shutil
    import tempfile

    from paddle_tpu.serving import tracing

    tmp = tempfile.mkdtemp(prefix="metrics_demo_flight_")
    try:
        tracer = tracing.RequestTracer(capacity=16, flight_dir=tmp)
        for i in range(24):             # 8 past capacity → drops count
            tracer.emit("req.token", "r%d" % (i % 4), arg=float(i))
        tracer.flush_metrics()
        tracer.dump_flight(reason="demo")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _demo_loadgen():
    """Short loadgen drill: a seeded burst trace against a 1-engine
    fleet whose autoscaler may grow to 2, so every ISSUE 15 series
    (paddle_tpu_loadgen_{ttft,itl}_seconds{tier}, _requests_total
    {tier,outcome}, _submit_retries_total, paddle_tpu_autoscaler_
    engines/backlog_seconds/scale_events_total/decisions_total) is
    live in the --demo snapshot."""
    import paddle_tpu as paddle
    from paddle_tpu import loadgen
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.serving import Router

    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny(
        vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
        num_key_value_heads=2, max_position_embeddings=32))
    router = Router()
    router.add_model("loadgen-demo", model, replicas=1, page_size=4,
                     num_pages=64, max_batch_slots=2, max_model_len=32,
                     token_budget=16, min_step_tokens=16, max_queue=64)
    trace = loadgen.generate_trace(loadgen.TraceConfig(
        seed=0, num_requests=12, vocab_size=64, arrival_rate=10.0,
        burst_start=0.1, burst_duration=0.8, burst_factor=6.0,
        prefix_len=5, max_prompt_len=16, max_output_len=4,
        slow_consumer_fraction=0.1))
    scaler = loadgen.QueueDepthAutoscaler(
        router, config=loadgen.AutoscalerConfig(
            min_engines=1, max_engines=2, scale_up_depth=1.5,
            scale_down_depth=0.25, hot_steps=2, cold_steps=4,
            cooldown_steps=4))
    loadgen.LoadDriver(router, trace, autoscaler=scaler).run()


def _demo_overload():
    """Miniature overload drill (ISSUE 19): a tiered burst with a
    step-latency storm against a capacity-capped 1-engine fleet with
    the OverloadController armed and a router retry budget attached,
    so every overload series (paddle_tpu_overload_brownout_level /
    _transitions_total / _decisions_total / _shed_total /
    _backlog_seconds, paddle_tpu_serving_expired_total,
    paddle_tpu_router_retry_budget_exhausted_total) is live in the
    --demo snapshot."""
    import paddle_tpu as paddle
    from paddle_tpu import loadgen
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.serving import (OverloadConfig, OverloadController,
                                    RetryBudget, Router)

    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny(
        vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
        num_key_value_heads=2, max_position_embeddings=32))
    router = Router(retry_budget=RetryBudget(capacity=4.0,
                                             refill_per_step=0.5))
    router.add_model("overload-demo", model, replicas=1, page_size=4,
                     num_pages=64, max_batch_slots=2, max_model_len=32,
                     token_budget=16, min_step_tokens=16, max_queue=64)
    tiers = (
        loadgen.TierSpec("interactive", priority=0, weight=0.2,
                         ttft_slo_s=1.5, itl_slo_s=0.5),
        loadgen.TierSpec("standard", priority=1, weight=0.5,
                         deadline_s=1.0, ttft_slo_s=2.0, itl_slo_s=1.0),
        loadgen.TierSpec("batch", priority=2, weight=0.3,
                         ttft_slo_s=10.0, itl_slo_s=5.0),
    )
    trace = loadgen.generate_trace(loadgen.TraceConfig(
        seed=0, num_requests=24, vocab_size=64, arrival_rate=10.0,
        burst_start=0.1, burst_duration=0.8, burst_factor=12.0,
        prefix_len=5, max_prompt_len=16, output_len_mean=10.0,
        output_len_sigma=0.5, max_output_len=12,
        slow_consumer_fraction=0.1, tiers=tiers))
    schedule = loadgen.FaultSchedule([
        loadgen.FaultEvent(t_s=0.05, kind="latency", delay_s=0.03,
                           steps=200),
    ])
    ctl = OverloadController(router, config=OverloadConfig(
        hot_backlog_s=0.06, cold_backlog_s=0.04, hot_steps=1,
        cold_steps=4, cooldown_steps=2, batch_chunk_cap=4))
    loadgen.LoadDriver(router, trace, overload=ctl,
                       fault_schedule=schedule, step_dt=0.02).run()


def _demo_train_sentinel():
    """Tiny sentinel-guarded train loop with one injected NaN batch and a
    persistent spike region, so the ISSUE 9 training-sentinel series
    (paddle_tpu_train_anomalies_total{kind}, _rollbacks_total,
    _skipped_batches_total, _last_good_step, loss/grad-norm histograms)
    are all live in the --demo snapshot."""
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import faults
    from paddle_tpu.io import DataLoader
    from paddle_tpu.io.dataset import Dataset
    from paddle_tpu.tensor import Tensor

    class DS(Dataset):
        def __len__(self):
            return 32

        def __getitem__(self, i):
            x = np.float32([i / 32.0, 1.0 - i / 32.0, (i % 5) / 5.0])
            return x, np.float32([x @ np.float32([0.5, -0.25, 1.0])])

    paddle.seed(0)
    net = nn.Linear(3, 1)
    opt = paddle.optimizer.AdamW(learning_rate=0.05,
                                 parameters=net.parameters())
    loss = nn.MSELoss()
    sent = faults.TrainSentinel(skip_limit=1, healthy_window=2,
                                min_history=4)
    loader = DataLoader(DS(), batch_size=4)
    sent.bind(model=net, optimizer=opt, dataloader=loader)
    sent.note_epoch(0)
    guarded = sent.guard(lambda x, y: loss(net(x), y), optimizer=opt)

    def poison():
        if net.weight.grad is not None:
            net.weight.grad = Tensor(
                jnp.full_like(net.weight.grad._value, jnp.nan))

    it, done = iter(loader), 0
    # hits 6-8 of train.grads: one skip, then an escalation to rollback
    with faults.inject("train.grads", call=poison, after=5, times=3):
        while done < 14:
            try:
                x, y = next(it)
            except StopIteration:
                it = iter(loader)
                continue
            if guarded(x, y).rolled_back:
                it = iter(loader)
            done += 1


def _demo_router_registry():
    """Router-fleet demo: least-loaded dispatch over 2 replicas, one
    watchdog degrade with exactly-once failover, and a rolling reload
    from a committed checkpoint — every router series ends up live."""
    import shutil
    import tempfile

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import metrics
    from paddle_tpu.checkpoint import CheckpointManager
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.serving import Router

    def model(seed):
        paddle.seed(seed)
        return LlamaForCausalLM(llama_tiny(
            vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
            num_key_value_heads=2, max_position_embeddings=32))

    router = Router()
    router.add_model("llama-tiny", [model(0), model(0)], page_size=4,
                     max_batch_slots=2, watchdog_recovery_steps=2)
    rng = np.random.default_rng(0)
    for n, new in ((5, 4), (3, 6), (7, 3), (4, 5)):
        router.submit(rng.integers(1, 64, (n,)), model="llama-tiny",
                      max_new_tokens=new)
    router.run()
    # degrade replica 0 mid-workload: its waiting request fails over
    e0 = router.engine("llama-tiny/0")
    e0.add_request(rng.integers(1, 64, (6,)), max_new_tokens=8)
    e0.step()
    e0.add_request(rng.integers(1, 64, (4,)), max_new_tokens=2)
    e0.watchdog.end_step(e0.watchdog.stall_threshold_s + 1)  # stall
    router.run()  # failover happens here; e0 recovers after 2 steps
    # rolling weight push from a committed checkpoint
    tmp = tempfile.mkdtemp(prefix="metrics_demo_ckpt_")
    try:
        CheckpointManager(tmp, max_to_keep=None).save(
            1, {"model": model(1).state_dict()})
        router.reload(tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return metrics.get_registry()


def _load_analysis(root):
    """paddle_tpu.analysis without importing paddle_tpu (which pulls
    jax): a scrape-only monitoring host running `--url --check-docs`
    has no jax. Same standalone spec load as tools/tpulint.py; the
    package import is used when it is already loaded (e.g. --demo)."""
    if "paddle_tpu" in sys.modules:
        from paddle_tpu import analysis
        return analysis
    name = "_metrics_dump_analysis"
    if name not in sys.modules:
        import importlib.util
        pkg_dir = os.path.join(root, "paddle_tpu", "analysis")
        spec = importlib.util.spec_from_file_location(
            name, os.path.join(pkg_dir, "__init__.py"),
            submodule_search_locations=[pkg_dir])
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
    return sys.modules[name]


def _check_docs(live_names, root):
    """Diff live metric families against the docs/OBSERVABILITY.md
    catalog via paddle_tpu.analysis.catalog (the TPL003 parser — one
    grammar, two checkers). Returns the exit code."""
    parse_metric_doc = _load_analysis(root).parse_metric_doc

    doc_path = os.path.join(root, "docs", "OBSERVABILITY.md")
    documented = set(parse_metric_doc(doc_path))
    live = set(live_names)
    if not live:
        # same fail-loudly contract as tpulint's empty lint path: a
        # parity gate that checked zero families must not pass green
        print("check-docs: ERROR: live registry is empty — nothing to "
              "check (did you want --demo or --url?)")
        return 1
    undocumented = sorted(live - documented)
    dark = sorted(documented - live)
    print(f"check-docs: {len(live)} live famil"
          f"{'y' if len(live) == 1 else 'ies'}, "
          f"{len(documented)} documented")
    if dark:
        print(f"  note: {len(dark)} documented famil"
              f"{'y' if len(dark) == 1 else 'ies'} not exercised by this "
              f"workload (expected for subsystems the run didn't touch):")
        for n in dark:
            print(f"    - {n}")
    if undocumented:
        print(f"  ERROR: {len(undocumented)} live famil"
              f"{'y' if len(undocumented) == 1 else 'ies'} missing from "
              f"docs/OBSERVABILITY.md:")
        for n in undocumented:
            print(f"    - {n}")
        return 1
    print("  every live family is documented")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", help="scrape a running MetricsServer "
                                  "(e.g. http://127.0.0.1:9100)")
    ap.add_argument("--demo", action="store_true",
                    help="populate via a tiny in-process serving run")
    ap.add_argument("--router", action="store_true",
                    help="with --demo: drive a 2-replica Router fleet "
                         "(dispatch/failover/reload) instead of one "
                         "engine, lighting up the router metrics")
    ap.add_argument("--prometheus", action="store_true",
                    help="text exposition instead of JSON")
    ap.add_argument("--check-docs", action="store_true",
                    help="instead of dumping, diff the live registry "
                         "against the docs/OBSERVABILITY.md catalog "
                         "(shared TPL003 parser); exit 1 on an "
                         "undocumented live family")
    ap.add_argument("--out", help="write here instead of stdout")
    args = ap.parse_args(argv)
    if args.check_docs and (args.out or args.prometheus):
        ap.error("--check-docs prints a diff report, not a snapshot — "
                 "it cannot honor --out/--prometheus")
    if args.url and args.demo:
        ap.error("--url and --demo are mutually exclusive")
    if args.router and not args.demo:
        ap.error("--router is a --demo mode (a live fleet is scraped "
                 "with --url)")

    root = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    if args.url:
        path = ("/metrics.json" if args.check_docs
                else "/metrics" if args.prometheus else "/metrics.json")
        with urllib.request.urlopen(args.url.rstrip("/") + path,
                                    timeout=10) as r:
            body = r.read().decode()
        if args.check_docs:
            return _check_docs(json.loads(body).keys(), root)
        text = body if args.prometheus else json.dumps(json.loads(body),
                                                       indent=2)
    else:
        if args.demo:
            reg = (_demo_router_registry() if args.router
                   else _demo_registry())
        else:
            from paddle_tpu import metrics

            reg = metrics.get_registry()
            if not reg.snapshot():
                print("warning: default registry is empty (no workload "
                      "ran in this process) — did you want --demo or "
                      "--url?", file=sys.stderr)
        if args.check_docs:
            return _check_docs(reg.snapshot().keys(), root)
        text = (reg.expose_prometheus() if args.prometheus
                else json.dumps(reg.snapshot(), indent=2))

    if args.out:
        with open(args.out, "w") as f:
            f.write(text if text.endswith("\n") else text + "\n")
        print(f"wrote {args.out}", file=sys.stderr)
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
