"""Shared helpers for the compile-only memory-budget tools
(llama7b_budget.py, gpt13_budget.py) — same pattern as _bench_timing.py
being the shared clock for the bench tools."""
from __future__ import annotations

import os
import re
import sys


def reexec_scrubbed(child_env_flag: str, n_devices: int | None = None) -> None:
    """Re-exec into a CPU-only env (axon plugin gated off, optional
    virtual-device count) — same pattern as __graft_entry__.dryrun_multichip."""
    if os.environ.get(child_env_flag) == "1":
        return
    env = dict(os.environ)
    env[child_env_flag] = "1"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.pop("PJRT_LIBRARY_PATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    if n_devices is not None:
        flags += f" --xla_force_host_platform_device_count={n_devices}"
    env["XLA_FLAGS"] = flags.strip()
    os.execve(sys.executable, [sys.executable, "-u"] + sys.argv, env)


def zero_init_parameters() -> None:
    """Patch Layer.create_parameter to zero-init: multi-billion-param fp32
    RNG normals on one core are minutes of wasted compute, and the values
    never matter — nothing executes in a compile-only budget."""
    import jax.numpy as jnp

    from paddle_tpu import dtypes
    from paddle_tpu.nn.layer_base import Layer
    from paddle_tpu.nn.param_attr import ParamAttr
    from paddle_tpu.tensor import Parameter

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        a = ParamAttr._to_attr(attr)
        if a is False:
            return None
        dt = dtypes.convert_dtype(dtype) or self._dtype
        p = Parameter(jnp.zeros(tuple(int(s) for s in shape), dt),
                      trainable=not (a is not None and not a.trainable),
                      name=(a.name if a is not None and a.name else None))
        if a is not None:
            p.optimize_attr["learning_rate"] = a.learning_rate
            p.regularizer = a.regularizer
        return p

    Layer.create_parameter = create_parameter
