#!/usr/bin/env bash
# Round-5 tunnel-return battery, most-valuable-first so a re-wedge costs
# least. Order tracks VERDICT.md r4 "Next round":
#   1. llama bisect (the quarantine is the #1 open item)
#   2. headline GPT ladder (banks the official TPU artifact evidence)
#   3. gpt13 — the 1.3B north-star config (>=40% MFU target)
#   4+ BASELINE.md cleanup re-measures + decode row
# Each step runs under its own timeout; a hang kills only that step.
set -uo pipefail
cd "$(dirname "$0")/.."
# everything also lands in a line-buffered log — pipe buffers lose
# output when a re-wedge gets steps SIGKILLed (happened r4)
exec > >(stdbuf -oL tee -a rerun_r05.log) 2>&1
echo "=== r5 battery start $(date -u +%H:%M:%S) ==="

echo "=== 1. llama anomaly bisect (answers the quarantine) ==="
timeout 1800 python tools/bisect_llama_tpu.py
echo "bisect rc=$?"

# ladder outer timeouts: worst case = rungs x 1800s inner budget + probe
# slack (the outer kill must never beat the ladder's own per-rung kills,
# or the combined best-line artifact is lost mid-ladder)
echo "=== 2. headline GPT ladder (official artifact evidence) ==="
BENCH_BONUS=0 timeout 5700 python bench.py --model gpt

echo "=== 3. gpt13: 1.3B north-star, 40% MFU target ==="
BENCH_BONUS=0 timeout 9500 python bench.py --model gpt13

echo "=== 4. resnet50 re-measure (old row is suspect-high) ==="
BENCH_SMALL=0 timeout 900 python bench.py --model resnet50

echo "=== 5. fused AdamW re-verdict at designed 256x1024 blocking ==="
timeout 900 python tools/bench_adamw.py

echo "=== 6. flash S=1024 block tie-break (reps=9) ==="
timeout 1200 python tools/bench_flash.py --s 1024 --reps 9

echo "=== 6b. flash D=128 block sweep (gpt13/llama head geometry) ==="
timeout 1200 python tools/bench_flash.py --d 128 --s 1024 --reps 5

echo "=== 7. bert re-measure with chained clock ==="
timeout 900 python bench.py --model bert

echo "=== 8. decode throughput (device-side while_loop) ==="
timeout 1800 python tools/bench_decode.py

echo "=== 9. bert B64 batch probe ==="
BENCH_BATCH=64 timeout 900 python bench.py --model bert

echo "=== 10. llama re-measure (if bisect un-quarantined it) ==="
BENCH_BATCH=8 BENCH_RECOMPUTE=1 timeout 2400 python bench.py --model llama

echo "=== 11. dynamic-shape vision: yoloe + ocr (BASELINE config 5) ==="
timeout 2400 python bench.py --model yoloe
timeout 1200 python bench.py --model ocr

echo "=== 12. digest ==="
python tools/notes_digest.py

echo "done — see BENCH_NOTES_r05.json"
