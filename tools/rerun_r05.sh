#!/usr/bin/env bash
# Round-5 tunnel-return battery, most-valuable-first so a re-wedge costs
# least. Order tracks VERDICT.md r4 "Next round":
#   1. llama bisect (the quarantine is the #1 open item)
#   2. headline GPT ladder (banks the official TPU artifact evidence)
#   3. gpt13 — the 1.3B north-star config (>=40% MFU target)
#   4+ BASELINE.md cleanup re-measures + decode row + vision configs
# Each step runs under its own timeout; a hang kills only that step.
# Between steps a killable probe (tools/probe_tunnel.sh — shared with the
# watcher) checks the tunnel is still healthy: a mid-battery re-wedge
# (the r4 failure mode) aborts the battery instead of burning hours of
# sequential step timeouts, re-arms the watcher, and — because every
# completed step leaves a done-marker — the NEXT window resumes at the
# first un-done step instead of replaying banked measurements.
set -uo pipefail
cd "$(dirname "$0")/.."
# everything also lands in a line-buffered log — pipe buffers lose
# output when a re-wedge gets steps SIGKILLed (happened r4)
exec > >(stdbuf -oL tee -a rerun_r05.log) 2>&1
echo "=== r5 battery start $(date -u +%H:%M:%S) ==="

DONE_DIR=.battery_done_r05
mkdir -p "$DONE_DIR"

gate() {
  if ! bash tools/probe_tunnel.sh; then
    echo "[battery] tunnel unhealthy before: $1 ($(date -u +%H:%M:%S))"
    echo "[battery] aborting; re-arming watcher for the next window"
    if ! pgrep -f "tunnel_watch.sh" > /dev/null; then
      nohup bash tools/tunnel_watch.sh 60 420 >> tunnel_watch.log 2>&1 &
    else
      echo "[battery] a watcher is already running — not stacking another"
    fi
    python tools/notes_digest.py || true
    exit 3
  fi
}

# run_step <marker> <timeout_s> <cmd...>: skip when already banked this
# round; mark done on success (rc==0) so a resumed battery starts at the
# first un-done step.
run_step() {
  local marker=$1 budget=$2
  shift 2
  if [ -e "$DONE_DIR/$marker" ]; then
    echo "[battery] $marker already done — skipping"
    return 0
  fi
  timeout "$budget" "$@"
  local rc=$?
  echo "[battery] $marker rc=$rc"
  if [ "$rc" -eq 0 ]; then
    touch "$DONE_DIR/$marker"
  fi
  return 0
}

gate "1. bisect"
echo "=== 1. llama anomaly bisect (answers the quarantine) ==="
# done = a COMPLETE verdict row exists — an INCOMPLETE verdict (some core
# probe errored) must re-run next window; individual probe rows are NOT
# done-ness either: r5's first window banked two kernel rows + one
# trajectory before its controls OOM'd, and the old any-row grep would
# have skipped the fixed bisect forever. Probes skip their own banked
# rows, so a resumed bisect only pays for what's missing. Healthy-tunnel
# cold run is ~35-40 min; the timeout covers the pathological case
# (kernel 600s + 8 x 1500s probe timeouts = 12600s, though 2 consecutive
# timeouts abort the sequence early).
if grep -q '"probe": "verdict", .*"complete": true' BENCH_NOTES_r05.json \
    2>/dev/null; then
  echo "[battery] complete bisect verdict already banked — skipping"
else
  timeout 14400 python tools/bisect_llama_tpu.py
  echo "bisect rc=$?"
  grep -q '"probe": "verdict", .*"complete": true' BENCH_NOTES_r05.json \
    2>/dev/null && touch "$DONE_DIR/01-bisect"
fi

gate "2. gpt ladder"
echo "=== 2. headline GPT ladder (official artifact evidence) ==="
# ladder outer timeouts: worst case = rungs x 1800s inner budget + probe
# slack (the outer kill must never beat the ladder's own per-rung kills)
BENCH_BONUS=0 BENCH_NO_CPU_FALLBACK=1 run_step 02-gpt-ladder 5700 python bench.py --model gpt

gate "3. gpt13"
echo "=== 3. gpt13: 1.3B north-star, 40% MFU target ==="
# 6 rungs x 1800s inner budget + 5 inter-rung probes x 150s + slack
BENCH_BONUS=0 BENCH_NO_CPU_FALLBACK=1 run_step 03-gpt13 12000 python bench.py --model gpt13

gate "4. resnet50"
echo "=== 4. resnet50 re-measure (old row is suspect-high) ==="
BENCH_SMALL=0 BENCH_NO_CPU_FALLBACK=1 run_step 04-resnet50 900 python bench.py --model resnet50

gate "5. adamw"
echo "=== 5. fused AdamW re-verdict at designed 256x1024 blocking ==="
run_step 05-adamw 900 python tools/bench_adamw.py

gate "6. flash tie-break"
echo "=== 6. flash S=1024 block tie-break (reps=9) ==="
run_step 06-flash-tiebreak 1200 python tools/bench_flash.py --s 1024 --reps 9

gate "6b. flash d128"
echo "=== 6b. flash D=128 block sweep (gpt13/llama head geometry) ==="
run_step 06b-flash-d128 1200 python tools/bench_flash.py --d 128 --s 1024 --reps 5

gate "7. bert"
echo "=== 7. bert re-measure with chained clock ==="
BENCH_NO_CPU_FALLBACK=1 run_step 07-bert 900 python bench.py --model bert

gate "8. decode"
echo "=== 8. decode throughput (device-side while_loop) ==="
run_step 08-decode 1800 python tools/bench_decode.py

gate "8b. decode B32"
echo "=== 8b. decode batch probe (B=32 — decode is memory-bound, batch amortizes the weight streaming) ==="
BENCH_BATCH=32 run_step 08b-decode-b32 1800 python tools/bench_decode.py

gate "9. bert B64"
echo "=== 9. bert B64 batch probe ==="
BENCH_BATCH=64 BENCH_NO_CPU_FALLBACK=1 run_step 09-bert-b64 900 python bench.py --model bert

gate "9b. bert S512"
echo "=== 9b. bert B16 S=512 probe (pretraining phase-2 geometry, better FLOP/byte than S=128) ==="
BENCH_BATCH=16 BENCH_SEQ=512 BENCH_NO_CPU_FALLBACK=1 run_step 09b-bert-s512 900 python bench.py --model bert

gate "10. llama"
echo "=== 10. llama re-measure ladder (proven rc config first, then no-remat probes) ==="
# 3 rungs x 1800s inner budget + 2 inter-rung probes x 150s + slack
BENCH_BONUS=0 BENCH_NO_CPU_FALLBACK=1 run_step 10-llama 6300 python bench.py --model llama

gate "11. vision"
echo "=== 11. dynamic-shape vision: yoloe + ocr (BASELINE config 5) ==="
BENCH_NO_CPU_FALLBACK=1 run_step 11-yoloe 2400 python bench.py --model yoloe
BENCH_NO_CPU_FALLBACK=1 run_step 11-ocr 1200 python bench.py --model ocr

# --- session-3 additions: long-context evidence + MFU probes ---

gate "12. flash long-S"
echo "=== 12. flash full S sweep (512..4096, D=64) — long-context kernel evidence ==="
run_step 12-flash-longs 3600 python tools/bench_flash.py

gate "12b. flash d128 s2048"
echo "=== 12b. flash D=128 S=2048 (llama/gpt13 geometry, long context) ==="
run_step 12b-flash-d128-s2048 1200 python tools/bench_flash.py --d 128 --s 2048 --reps 5

gate "13. gpt13 b2"
echo "=== 13. gpt13 b2-fce probe rung (does the b8->b4 HBM-pressure trend continue?) ==="
BENCH_BATCH=2 BENCH_NO_CPU_FALLBACK=1 run_step 13-gpt13-b2 2400 python bench.py --model gpt13

gate "13b. gpt13 s2048"
echo "=== 13b. gpt13 b2 S=2048 — the GPT-3 paper context for the XL row ==="
# the gpt13 ladder's last rung measures this same config on a FRESH
# ladder run (driver path) — skip when a TPU row is already banked
if grep -q '"config": "gpt13-h2048-l24-b2-s2048.*"device": "tpu"' \
    BENCH_NOTES_r05.json 2>/dev/null; then
  echo "[battery] 13b already banked by the ladder — skipping"
  touch "$DONE_DIR/13b-gpt13-s2048"
else
  BENCH_BATCH=2 BENCH_SEQ=2048 BENCH_NO_CPU_FALLBACK=1 run_step 13b-gpt13-s2048 2400 python bench.py --model gpt13
fi

gate "14. gpt long-context"
echo "=== 14. gpt-355m S=2048 training row (long-context training on silicon) ==="
BENCH_SEQ=2048 BENCH_BATCH=4 BENCH_NO_CPU_FALLBACK=1 run_step 14-gpt-s2048 2400 python bench.py --model gpt

echo "=== 15. digest ==="
python tools/notes_digest.py

echo "done — see BENCH_NOTES_r05.json"
