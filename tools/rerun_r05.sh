#!/usr/bin/env bash
# Round-5 tunnel-return battery, most-valuable-first so a re-wedge costs
# least. Order tracks VERDICT.md r4 "Next round":
#   1. llama bisect (the quarantine is the #1 open item)
#   2. headline GPT ladder (banks the official TPU artifact evidence)
#   3. gpt13 — the 1.3B north-star config (>=40% MFU target)
#   4+ BASELINE.md cleanup re-measures + decode row + vision configs
# Each step runs under its own timeout; a hang kills only that step.
# Between steps a killable probe checks the tunnel is still healthy —
# a mid-battery re-wedge (the r4 failure mode) must abort the battery
# (not burn hours of sequential step timeouts) and re-arm the watcher
# so the remaining steps ride the next healthy window.
set -uo pipefail
cd "$(dirname "$0")/.."
# everything also lands in a line-buffered log — pipe buffers lose
# output when a re-wedge gets steps SIGKILLed (happened r4)
exec > >(stdbuf -oL tee -a rerun_r05.log) 2>&1
echo "=== r5 battery start $(date -u +%H:%M:%S) ==="

probe() {
  timeout 140 python - <<'EOF'
import subprocess, sys
r = subprocess.run(
    [sys.executable, "-c", "import jax; d=jax.devices()[0]; "
     "assert d.platform in ('tpu','axon'); print('PROBE_OK')"],
    capture_output=True, text=True, timeout=120)
sys.exit(0 if (r.returncode == 0 and "PROBE_OK" in r.stdout) else 1)
EOF
}

gate() {
  if ! probe; then
    echo "[battery] tunnel unhealthy before: $1 ($(date -u +%H:%M:%S)) — "
    echo "[battery] aborting battery, re-arming watcher for the next window"
    nohup bash tools/tunnel_watch.sh 60 420 > tunnel_watch.log 2>&1 &
    python tools/notes_digest.py || true
    exit 3
  fi
}

echo "=== 1. llama anomaly bisect (answers the quarantine) ==="
timeout 1800 python tools/bisect_llama_tpu.py
echo "bisect rc=$?"

gate "2. gpt ladder"
# ladder outer timeouts: worst case = rungs x 1800s inner budget + probe
# slack (the outer kill must never beat the ladder's own per-rung kills,
# or the combined best-line artifact is lost mid-ladder)
echo "=== 2. headline GPT ladder (official artifact evidence) ==="
BENCH_BONUS=0 timeout 5700 python bench.py --model gpt

gate "3. gpt13"
echo "=== 3. gpt13: 1.3B north-star, 40% MFU target ==="
BENCH_BONUS=0 timeout 9500 python bench.py --model gpt13

gate "4. resnet50"
echo "=== 4. resnet50 re-measure (old row is suspect-high) ==="
BENCH_SMALL=0 timeout 900 python bench.py --model resnet50

gate "5. adamw"
echo "=== 5. fused AdamW re-verdict at designed 256x1024 blocking ==="
timeout 900 python tools/bench_adamw.py

gate "6. flash tie-break"
echo "=== 6. flash S=1024 block tie-break (reps=9) ==="
timeout 1200 python tools/bench_flash.py --s 1024 --reps 9

gate "6b. flash d128"
echo "=== 6b. flash D=128 block sweep (gpt13/llama head geometry) ==="
timeout 1200 python tools/bench_flash.py --d 128 --s 1024 --reps 5

gate "7. bert"
echo "=== 7. bert re-measure with chained clock ==="
timeout 900 python bench.py --model bert

gate "8. decode"
echo "=== 8. decode throughput (device-side while_loop) ==="
timeout 1800 python tools/bench_decode.py

gate "9. bert B64"
echo "=== 9. bert B64 batch probe ==="
BENCH_BATCH=64 timeout 900 python bench.py --model bert

gate "10. llama"
echo "=== 10. llama re-measure (if bisect un-quarantined it) ==="
BENCH_BATCH=8 BENCH_RECOMPUTE=1 timeout 2400 python bench.py --model llama

gate "11. vision"
echo "=== 11. dynamic-shape vision: yoloe + ocr (BASELINE config 5) ==="
timeout 2400 python bench.py --model yoloe
timeout 1200 python bench.py --model ocr

echo "=== 12. digest ==="
python tools/notes_digest.py

echo "done — see BENCH_NOTES_r05.json"
