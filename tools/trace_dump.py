#!/usr/bin/env python
"""Render the request trace journal as a Chrome-trace / Perfetto file.

The read-side view of ``paddle_tpu.serving.tracing`` (ISSUE 17,
docs/OBSERVABILITY.md "Request tracing & flight recorder"): each request
becomes ONE named track — every gap between consecutive journal events
is a slice labeled by the event that ENDS it, so a track reads as
"where this request's time went" (queue wait ends at req.admit, a
prefill wait ends at req.chunk, a migration hop shows as
req.export/req.adopt slices) — and each engine's ``step.tokens`` events
become a counter track. A request that hopped engines mid-decode
renders as ONE contiguous track: the tracer's fleet-global seq stream
orders events across the hop, and the exactly-once audit
(``tracing.validate_events``) runs before export — a duplicated or
missing event fails the dump, it does not render as a glitch.

The output is the SAME chrome-trace dialect the profiler writes
(``{"traceEvents": [...], "displayTimeUnit": "ms"}``, "X" slices with
microsecond ts/dur, "C" counters with ``args.value``) so a serving
trace and a profiler window load side by side in chrome://tracing or
https://ui.perfetto.dev.

Inputs: a flight-recorder dump (``--in flight-*.json``, as written by
``RequestTracer.dump_flight``) or ``--demo`` (a seeded 2-engine drill
that kills one engine mid-decode, so the exported trace shows a real
migration hop). Exit code 1 if the exactly-once audit fails.

Run: JAX_PLATFORMS=cpu python tools/trace_dump.py --demo --out t.json
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

__all__ = ["chrome_trace", "load_events", "main"]


def chrome_trace(events, pid=None):
    """Chrome-trace dict for a list of journal event dicts (the shape
    ``RequestTracer.events()`` / ``dump_flight`` emit). ``req.*``
    timelines become one named track per request; ``step.tokens``
    becomes one counter track per engine."""
    from paddle_tpu.serving import tracing

    pid = os.getpid() if pid is None else pid
    out = []
    req_events = [e for e in events if e["name"] != "step.tokens"]
    problems = tracing.validate_events(req_events)

    by_req = {}
    for e in req_events:
        by_req.setdefault(e["req_id"], []).append(e)
    for tid, (rid, tl) in enumerate(
            sorted(by_req.items(), key=lambda kv: str(kv[0])), start=2):
        tl.sort(key=lambda e: e["seq"])
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": f"req {rid}"}})
        prev_t = tl[0]["t"]
        for e in tl:
            t0, dur = prev_t, e["t"] - prev_t
            prev_t = e["t"]
            out.append({
                "name": e["name"], "ph": "X", "cat": "request",
                "ts": t0 * 1e6, "dur": max(dur, 0.0) * 1e6,
                "pid": pid, "tid": tid,
                "args": {"req_id": str(rid), "seq": e["seq"],
                         "arg": e["arg"], "label": e["label"]}})
    for e in events:
        if e["name"] == "step.tokens":
            out.append({"name": f"step.tokens/{e['req_id']}", "ph": "C",
                        "cat": "counter", "ts": e["t"] * 1e6, "pid": pid,
                        "args": {"value": e["arg"]}})
    return ({"traceEvents": out, "displayTimeUnit": "ms"}, problems)


def load_events(path):
    """Journal events from ``path``: a flight-recorder dump (reads its
    ``events``) or a bare JSON list of event dicts."""
    with open(path) as f:
        payload = json.load(f)
    return payload["events"] if isinstance(payload, dict) else payload


def _demo_events():
    """Seeded 2-engine drill with a real mid-decode engine kill, so the
    exported trace exercises every track type including the migration
    hop. Returns the live journal."""
    os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import faults
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.serving import Router, tracing

    old = tracing.set_tracer(tracing.RequestTracer(capacity=8192))
    try:
        tracer = tracing.get_tracer()
        paddle.seed(0)
        model = LlamaForCausalLM(llama_tiny(
            vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
            num_key_value_heads=2, max_position_embeddings=64))
        r = Router()
        r.add_model("m", model, replicas=2, page_size=4,
                    max_batch_slots=2)
        rng = np.random.RandomState(7)
        e0 = r.engine("m/0")
        for n, t, s in ((10, 0.9, 21), (9, 0.7, 22), (8, 1.1, 23)):
            e0.add_request(rng.randint(0, 128, (5,)), max_new_tokens=n,
                           temperature=t, seed=s)
        for _ in range(3):
            r.step()
        with faults.inject("router.engine_step",
                           raise_=RuntimeError("demo engine kill"),
                           times=1, seed=0):
            r.step()
        r.run()
        return tracer.events()
    finally:
        tracing.set_tracer(old)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Export the request trace journal as chrome-trace")
    ap.add_argument("--in", dest="inp", metavar="PATH",
                    help="flight-recorder dump (or bare event list) JSON")
    ap.add_argument("--demo", action="store_true",
                    help="run the seeded kill-mid-decode drill and "
                         "export its live journal")
    ap.add_argument("--out", default="trace_dump.json",
                    help="chrome-trace output path (default: %(default)s)")
    args = ap.parse_args(argv)
    if bool(args.inp) == bool(args.demo):
        ap.error("exactly one of --in / --demo required")
    events = _demo_events() if args.demo else load_events(args.inp)
    trace, problems = chrome_trace(events)
    with open(args.out, "w") as f:
        json.dump(trace, f, indent=1)
    n_tracks = sum(1 for e in trace["traceEvents"] if e["ph"] == "M")
    n_counters = len({e["name"] for e in trace["traceEvents"]
                      if e["ph"] == "C"})
    print(f"trace_dump: {len(events)} journal events -> {args.out} "
          f"({n_tracks} request tracks, {n_counters} counter tracks)")
    for p in problems:
        print(f"  EXACTLY-ONCE VIOLATION: {p}", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
