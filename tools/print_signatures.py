#!/usr/bin/env python
"""Dump the public API surface as signature fingerprints.

Reference parity: ``/root/reference/tools/print_signatures.py`` → the
``paddle/fluid/API.spec`` CI gate — the reference hashes every public
callable's signature so a silent argument rename/reorder fails CI. Here:
one line per public callable, ``<dotted name> <signature>``, sorted;
the checked-in ``API.spec`` is diffed by ``tests/test_api_fingerprint.py``
(and ``tools/check_parity.sh``).

Regenerate after an intentional API change:
    python tools/print_signatures.py > API.spec
"""
from __future__ import annotations

import inspect
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

# Namespaces whose __all__ constitutes the fingerprinted surface. Chosen to
# match the reference's API.spec scope: everything a user program imports.
NAMESPACES = [
    "paddle_tpu",
    "paddle_tpu.nn",
    "paddle_tpu.nn.functional",
    "paddle_tpu.nn.initializer",
    "paddle_tpu.optimizer",
    "paddle_tpu.optimizer.lr",
    "paddle_tpu.distributed",
    "paddle_tpu.distributed.fleet",
    "paddle_tpu.distributed.ps",
    "paddle_tpu.amp",
    "paddle_tpu.autograd",
    "paddle_tpu.jit",
    "paddle_tpu.static",
    "paddle_tpu.static.nn",
    "paddle_tpu.io",
    "paddle_tpu.vision.models",
    "paddle_tpu.vision.transforms",
    "paddle_tpu.vision.ops",
    "paddle_tpu.models",
    "paddle_tpu.metric",
    "paddle_tpu.metrics",
    "paddle_tpu.faults",
    "paddle_tpu.checkpoint",
    "paddle_tpu.analysis",
    "paddle_tpu.distribution",
    "paddle_tpu.sparse",
    "paddle_tpu.fft",
    "paddle_tpu.signal",
    "paddle_tpu.onnx",
    "paddle_tpu.inference",
    "paddle_tpu.serving",
    "paddle_tpu.loadgen",
    "paddle_tpu.quantization",
    "paddle_tpu.profiler",
    "paddle_tpu.incubate.nn",
    "paddle_tpu.incubate.optimizer",
    "paddle_tpu.incubate.autograd",
]


def _sig_of(obj) -> str:
    """Signature string, or a stable fallback class for uninspectables."""
    target = obj
    if inspect.isclass(obj):
        target = obj.__init__
    try:
        sig = inspect.signature(target)
    except (ValueError, TypeError):
        return "(*uninspectable*)"
    parts = []
    for p in sig.parameters.values():
        if p.name == "self":
            continue
        s = p.name
        if p.kind == inspect.Parameter.VAR_POSITIONAL:
            s = "*" + s
        elif p.kind == inspect.Parameter.VAR_KEYWORD:
            s = "**" + s
        if p.default is not inspect.Parameter.empty:
            d = repr(p.default)
            if " object at 0x" in d:  # unstable instance repr
                d = f"<{type(p.default).__name__}>"
            s += f"={d}"
        parts.append(s)
    return "(" + ", ".join(parts) + ")"


def fingerprint_lines() -> list:
    import importlib
    import types

    # import everything FIRST: for namespaces without __all__ the dir()
    # fallback must not depend on which submodules a prior test imported
    mods = {}
    for ns in NAMESPACES:
        try:
            mods[ns] = importlib.import_module(ns)
        except ImportError as e:  # a namespace vanishing IS a finding
            mods[ns] = e

    lines = []
    for ns, mod in mods.items():
        if isinstance(mod, ImportError):
            lines.append(f"{ns} <IMPORT ERROR: {type(mod).__name__}>")
            continue
        names = getattr(mod, "__all__", None)
        if names is None:
            names = [n for n in dir(mod) if not n.startswith("_")]
        for name in sorted(set(names)):
            obj = getattr(mod, name, None)
            if isinstance(obj, types.ModuleType):
                continue  # submodule attrs aren't signatures (and their
                # presence depends on import order)
            if obj is None:
                lines.append(f"{ns}.{name} <MISSING>")
            elif callable(obj):
                lines.append(f"{ns}.{name} {_sig_of(obj)}")
            else:
                lines.append(f"{ns}.{name} <{type(obj).__name__}>")
    return sorted(set(lines))


if __name__ == "__main__":
    print("\n".join(fingerprint_lines()))
