#!/usr/bin/env python
"""GPT-3 1.3B single-chip memory-budget sweep (BASELINE.json north star).

The north-star config is GPT-3 1.3B (h2048 l24 heads16 — the GPT-3 paper's
"XL" row, d_head 128) at >=40% MFU. One v5e chip has 16 GiB; with fp32
master weights AdamW state alone is ~18.4 GiB (14 B/param), so the fit
depends on which levers are on. This tool AOT-lowers the REAL train step
(StaticFunction.lower -> compiled.memory_analysis, the same flow as
tools/llama7b_budget.py) for each lever combo on one virtual CPU device
and prints XLA's per-chip peak, worst-first-screened so the bench ladder
(bench.py --model gpt13) ranks only configs that actually fit.

Levers swept:
  master  — amp O2 fp32 master weights on/off. Off (paddle's
            multi_precision default) the accumulators are zeros_like(param)
            — bf16 params give bf16 m/v: 6 B/param, ~7.3 GiB state at
            1.3B (the sweep's measured argument_gb = 7.34 = 3 x 2.45
            confirms all three are bf16)
  rc      — recompute off / 'dots' (save MXU outputs) / full
  fce     — fused chunked linear+CE (never materializes [B*S, 50304])
  B       — per-chip batch at S=1024

Usage:
    python tools/gpt13_budget.py            # full sweep, writes GPT13_BUDGET.md
    python tools/gpt13_budget.py --smoke    # tiny shapes, CI-speed
Prints one JSON line per combo + a final summary line.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

V5E_HBM_GB = 16.0
GB = 1024 ** 3


def _reexec_scrubbed() -> None:
    from _budget_common import reexec_scrubbed
    reexec_scrubbed("_GPT13_BUDGET_CHILD")


def _zero_init_parameters() -> None:
    from _budget_common import zero_init_parameters
    zero_init_parameters()


def measure(combo: dict, smoke: bool) -> dict:
    """Build + AOT-lower one lever combo; returns the budget record.
    Runs in a child process (caller) so 13-GiB host buffers are freed
    between combos."""
    import numpy as np

    _zero_init_parameters()

    import paddle_tpu as paddle
    from paddle_tpu import amp, jit
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    if smoke:
        cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                        num_heads=4, max_position_embeddings=256,
                        hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                        recompute=combo["rc"] is not None,
                        recompute_policy=combo["rc"],
                        fused_loss=combo["fce"])
        B, S = 2, 128
    else:
        S = combo.get("S", 1024)
        cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                        num_heads=16, max_position_embeddings=max(S, 1024),
                        hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                        recompute=combo["rc"] is not None,
                        recompute_policy=(None if combo["rc"] == "full"
                                          else combo["rc"]),
                        fused_loss=combo["fce"])
        B = combo["B"]

    model = GPTForCausalLM(cfg)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    model, opt = amp.decorate(model, opt, level="O2", dtype="bfloat16",
                              master_weight=combo["master"])

    def train_fn(ids, labels):
        with amp.auto_cast(level="O2", dtype="bfloat16"):
            _, loss = model(ids, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = jit.StaticFunction(train_fn, observe=[model, opt], warmup=False)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (B, S)))
    labels = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (B, S)))

    t0 = time.time()
    compiled = step.lower(ids, labels).compile()
    ma = compiled.memory_analysis()
    peak = int(ma.peak_memory_in_bytes)
    import jax
    on_cpu = jax.devices()[0].platform == "cpu"
    return {
        "metric": "gpt13_budget_peak_gb",
        "value": round(peak / GB, 2),
        "unit": "GiB/chip",
        "combo": combo["tag"],
        "params_b": round(n_params / 1e9, 3),
        "argument_gb": round(ma.argument_size_in_bytes / GB, 2),
        "temp_gb": round(ma.temp_size_in_bytes / GB, 2),
        "alias_gb": round(ma.alias_size_in_bytes / GB, 2),
        # CPU buffer assignment does not liveness-schedule temps (the
        # llama smoke row's peak reads 0.0 on CPU) — a CPU "peak" can
        # only certify structure, never fit. Authoritative fit = the
        # TPU bench ladder (each rung OOMs in its own subprocess).
        "fits": (peak / GB < V5E_HBM_GB) if not on_cpu else None,
        "cpu_aot": on_cpu,
        "compile_s": round(time.time() - t0, 1),
    }


COMBOS = [
    # tag, master, rc, fce, B  (S defaults 1024)
    {"tag": "b8-dots-fce-nomaster", "master": False, "rc": "dots",
     "fce": True, "B": 8},
    {"tag": "b8-fce-nomaster", "master": False, "rc": None,
     "fce": True, "B": 8},
    {"tag": "b4-fce-nomaster", "master": False, "rc": None,
     "fce": True, "B": 4},
    {"tag": "b16-dots-fce-nomaster", "master": False, "rc": "dots",
     "fce": True, "B": 16},
    {"tag": "b8-full-fce-nomaster", "master": False, "rc": "full",
     "fce": True, "B": 8},
    # the master-weights control: expected NOT to fit (18.4 GB state)
    {"tag": "b4-dots-fce-master", "master": True, "rc": "dots",
     "fce": True, "B": 4},
]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--combo", help="run ONE combo by tag (child mode)")
    ap.add_argument("--no-write", action="store_true")
    args = ap.parse_args()
    _reexec_scrubbed()

    if args.combo:  # child: measure one combo, print one JSON line
        combo = next(c for c in COMBOS if c["tag"] == args.combo)
        print(json.dumps(measure(combo, args.smoke)), flush=True)
        return 0

    import subprocess
    results = []
    combos = COMBOS[:2] if args.smoke else COMBOS
    for combo in combos:
        print(f"[gpt13-budget] {combo['tag']}...", file=sys.stderr,
              flush=True)
        cmd = [sys.executable, os.path.abspath(__file__),
               "--combo", combo["tag"]]
        if args.smoke:
            cmd.append("--smoke")
        # own process group + group kill on timeout (a plain subprocess
        # kill leaves grandchildren parked in backend init — the exact
        # orphaned-claim wedge bench.py _launch_banked guards against),
        # and a slow combo must cost only itself, not the sweep
        import signal
        p = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True,
                             start_new_session=True)
        try:
            out, err = p.communicate(timeout=3600)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                p.kill()
            p.communicate()
            print(f"[gpt13-budget] {combo['tag']} TIMED OUT (killed group)",
                  file=sys.stderr, flush=True)
            continue
        line = next((ln for ln in reversed(out.splitlines())
                     if ln.startswith("{")), None)
        if line is None:
            print(f"[gpt13-budget] {combo['tag']} FAILED rc={p.returncode}: "
                  f"{err[-300:]}", file=sys.stderr, flush=True)
            continue
        rec = json.loads(line)
        print(json.dumps(rec), flush=True)
        results.append(rec)

    cpu_aot = any(r.get("cpu_aot") for r in results)
    fitting = [r for r in results if r["fits"]]
    summary = {
        "metric": "gpt13_budget_summary",
        "value": len(results) if cpu_aot else len(fitting),
        "unit": "compiled_configs" if cpu_aot else "fitting_configs",
        "vs_baseline": 1.0,
        "fitting": [r["combo"] for r in fitting],
        "peaks_gb": {r["combo"]: r["value"] for r in results},
        "cpu_aot": cpu_aot,
    }
    print(json.dumps(summary), flush=True)

    if not args.smoke and not args.no_write and results:
        lines = [
            "# GPT-3 1.3B single-chip memory budget (v5e, compile-only)",
            "",
            "North-star config (BASELINE.json): GPT-3 1.3B, h2048 l24 "
            "heads16 (d_head 128), S=1024, AdamW. Per-chip peak from XLA "
            "buffer assignment (StaticFunction.lower -> memory_analysis) "
            "on one virtual device — same flow as LLAMA7B_BUDGET.md.",
            "",
            "`nomaster` = amp O2 with master_weight=False (paddle's "
            "multi_precision default): accumulators are zeros_like(param), "
            "so bf16 params give bf16 m+v = 6 B/param (~7.3 GiB state — "
            "the measured argument_gb 7.34 = 3 x 2.45 GiB bf16 buffers) "
            "vs ~18.4 GiB with fp32 masters+moments, which cannot fit "
            "one 16 GiB chip.",
            "",
            "| combo | peak GiB | args GiB | temps GiB | fits 16 GiB |",
            "|---|---|---|---|---|",
        ]
        for r in results:
            fit = ("n/a (cpu aot)" if r["fits"] is None
                   else ("yes" if r["fits"] else "NO"))
            lines.append(
                f"| {r['combo']} | {r['value']:.2f} | {r['argument_gb']:.2f}"
                f" | {r['temp_gb']:.2f} | {fit} |")
        lines += [
            "",
            "CPU AOT caveat: CPU buffer assignment does not "
            "liveness-schedule temps, so a CPU 'peak' certifies structure "
            "and argument (param+opt-state) size only. Authoritative fit "
            "is the TPU bench ladder — each rung claims the chip in its "
            "own subprocess and an OOM fails only that rung "
            "(bench.py _LADDERS['gpt13']).",
            "",
            f"Params: {results[0]['params_b']} B. Generated by "
            "`tools/gpt13_budget.py`."]
        out = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "GPT13_BUDGET.md")
        with open(out, "w") as f:
            f.write("\n".join(lines) + "\n")
        print(f"[gpt13-budget] wrote {out}", file=sys.stderr, flush=True)
    # on CPU AOT 'fits' is unknowable (None) — success = every combo
    # compiled; on TPU success = at least one fitting config
    if cpu_aot:
        return 0 if len(results) == len(combos) else 1
    return 0 if fitting else 1


if __name__ == "__main__":
    sys.exit(main())
