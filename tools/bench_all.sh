#!/usr/bin/env bash
# One-command TPU bench battery — run the moment the tunnel is healthy.
# Persists every result to BENCH_NOTES_r05.json (each tool appends).
set -uo pipefail
cd "$(dirname "$0")/.."

echo "=== gpt ladder (proven + levers) ==="
python bench.py --model gpt

echo "=== bert-base ==="
python bench.py --model bert

echo "=== resnet50 ==="
python bench.py --model resnet50

echo "=== llama 0.76B single-chip ==="
python bench.py --model llama

echo "=== llama7b (8-chip run, or compile-only fit certificate) ==="
python bench.py --model llama7b

echo "=== flash-attention A/B + block sweep ==="
python tools/bench_flash.py

echo "=== fused AdamW A/B ==="
python tools/bench_adamw.py

echo "=== decode throughput (device-side while_loop) ==="
python tools/bench_decode.py

echo "=== eager dispatch (TPU) ==="
python tools/bench_eager.py

echo "done — see BENCH_NOTES_r05.json"
