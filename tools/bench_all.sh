#!/usr/bin/env bash
# One-command TPU bench battery — run the moment the tunnel is healthy.
# Persists every result to BENCH_NOTES_r03.json (each tool appends).
set -uo pipefail
cd "$(dirname "$0")/.."

echo "=== gpt ladder (proven + levers) ==="
python bench.py --model gpt

echo "=== bert-base ==="
python bench.py --model bert

echo "=== resnet50 ==="
python bench.py --model resnet50

echo "=== flash-attention A/B + block sweep ==="
python tools/bench_flash.py

echo "=== fused AdamW A/B ==="
python tools/bench_adamw.py

echo "=== eager dispatch (TPU) ==="
python tools/bench_eager.py

echo "done — see BENCH_NOTES_r03.json"
